"""Declarative SLOs with multi-window burn-rate evaluation — "are we
inside our objectives RIGHT NOW", answered from the histograms and
counters the serve stack already maintains.

An ``SloSpec`` declares an objective over existing series — no new
instrumentation, no second bookkeeping path:

- ``kind="latency"``: fraction of events in a recorder histogram at or
  under ``threshold_s`` (the threshold snaps UP to the histogram's
  power-of-two bucket bounds; the effective value is reported).  The
  default serve spec reads ``pathway_serve_request_seconds`` — the same
  family the trace tail-sampler and the exemplars ride — and the decode
  spec reads ``pathway_generator_ttlt_seconds``.
- ``kind="availability"``: 1 − degraded fraction, with bad events from
  a counter family (summed over label sets: every ladder rung counts)
  and totals from a histogram family's event count.
- ``kind="freshness"``: latency-shaped over the ingest plane's
  ``pathway_freshness_seconds`` (arrival → retrievable), PLUS the live
  maintenance lag: every pending document already OLDER than the
  threshold (read from the registered ingest runners) counts as a bad
  event right now — the burn rate rises while the backlog ages, not
  only after slow documents finally land.

Evaluation is the standard SRE burn-rate construction: the error budget
is ``1 − objective``; the burn rate over a window is the window's error
ratio divided by the budget (burn 1.0 = spending exactly the budget);
the alert fires when BOTH a fast and a slow window burn above the
threshold — fast for responsiveness, slow so a transient blip can't
page.  Windows are measured by snapshotting the cumulative
(good, total) counts at each evaluation and differencing against the
ring of past snapshots, so the engine needs no timers of its own: the
scrape (or ``GET /slo``, or the scheduler's ``should_shed`` probe)
drives it, throttled to at most one fresh evaluation per
``PATHWAY_SLO_TICK_S``.

Knobs: ``PATHWAY_SLO_LATENCY_MS`` / ``PATHWAY_SLO_LATENCY_OBJECTIVE``,
``PATHWAY_SLO_AVAILABILITY``, ``PATHWAY_SLO_TTLT_MS``,
``PATHWAY_SLO_FAST_WINDOW_S`` / ``PATHWAY_SLO_SLOW_WINDOW_S``,
``PATHWAY_SLO_BURN`` (threshold, default 14.4 — the classic 2%-of-
budget-in-an-hour page), ``PATHWAY_SLO_TICK_S``, ``PATHWAY_SLO=0`` to
disable the scheduler's shed advisory.

``should_shed()`` is the seam the scheduler consumes: True while any
``shed=True`` spec is firing.  Since round 19 the scheduler ACTS on it
(``PATHWAY_SERVE_SHED``): shed-class priorities get an empty flagged
result at admission; ``PATHWAY_SERVE_SHED=0`` restores the round-15
advisory-only behavior.  ``firing_specs()`` exposes which objectives
are firing so the ingest runner can tell "serve latency is the binding
constraint" (yield absorb cadence) from "freshness is burning" (keep
absorbing).

Degrade-never-fail: the ``slo.evaluate`` chaos site fires at the top of
a fresh evaluation under a spent deadline — any armed fault serves the
last-known (stale) document, counted on
``pathway_slo_evaluations_dropped_total``; ``GET /slo`` never 500s and
``should_shed`` never blocks a serve.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .. import config
from .histogram import N_BUCKETS, bucket_bounds_s
from .recorder import counter, register_provider
from . import recorder as _recorder

__all__ = [
    "SloSpec",
    "default_specs",
    "engine",
    "evaluate",
    "firing_specs",
    "reset",
    "should_shed",
    "shed_advisory_enabled",
]


_C_EVALS = counter("pathway_slo_evaluations_total")
_C_DROPPED = counter("pathway_slo_evaluations_dropped_total")
_C_SHED_ADVISED = counter("pathway_slo_shed_advised_total")

_inject_mod: Any = None


def _inject():
    global _inject_mod
    if _inject_mod is None:
        try:
            from ..robust import inject as mod
        except Exception:  # pragma: no cover - partial teardown
            return None
        _inject_mod = mod
    return _inject_mod


def _evaluate_allowed() -> bool:
    inj = _inject()
    if inj is None or not inj.any_armed():
        return True
    try:
        from ..robust.deadline import Deadline

        before = inj.fired_count("slo.evaluate")
        inj.fire("slo.evaluate", deadline=Deadline.after_ms(0.0))
        return inj.fired_count("slo.evaluate") == before
    except Exception:
        return False


# -- reading the recorder's registry ----------------------------------------
def _family_hist_counts(name: str) -> Tuple[List[int], int]:
    """Merged per-bucket counts + total event count over every label set
    of one recorder histogram family."""
    with _recorder._registry_lock:
        series = list(_recorder._hists.get(name, {}).values())
    counts = [0] * N_BUCKETS
    total = 0
    for h in series:
        c, _sum_ns, n = h.snapshot()
        for i, v in enumerate(c):
            counts[i] += v
        total += n
    return counts, total


def _family_counter_total(name: str) -> int:
    """Sum over every label set of one recorder counter family."""
    with _recorder._registry_lock:
        series = list(_recorder._counters.get(name, {}).values())
    return sum(c.value for c in series)


def _good_under_threshold(name: str, threshold_s: float) -> Tuple[int, int, float]:
    """(good, total, effective_threshold_s) for a latency objective:
    good = events whose bucket's upper bound is <= the snapped
    threshold (snapped UP to the next power-of-two bound, so "under
    500 ms" means "under 537 ms" on this histogram — reported, not
    hidden)."""
    bounds = bucket_bounds_s()
    cut = len(bounds) - 1
    for i, b in enumerate(bounds):
        if b >= threshold_s:
            cut = i
            break
    counts, total = _family_hist_counts(name)
    good = sum(counts[: cut + 1])
    return good, total, bounds[cut]


def _overdue_pending(threshold_s: float) -> int:
    """Documents sitting in a live ingest runner's queue LONGER than the
    freshness threshold — already-blown budget that no histogram has
    seen yet.  Lazy import: serve/ingest.py imports this module."""
    try:
        from ..serve.ingest import ingest_runners
    except Exception:  # pragma: no cover - partial teardown
        return 0
    n = 0
    for runner in ingest_runners():
        try:
            n += runner.overdue_pending(threshold_s)
        except Exception:
            continue
    return n


class SloSpec:
    """One declarative objective.  ``kind``:

    - ``"latency"``: ``hist`` (family name) + ``threshold_s``; good =
      events at or under the threshold.
    - ``"availability"``: ``bad`` (counter family) + ``total_hist``
      (histogram family whose count is the event total); good = total −
      bad (clamped).
    - ``"freshness"``: latency over ``hist`` + each currently-pending
      ingest document older than ``threshold_s`` counted as one bad
      event (maintenance lag feeds the burn before the doc lands).
    """

    __slots__ = (
        "name", "kind", "objective", "hist", "threshold_s", "bad",
        "total_hist", "shed", "description",
    )

    def __init__(
        self,
        name: str,
        kind: str,
        objective: float,
        hist: Optional[str] = None,
        threshold_s: Optional[float] = None,
        bad: Optional[str] = None,
        total_hist: Optional[str] = None,
        shed: bool = False,
        description: str = "",
    ):
        if kind not in ("latency", "availability", "freshness"):
            raise ValueError(f"unknown SLO kind {kind!r}")
        if kind in ("latency", "freshness") and (
            hist is None or threshold_s is None
        ):
            raise ValueError(f"{kind} spec needs hist + threshold_s")
        if kind == "availability" and (bad is None or total_hist is None):
            raise ValueError("availability spec needs bad + total_hist")
        if not 0.0 < objective < 1.0:
            raise ValueError(f"objective must be in (0, 1): {objective}")
        self.name = str(name)
        self.kind = kind
        self.objective = float(objective)
        self.hist = hist
        self.threshold_s = threshold_s
        self.bad = bad
        self.total_hist = total_hist
        self.shed = bool(shed)
        self.description = description

    def counts(self) -> Tuple[int, int, Optional[float]]:
        """Cumulative (good, total, effective_threshold_s | None)."""
        if self.kind == "latency":
            return _good_under_threshold(self.hist, float(self.threshold_s))
        if self.kind == "freshness":
            good, total, eff = _good_under_threshold(
                self.hist, float(self.threshold_s)
            )
            # overdue queue residents: bad events added to the total only
            # (they leave this term once they land and the histogram
            # takes over — no double count, since the snapshot ring
            # differences cumulative values each evaluation)
            total += _overdue_pending(float(self.threshold_s))
            return good, total, eff
        total = _family_hist_counts(self.total_hist)[1]
        bad = min(_family_counter_total(self.bad), total)
        return total - bad, total, None


def default_specs() -> List[SloSpec]:
    """The shipped objectives, env-tunable.  Serve latency,
    availability, and ingest freshness carry ``shed=True`` — the
    admission seams the scheduler's load-shedding decision acts on
    (``serve.shed`` + priority classes); decode TTLT is observe-only."""
    return [
        SloSpec(
            "serve_latency",
            "latency",
            objective=config.get("observe.slo_latency_objective"),
            hist="pathway_serve_request_seconds",
            threshold_s=config.get("observe.slo_latency_ms") * 1e-3,
            shed=True,
            description="serve requests at/under the latency threshold",
        ),
        SloSpec(
            "serve_availability",
            "availability",
            objective=config.get("observe.slo_availability"),
            bad="pathway_serve_degraded_total",
            total_hist="pathway_serve_request_seconds",
            shed=True,
            description="1 - degraded fraction (every ladder rung counts)",
        ),
        SloSpec(
            "decode_ttlt",
            "latency",
            objective=0.99,
            hist="pathway_generator_ttlt_seconds",
            threshold_s=config.get("observe.slo_ttlt_ms") * 1e-3,
            description="decode requests at/under the TTLT threshold",
        ),
        SloSpec(
            "freshness",
            "freshness",
            objective=config.get("observe.slo_freshness_objective"),
            hist="pathway_freshness_seconds",
            threshold_s=config.get("observe.slo_freshness_ms") * 1e-3,
            shed=True,
            description="documents retrievable within the freshness "
            "threshold (overdue pending docs count against it)",
        ),
    ]


class SloEngine:
    """Burn-rate evaluator over a spec list.  Each evaluation appends
    one cumulative (t, good, total) snapshot per spec to a bounded ring
    and differences against the oldest snapshot inside each window."""

    _RING = 512

    def __init__(self, specs: Optional[List[SloSpec]] = None):
        self.specs = list(specs) if specs is not None else default_specs()
        self.fast_window_s = config.get("observe.slo_fast_window_s")
        self.slow_window_s = max(
            self.fast_window_s, config.get("observe.slo_slow_window_s")
        )
        self.burn_threshold = config.get("observe.slo_burn")
        self.tick_s = config.get("observe.slo_tick_s")
        self._lock = threading.Lock()
        self._rings: Dict[str, List[Tuple[float, int, int]]] = {
            s.name: [] for s in self.specs
        }
        self._last_doc: Optional[Dict[str, Any]] = None
        self._last_eval_s = 0.0

    # -- window math --------------------------------------------------------
    def _window_ratio(
        self, ring: List[Tuple[float, int, int]], now_s: float, window_s: float
    ) -> Tuple[float, int]:
        """(error_ratio, total_delta) over the window ending now.  The
        baseline is the OLDEST snapshot inside the window (standard
        burn-rate semantics: with history shorter than the window, the
        available history stands in for it)."""
        if not ring:
            return 0.0, 0
        t_now, good_now, total_now = ring[-1]
        base = ring[0]
        for snap in ring:
            if snap[0] >= now_s - window_s:
                base = snap
                break
        _t0, good0, total0 = base
        total_delta = total_now - total0
        if total_delta <= 0:
            return 0.0, 0
        bad_delta = max(0, (total_now - good_now) - (total0 - good0))
        return min(1.0, bad_delta / total_delta), total_delta

    def _evaluate_fresh(self, now_s: float) -> Dict[str, Any]:
        _C_EVALS.inc()
        slos: Dict[str, Any] = {}
        any_firing = False
        shed = False
        for spec in self.specs:
            good, total, eff_threshold = spec.counts()
            ring = self._rings[spec.name]
            ring.append((now_s, good, total))
            if len(ring) > self._RING:
                del ring[: len(ring) - self._RING]
            budget = 1.0 - spec.objective
            windows: Dict[str, Any] = {}
            burns: Dict[str, float] = {}
            for label, window_s in (
                ("fast", self.fast_window_s),
                ("slow", self.slow_window_s),
            ):
                ratio, events = self._window_ratio(ring, now_s, window_s)
                burn = ratio / budget if budget > 0 else 0.0
                burns[label] = burn
                windows[label] = {
                    "window_s": window_s,
                    "error_ratio": round(ratio, 6),
                    "burn_rate": round(burn, 3),
                    "events": events,
                }
            firing = (
                windows["fast"]["events"] > 0
                and burns["fast"] >= self.burn_threshold
                and burns["slow"] >= self.burn_threshold
            )
            any_firing = any_firing or firing
            shed = shed or (firing and spec.shed)
            row = {
                "kind": spec.kind,
                "objective": spec.objective,
                "description": spec.description,
                "good": good,
                "total": total,
                "compliance": round(good / total, 6) if total else None,
                "windows": windows,
                "state": "firing" if firing else "ok",
                "shed": spec.shed,
            }
            if eff_threshold is not None:
                row["threshold_s"] = spec.threshold_s
                row["effective_threshold_s"] = eff_threshold
            slos[spec.name] = row
        return {
            "ts": time.time(),
            "stale": False,
            "burn_threshold": self.burn_threshold,
            "alerting": any_firing,
            "should_shed": shed,
            "slos": slos,
        }

    def evaluate(self, max_age_s: Optional[float] = None) -> Dict[str, Any]:
        """The engine's one entry: a throttled (``max_age_s``, default
        the tick) fresh evaluation, the cached document otherwise, and
        the stale-on-fault chaos contract on the fresh path."""
        age = self.tick_s if max_age_s is None else max_age_s
        now_s = time.monotonic()
        with self._lock:
            if (
                self._last_doc is not None
                and now_s - self._last_eval_s < age
            ):
                return self._last_doc
            if not _evaluate_allowed():
                _C_DROPPED.inc()
                if self._last_doc is not None:
                    return {**self._last_doc, "stale": True}
                return {
                    "ts": time.time(), "stale": True, "alerting": False,
                    "should_shed": False, "slos": {},
                    "burn_threshold": self.burn_threshold,
                }
            doc = self._evaluate_fresh(now_s)
            self._last_doc = doc
            self._last_eval_s = now_s
            return doc

    def should_shed(self) -> bool:
        return bool(self.evaluate().get("should_shed"))

    # -- flight-recorder provider ------------------------------------------
    def observe_metrics(self):
        doc = self.evaluate()
        for name, row in doc.get("slos", {}).items():
            labels = {"slo": name}
            yield ("gauge", "pathway_slo_objective", labels, row["objective"])
            yield (
                "gauge",
                "pathway_slo_alert",
                labels,
                1.0 if row["state"] == "firing" else 0.0,
            )
            for label, w in row["windows"].items():
                yield (
                    "gauge",
                    "pathway_slo_burn_rate",
                    {**labels, "window": label},
                    w["burn_rate"],
                )


_engine_lock = threading.Lock()
_engine: Optional[SloEngine] = None
_shed_on = config.get("observe.slo")


def engine() -> SloEngine:
    """The process-wide engine, built lazily from the env-derived
    default specs and registered on the scrape surface."""
    global _engine
    with _engine_lock:
        if _engine is None:
            _engine = SloEngine()
            register_provider(_engine)
        return _engine


def set_engine(specs: Optional[List[SloSpec]]) -> SloEngine:
    """Install a fresh engine (tests/bench: custom specs or re-read env
    knobs).  Passing None rebuilds the defaults."""
    global _engine
    with _engine_lock:
        _engine = SloEngine(specs)
        register_provider(_engine)
        return _engine


def evaluate(max_age_s: Optional[float] = None) -> Dict[str, Any]:
    """Module-level convenience — the ``GET /slo`` payload."""
    return engine().evaluate(max_age_s)


def shed_advisory_enabled() -> bool:
    return _shed_on


def set_shed_advisory(flag: bool) -> None:
    """The bench A/B switch for the scheduler's advisory probe (also
    ``PATHWAY_SLO=0``)."""
    global _shed_on
    _shed_on = bool(flag)


def should_shed() -> bool:
    """The scheduler's admission probe: True while any ``shed=True``
    objective is firing.  With ``PATHWAY_SERVE_SHED`` on the scheduler
    ACTS on it for shed-class priorities (empty flagged result, counted
    on ``pathway_serve_shed_total``); otherwise it logs and counts
    (``pathway_slo_shed_advised_total``) and admits normally.  One
    throttled evaluation at most per tick, so the steady-state cost is
    a clock read."""
    if not _shed_on:
        return False
    try:
        return engine().should_shed()
    except Exception:
        return False  # the advisory path may never fail an admission


def record_shed_advised() -> None:
    _C_SHED_ADVISED.inc()


def firing_specs() -> Tuple[str, ...]:
    """Names of the objectives currently firing (from the throttled
    evaluation — same cost profile as ``should_shed``).  The ingest
    runner reads this to decide WHICH side yields: serve_latency firing
    while freshness is quiet means serve p99 is the binding constraint,
    so maintenance backs off its absorb cadence."""
    if not _shed_on:
        return ()
    try:
        doc = engine().evaluate()
    except Exception:
        return ()
    return tuple(
        name
        for name, row in doc.get("slos", {}).items()
        if row.get("state") == "firing"
    )


def reset() -> None:
    """Drop the engine (tests: re-read env knobs, clear rings)."""
    global _engine
    with _engine_lock:
        _engine = None
