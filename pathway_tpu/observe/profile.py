"""Per-callable device-time attribution — which compiled kernel owns
our device seconds.

The stage histograms (recorder.py) answer "where does a REQUEST spend
time"; the traces (trace.py) answer it per request.  Neither answers
the capacity-planning question: across everything the process runs,
which COMPILED CALLABLE owns the device, and what share of wall time
is it?  That attribution is the prerequisite for every kernel-level
optimization (speculative decode, quantized KV slots — the "Accelerating
RAG" observation that e2e cost concentrates in a few stages) and the
input the SLO engine's capacity math wants.

Design, in the package's cost order:

- **Sampling at the wrapper, timing at the fetch.**  Every compiled-fn
  cache in the serve stack stores its jitted callable through
  ``profile.wrap(site, fn)``.  The wrapper is transparent: it calls the
  underlying function and returns its (async, un-fetched) result.  On a
  SAMPLED call it stamps submit time, hands the first output leaf to a
  background completer thread, and returns immediately — the completer
  blocks on ``block_until_ready`` OFF the serve path, so the measured
  interval is submit→ready (device queue + execution) without ever
  adding a sync to a dispatch.  The 2+2 budget and the off-lock launch
  discipline are untouched by construction: nothing is fetched on the
  calling thread.
- **Zero-alloc when off.**  Disabled (``PATHWAY_OBSERVE=0``) or sampled
  out, the wrapper is one flag check + one modulo on a pre-resolved
  per-site record — no allocation, no clock read.
  ``PATHWAY_PROFILE_SAMPLE`` (default 0.25) sets the sampled fraction;
  sampling is a deterministic 1-in-N stride, so overhead is flat and
  replayable.
- **Degrade, never fail.**  The ``profile.sample`` chaos site
  (robust/inject.py) fires on the sampling path under an already-spent
  deadline: ANY armed fault — raise, delay, hang — drops that sample
  (counted on ``pathway_profile_samples_dropped_total``) and the serve
  proceeds untouched.  A full pending queue, a deleted/donated buffer,
  a completer error: same contract, drop + count.

Rendered under ``pathway_profile_*``: per-callable device-seconds
histograms (``pathway_profile_device_seconds{callable=...}``, whose
``_sum`` IS the attributed device seconds), sampled-call counters, and
share-of-wall gauges (``pathway_profile_device_share`` = attributed
device seconds / wall seconds since the window started, corrected for
the sampling fraction).  ``/serve_stats`` carries the same attribution
as a ``profile`` column.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import config
from . import _state
from .histogram import LatencyHistogram
from .recorder import counter, histogram

__all__ = [
    "profile_stats",
    "reset",
    "sample_stride",
    "set_sample",
    "wrap",
]


def _stride_of(fraction: float) -> int:
    """Sampled fraction -> deterministic 1-in-N stride (0 = off)."""
    if fraction <= 0.0:
        return 0
    if fraction >= 1.0:
        return 1
    return max(1, int(round(1.0 / fraction)))


_stride = _stride_of(config.get("observe.profile_sample"))

_C_DROPPED = counter("pathway_profile_samples_dropped_total")

# pending submit→ready samples awaiting the completer: a small bounded
# buffer — device work is serialized per stream, so a handful of
# in-flight samples covers any realistic pipeline depth; past capacity
# we drop (counted) rather than grow or block
_PENDING_CAP = 64
# (site, t0_ns, output leaf, stride in effect when sampled)
_pending: List[Tuple["_Site", int, Any, int]] = []
_pending_cv = threading.Condition()
_inflight = 0  # popped by the completer, not yet recorded (drain() waits)
_completer: Optional[threading.Thread] = None

# wall-clock anchor for the share-of-wall gauges (perf_counter_ns so it
# shares the clock the samples use); reset() re-anchors
_wall_t0_ns = time.perf_counter_ns()

_sites_lock = threading.Lock()
_sites: Dict[str, "_Site"] = {}

# lazy robust import (robust/ imports the observe package)
_inject_mod: Any = None


def _inject():
    global _inject_mod
    if _inject_mod is None:
        try:
            from ..robust import inject as mod
        except Exception:  # pragma: no cover - partial teardown
            return None
        _inject_mod = mod
    return _inject_mod


def _sample_allowed() -> bool:
    """Chaos gate for the sampling path (site ``profile.sample``): True
    = sample normally.  Fired under an already-spent deadline so an
    armed hang releases immediately and an armed delay is clamped to
    ~10 ms — the serve is never slowed by its own profiler."""
    inj = _inject()
    if inj is None or not inj.any_armed():
        return True
    try:
        from ..robust.deadline import Deadline

        before = inj.fired_count("profile.sample")
        inj.fire("profile.sample", deadline=Deadline.after_ms(0.0))
        return inj.fired_count("profile.sample") == before
    except Exception:
        return False


class _Site:
    """Per-callable attribution record, resolved once at wrap time so
    the per-call cost is attribute reads on this object."""

    __slots__ = (
        "name", "calls", "device_ns", "weighted_ns", "hist", "sampled",
    )

    def __init__(self, name: str):
        self.name = name
        self.calls = 0  # plain int bump (GIL-atomic enough for a stride)
        self.device_ns = 0  # accumulated submit→ready ns (sampled calls)
        # stride-weighted accumulator for share-of-wall: each sample
        # adds dt × (the stride IN EFFECT when it was taken), so the
        # estimate stays right across set_sample() flips (the bench A/B
        # restores the env stride before reading the attribution)
        self.weighted_ns = 0
        self.hist: LatencyHistogram = histogram(
            "pathway_profile_device_seconds", callable=name
        )
        self.sampled = counter("pathway_profile_samples_total", callable=name)


def _site(name: str) -> _Site:
    with _sites_lock:
        st = _sites.get(name)
        if st is None:
            st = _sites[name] = _Site(name)
        return st


def _first_leaf(out: Any) -> Any:
    """First array-like leaf of a jitted call's output (the object the
    completer blocks on — one output of a dispatch is ready iff the
    whole dispatch is)."""
    seen = 0
    stack = [out]
    while stack and seen < 16:
        x = stack.pop()
        seen += 1
        if hasattr(x, "block_until_ready"):
            return x
        if isinstance(x, (tuple, list)):
            stack.extend(reversed(x))
        elif isinstance(x, dict):
            stack.extend(reversed(list(x.values())))
    return None


def _completer_loop() -> None:  # pragma: no cover - exercised via wrap()
    global _inflight
    while True:
        with _pending_cv:
            while not _pending:
                _pending_cv.wait()
            st, t0_ns, leaf, stride = _pending.pop(0)
            _inflight += 1
        try:
            try:
                leaf.block_until_ready()
            except Exception:
                # deleted/donated buffer, backend teardown: the sample
                # is unrecoverable — drop it, never surface the error
                _C_DROPPED.inc()
                continue
            dt = time.perf_counter_ns() - t0_ns
            st.hist.observe_ns(dt)
            st.device_ns += dt
            st.weighted_ns += dt * max(1, stride)
            st.sampled.inc()
        finally:
            with _pending_cv:
                _inflight -= 1
                _pending_cv.notify_all()


def _enqueue(st: _Site, t0_ns: int, out: Any, stride: int) -> None:
    """Queue one sampled call for completion; every failure mode drops
    the sample (counted) and returns — the caller's serve result is
    already in hand and is never touched."""
    global _completer
    try:
        if not _sample_allowed():
            _C_DROPPED.inc()
            return
        leaf = _first_leaf(out)
        if leaf is None:
            _C_DROPPED.inc()
            return
        with _pending_cv:
            if len(_pending) >= _PENDING_CAP:
                _C_DROPPED.inc()
                return
            if _completer is None or not _completer.is_alive():
                _completer = threading.Thread(
                    target=_completer_loop, daemon=True, name="pw-profile"
                )
                _completer.start()
            _pending.append((st, t0_ns, leaf, stride))
            _pending_cv.notify()
    except Exception:
        try:
            _C_DROPPED.inc()
        except Exception:  # pragma: no cover - interpreter teardown
            pass


def wrap(site: str, fn: Callable) -> Callable:
    """Instrument one compiled callable for device-time attribution.

    Called at compiled-fn-cache creation time (the ``_fns[key] =
    profile.wrap(site, fused)`` idiom), so steady-state calls pay only
    the sampling check.  The wrapper is transparent — same args, same
    (async) result — and the analyzer registry treats an assignment from
    ``profile.wrap(site, jitted)`` as binding a jitted callable, so the
    lock-discipline/hidden-sync rules see straight through it."""
    st = _site(site)

    def profiled(*args: Any, **kwargs: Any):
        # one read of the module global: a concurrent set_sample(0)
        # between a two-read guard and modulo would divide by zero INTO
        # the serve path
        stride = _stride
        if not _state.enabled or stride == 0:
            return fn(*args, **kwargs)
        st.calls += 1
        if st.calls % stride:
            return fn(*args, **kwargs)
        t0 = time.perf_counter_ns()
        out = fn(*args, **kwargs)
        _enqueue(st, t0, out, stride)
        return out

    profiled.__wrapped__ = fn
    profiled.profile_site = site
    return profiled


def set_sample(fraction: float) -> None:
    """Sampled fraction of calls (also ``PATHWAY_PROFILE_SAMPLE``):
    1.0 = every call, 0.0 = profiler off (the bench A/B switch)."""
    global _stride
    _stride = _stride_of(min(1.0, max(0.0, float(fraction))))


def sample_stride() -> int:
    """Current 1-in-N sampling stride (0 = off) — tests/bench probe."""
    return _stride


def drain(timeout_s: float = 2.0) -> bool:
    """Block until every enqueued sample has been RECORDED — queue empty
    AND nothing popped-but-unfinished in the completer (tests/bench:
    make every sample visible before reading stats)."""
    deadline = time.monotonic() + timeout_s
    with _pending_cv:
        while _pending or _inflight:
            left = deadline - time.monotonic()
            if left <= 0:
                return False
            _pending_cv.wait(timeout=min(left, 0.05))
    return True


def profile_stats() -> Dict[str, Dict[str, float]]:
    """Per-callable attribution snapshot — the ``/serve_stats``
    ``profile`` column: sampled calls, attributed device seconds, and
    the share-of-wall estimate (sampling-fraction corrected)."""
    wall_s = max((time.perf_counter_ns() - _wall_t0_ns) * 1e-9, 1e-9)
    with _sites_lock:
        sites = list(_sites.values())
    out: Dict[str, Dict[str, float]] = {}
    for st in sites:
        dev_s = st.device_ns * 1e-9
        out[st.name] = {
            "calls": st.calls,
            "samples": st.hist.count,
            "device_s": dev_s,
            # weighted_ns already carries each sample's own stride, so
            # the estimate survives set_sample() flips mid-window
            "share_of_wall": min(1.0, st.weighted_ns * 1e-9 / wall_s),
            "p50_s": st.hist.quantile_s(0.50) or 0.0,
            "p99_s": st.hist.quantile_s(0.99) or 0.0,
        }
    return out


class _Provider:
    """Scrape-time gauges (flight-recorder provider): the histograms
    and counters render through the registry already; the provider adds
    the derived share-of-wall gauges."""

    def observe_metrics(self):
        for name, row in profile_stats().items():
            labels = {"callable": name}
            yield (
                "gauge",
                "pathway_profile_device_share",
                labels,
                row["share_of_wall"],
            )
            yield (
                "gauge",
                "pathway_profile_calls",
                labels,
                row["calls"],
            )


_provider = _Provider()  # module-global: stays alive for the weak registry


def _register_provider() -> None:
    from .recorder import register_provider

    register_provider(_provider)


_register_provider()


def reset() -> None:
    """Zero the attribution window: per-site accumulators and the wall
    anchor (the registered histogram/counter series stay attached —
    recorder.reset() zeroes those)."""
    global _wall_t0_ns
    with _sites_lock:
        for st in _sites.values():
            st.device_ns = 0
            st.weighted_ns = 0
            st.calls = 0
    _wall_t0_ns = time.perf_counter_ns()
