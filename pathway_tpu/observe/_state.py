"""The recorder's global enable flag, as a plain module attribute.

Lives in its own leaf module so BOTH ``histogram.py`` (imported by
``recorder.py``) and ``recorder.py`` read it without a circular import —
and, critically, without per-call import machinery: the hot-path check
is one module-attribute read (``_state.enabled``), which is the "bool
check" the package docstring promises for the disabled path.
"""

from __future__ import annotations

from .. import config

enabled = config.get("observe.enabled")
