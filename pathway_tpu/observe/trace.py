"""End-to-end serve tracing: per-request span trees with tail-based
sampling and histogram exemplars.

The flight recorder's histograms answer "what is the fleet doing"; this
module answers "why was *this* request slow".  A single serve crosses
the cache tiers, the coalescing scheduler, an N-shard scatter-dispatch,
and a multi-stage rerank cascade — its latency is smeared across shared
batches that aggregate histograms cannot decompose.  The fix is the
Dapper one (PAPERS.md): per-request trace trees with aggregate↔trace
linkage.

Model
-----

- A ``TraceContext`` is created at ``ServeScheduler.submit`` admission
  (trace id, root span, deadline, head-sampling bit) and carried on the
  request; the scheduler activates it (``use``) around the hops that run
  on other threads, so every instrumentation site reaches it with one
  ``trace.current()`` call.
- Requests that share a coalesced batch each carry a **link span**: the
  batch's work (stage-1 dispatch, per-shard fan-out, merge, cascade
  stages, model round trips) records into ONE batch trace, and each
  rider's tree holds a ``batch`` span with the queue wait and the batch
  trace id — ``/traces`` inlines the linked batch tree so a rider's view
  shows who it rode with and where the shared time went.
- Spans carry EXPLICIT timestamps (``add_span(name, t0_ns, t1_ns)``) —
  the serve path already measures its stages for the histograms, so
  tracing adds no second clock read, and no span context manager is
  ever held across a lock (the analyzer's span-across-lock rule).

Tail-based sampling
-------------------

Spans buffer per-trace; the keep/drop decision happens at ``finish``,
when the outcome is known (the whole point of tail sampling).  Kept:

- **degraded** — any ladder rung recorded (``robust.record_degraded``
  stamps the active trace);
- **deadline** — the request's deadline expired;
- **slow** — the root duration reaches the top-percentile bucket of the
  ``pathway_serve_request_seconds`` histogram
  (``PATHWAY_TRACE_SLOW_PCT``, default 0.99, once ≥ 64 observations);
- **linked** — a batch trace referenced by a kept rider is promoted
  from the bounded pending ring so the rider's tree always resolves.

Kept traces land in a bounded LRU store (``PATHWAY_TRACE_KEEP``,
default 256) served as JSON span trees on ``GET /traces``; everything
else is dropped after a bounded stay in the pending ring.  On keep, the
trace id is stamped as an **exemplar** onto the histogram bucket each
span's duration landed in, so a p99 bucket on ``/metrics`` links
directly to a kept trace.

Cost discipline
---------------

``PATHWAY_OBSERVE=0`` / ``set_enabled(False)`` (or a zero
``PATHWAY_TRACE_SAMPLE``) makes ``start_trace`` return ``None`` after a
single flag check with zero allocations; every instrumentation site is
``t = trace.current()`` / ``if t is None: return`` — one context-var
read.  The ``tracing_overhead`` bench phase prices the enabled path
(< 3% p50 at concurrency 16, 2+2 budget intact).

Chaos: the ``trace.record`` / ``trace.export`` sites (robust/inject.py)
prove that a faulted tracing path degrades to DROPPED spans (counted on
``pathway_trace_spans_dropped_total``), never a failed or slowed serve.
"""

from __future__ import annotations

import itertools
import random
import threading
import time
from collections import OrderedDict
from contextvars import ContextVar
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import config
from . import _state
from .recorder import counter, histogram, register_provider

__all__ = [
    "TraceContext",
    "current",
    "finish",
    "get_trace",
    "reset",
    "ring_stats",
    "sample_rate",
    "set_sample",
    "snapshot_traces",
    "start_trace",
    "stats",
    "use",
]


_KEEP_CAPACITY = config.get("observe.trace_keep")
_PENDING_CAPACITY = config.get("observe.trace_pending")
_MAX_SPANS = config.get("observe.trace_max_spans")
_SLOW_PCT = config.get("observe.trace_slow_pct")
_SLOW_MIN_COUNT = 64
_sample = config.get("observe.trace_sample")

# the request-level end-to-end latency histogram: observed at rider
# finish, it is BOTH the tail sampler's "slow" threshold source and the
# flagship exemplar family (a p99 bucket links to a kept trace id)
_H_REQUEST = histogram("pathway_serve_request_seconds")
# the ingest plane's arrival→retrievable histogram (observed by
# serve/ingest.py per document): its quantile is the slow threshold for
# kind="ingest" traces — a slow document keeps its trace exactly like a
# slow serve does
_H_INGEST = histogram("pathway_freshness_seconds")

# per-kind slow-rule source: the histogram whose tail quantile defines
# "slow" for traces of that kind
_SLOW_HISTS = {"request": _H_REQUEST, "ingest": _H_INGEST}

_C_SPANS_DROPPED = counter("pathway_trace_spans_dropped_total")
_C_SAMPLED_OUT = counter("pathway_trace_sampled_out_total")
_C_EXPORT_FAILURES = counter("pathway_trace_export_failures_total")
_kept_counters: Dict[str, Any] = {}


def _kept_counter(reason: str):
    c = _kept_counters.get(reason)
    if c is None:
        c = _kept_counters[reason] = counter(
            "pathway_trace_kept_total", reason=reason
        )
    return c


# deterministic-enough ids: a per-process nonce plus a monotone counter
# (uuid4 per trace would be an allocation-heavy syscall on admission)
_NONCE = f"{random.SystemRandom().getrandbits(32):08x}"
_ids = itertools.count(1)
_rng = random.Random(0x7A3CE)  # head-sampling draws (seeded: replayable)

_CURRENT: "ContextVar[Optional[TraceContext]]" = ContextVar(
    "pathway_trace_ctx", default=None
)

_store_lock = threading.Lock()
_kept: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
_pending: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
_kept_evicted = 0
_pending_evicted = 0
_started = 0

# lazy robust imports: robust/ imports the observe package, so a
# module-level import here would be circular.  Resolved once, cached.
_inject_mod = None


def _inject():
    global _inject_mod
    if _inject_mod is None:
        try:
            from ..robust import inject as mod
        except Exception:  # pragma: no cover - partial interpreter teardown
            return None
        _inject_mod = mod
    return _inject_mod


def _spent_deadline():
    """An already-expired Deadline: an armed ``hang`` at a tracing chaos
    site must release IMMEDIATELY (the tracing path may never stall a
    serve), and an armed ``delay`` is capped to ~10 ms by fire()'s
    remaining-budget clamp."""
    from ..robust.deadline import Deadline

    return Deadline.after_ms(0.0)


def _record_allowed(site: str) -> bool:
    """Chaos gate for the tracing path: True = record normally.  ANY
    armed fault at ``site`` — raise, delay, hang — means the affected
    span/export is dropped (and counted); the serve itself proceeds."""
    inj = _inject()
    if inj is None or not inj.any_armed():
        return True
    try:
        before = inj.fired_count(site)
        inj.fire(site, deadline=_spent_deadline())
        return inj.fired_count(site) == before
    except Exception:
        return False


class TraceContext:
    """One trace: the root span plus a bounded per-trace span buffer.

    Span tuples are ``(span_id, parent_id, name, t0_ns, dur_ns, status,
    attrs|None, exemplar_hist|None)`` — root is span id 1.  All methods
    are thread-safe; span recording is list-append under the context's
    own lock (never held across anything blocking)."""

    __slots__ = (
        "trace_id", "name", "kind", "t0_ns", "deadline", "spans",
        "statuses", "links", "attrs", "dispatches", "fetches",
        "physical_dispatches", "dropped", "finished", "force_keep",
        "_lock", "_next_sid",
    )

    def __init__(self, name: str, kind: str, deadline=None):
        self.trace_id = f"{_NONCE}{next(_ids):08x}"
        self.name = str(name)
        self.kind = str(kind)
        self.t0_ns = time.perf_counter_ns()
        self.deadline = deadline
        self.spans: List[tuple] = []
        self.statuses: List[str] = []
        self.links: List[str] = []
        self.attrs: Dict[str, Any] = {}
        self.dispatches = 0
        self.fetches = 0
        self.physical_dispatches = 0
        self.dropped = 0
        self.finished = False
        self.force_keep = False
        self._lock = threading.Lock()
        self._next_sid = 2

    # -- span recording -----------------------------------------------------
    def add_span(
        self,
        name: str,
        t0_ns: int,
        t1_ns: int,
        status: str = "ok",
        parent: int = 1,
        exemplar=None,
        **attrs: Any,
    ) -> int:
        """Record one finished span with explicit timestamps (the serve
        path measures its stages anyway — tracing reuses those clock
        reads).  ``exemplar`` is the LatencyHistogram this duration was
        also observed into: if the trace is KEPT, the trace id is
        stamped onto that histogram's matching bucket.  Returns the span
        id (0 = dropped: trace full, finished, or chaos-faulted)."""
        if not _record_allowed("trace.record"):
            with self._lock:
                self.dropped += 1
            _C_SPANS_DROPPED.inc()
            return 0
        with self._lock:
            if self.finished or len(self.spans) >= _MAX_SPANS:
                self.dropped += 1
                _C_SPANS_DROPPED.inc()
                return 0
            sid = self._next_sid
            self._next_sid += 1
            self.spans.append((
                sid, int(parent), str(name), int(t0_ns),
                max(0, int(t1_ns) - int(t0_ns)), str(status),
                attrs or None, exemplar,
            ))
        return sid

    def add_event(self, name: str, status: str = "ok", **attrs: Any) -> int:
        """A zero-duration annotation span (cache hit/miss, shard skip,
        rung outcome) stamped at the current instant."""
        t = time.perf_counter_ns()
        return self.add_span(name, t, t, status=status, **attrs)

    # -- trace-level annotations --------------------------------------------
    def annotate(self, **attrs: Any) -> None:
        with self._lock:
            self.attrs.update(attrs)

    def set_status(self, reason: str) -> None:
        """Record one degradation-ladder rung on this trace (drives the
        tail sampler's "degraded" keep rule).  Deduped."""
        reason = str(reason)
        with self._lock:
            if reason not in self.statuses:
                self.statuses.append(reason)

    def add_link(self, trace_id: str) -> None:
        with self._lock:
            if trace_id not in self.links:
                self.links.append(trace_id)

    # -- dispatch/fetch stamping (ops/dispatch_counter.py) ------------------
    def note_dispatch(self, tag: str, shards: int = 1) -> None:
        # plain int bumps (GIL-atomic enough for stamped diagnostics)
        self.dispatches += 1
        self.physical_dispatches += max(1, int(shards))

    def note_fetch(self, tag: str, shards: int = 1) -> None:
        self.fetches += 1


class _Activation:
    """Context manager installing a TraceContext as the thread's current
    trace — how a trace follows its request across the scheduler thread
    (dispatch) and the waiter thread (fetch/demux)."""

    __slots__ = ("_ctx", "_token")

    def __init__(self, ctx: Optional[TraceContext]):
        self._ctx = ctx

    def __enter__(self) -> Optional[TraceContext]:
        self._token = _CURRENT.set(self._ctx)
        return self._ctx

    def __exit__(self, *exc) -> None:
        _CURRENT.reset(self._token)


def use(ctx: Optional[TraceContext]) -> _Activation:
    return _Activation(ctx)


def current() -> Optional[TraceContext]:
    """The thread's active TraceContext, or None.  THE instrumentation
    entry: every serve-path site does ``t = trace.current()`` and
    returns on None — one context-var read, zero allocations, whether
    tracing is disabled, sampled out, or simply not on this path."""
    return _CURRENT.get()


def start_trace(
    name: str,
    deadline=None,
    kind: str = "request",
    sample: bool = True,
) -> Optional[TraceContext]:
    """Create a trace — or None when the recorder is disabled (single
    flag check, no allocation) or head-sampling passes on this request.
    ``sample=False`` skips the head-sampling draw (batch traces: their
    riders already drew — a batch exists iff a traced rider does)."""
    if not _state.enabled:
        return None
    if sample:
        s = _sample
        if s <= 0.0:
            return None
        if s < 1.0 and _rng.random() >= s:
            return None
    global _started
    _started += 1
    return TraceContext(name, kind, deadline)


def set_sample(p: float) -> None:
    """Head-sampling probability (also ``PATHWAY_TRACE_SAMPLE``): 1.0
    traces every request, 0.0 none (the bench A/B switch).  Tail
    sampling then decides which TRACED requests are kept."""
    global _sample
    _sample = min(1.0, max(0.0, float(p)))


def sample_rate() -> float:
    return _sample


# -- tail sampling -----------------------------------------------------------
def _keep_reason(ctx: TraceContext, dur_ns: int) -> Optional[str]:
    if ctx.force_keep:
        return "forced"
    if ctx.statuses:
        return "degraded"
    d = ctx.deadline
    if d is not None:
        try:
            if d.expired():
                return "deadline"
        except Exception:
            pass
    h = _SLOW_HISTS.get(ctx.kind)
    if h is not None and h.count >= _SLOW_MIN_COUNT:
        q = h.quantile_s(_SLOW_PCT)
        if q is not None and dur_ns * 1e-9 >= q:
            return "slow"
    return None


def _keep(record: Dict[str, Any], reason: str) -> None:
    global _kept_evicted
    record["keep_reason"] = reason
    tid = record["trace_id"]
    # aggregate↔trace linkage: stamp this trace id onto the histogram
    # bucket each exemplar-carrying span landed in — ONLY for kept
    # traces, so every exemplar on /metrics resolves on /traces
    for span in record["_spans"]:
        ex = span[7]
        if ex is not None:
            try:
                ex.set_exemplar(span[4], tid)
            except Exception:  # pragma: no cover - defensive
                pass
    if record["kind"] == "request":
        _H_REQUEST.set_exemplar(record["_dur_ns"], tid)
    with _store_lock:
        _pending.pop(tid, None)
        _kept[tid] = record
        while len(_kept) > _KEEP_CAPACITY:
            _kept.popitem(last=False)
            _kept_evicted += 1
    _kept_counter(reason).inc()


def finish(
    ctx: Optional[TraceContext],
    statuses: Sequence[str] = (),
    force_keep: bool = False,
) -> Optional[str]:
    """End a trace's root span and run the tail sampler.  Idempotent.
    Returns the keep reason, or None when the trace was sampled out
    (parked in the bounded pending ring for possible link promotion)."""
    global _pending_evicted
    if ctx is None:
        return None
    for s in statuses:
        ctx.set_status(s)
    if force_keep:
        ctx.force_keep = True
    with ctx._lock:
        if ctx.finished:
            return None
        ctx.finished = True
        spans = list(ctx.spans)
        links = list(ctx.links)
    dur_ns = time.perf_counter_ns() - ctx.t0_ns
    if ctx.kind == "request":
        _H_REQUEST.observe_ns(dur_ns)
    record: Dict[str, Any] = {
        "trace_id": ctx.trace_id,
        "name": ctx.name,
        "kind": ctx.kind,
        "ts": time.time(),
        "duration_ms": dur_ns * 1e-6,
        "statuses": list(ctx.statuses),
        "dispatches": ctx.dispatches,
        "physical_dispatches": ctx.physical_dispatches,
        "fetches": ctx.fetches,
        "spans_dropped": ctx.dropped,
        "attrs": dict(ctx.attrs),
        "links": links,
        "keep_reason": None,
        "_t0_ns": ctx.t0_ns,
        "_dur_ns": dur_ns,
        "_spans": spans,
    }
    reason = _keep_reason(ctx, dur_ns)
    if reason is None:
        with _store_lock:
            _pending[ctx.trace_id] = record
            while len(_pending) > _PENDING_CAPACITY:
                _pending.popitem(last=False)
                _pending_evicted += 1
        _C_SAMPLED_OUT.inc()
        return None
    _keep(record, reason)
    # link promotion: a kept rider must be able to resolve its batch —
    # pull the linked traces out of the pending ring into the kept store
    for lid in links:
        with _store_lock:
            linked = _pending.pop(lid, None)
        if linked is not None:
            _keep(linked, "linked")
    return reason


# -- export ------------------------------------------------------------------
def _span_dict(record: Dict[str, Any], span: tuple) -> Dict[str, Any]:
    sid, parent, name, t0, dur, status, attrs, _ex = span
    d: Dict[str, Any] = {
        "span_id": sid,
        "parent_id": parent,
        "name": name,
        "start_ms": (t0 - record["_t0_ns"]) * 1e-6,
        "duration_ms": dur * 1e-6,
        "status": status,
    }
    if attrs:
        d["attrs"] = dict(attrs)
    return d


def _tree(
    record: Dict[str, Any],
    index: Dict[str, Dict[str, Any]],
    inline: bool = True,
) -> Dict[str, Any]:
    """One kept trace as a JSON span tree.  Link spans carrying a
    ``linked_trace`` attr inline the linked (batch) trace's tree when it
    is also kept — a rider's view shows the shared batch work in place.
    Inlining is one level deep (batch traces do not link further)."""
    root: Dict[str, Any] = {
        "span_id": 1,
        "parent_id": 0,
        "name": record["name"],
        "start_ms": 0.0,
        "duration_ms": record["duration_ms"],
        "status": "degraded" if record["statuses"] else "ok",
        "children": [],
    }
    nodes: Dict[int, Dict[str, Any]] = {1: root}
    for span in sorted(record["_spans"], key=lambda s: (s[3], s[0])):
        d = _span_dict(record, span)
        d["children"] = []
        attrs = span[6] or {}
        linked_id = attrs.get("linked_trace")
        if linked_id is not None and inline:
            target = index.get(linked_id)
            if target is not None:
                d["linked"] = _tree(target, index, inline=False)
        nodes[span[0]] = d
        nodes.get(span[1], root)["children"].append(d)
    out = {k: v for k, v in record.items() if not k.startswith("_")}
    out["root"] = root
    return out


def snapshot_traces(limit: Optional[int] = None) -> Dict[str, Any]:
    """The ``GET /traces`` payload: kept traces (newest first) as span
    trees, plus the sampler/ring counters.  A faulted export
    (``trace.export`` chaos site) degrades to an empty, flagged payload
    — the endpoint never 500s."""
    base: Dict[str, Any] = {
        "enabled": _state.enabled,
        "sample": _sample,
        "capacity": _KEEP_CAPACITY,
        "started_total": _started,
        "sampled_out_total": _C_SAMPLED_OUT.value,
        "spans_dropped_total": _C_SPANS_DROPPED.value,
    }
    if not _record_allowed("trace.export"):
        _C_EXPORT_FAILURES.inc()
        base["traces"] = []
        base["export_failed"] = True
        return base
    with _store_lock:
        records = list(_kept.values())
        index = {r["trace_id"]: r for r in records}
    if limit is not None and limit > 0:
        records = records[-int(limit):]
    base["traces"] = [_tree(r, index) for r in reversed(records)]
    base["export_failed"] = False
    return base


def get_trace(trace_id: str) -> Optional[Dict[str, Any]]:
    """One kept trace's span tree by id (how an exemplar on /metrics
    resolves), or None."""
    with _store_lock:
        record = _kept.get(trace_id)
        index = {r["trace_id"]: r for r in _kept.values()}
    if record is None:
        return None
    return _tree(record, index)


# -- introspection / lifecycle ----------------------------------------------
def stats() -> Dict[str, int]:
    with _store_lock:
        kept = len(_kept)
        pending = len(_pending)
    return {
        "started": _started,
        "kept": kept,
        "pending": pending,
        "kept_evicted": _kept_evicted,
        "pending_evicted": _pending_evicted,
        "spans_dropped": _C_SPANS_DROPPED.value,
        "sampled_out": _C_SAMPLED_OUT.value,
    }


def ring_stats() -> List[Tuple[str, int, int]]:
    """(ring name, capacity, dropped/evicted) rows for the recorder's
    bounded-ring health rendering (pathway_observe_events_dropped_total
    / pathway_observe_ring_capacity)."""
    return [
        ("trace_kept", _KEEP_CAPACITY, _kept_evicted),
        ("trace_pending", _PENDING_CAPACITY, _pending_evicted),
    ]


def reset() -> None:
    """Drop every kept/pending trace (tests, bench phase boundaries).
    Counters are zeroed by ``observe.reset`` like every other series."""
    global _kept_evicted, _pending_evicted, _started
    with _store_lock:
        _kept.clear()
        _pending.clear()
        _kept_evicted = 0
        _pending_evicted = 0
    _started = 0


class _TraceProvider:
    """Scrape-time gauges for the trace stores (zero hot-path cost).
    Family name deliberately disjoint from the ``pathway_trace_kept_total``
    counter family: an OpenMetrics counter family ``x`` reserves the
    ``x_total`` sample name, so a gauge family ``x`` would clash and
    fail a strict scrape."""

    def observe_metrics(self):
        with _store_lock:
            kept = len(_kept)
            pending = len(_pending)
        yield ("gauge", "pathway_trace_store_entries", {"store": "kept"}, kept)
        yield (
            "gauge", "pathway_trace_store_entries", {"store": "pending"},
            pending,
        )


_provider = _TraceProvider()
register_provider(_provider)
