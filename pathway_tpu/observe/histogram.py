"""Fixed-slot latency histogram + bounded event ring — the flight
recorder's storage primitives.

Both are built for the serving hot path: recording is a few integer ops
under a lock held only for the increment itself (never across a timing
section, a dispatch, or any other blocking call — the PR 2 lock-discipline
rules apply to this package too), and neither allocates per request.  The
histogram pre-allocates its count slots once; the ring pre-allocates its
slot list and overwrites in place.

Buckets are powers of two over nanoseconds: bucket ``i`` holds durations
in ``(2^(SHIFT+i-1), 2^(SHIFT+i)]`` ns with ``SHIFT = 10`` — the first
bucket tops out at ~1 µs and the second-to-last at ~2^40 ns ≈ 18 min; the
final bucket is the +Inf overflow.  Power-of-two bounds make the bucket
index one ``bit_length`` call (no search, no float math) and give uniform
relative resolution (every bucket is 2x the last), which is what latency
distributions need: the same histogram covers a 40 ns counter read and a
70 ms tunnel round trip without configuration.
"""

from __future__ import annotations

import threading
import time
from typing import Iterable, List, Optional, Tuple

from . import _state

__all__ = ["LatencyHistogram", "EventRing", "N_BUCKETS", "bucket_bounds_s"]

N_BUCKETS = 32
_SHIFT = 10  # first bucket upper bound: 2^10 ns = 1.024 us


def _bucket_index(ns: int) -> int:
    """Bucket for a duration in ns: smallest ``i`` with ns <= 2^(SHIFT+i),
    clamped into [0, N_BUCKETS-1] (the last bucket is +Inf)."""
    if ns <= 0:
        return 0
    i = (int(ns) - 1).bit_length() - _SHIFT
    if i < 0:
        return 0
    if i >= N_BUCKETS - 1:
        return N_BUCKETS - 1
    return i


def bucket_bounds_s() -> List[float]:
    """Upper bounds of the finite buckets, in seconds (the Prometheus
    ``le`` values; the +Inf bucket is implicit)."""
    return [(1 << (_SHIFT + i)) * 1e-9 for i in range(N_BUCKETS - 1)]


class LatencyHistogram:
    """Fixed-slot power-of-two-bucket histogram over durations in ns.

    ``observe_ns`` is the hot-path entry: one bucket-index computation and
    three integer increments under the instance lock.  ``snapshot``
    returns a consistent (counts, sum, count) view for rendering —
    cumulative bucket series are computed by the RENDERER from one
    snapshot, so scraped ``_bucket`` values are monotone by construction
    even while concurrent observes land.
    """

    __slots__ = ("_counts", "_sum_ns", "_n", "_lock", "_exemplars")

    def __init__(self) -> None:
        self._counts = [0] * N_BUCKETS
        self._sum_ns = 0
        self._n = 0
        self._lock = threading.Lock()
        # per-bucket exemplar slots (trace_id, value_s, unix_ts) — lazily
        # allocated on the first stamp, so histograms that never carry
        # exemplars (the overwhelming majority) pay one None field
        self._exemplars: Optional[List[Optional[Tuple[str, float, float]]]] = None

    def observe_ns(self, ns: int) -> None:
        if not _state.enabled:
            return
        i = _bucket_index(ns)
        with self._lock:
            self._counts[i] += 1
            self._sum_ns += int(ns)
            self._n += 1

    def observe_s(self, seconds: float) -> None:
        self.observe_ns(int(seconds * 1e9))

    def snapshot(self) -> Tuple[Tuple[int, ...], int, int]:
        """(per-bucket counts, sum_ns, count) — one consistent view."""
        with self._lock:
            return tuple(self._counts), self._sum_ns, self._n

    def set_exemplar(self, ns: int, trace_id: str) -> None:
        """Stamp ``trace_id`` as the exemplar of the bucket a duration of
        ``ns`` lands in (newest-wins).  Called ONLY for traces the tail
        sampler kept, so every exemplar on /metrics resolves on /traces
        — the Dapper-style aggregate↔trace linkage."""
        i = _bucket_index(int(ns))
        with self._lock:
            if self._exemplars is None:
                self._exemplars = [None] * N_BUCKETS
            self._exemplars[i] = (str(trace_id), int(ns) * 1e-9, time.time())

    def exemplars(self) -> Optional[List[Optional[Tuple[str, float, float]]]]:
        """Per-bucket exemplar snapshot (index-aligned with the counts),
        or None when this histogram never carried one."""
        with self._lock:
            if self._exemplars is None:
                return None
            return list(self._exemplars)

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * N_BUCKETS
            self._sum_ns = 0
            self._n = 0
            self._exemplars = None

    def merge_from(self, other: "LatencyHistogram") -> None:
        """Element-wise accumulate ``other`` into this histogram (shard
        aggregation: per-thread or per-process histograms sum exactly —
        identical buckets make the merge a vector add)."""
        counts, sum_ns, n = other.snapshot()
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += c
            self._sum_ns += sum_ns
            self._n += n

    @property
    def count(self) -> int:
        return self._n

    @property
    def sum_seconds(self) -> float:
        return self._sum_ns * 1e-9

    def quantile_s(self, q: float) -> Optional[float]:
        """Upper-bound estimate of the ``q`` quantile in seconds (the
        bucket boundary where the cumulative count crosses ``q * n``);
        None when empty.  The overflow bucket reports the largest finite
        bound — an explicit floor, not a fabricated value."""
        counts, _sum_ns, n = self.snapshot()
        if n == 0:
            return None
        bounds = bucket_bounds_s()
        target = q * n
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= target:
                return bounds[min(i, N_BUCKETS - 2)]
        return bounds[-1]


class EventRing:
    """Bounded ring of per-request events: ``capacity`` pre-allocated
    slots overwritten in place (no per-request allocation beyond the
    event tuple itself), newest-wins.  ``snapshot`` returns the retained
    events oldest -> newest plus the total-appended counter, so a reader
    can tell how many were overwritten."""

    __slots__ = ("_slots", "_n", "_lock", "capacity")

    def __init__(self, capacity: int = 256) -> None:
        self.capacity = int(capacity)
        self._slots: List[Optional[tuple]] = [None] * self.capacity
        self._n = 0
        self._lock = threading.Lock()

    def append(self, event: tuple) -> None:
        if not _state.enabled:
            return
        with self._lock:
            self._slots[self._n % self.capacity] = event
            self._n += 1

    def snapshot(self) -> Tuple[List[tuple], int]:
        with self._lock:
            n = self._n
            if n <= self.capacity:
                events = [e for e in self._slots[:n]]
            else:
                head = n % self.capacity
                events = [
                    e
                    for e in self._slots[head:] + self._slots[:head]
                    if e is not None
                ]
            return events, n

    @property
    def dropped(self) -> int:
        """How many appended events have been overwritten (the ring's
        drop count, rendered on pathway_observe_events_dropped_total)."""
        with self._lock:
            return max(0, self._n - self.capacity)

    def reset(self) -> None:
        with self._lock:
            self._slots = [None] * self.capacity
            self._n = 0

    def __len__(self) -> int:
        with self._lock:
            return min(self._n, self.capacity)
