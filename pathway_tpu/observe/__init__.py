"""Serve-path flight recorder — always-on, low-overhead observability
for the ML hot path.

PRs 1–2 made the fused retrieve→rerank serve fast (2 dispatches + 2
fetches) and statically safe; this package makes it *visible*: where a
serve call spends time (tokenize/pack on host, stage-1 dispatch→fetch
RTT, stage-2 rescore RTT, post-process), how full the packed batches
are, what the IVF index / recompile tripwires / exchange plane are doing
— without re-running ``bench.py``.  Multi-stage ranking systems live or
die by per-stage accounting (PAPERS.md: "An Exploration of Approaches to
Integrating Neural Reranking Models in Multi-Stage Ranking
Architectures"; "Accelerating Retrieval-Augmented Generation" names the
retrieval-vs-inference stage breakdown as the prerequisite for every
serving optimization).

Design constraints, in order:

1. **Nearly free.**  Fixed-slot power-of-two-bucket histograms (one
   ``bit_length`` + three increments per event), pre-resolved series
   objects on the hot sites, a bounded pre-allocated event ring, and
   scrape-time *providers* for anything derivable from live state.  The
   ``observe_overhead`` bench phase prices the recorder on-vs-off; the
   budget is < 3% added serve latency.
2. **Analyzer-clean.**  The recorder itself passes the PR 2
   lock-discipline / hidden-sync / recompile-hazard rules: locks are
   held only for integer updates, instrumentation points sit outside
   dispatch scopes, and nothing here touches jax at all.
3. **One surface.**  Everything renders on the existing scrape endpoint
   (``internals/metrics.py``): ``pathway_serve_*`` stage histograms,
   ``pathway_ivf_*`` index gauges, ``pathway_recompile_*`` census,
   ``pathway_exchange_*`` plane counters — plus a ``/serve_stats`` JSON
   view and OTLP spans via ``internals/telemetry.py`` when an endpoint
   is configured.

``PATHWAY_OBSERVE=0`` (or ``set_enabled(False)``) reduces every record
call to a bool check.

``trace`` (observe/trace.py) is the per-request layer on top: Dapper-
style span trees across the coalescing scheduler, shards, cascade
stages and cache tiers, tail-sampled into a bounded kept store served
on ``GET /traces``, with kept-trace exemplars stamped onto the
histogram buckets above.

``profile`` / ``hbm`` / ``slo`` (round 15) are the attribution layer:
sampled submit→ready device time per compiled callable
(``pathway_profile_*``), a pull-based HBM ledger cross-checked against
the backend's own byte accounting (``pathway_hbm_*``), and declarative
SLOs evaluated with multi-window burn-rate math (``pathway_slo_*`` +
``GET /slo`` + the scheduler's advisory ``should_shed`` probe).
"""

from .histogram import EventRing, LatencyHistogram, N_BUCKETS, bucket_bounds_s
from . import trace
from . import profile
from . import hbm
from . import slo
from .recorder import (
    Counter,
    Gauge,
    count,
    counter,
    emit_span,
    enabled,
    gauge,
    histogram,
    next_id,
    record_event,
    record_occupancy,
    register_provider,
    render_prometheus,
    reset,
    set_enabled,
    snapshot,
)

__all__ = [
    "Counter",
    "EventRing",
    "Gauge",
    "LatencyHistogram",
    "N_BUCKETS",
    "bucket_bounds_s",
    "count",
    "counter",
    "emit_span",
    "enabled",
    "gauge",
    "hbm",
    "histogram",
    "next_id",
    "profile",
    "record_event",
    "record_occupancy",
    "register_provider",
    "render_prometheus",
    "reset",
    "set_enabled",
    "slo",
    "snapshot",
    "trace",
]
