"""Process-wide flight recorder: the registry behind every
``pathway_serve_*`` / ``pathway_ivf_*`` / ``pathway_recompile_*`` /
``pathway_exchange_*`` series on the scrape endpoint.

Three ways data gets here, by cost profile:

- **histograms / counters** (hot path): instrumentation sites resolve
  their series object ONCE (module/instance scope) and call
  ``observe_ns`` / ``inc`` per event — a dict-free few-integer-ops
  update.  ``count(...)`` is the dynamic-label convenience for cold-ish
  sites (one dict lookup per call);
- **providers** (zero hot-path cost): long-lived objects (an IVF index,
  an exchange plane, a recompile tripwire) register themselves weakly
  and are asked for their current gauge/counter samples AT SCRAPE TIME
  only — live state costs nothing until someone looks;
- **event ring**: a bounded trace of recent serve-path events for the
  ``/serve_stats`` JSON view (capacity slots, overwrite-oldest).

``set_enabled(False)`` (or ``PATHWAY_OBSERVE=0``) turns every record
call into an early-return bool check — the knob the ``observe_overhead``
bench phase flips to price the recorder itself.  Rendering snapshots
each series before formatting, so scraped histogram buckets are
cumulative and monotone even under concurrent writes.
"""

from __future__ import annotations

import itertools
import threading
import time
import weakref
from typing import Any, Dict, Iterable, List, Optional, Tuple

from . import _state
from .histogram import EventRing, LatencyHistogram, bucket_bounds_s

__all__ = [
    "Counter",
    "Gauge",
    "count",
    "counter",
    "emit_span",
    "enabled",
    "gauge",
    "histogram",
    "next_id",
    "record_event",
    "register_provider",
    "render_prometheus",
    "reset",
    "set_enabled",
    "snapshot",
]

_LabelKey = Tuple[Tuple[str, str], ...]


def enabled() -> bool:
    return _state.enabled


def set_enabled(flag: bool) -> None:
    """Flip the recorder globally (bench's on/off A-B switch; production
    opt-out via PATHWAY_OBSERVE=0).  Disabled record calls early-return;
    already-recorded data stays and keeps rendering."""
    _state.enabled = bool(flag)


class Counter:
    """Monotone counter; ``inc`` is the hot-path entry."""

    __slots__ = ("_value", "_lock")

    def __init__(self) -> None:
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        if not _state.enabled:
            return
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0


class Gauge:
    """Last-write-wins gauge for push-style values (prefer a provider
    for anything derivable from live object state)."""

    __slots__ = ("_value",)

    def __init__(self) -> None:
        self._value = 0.0

    def set(self, value: float) -> None:
        if not _state.enabled:
            return
        self._value = float(value)

    @property
    def value(self) -> float:
        return self._value

    def reset(self) -> None:
        self._value = 0.0


_registry_lock = threading.Lock()
_hists: Dict[str, Dict[_LabelKey, LatencyHistogram]] = {}
_counters: Dict[str, Dict[_LabelKey, Counter]] = {}
_gauges: Dict[str, Dict[_LabelKey, Gauge]] = {}
_providers: "weakref.WeakSet" = weakref.WeakSet()
_ring = EventRing(capacity=512)
_ids = itertools.count()


def next_id() -> int:
    """Process-unique small integer for the ``id`` label that uniquifies
    per-instance series (two encoders with the same model name must not
    collide into one Prometheus label set — duplicate label sets fail
    the whole scrape)."""
    return next(_ids)


def _label_key(labels: Dict[str, Any]) -> _LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def histogram(name: str, **labels: Any) -> LatencyHistogram:
    """The (name, labels) histogram, created on first use.  Resolve once
    at module/instance scope and keep the reference — the per-event call
    is then ``h.observe_ns(dt)`` with no registry lookup."""
    key = _label_key(labels)
    with _registry_lock:
        series = _hists.setdefault(name, {})
        h = series.get(key)
        if h is None:
            h = series[key] = LatencyHistogram()
        return h


def counter(name: str, **labels: Any) -> Counter:
    key = _label_key(labels)
    with _registry_lock:
        series = _counters.setdefault(name, {})
        c = series.get(key)
        if c is None:
            c = series[key] = Counter()
        return c


def gauge(name: str, **labels: Any) -> Gauge:
    key = _label_key(labels)
    with _registry_lock:
        series = _gauges.setdefault(name, {})
        g = series.get(key)
        if g is None:
            g = series[key] = Gauge()
        return g


def count(name: str, n: int = 1, **labels: Any) -> None:
    """Dynamic-label counter increment (one registry lookup per call) —
    for sites whose label values vary at runtime (e.g. the batch bucket
    actually chosen)."""
    if not _state.enabled:
        return
    counter(name, **labels).inc(n)


# resolved occupancy-counter trios per (site, bucket): sites and buckets
# are small fixed sets, so this cache keeps the per-dispatch cost at one
# dict read + three locked increments instead of three _registry_lock
# acquisitions (a benign GIL race on first resolution hands back the
# same registered objects — counter() is idempotent)
_occ_cache: Dict[Tuple[str, int], Tuple[Counter, Counter, Counter]] = {}


def record_occupancy(site: str, real: int, padded: int) -> None:
    """Packing/batch occupancy accounting for one dispatch: ``real``
    rows of actual work inside ``padded`` bucketed rows, plus a counter
    on the bucket actually chosen.  Occupancy ratio = real/padded over
    any scrape window; bucket counters expose compile-shape churn."""
    if not _state.enabled:
        return
    key = (site, int(padded))
    trio = _occ_cache.get(key)
    if trio is None:
        trio = _occ_cache[key] = (
            counter("pathway_serve_pack_rows_total", site=site, kind="real"),
            counter("pathway_serve_pack_rows_total", site=site, kind="padded"),
            counter(
                "pathway_serve_batch_bucket_total", site=site, bucket=str(padded)
            ),
        )
    trio[0].inc(int(real))
    trio[1].inc(int(padded))
    trio[2].inc()


def record_event(kind: str, tag: str, dur_ns: int = 0, **extra: Any) -> None:
    """Append one serve-path event to the bounded ring (shown on
    ``/serve_stats``).  ``extra`` must be JSON-able scalars."""
    if not _state.enabled:
        return
    _ring.append((time.time(), kind, tag, int(dur_ns), extra or None))


def register_provider(obj: Any) -> None:
    """Weakly register an object exposing ``observe_metrics() ->
    iterable of (kind, name, labels_dict, value)`` with ``kind`` in
    {"gauge", "counter"}.  Sampled at scrape time only; a collected
    object silently drops out."""
    _providers.add(obj)


def _provider_samples() -> List[Tuple[str, str, _LabelKey, float]]:
    samples: List[Tuple[str, str, _LabelKey, float]] = []
    for obj in list(_providers):
        try:
            for kind, name, labels, value in obj.observe_metrics():
                samples.append((kind, name, _label_key(labels), float(value)))
        except Exception:
            # a half-torn-down provider (closed plane, dropped index)
            # must not take the scrape endpoint down with it
            continue
    samples.sort(key=lambda s: (s[1], s[2]))
    return samples


# -- OTLP spans ----------------------------------------------------------
_telemetry = None
_spans_on: Optional[bool] = None


def emit_span(name: str, **attributes: Any) -> None:
    """Emit one span for the current instant: onto the ACTIVE per-request
    trace (observe/trace.py — the round-13 rework of what used to be an
    OTLP-only stub) and, when an endpoint is configured
    (PATHWAY_MONITORING_SERVER), as an OTLP span through
    ``internals/telemetry.py``.  The span carries the measured stage
    durations as attributes — serve timing is measured by the recorder,
    the span is its export.  Gated on the same global switch as every
    other record call — PATHWAY_OBSERVE=0 silences span export too."""
    global _telemetry, _spans_on
    if not _state.enabled:
        return
    from . import trace as _trace  # lazy: trace.py imports this module

    t = _trace.current()
    if t is not None:
        t.add_event(name, **attributes)
    if _spans_on is False:
        return
    if _spans_on is None:
        try:
            from ..internals.telemetry import NoopTelemetry, maybe_telemetry

            _telemetry = maybe_telemetry()
            _spans_on = not isinstance(_telemetry, NoopTelemetry)
        except Exception:
            _spans_on = False
        if not _spans_on:
            return
    try:
        with _telemetry.span(name, **attributes):
            pass
    except Exception:
        pass


# -- rendering -----------------------------------------------------------
def _escape(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt_labels(key: _LabelKey, extra: Optional[Tuple[Tuple[str, str], ...]] = None) -> str:
    pairs = list(key) + list(extra or ())
    if not pairs:
        return ""
    return "{" + ",".join(f'{k}="{_escape(v)}"' for k, v in pairs) + "}"


def _fmt_le(bound: float) -> str:
    return repr(bound)


def _fmt_exemplar(exemplars, i: int) -> str:
    """OpenMetrics exemplar suffix for bucket ``i`` ('' when none)."""
    if exemplars is None or exemplars[i] is None:
        return ""
    trace_id, value_s, ts = exemplars[i]
    return f' # {{trace_id="{_escape(trace_id)}"}} {repr(value_s)} {ts:.3f}'


def _ring_health() -> List[Tuple[str, int, int]]:
    """(ring, capacity, dropped) rows for every bounded ring: the serve
    event ring, the trace kept/pending stores, and — when a test/bench
    counter is installed — the dispatch counter's event buffer.  Drop
    counts were previously tracked but never rendered (ISSUE 9)."""
    rows: List[Tuple[str, int, int]] = [
        ("serve_events", _ring.capacity, _ring.dropped)
    ]
    try:
        from . import trace as _trace

        rows.extend(_trace.ring_stats())
    except Exception:  # pragma: no cover - partial teardown
        pass
    try:
        from ..ops import dispatch_counter as _dc

        active = _dc._active
        if active is not None:
            rows.append(
                ("dispatch_counter", active.max_events, active.events_dropped)
            )
    except Exception:  # pragma: no cover - partial teardown
        pass
    return rows


def _fmt_value(value: float) -> str:
    """Exact sample formatting: integral values render as integers
    (``%g`` would truncate to 6 significant digits — a bytes counter
    past ~1e6 would appear frozen across scrapes and rate() would read
    0), floats via repr (shortest exact form)."""
    if float(value).is_integer():
        return str(int(value))
    return repr(float(value))


def render_prometheus(openmetrics: bool = False) -> List[str]:
    """All recorder series in Prometheus text exposition format —
    appended to ``internals/metrics.py``'s ``render_metrics`` output so
    one scrape covers engine, connectors, and the serve flight recorder.
    Deterministic ordering (sorted names, sorted label sets) and one
    consistent snapshot per series.

    ``openmetrics=True`` additionally renders kept-trace exemplars on
    the histogram bucket samples.  Exemplar syntax is ONLY legal in the
    OpenMetrics exposition (negotiated via the Accept header and served
    as ``application/openmetrics-text``); a classic
    ``text/plain; version=0.0.4`` parser errors on the ``#`` token and
    the WHOLE scrape fails — so the classic rendering never carries
    them."""
    lines: List[str] = []
    bounds = bucket_bounds_s()

    with _registry_lock:
        hist_items = {
            name: dict(series) for name, series in _hists.items()
        }
        counter_items = {
            name: dict(series) for name, series in _counters.items()
        }
        gauge_items = {
            name: dict(series) for name, series in _gauges.items()
        }

    for name in sorted(hist_items):
        series = hist_items[name]
        if not series:
            continue
        lines.append(f"# TYPE {name} histogram")
        for key in sorted(series):
            h = series[key]
            counts, sum_ns, n = h.snapshot()
            # OpenMetrics exemplars: kept-trace ids stamped by the tail
            # sampler (observe/trace.py) onto the bucket their span
            # duration landed in — "# {trace_id=...} value ts" appended
            # to the bucket sample, so a p99 bucket links to /traces
            exemplars = h.exemplars() if openmetrics else None
            cum = 0
            for i, bound in enumerate(bounds):
                cum += counts[i]
                line = (
                    f"{name}_bucket"
                    f"{_fmt_labels(key, (('le', _fmt_le(bound)),))} {cum}"
                )
                lines.append(line + _fmt_exemplar(exemplars, i))
            inf_line = (
                f"{name}_bucket{_fmt_labels(key, (('le', '+Inf'),))} {n}"
            )
            lines.append(
                inf_line + _fmt_exemplar(exemplars, len(bounds))
            )
            lines.append(f"{name}_sum{_fmt_labels(key)} {sum_ns * 1e-9:.9f}")
            lines.append(f"{name}_count{_fmt_labels(key)} {n}")

    provider = _provider_samples()
    prov_counters: Dict[str, List[Tuple[_LabelKey, float]]] = {}
    prov_gauges: Dict[str, List[Tuple[_LabelKey, float]]] = {}
    for kind, name, key, value in provider:
        (prov_counters if kind == "counter" else prov_gauges).setdefault(
            name, []
        ).append((key, value))

    counter_names = sorted(set(counter_items) | set(prov_counters))
    for name in counter_names:
        rows = [
            (key, float(c.value)) for key, c in counter_items.get(name, {}).items()
        ] + prov_counters.get(name, [])
        if not rows:
            continue
        lines.append(f"# TYPE {name} counter")
        for key, value in sorted(rows):
            lines.append(f"{name}{_fmt_labels(key)} {_fmt_value(value)}")

    gauge_names = sorted(set(gauge_items) | set(prov_gauges))
    for name in gauge_names:
        rows = [
            (key, g.value) for key, g in gauge_items.get(name, {}).items()
        ] + prov_gauges.get(name, [])
        if not rows:
            continue
        lines.append(f"# TYPE {name} gauge")
        for key, value in sorted(rows):
            lines.append(f"{name}{_fmt_labels(key)} {_fmt_value(value)}")
    # bounded-ring health: the drop counters were tracked (event ring,
    # dispatch counter) but never rendered; a silently-saturating ring
    # reads as "nothing happened" exactly when the most is happening
    rings = _ring_health()
    lines.append("# TYPE pathway_observe_events_dropped_total counter")
    for ring, _capacity, dropped in rings:
        lines.append(
            f'pathway_observe_events_dropped_total{{ring="{ring}"}} {dropped}'
        )
    lines.append("# TYPE pathway_observe_ring_capacity gauge")
    for ring, capacity, _dropped in rings:
        lines.append(
            f'pathway_observe_ring_capacity{{ring="{ring}"}} {capacity}'
        )
    return lines


def _shard_sort_key(shard: str):
    try:
        return (0, int(shard))
    except ValueError:
        return (1, shard)


def snapshot() -> Dict[str, Any]:
    """JSON-able view for ``GET /serve_stats``: per-series histogram
    summaries (count/sum/p50/p95/p99 bucket-bound estimates), counters,
    gauges (provider-sampled), a per-shard column (every provider
    sample labeled ``shard=...`` grouped by shard id), a per-tier cache
    column (samples labeled ``tier=...`` — the pathway_tpu/cache
    hit/miss/evict/bytes families), a per-runner ingest column (samples
    labeled ``ingest=...`` — lag, pending docs, freshness quantiles),
    and the recent event ring."""
    with _registry_lock:
        hist_items = {name: dict(series) for name, series in _hists.items()}
        counter_items = {
            name: dict(series) for name, series in _counters.items()
        }
        gauge_items = {name: dict(series) for name, series in _gauges.items()}

    def series_name(name: str, key: _LabelKey) -> str:
        return name + _fmt_labels(key)

    hists = {}
    for name, series in hist_items.items():
        for key, h in series.items():
            counts, sum_ns, n = h.snapshot()
            hists[series_name(name, key)] = {
                "count": n,
                "sum_s": sum_ns * 1e-9,
                "p50_s": h.quantile_s(0.50),
                "p95_s": h.quantile_s(0.95),
                "p99_s": h.quantile_s(0.99),
            }
    counters = {
        series_name(name, key): c.value
        for name, series in counter_items.items()
        for key, c in series.items()
    }
    gauges = {
        series_name(name, key): g.value
        for name, series in gauge_items.items()
        for key, g in series.items()
    }
    # the shard column: any provider sample carrying a "shard" label is
    # ALSO grouped per shard id, so /serve_stats shows one row per shard
    # (resident vectors, tail size, skips, breaker state, forward docs)
    # without the reader having to parse Prometheus label strings.  The
    # remaining labels stay ON the per-shard key — several sharded
    # structures (two replicas' groups, a 1-shard vs 8-shard bench pair)
    # legitimately report the same metric for the same shard id, and
    # keying by bare metric name would let whichever provider iterates
    # last silently overwrite the others
    shards: Dict[str, Dict[str, float]] = {}
    # the cache column: provider samples labeled tier=... (the
    # pathway_tpu/cache tiers) grouped per tier, same shape as shards —
    # /serve_stats readers get hit/miss/evict/bytes per tier without
    # parsing Prometheus label strings
    caches: Dict[str, Dict[str, float]] = {}
    # the generator column: samples labeled generator=... (the
    # continuous-decode engines, serve/decode.py) grouped per engine —
    # slot occupancy, prefill/decode token counters, finished/evicted
    # requests, quarantined slots, per engine name
    generators: Dict[str, Dict[str, float]] = {}
    # the ingest column: samples labeled ingest=... (the live-ingest
    # runners, serve/ingest.py) grouped per runner — pending docs,
    # oldest-pending age, per-connector lag, freshness p50/p99 — so the
    # one scrape surface stays the single pane of glass for the
    # ingest+serve plane
    ingests: Dict[str, Dict[str, float]] = {}
    for kind, name, key, value in _provider_samples():
        target = counters if kind == "counter" else gauges
        target[series_name(name, key)] = value
        labels = dict(key)
        shard = labels.get("shard")
        if shard is not None:
            rest = tuple(
                (lk, lv) for lk, lv in key if lk != "shard"
            )
            shards.setdefault(shard, {})[series_name(name, rest)] = value
        tier = labels.get("tier")
        if tier is not None:
            rest = tuple((lk, lv) for lk, lv in key if lk != "tier")
            caches.setdefault(tier, {})[series_name(name, rest)] = value
        gen = labels.get("generator")
        if gen is not None:
            rest = tuple((lk, lv) for lk, lv in key if lk != "generator")
            generators.setdefault(gen, {})[series_name(name, rest)] = value
        ing = labels.get("ingest")
        if ing is not None:
            rest = tuple((lk, lv) for lk, lv in key if lk != "ingest")
            ingests.setdefault(ing, {})[series_name(name, rest)] = value
    events, total = _ring.snapshot()
    # the profile column: per-callable device-time attribution from the
    # sampling profiler (observe/profile.py — lazy import: profile
    # resolves its series through this module).  hbm and slo ride along:
    # the ledger sample and the current burn-rate document, so one
    # /serve_stats read answers "who owns device time, who owns HBM,
    # are we in budget" together
    profile_col: Dict[str, Any] = {}
    hbm_col: Dict[str, Any] = {}
    slo_col: Dict[str, Any] = {}
    try:
        from . import hbm as _hbm
        from . import profile as _profile
        from . import slo as _slo

        profile_col = _profile.profile_stats()
        hbm_col = _hbm.ledger_stats()
        slo_col = _slo.evaluate()
    except Exception:  # pragma: no cover - partial teardown
        pass
    return {
        "enabled": _state.enabled,
        "rings": {
            ring: {"capacity": capacity, "dropped": dropped}
            for ring, capacity, dropped in _ring_health()
        },
        "histograms": hists,
        "counters": counters,
        "gauges": gauges,
        "shards": {k: shards[k] for k in sorted(shards, key=_shard_sort_key)},
        "caches": {k: caches[k] for k in sorted(caches)},
        "generators": {k: generators[k] for k in sorted(generators)},
        "ingest": {k: ingests[k] for k in sorted(ingests)},
        "profile": profile_col,
        "hbm": hbm_col,
        "slo": slo_col,
        "events": [
            {
                "ts": e[0],
                "kind": e[1],
                "tag": e[2],
                "dur_ns": e[3],
                **(e[4] or {}),
            }
            for e in events
        ],
        "events_total": total,
    }


def reset() -> None:
    """Zero every registered series and the event ring WITHOUT dropping
    the series objects (instrumentation sites hold direct references;
    replacing the objects would silently detach them from the scrape
    output).  Tests and the bench overhead phase use this between runs."""
    with _registry_lock:
        for series in _hists.values():
            for h in series.values():
                h.reset()
        for series in _counters.values():
            for c in series.values():
                c.reset()
        for series in _gauges.values():
            for g in series.values():
                g.reset()
    _ring.reset()
