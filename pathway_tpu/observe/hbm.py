"""Central HBM attribution ledger — which subsystem owns device memory,
and when does it run out.

Every long-lived device-resident structure in the serve stack registers
here: the IVF resident slabs + exact tail, the forward index's row
buckets, the continuous-decode slot KV pool, the embedding-cache rows
and prefix-cache prefill blocks, the model parameter trees.  The ledger
is PULL-based — registration stores a weakref plus a byte-reporting
callback, and byte counts are read at sample time only (scrape,
``/serve_stats``, bench) — so the serve path pays nothing: absorbing a
batch, joining a slot, or evicting a cache row never touches the
ledger.  ``.nbytes`` on a jax array is metadata, not a host sync, so a
sample never blocks on the device either.

What a sample produces:

- ``pathway_hbm_bytes{subsystem,component}`` — per-structure gauges,
  summed across instances (two indexes both report ``ivf/resident``);
- ``pathway_hbm_total_bytes`` and ``pathway_hbm_watermark_bytes`` — the
  ledger total and its high-water mark (watermark advances at sample
  time: scrape cadence is the resolution);
- ``pathway_hbm_device_bytes`` — the BACKEND's own accounting
  (``device.memory_stats()["bytes_in_use"]`` where the platform
  provides it, the sum over ``jax.live_arrays()`` otherwise), the
  cross-check that catches an unregistered consumer: ledger ≈ device
  within tolerance or something is eating HBM off the books;
- ``pathway_hbm_resource_used/capacity`` and
  ``pathway_hbm_exhaustion_eta_seconds{resource}`` — for registered
  capacity-bounded resources (decode slots, forward-index rows, cache
  byte budgets), the observed growth rate over recent samples projected
  to exhaustion (-1 = not growing).

Degrade-never-fail: the ``hbm.ledger`` chaos site fires on the sample
path under an already-spent deadline — ANY armed fault yields the
last-known (stale) sample, counted on
``pathway_hbm_samples_dropped_total``, and a single misbehaving
registrant (raising callback, collected object) is skipped, never
poisoning the scrape or a serve.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

from .recorder import counter, register_provider

__all__ = [
    "device_bytes",
    "ledger_stats",
    "reset",
    "sample",
    "track",
    "track_params",
    "track_resource",
    "tree_nbytes",
]

_C_DROPPED = counter("pathway_hbm_samples_dropped_total")

_lock = threading.Lock()
# byte registrants: (subsystem, weakref(obj), fn) with fn(obj) ->
# {component: bytes}
_tracked: List[Tuple[str, "weakref.ref", Callable[[Any], Dict[str, int]]]] = []
# capacity resources: (name, weakref(obj), used_fn, cap_fn)
_resources: List[
    Tuple[str, "weakref.ref", Callable[[Any], float], Callable[[Any], float]]
] = []
# per-resource growth history: name -> (t_s, used) of the previous
# sample, plus an EWMA of the growth rate in units/s
_growth: Dict[str, Tuple[float, float, float]] = {}

_watermark = 0
_last_sample: Optional[Dict[str, Any]] = None
_last_sample_t = 0.0

_inject_mod: Any = None


def _inject():
    global _inject_mod
    if _inject_mod is None:
        try:
            from ..robust import inject as mod
        except Exception:  # pragma: no cover - partial teardown
            return None
        _inject_mod = mod
    return _inject_mod


def _sample_allowed() -> bool:
    """Chaos gate (site ``hbm.ledger``): fired under a spent deadline so
    armed hangs release instantly; any firing = serve the stale sample."""
    inj = _inject()
    if inj is None or not inj.any_armed():
        return True
    try:
        from ..robust.deadline import Deadline

        before = inj.fired_count("hbm.ledger")
        inj.fire("hbm.ledger", deadline=Deadline.after_ms(0.0))
        return inj.fired_count("hbm.ledger") == before
    except Exception:
        return False


def tree_nbytes(tree: Any) -> int:
    """Total ``.nbytes`` over an arbitrary pytree-ish container of
    arrays (params dicts, tuples of buffers) — metadata only, no sync."""
    total = 0
    stack = [tree]
    while stack:
        x = stack.pop()
        nb = getattr(x, "nbytes", None)
        if nb is not None and not isinstance(x, (str, bytes)):
            total += int(nb)
        elif isinstance(x, dict):
            stack.extend(x.values())
        elif isinstance(x, (tuple, list)):
            stack.extend(x)
    return total


def track(
    subsystem: str,
    obj: Any,
    fn: Optional[Callable[[Any], Dict[str, int]]] = None,
) -> None:
    """Register ``obj`` as a device-memory owner under ``subsystem``.

    ``fn(obj)`` returns ``{component: bytes}``; the default calls
    ``obj.hbm_bytes()`` (int -> one ``total`` component, dict passed
    through).  Weakly held: a collected structure leaves the ledger on
    its own."""
    if fn is None:
        def fn(o):  # noqa: E306 - default byte reader
            got = o.hbm_bytes()
            return got if isinstance(got, dict) else {"total": int(got)}

    with _lock:
        _tracked.append((str(subsystem), weakref.ref(obj), fn))


def track_params(name: str, model: Any) -> None:
    """Register a model's parameter tree under ``params/<name>`` —
    params are usually the single largest resident allocation and the
    cross-check is meaningless without them."""
    track(
        "params",
        model,
        lambda m, _n=str(name): {_n: tree_nbytes(getattr(m, "params", None))},
    )


def track_resource(
    name: str,
    obj: Any,
    used_fn: Callable[[Any], float],
    cap_fn: Callable[[Any], float],
) -> None:
    """Register a capacity-bounded resource for exhaustion-ETA tracking
    (decode slots, forward-index rows, cache byte budgets).  Rates are
    derived from successive samples — absorb/join rates as actually
    observed, not as configured."""
    with _lock:
        _resources.append((str(name), weakref.ref(obj), used_fn, cap_fn))


def device_bytes() -> Optional[int]:
    """The backend's own resident-byte accounting: TPU/GPU platforms
    report ``memory_stats()['bytes_in_use']``; the CPU backend doesn't,
    so fall back to summing ``jax.live_arrays()`` — every live buffer
    the backend still holds.  None when jax is unavailable."""
    try:
        import jax
    except Exception:  # pragma: no cover - jax always present in-tree
        return None
    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats and stats.get("bytes_in_use"):
            return int(stats["bytes_in_use"])
    except Exception:
        pass
    try:
        return int(
            sum(int(getattr(a, "nbytes", 0)) for a in jax.live_arrays())
        )
    except Exception:
        return None


_EWMA_ALPHA = 0.5  # recent growth dominates: exhaustion is a NOW question
# growth observations closer together than this reuse the previous rate
# instead of updating the EWMA: back-to-back samples (a scrape that
# reads the ledger twice, several registrants landing in one pass) would
# otherwise inject zero-dt/zero-growth updates that halve the rate
_MIN_GROWTH_DT_S = 0.05


def _sample_resources(now_s: float) -> Dict[str, Dict[str, float]]:
    # aggregate used/capacity ACROSS registrants sharing a name first
    # (every shard of a ShardedForwardIndex registers "forward_rows",
    # every embedding cache its byte budget): growth is then derived
    # from ONE total per resource — per-registrant updates would
    # overwrite each other within a single pass and read as a huge
    # instantaneous growth spike
    totals: Dict[str, Tuple[float, float]] = {}
    with _lock:
        live = [
            (name, ref, used_fn, cap_fn)
            for name, ref, used_fn, cap_fn in _resources
            if ref() is not None
        ]
        _resources[:] = live
    for name, ref, used_fn, cap_fn in live:
        obj = ref()
        if obj is None:
            continue
        try:
            used = float(used_fn(obj))
            cap = float(cap_fn(obj))
        except Exception:
            continue  # one bad registrant never poisons the sample
        u0, c0 = totals.get(name, (0.0, 0.0))
        totals[name] = (u0 + used, c0 + cap)
    out: Dict[str, Dict[str, float]] = {}
    for name, (used, cap) in totals.items():
        prev = _growth.get(name)
        rate = 0.0
        if prev is not None:
            t_prev, used_prev, rate_prev = prev
            dt = now_s - t_prev
            if dt < _MIN_GROWTH_DT_S:
                # too soon to say anything about growth: keep the
                # previous observation point and rate untouched
                rate = rate_prev
                used_prev_kept = True
            else:
                inst = max(0.0, (used - used_prev) / dt)  # growth only
                rate = _EWMA_ALPHA * inst + (1 - _EWMA_ALPHA) * rate_prev
                used_prev_kept = False
        else:
            used_prev_kept = False
        if prev is None or not used_prev_kept:
            _growth[name] = (now_s, used, rate)
        headroom = max(0.0, cap - used)
        eta = headroom / rate if rate > 1e-9 else -1.0
        out[name] = {
            "used": used,
            "capacity": cap,
            "growth_per_s": rate,
            "exhaustion_eta_s": eta,
        }
    return out


def sample(max_age_s: float = 0.0) -> Dict[str, Any]:
    """Read every registrant and produce one ledger sample (also cached
    as the stale fallback for the chaos path).  Called at scrape time
    and on demand by tests/bench — never from the serve path.

    ``max_age_s > 0`` reuses the cached sample when it is fresh enough —
    a scrape that renders the provider gauges AND the ``/serve_stats``
    ``hbm`` column must not walk the registry (and, on CPU, sum
    ``jax.live_arrays()``) twice back to back."""
    global _watermark, _last_sample, _last_sample_t
    if (
        max_age_s > 0.0
        and _last_sample is not None
        and time.monotonic() - _last_sample_t < max_age_s
    ):
        return _last_sample
    if not _sample_allowed():
        _C_DROPPED.inc()
        if _last_sample is not None:
            return {**_last_sample, "stale": True}
        return {
            "stale": True, "subsystems": {}, "total_bytes": 0,
            "watermark_bytes": _watermark, "device_bytes": None,
            "resources": {},
        }
    now_s = time.monotonic()
    with _lock:
        live = [
            (subsystem, ref, fn)
            for subsystem, ref, fn in _tracked
            if ref() is not None
        ]
        _tracked[:] = live
    by_key: Dict[Tuple[str, str], int] = {}
    for subsystem, ref, fn in live:
        obj = ref()
        if obj is None:
            continue
        try:
            parts = fn(obj)
        except Exception:
            continue  # half-torn-down registrant: skip, never raise
        for component, nbytes in parts.items():
            key = (subsystem, str(component))
            by_key[key] = by_key.get(key, 0) + int(nbytes)
    total = sum(by_key.values())
    if total > _watermark:
        _watermark = total
    subsystems: Dict[str, Dict[str, int]] = {}
    for (subsystem, component), nbytes in sorted(by_key.items()):
        subsystems.setdefault(subsystem, {})[component] = nbytes
    doc = {
        "stale": False,
        "subsystems": subsystems,
        "total_bytes": total,
        "watermark_bytes": _watermark,
        "device_bytes": device_bytes(),
        "resources": _sample_resources(now_s),
    }
    _last_sample = doc
    _last_sample_t = time.monotonic()
    return doc


def ledger_stats() -> Dict[str, Any]:
    """The ``/serve_stats`` ``hbm`` column — reuses a fraction-of-a-
    second-fresh sample so one snapshot() never walks the ledger twice."""
    return sample(max_age_s=0.25)


class _Provider:
    """Flight-recorder provider: the ledger rendered as gauges on the
    one scrape surface."""

    def observe_metrics(self):
        doc = sample()
        for subsystem, parts in doc["subsystems"].items():
            for component, nbytes in parts.items():
                yield (
                    "gauge",
                    "pathway_hbm_bytes",
                    {"subsystem": subsystem, "component": component},
                    nbytes,
                )
        yield ("gauge", "pathway_hbm_total_bytes", {}, doc["total_bytes"])
        yield (
            "gauge", "pathway_hbm_watermark_bytes", {},
            doc["watermark_bytes"],
        )
        if doc["device_bytes"] is not None:
            yield (
                "gauge", "pathway_hbm_device_bytes", {}, doc["device_bytes"]
            )
        for name, row in doc["resources"].items():
            labels = {"resource": name}
            yield (
                "gauge", "pathway_hbm_resource_used", labels, row["used"]
            )
            yield (
                "gauge", "pathway_hbm_resource_capacity", labels,
                row["capacity"],
            )
            yield (
                "gauge",
                "pathway_hbm_exhaustion_eta_seconds",
                labels,
                row["exhaustion_eta_s"],
            )


_provider = _Provider()
register_provider(_provider)


def reset() -> None:
    """Drop every registration and the watermark (tests only — live
    structures re-register on construction, not on reset)."""
    global _watermark, _last_sample, _last_sample_t
    with _lock:
        _tracked.clear()
        _resources.clear()
    _growth.clear()
    _watermark = 0
    _last_sample = None
    _last_sample_t = 0.0
