"""The one bounded, thread-safe, metrics-instrumented cache store.

Every serve-cache tier (result / embedding / generator KV) is a
``CacheTier`` — an LRU dict bounded by a BYTE budget (entry count is a
secondary cap), with optional TTL, integrity fingerprints, and the
``cache.get`` / ``cache.put`` chaos sites wired through
``robust/inject.py``.  Design constraints, in order:

1. **A cache failure is a miss, never a failed or wrong serve.**  Every
   internal error on the lookup path — an armed chaos site, a corrupt
   entry (fingerprint mismatch), an expired TTL, a poisoned value —
   degrades to ``None`` (recompute); every error on the store path drops
   the entry.  The serve path cannot tell a broken cache from a cold one.
2. **Lookups stay off the serve locks** (the analyzer's lock-discipline
   rule): the tier's internal lock guards only dict/int operations —
   never a device dispatch, a fetch, or the chaos sites (``fire`` runs
   BEFORE the lock so an armed ``hang`` wedges only the calling request,
   not every cache user).
3. **Bounded by construction.**  ``max_bytes`` is enforced at put time
   with LRU eviction; values carry their own byte estimate (device
   arrays report ``.nbytes`` without a host sync).  TTL expiry is lazy
   (checked at get) plus opportunistic at put.
4. **One scrape surface.**  Each tier registers as a flight-recorder
   provider: ``pathway_cache_{hits,misses,evictions,insertions,
   corrupt,failures}_total{tier=...}`` counters plus
   ``pathway_cache_{bytes,entries}{tier=...}`` gauges render on the
   existing ``/metrics`` endpoint, and ``/serve_stats`` groups the
   ``tier``-labeled samples into a per-tier cache column.

The motivating numbers are in "Accelerating Retrieval-Augmented
Generation" (arxiv 2412.15246): production RAG query streams are
hot-headed across seconds-to-minutes windows, and the caching layer is
the dominant serving speedup once the dispatch path itself is tight.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional

from .. import config, observe
from ..observe import trace
from ..robust import log_once
from ..robust import inject

__all__ = ["CacheTier", "cache_enabled", "live_tiers"]

# every live tier, weakly: the online tuner (serve/tuner.py) walks this
# to retarget byte budgets on RUNNING tiers — a registry lookup at
# construction time only would strand long-lived caches on stale budgets
_LIVE_TIERS: "weakref.WeakSet[CacheTier]" = weakref.WeakSet()


def live_tiers() -> "List[CacheTier]":
    """Snapshot of every live ``CacheTier`` (tuner discovery surface)."""
    return list(_LIVE_TIERS)


def cache_enabled() -> bool:
    """Global kill switch: ``PATHWAY_CACHE=0`` disables every tier."""
    return config.get("cache.enabled")


def _default_nbytes(value: Any) -> int:
    """Byte estimate for budget accounting: device/numpy arrays report
    exactly (``.nbytes`` is metadata, not a host sync); containers
    recurse one level; everything else pays a flat floor."""
    nbytes = getattr(value, "nbytes", None)
    if nbytes is not None:
        return int(nbytes)
    if isinstance(value, (tuple, list)):
        return 64 + sum(_default_nbytes(v) for v in value)
    if isinstance(value, (bytes, str)):
        return 64 + len(value)
    return 64


class _Entry:
    __slots__ = ("value", "nbytes", "expires_at", "fingerprint")

    def __init__(self, value, nbytes, expires_at, fingerprint):
        self.value = value
        self.nbytes = nbytes
        self.expires_at = expires_at
        self.fingerprint = fingerprint


class CacheTier:
    """One LRU + byte-budget bounded tier behind the shared contract.

    ``fingerprint`` (optional) is a cheap pure function of a value used
    as an integrity check: computed at put, re-checked at get — a
    mismatch means the entry was corrupted in place, and the get
    degrades to a miss (and drops the entry) instead of serving a wrong
    result.  Only use it for host values; fingerprinting a device array
    would be a hidden sync."""

    def __init__(
        self,
        tier: str,
        max_bytes: int,
        ttl_s: Optional[float] = None,
        max_entries: Optional[int] = None,
        fingerprint: Optional[Callable[[Any], Any]] = None,
    ):
        self.tier = str(tier)
        self.max_bytes = int(max_bytes)
        self.ttl_s = float(ttl_s) if ttl_s else None
        self.max_entries = int(max_entries) if max_entries else None
        self._fingerprint = fingerprint
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Any, _Entry]" = OrderedDict()
        self._bytes = 0
        # plain ints under the tier lock; the recorder samples them at
        # scrape time through the provider registry
        self.stats: Dict[str, int] = {
            "hits": 0,
            "misses": 0,
            "insertions": 0,
            "evictions": 0,
            "expirations": 0,
            "corrupt": 0,
            "failures": 0,  # chaos/internal errors degraded to miss/drop
        }
        # per-instance `id` label: two live caches of the SAME tier (two
        # serve stacks, encoder-side + serve-side embedding tiers) must
        # not collapse into one Prometheus label set — duplicate label
        # sets fail the whole scrape (same rule as every other
        # per-instance series; see observe.next_id)
        self.labels = {"tier": self.tier, "id": str(observe.next_id())}
        observe.register_provider(self)
        _LIVE_TIERS.add(self)

    def _trace_note(self, op: str, outcome: str) -> None:
        """Hit/miss annotation on the active trace (observe/trace.py):
        one zero-duration span per cache operation, so a kept trace
        shows which tiers this request touched and how they answered.
        One context-var read when untraced."""
        t = trace.current()
        if t is not None:
            t.add_event("cache." + op, tier=self.tier, outcome=outcome)

    # -- the serve-facing contract ------------------------------------------
    def get(self, key: Any, deadline=None) -> Optional[Any]:
        """The cached value, or None.  EVERY failure mode — armed chaos
        site, expired TTL, corrupt entry, internal error — is a miss;
        the caller recomputes and the serve result stays correct."""
        try:
            # chaos site OUTSIDE the tier lock: an armed hang must wedge
            # only this request, never every cache user behind the lock
            inject.fire("cache.get", deadline=deadline)
        except Exception as exc:
            self._count("failures")
            self._count("misses")
            log_once(
                f"cache.get:{type(exc).__name__}",
                "cache get failed on tier %s (%r); degrading to recompute",
                self.tier,
                exc,
            )
            self._trace_note("get", "error")
            return None
        now = time.monotonic()
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.stats["misses"] += 1
                entry_state = "miss"
            elif entry.expires_at is not None and now >= entry.expires_at:
                self._drop_locked(key, entry)
                self.stats["expirations"] += 1
                self.stats["misses"] += 1
                entry_state = "expired"
            else:
                self._entries.move_to_end(key)
                value = entry.value
                fp = entry.fingerprint
                entry_state = "hit"
        if entry_state != "hit":
            self._trace_note("get", entry_state)
            return None
        if fp is not None:
            # integrity re-check OFF the lock (pure host compute): a
            # mutated-in-place entry must never become a wrong serve
            try:
                ok = self._fingerprint(value) == fp
            except Exception:
                ok = False
            if not ok:
                self.discard(key)
                self._count("corrupt")
                self._count("misses")
                log_once(
                    f"cache.corrupt:{self.tier}",
                    "corrupt cache entry on tier %s; dropped and recomputing",
                    self.tier,
                )
                self._trace_note("get", "corrupt")
                return None
        self._count("hits")
        self._trace_note("get", "hit")
        return value

    def put(
        self, key: Any, value: Any, nbytes: Optional[int] = None, deadline=None
    ) -> bool:
        """Insert (last-writer-wins).  A failure — chaos site, byte
        estimate error — drops the entry silently: the cache is an
        optimization, never a correctness dependency.  Values larger
        than the whole budget are refused (they would evict everything
        for one entry that LRU would then immediately rotate out)."""
        try:
            inject.fire("cache.put", deadline=deadline)
            size = int(nbytes) if nbytes is not None else _default_nbytes(value)
            fp = self._fingerprint(value) if self._fingerprint else None
        except Exception as exc:
            self._count("failures")
            log_once(
                f"cache.put:{type(exc).__name__}",
                "cache put failed on tier %s (%r); entry dropped "
                "(next lookup recomputes)",
                self.tier,
                exc,
            )
            self._trace_note("put", "dropped")
            return False
        if self.max_bytes <= 0:
            # a zero/negative budget DISABLES the tier (matching the TTL
            # knobs' `0 = off` convention) — it must never mean
            # "unbounded", which is what skipping the eviction loop
            # below would silently produce
            return False
        if size > self.max_bytes:
            return False
        expires = (
            time.monotonic() + self.ttl_s if self.ttl_s is not None else None
        )
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[key] = _Entry(value, size, expires, fp)
            self._bytes += size
            self.stats["insertions"] += 1
            while self._entries and (
                (self.max_bytes and self._bytes > self.max_bytes)
                or (self.max_entries and len(self._entries) > self.max_entries)
            ):
                k, e = self._entries.popitem(last=False)
                self._bytes -= e.nbytes
                self.stats["evictions"] += 1
        return True

    def __contains__(self, key: Any) -> bool:
        with self._lock:
            return key in self._entries

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def bytes(self) -> int:
        return self._bytes

    def discard(self, key: Any) -> None:
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is not None:
                self._bytes -= entry.nbytes

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    # -- durable warm state (serve/warmstate.py) -----------------------------
    def warm_entries(self) -> List[Any]:
        """LRU-ordered ``(key, value, nbytes)`` triples of the live,
        unexpired entries (oldest first, so a replay preserves eviction
        order).  Values are returned by REFERENCE — callers that need
        host-picklable payloads (the embedding tier's device rows)
        override this in the owning wrapper."""
        now = time.monotonic()
        with self._lock:
            return [
                (k, e.value, e.nbytes)
                for k, e in self._entries.items()
                if e.expires_at is None or now < e.expires_at
            ]

    def load_warm_entries(self, entries: List[Any]) -> int:
        """Replay ``warm_entries()`` triples through ``put`` (fingerprints
        recomputed, TTL clocks restart — a restored entry is as fresh as
        a just-inserted one).  Returns the number of entries accepted;
        a failed put is just a cold key, never an error."""
        loaded = 0
        for k, v, nbytes in entries:
            if self.put(k, v, nbytes=nbytes):
                loaded += 1
        return loaded

    # -- internals -----------------------------------------------------------
    def _drop_locked(self, key: Any, entry: _Entry) -> None:
        self._entries.pop(key, None)
        self._bytes -= entry.nbytes

    def _count(self, stat: str) -> None:
        with self._lock:
            self.stats[stat] += 1

    # -- flight-recorder provider -------------------------------------------
    def observe_metrics(self):
        labels = self.labels
        for stat in (
            "hits", "misses", "evictions", "insertions", "expirations",
            "corrupt", "failures",
        ):
            yield (
                "counter",
                f"pathway_cache_{stat}_total",
                labels,
                self.stats[stat],
            )
        yield ("gauge", "pathway_cache_bytes", labels, self._bytes)
        yield ("gauge", "pathway_cache_entries", labels, len(self._entries))
        yield (
            "gauge", "pathway_cache_max_bytes", labels, self.max_bytes
        )
