"""Tier 0: the cross-window serve-result cache.

The coalescing scheduler's in-window dedup absorbs *simultaneous*
duplicates; production hot-head traffic repeats across seconds and
minutes ("Accelerating Retrieval-Augmented Generation", arxiv
2412.15246).  This tier turns those repeats into ZERO-dispatch serves:
the scheduler looks rows up before admission, and a full hit skips the
coalescing window, the device, and the demux entirely.

Keying and invalidation (cache/keys.py ``result_key``):

- ``(query text, index generation, k)`` — the generation is the index's
  public result-visibility counter (bumped by every absorb / retrain /
  add / remove), so a mutation makes every pre-mutation entry
  structurally unreachable: no epoch scans, no invalidation callbacks,
  no stale-hit window.  TTL bounds staleness of everything else (doc
  text drift behind unchanged keys).
- Only CLEAN results are cached: a degraded serve (rerank_skipped,
  shard_skipped, …) reflects a transient outage, and caching it would
  pin the outage for a TTL.
- The capture path double-checks the DISPATCH-time generation the serve
  path stamps into ``meta["index_generation"]`` (ops/serving.py):
  a result whose dispatch observed a newer generation than its
  admission is never stored under the stale admission key.

A hit is bit-identical to the serve that populated it — the rows ARE
that serve's rows — which is exactly the acceptance contract: repeat a
query at a stable generation and you get the same bytes with zero
device work; mutate the index and the next serve re-dispatches.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

from .. import config
from .keys import result_key
from .store import CacheTier, cache_enabled

__all__ = ["ResultCache", "result_cache_from_env"]


def _rows_fingerprint(row: Any) -> int:
    """Integrity fingerprint for one cached result row (a list of
    ``(key, score)`` pairs): cheap, exact for the int/float payloads,
    recomputed on every hit so an entry mutated in place degrades to a
    recompute instead of a wrong serve."""
    return hash(tuple((int(k), float(s)) for k, s in row))


class ResultCache:
    """The serve-result tier over one bounded ``CacheTier``.

    ``get_rows`` is all-or-nothing over a request's texts: a request
    only skips dispatch when EVERY row is cached (partial hits fall
    through to the shared batch — the embedding tier still catches the
    encode, and a split serve would change batch composition and break
    the bit-identity contract)."""

    def __init__(
        self,
        max_bytes: Optional[int] = None,
        ttl_s: Optional[float] = None,
        max_entries: Optional[int] = None,
    ):
        if max_bytes is None:
            max_bytes = config.get("cache.result_bytes")
        if ttl_s is None:
            ttl = config.get("cache.result_ttl_s")
            ttl_s = ttl if ttl > 0 else None
        self._tier = CacheTier(
            "result",
            max_bytes=max_bytes,
            ttl_s=ttl_s,
            max_entries=max_entries,
            fingerprint=_rows_fingerprint,
        )

    @property
    def stats(self):
        return self._tier.stats

    def __len__(self) -> int:
        return len(self._tier)

    def clear(self) -> None:
        self._tier.clear()

    def get_rows(
        self,
        items: Sequence[Tuple[str, int]],
        k: int,
        deadline=None,
    ) -> Optional[List[list]]:
        """Rows for a full request of ``(text, generation)`` dedup items
        at serve config ``k`` — or None unless every text hits."""
        rows: List[list] = []
        for text, gen in items:
            row = self._tier.get(result_key(text, gen, k), deadline=deadline)
            if row is None:
                return None
            rows.append(list(row))
        return rows

    def put_row(
        self,
        text: str,
        generation: int,
        k: int,
        row: Sequence[Tuple[int, float]],
        deadline=None,
    ) -> bool:
        try:
            # canonicalize INSIDE the failure containment: the scheduler
            # is generic over its target, and a target emitting rows that
            # are not (numeric, numeric) pairs must cost a dropped store,
            # never a failed ticket on the waiter thread
            row = [(int(key), float(s)) for key, s in row]
        except Exception:
            self._tier._count("failures")
            return False
        # ~32 B per (key, score) pair + entry overhead
        return self._tier.put(
            result_key(text, generation, k),
            row,
            nbytes=64 + 32 * len(row),
            deadline=deadline,
        )

    # -- durable warm state (serve/warmstate.py) -----------------------------
    def warm_state(self) -> dict:
        """Picklable snapshot of the live rows (values are host lists of
        ``(key, score)`` pairs already — nothing to fetch)."""
        return {"kind": "result_cache", "entries": self._tier.warm_entries()}

    def load_warm_state(self, state: dict) -> int:
        if state.get("kind") != "result_cache":
            raise ValueError(
                f"not a result-cache warm state: {state.get('kind')!r}"
            )
        return self._tier.load_warm_entries(state["entries"])

    def observe_metrics(self):  # delegate: one provider per tier is enough
        return iter(())


def result_cache_from_env() -> Optional[ResultCache]:
    """The scheduler's default tier-0 construction: enabled unless
    ``PATHWAY_CACHE=0`` or ``PATHWAY_CACHE_RESULT=0``."""
    if not cache_enabled():
        return None
    if not config.get("cache.result"):
        return None
    return ResultCache()
