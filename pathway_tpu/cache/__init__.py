"""Multi-tier serve cache: cross-window results, query embeddings, and
generator prefix/KV blocks behind ONE bounded, thread-safe,
metrics-instrumented store.

The serve path is sharded, batched, and fault-tolerant; what it still
pays on every request is *repeat work*.  Production RAG traffic is
hot-headed across seconds-to-minutes windows ("Accelerating
Retrieval-Augmented Generation", arxiv 2412.15246 — which reports this
caching layer as the dominant RAG serving speedup), and three kinds of
repeat work dominate:

========================  ==========================================  =============================
tier                      keyed on                                    invalidation
========================  ==========================================  =============================
result (``result.py``)    (query text, index generation, k)           generation bump (structural)
                                                                      + TTL + LRU/bytes
embedding                 token ids digest                            LRU/bytes (+ optional TTL) —
(``embedding.py``)                                                    index mutations do NOT apply
generator KV              hash chain over token-id blocks             LRU/bytes (+ optional TTL) —
(``prefix.py``)                                                       content-addressed, can never
                                                                      alias a different prefix
========================  ==========================================  =============================

- A **result hit is a zero-dispatch serve**: the scheduler
  (serve/scheduler.py) resolves the ticket before admission — no
  coalescing window, no device work, bit-identical to the serve that
  populated the entry.
- An **embedding hit skips the stage-1 encode**: the serving path
  composes cached device rows with freshly encoded ones in the shared
  bucketed batch and dispatches a search-only kernel (ops/serving.py).
- A **KV-block hit skips generator prefill** for the shared prompt
  prefix (models/generator.py) — sub-linear prefill cost across RAG
  prompts sharing system-prompt + chunk prefixes.

Shared guarantees (``store.py``): LRU + byte-budget bounded; lookups
off the serve locks; ``cache.get`` / ``cache.put`` chaos sites where a
failed or corrupt entry degrades to a recompute (a miss), never a
failed or wrong serve; ``pathway_cache_*`` hit/miss/evict/bytes on the
one scrape surface plus a ``/serve_stats`` per-tier column.

Env knobs: ``PATHWAY_CACHE`` (global kill switch),
``PATHWAY_CACHE_RESULT[_BYTES|_TTL_S]``,
``PATHWAY_CACHE_EMBED[_BYTES|_TTL_S]`` (opt-in),
``PATHWAY_CACHE_KV[_BYTES|_TTL_S|_BLOCK]``.
"""

from .embedding import EmbeddingCache, embedding_cache_from_env
from .keys import (
    block_chain_keys,
    normalize_generation,
    query_key,
    result_key,
    token_ids_key,
)
from .prefix import PrefixKVCache, prefix_kv_cache_from_env
from .result import ResultCache, result_cache_from_env
from .store import CacheTier, cache_enabled

__all__ = [
    "CacheTier",
    "EmbeddingCache",
    "PrefixKVCache",
    "ResultCache",
    "block_chain_keys",
    "cache_enabled",
    "embedding_cache_from_env",
    "normalize_generation",
    "prefix_kv_cache_from_env",
    "query_key",
    "result_cache_from_env",
    "result_key",
    "token_ids_key",
]
