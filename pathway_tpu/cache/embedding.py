"""Tier 1: the query-embedding cache, keyed on token ids.

A result-cache miss on a *known* query (the common post-mutation shape:
absorb bumped the generation, the hot head repeats) still should not pay
the stage-1 trunk forward — the embedding depends on the tokenizer and
encoder params, NOT on index state, so it survives every generation
bump.  Entries are DEVICE-RESIDENT ``[d]`` rows (f32, a few KB each):

- the serve path composes cached rows with freshly encoded ones into
  the shared bucketed ``[B, d]`` batch on device (ops/serving.py
  ``_cached_embeddings``) and feeds the search-only kernels — an
  all-hit batch skips the encode launch entirely;
- ``SentenceEncoder.encode_to_device`` reuses the same tier for the
  ingest/QA encode paths.

Keeping rows device-resident means a hit never re-crosses the host link
(capturing a row is an async device slice; no fetch, no upload).  Byte
accounting uses the array's ``.nbytes`` metadata — no sync.  The tier is
per-encoder: token ids only mean anything relative to one tokenizer +
parameter set, so sharing a tier across encoders would be a correctness
bug, not a win.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from .. import config
from .keys import token_ids_key
from .store import CacheTier, cache_enabled

__all__ = ["EmbeddingCache", "embedding_cache_from_env"]


class EmbeddingCache:
    """Device-resident embedding rows behind one bounded ``CacheTier``.

    No integrity fingerprint: checksumming a device array is a hidden
    host sync (the analyzer's rule); corruption of immutable device
    buffers is not a failure mode the serve path defends against."""

    def __init__(
        self,
        max_bytes: Optional[int] = None,
        ttl_s: Optional[float] = None,
        max_entries: Optional[int] = None,
    ):
        if max_bytes is None:
            max_bytes = config.get("cache.embed_bytes")
        if ttl_s is None:
            ttl = config.get("cache.embed_ttl_s")
            ttl_s = ttl if ttl > 0 else None
        self._tier = CacheTier(
            "embedding",
            max_bytes=max_bytes,
            ttl_s=ttl_s,
            max_entries=max_entries,
        )
        # HBM ledger (observe/hbm.py): the cached rows are DEVICE
        # arrays, so the tier's byte accounting IS resident HBM; the
        # byte budget doubles as the exhaustion-ETA capacity
        from ..observe import hbm

        hbm.track(
            "cache", self, lambda c: {"embedding_rows": c._tier.bytes}
        )
        hbm.track_resource(
            "embedding_cache_bytes",
            self,
            lambda c: c._tier.bytes,
            lambda c: c._tier.max_bytes,
        )

    @property
    def stats(self):
        return self._tier.stats

    def __len__(self) -> int:
        return len(self._tier)

    def clear(self) -> None:
        self._tier.clear()

    def row_key(
        self, ids_row: np.ndarray, mask_row: np.ndarray, space: str = ""
    ) -> bytes:
        # ``space`` partitions the key space per PRODUCER: the serve
        # path stores metric-normalized rows from the fused trunk while
        # the plain encoder stores its own normalize-contract rows —
        # same token ids, different value spaces.  Folding the producer
        # signature into the key makes sharing one tier instance across
        # both paths safe by construction (no cross-space aliasing).
        return space.encode() + b"\x00" + token_ids_key(ids_row, mask_row)

    def lookup_rows(
        self,
        ids: np.ndarray,
        mask: np.ndarray,
        n_real: int,
        deadline=None,
        space: str = "",
    ) -> Tuple[List[Any], List[int], List[bytes]]:
        """Per-row lookup for a tokenized batch: returns ``(rows,
        miss_indices, keys)`` where ``rows[i]`` is a device ``[d]`` row
        or None, ``miss_indices`` the real rows needing a fresh encode,
        and ``keys`` each real row's cache key (for the capture pass).
        ``space`` is the producer's value-space signature (see
        ``row_key``)."""
        rows: List[Any] = []
        misses: List[int] = []
        keys: List[bytes] = []
        for i in range(n_real):
            key = self.row_key(ids[i], mask[i], space)
            keys.append(key)
            row = self._tier.get(key, deadline=deadline)
            rows.append(row)
            if row is None:
                misses.append(i)
        return rows, misses, keys

    def put_row(self, key: bytes, row: Any, deadline=None) -> bool:
        return self._tier.put(
            key, row, nbytes=getattr(row, "nbytes", 64), deadline=deadline
        )

    # -- durable warm state (serve/warmstate.py) -----------------------------
    def warm_state(self) -> dict:
        """Picklable snapshot: the device ``[d]`` rows are fetched to
        host np arrays here — the one place this tier pays a host
        transfer, and it runs on the snapshot cadence, never a serve."""
        entries = [
            (k, np.asarray(v), nbytes)
            for k, v, nbytes in self._tier.warm_entries()
        ]
        return {"kind": "embedding_cache", "entries": entries}

    def load_warm_state(self, state: dict) -> int:
        """Re-upload snapshotted rows to device and replay them through
        ``put`` (bring-up path; hits after restore are device-resident
        again, bit-identical to the writer's rows)."""
        if state.get("kind") != "embedding_cache":
            raise ValueError(
                f"not an embedding-cache warm state: {state.get('kind')!r}"
            )
        import jax.numpy as jnp

        loaded = 0
        for k, v, nbytes in state["entries"]:
            if self._tier.put(k, jnp.asarray(v), nbytes=nbytes):
                loaded += 1
        return loaded


def embedding_cache_from_env() -> Optional[EmbeddingCache]:
    """Serve-path construction: OPT-IN via ``PATHWAY_CACHE_EMBED=1``
    (gated on the global ``PATHWAY_CACHE`` switch).  Unlike the result
    tier, composing cached embeddings swaps the fused encode+search
    kernel for the split encode → search-only pair, so the tier changes
    low-order score bits across compositions — it defaults off and is
    enabled deliberately (bench/serving configs), while ``ServeScheduler``
    callers get the bit-stable result tier by default."""
    if not cache_enabled():
        return None
    if config.get("cache.embed"):
        return EmbeddingCache()
    return None
