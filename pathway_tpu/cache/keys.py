"""The ONE place serve-cache keys are derived.

Three consumers key on overlapping facts and must never drift:

- the coalescing scheduler's **in-window dedup** key — ``(text, index
  generation)``: only duplicates that observed the SAME index state may
  share a dispatched slot (serve/scheduler.py);
- the **cross-window result cache** key — the dedup key plus the serve
  config (the requested ``k``): a hit must be exactly the result the
  same request would have dispatched, so everything that shapes the
  response is in the key, and a generation bump (absorb / retrain /
  remove) makes a stale hit *structurally impossible* — the old entry's
  key simply can never be asked for again (generations are monotone);
- the **embedding cache** key — the token ids alone: an embedding
  depends on the tokenizer + trunk, NOT on index state, so it survives
  generation bumps (that asymmetry is the whole point of the tier — a
  result-cache miss on a known query still skips the stage-1 encode);
- the **generator prefix/KV** block keys — a hash CHAIN over token-id
  blocks, so two prompts sharing a prefix share exactly the cached
  blocks covering it (causal attention makes a block's K/V a pure
  function of the tokens up to its end).

Before this module the dedup key was derived inline in
``serve/scheduler.py`` — the result cache arriving with its own spelling
would have been the classic two-sites-one-fact drift bug.
"""

from __future__ import annotations

import hashlib
from typing import Any, Tuple

import numpy as np

__all__ = [
    "block_chain_keys",
    "normalize_generation",
    "query_key",
    "result_key",
    "token_ids_key",
]


def normalize_generation(generation: Any):
    """Canonical hashable spelling of an index generation — a plain
    ``int`` for a single index, a tuple of ints for a PARTITIONED fleet
    (one generation per partition, in partition order).  The vector form
    exists so a partition absorb on host B changes the whole fleet's
    key: caching on any single host's scalar would let host A keep
    serving rows that host B's absorb just invalidated."""
    if isinstance(generation, (list, tuple)):
        return tuple(int(g) for g in generation)
    return int(generation)


def query_key(text: Any, generation: Any) -> Tuple[str, Any]:
    """``(text, index generation)`` — the scheduler's in-window dedup
    item AND the result-cache key prefix.  Everything downstream treats
    it as opaque; only this function spells it.  ``generation`` may be a
    scalar or a fleet generation vector (see ``normalize_generation``)."""
    return (str(text), normalize_generation(generation))


def result_key(
    text: Any, generation: Any, k: int
) -> Tuple[str, Any, int]:
    """Cross-window serve-result cache key: the dedup key plus the
    requested ``k`` (the serve config that shapes the response rows).
    Keyed on the SAME ``query_key`` fields so the two can never drift."""
    return query_key(text, generation) + (int(k),)


def token_ids_key(ids_row: np.ndarray, mask_row: np.ndarray) -> bytes:
    """Embedding-cache key: a digest of one query's REAL token ids (the
    masked prefix).  Trimming the pad tail makes the key invariant to
    the batch's padded length — the same query tokenized into a longer
    batch must hit the row it cached from a shorter one (a pooled
    embedding never depends on pad tokens).  Deliberately independent of
    index generation: embeddings survive absorb/retrain, which is the
    whole point of the tier."""
    ids_row = np.ascontiguousarray(ids_row)
    real = np.ascontiguousarray(ids_row[np.asarray(mask_row) > 0])
    h = hashlib.blake2b(digest_size=16)
    h.update(str(real.dtype).encode())
    h.update(np.int64(real.size).tobytes())
    h.update(real.tobytes())
    return h.digest()


def block_chain_keys(ids_row: np.ndarray, n_blocks: int, block: int) -> list:
    """Generator prefix/KV block keys: ``key[j] = H(key[j-1] || tokens of
    block j)`` — content addressing over the PREFIX, so block j's key
    commits to every token before it (a block's K/V under causal
    attention is a function of exactly that prefix).  Two prompts
    sharing ``m`` leading blocks produce identical ``keys[:m]``."""
    ids_row = np.ascontiguousarray(ids_row)
    keys = []
    prev = b"pathway-kv-root"
    for j in range(n_blocks):
        h = hashlib.blake2b(digest_size=16)
        h.update(prev)
        h.update(ids_row[j * block : (j + 1) * block].tobytes())
        prev = h.digest()
        keys.append(prev)
    return keys
