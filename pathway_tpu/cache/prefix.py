"""Tier 2: generator prefix/KV reuse — content-addressed prefill blocks.

RAG prompts share long prefixes (system prompt + retrieved chunks vary
far more slowly than the trailing question), and under causal attention
a token's K/V depends ONLY on the tokens at or before it — so the K/V
of a shared prefix is a pure function of that prefix's token ids and can
be computed once and reused by every prompt that starts with it (the
paged-KV / prefix-caching design arxiv 2412.15246 credits with the
generator-side RAG speedup).

Storage is BLOCK-granular: prompt token ids are split into fixed-size
blocks (``PATHWAY_CACHE_KV_BLOCK``, default 32) and each block's K/V
``[n_layers, block, heads, head_dim]`` (device-resident, never fetched)
is stored under a hash CHAIN key — ``key[j] = H(key[j-1] || block_j
tokens)`` (cache/keys.py) — so a block's key commits to the entire
prefix before it, two prompts sharing ``m`` blocks share exactly
``m`` entries, and no entry can ever be reused under a different
prefix.  Lookup walks the chain until the first miss; the generator
prefills only the remainder.

Only FULL blocks of real (non-pad) tokens are cached, and at least one
real suffix token is always left for the prefill (the decode needs the
last prompt position's hidden state, which K/V blocks do not carry).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from .. import config
from .keys import block_chain_keys
from .store import CacheTier, cache_enabled

__all__ = ["PrefixKVCache", "prefix_kv_cache_from_env"]


class PrefixKVCache:
    def __init__(
        self,
        block: Optional[int] = None,
        max_bytes: Optional[int] = None,
        ttl_s: Optional[float] = None,
    ):
        if block is None:
            block = config.get("cache.kv_block")
        if max_bytes is None:
            max_bytes = config.get("cache.kv_bytes")
        if ttl_s is None:
            ttl = config.get("cache.kv_ttl_s")
            ttl_s = ttl if ttl > 0 else None
        self.block = max(1, int(block))
        self._tier = CacheTier("generator_kv", max_bytes=max_bytes, ttl_s=ttl_s)
        # prefill-token accounting for the sub-linearity claim: reused =
        # prompt tokens served from cached K/V, computed = tokens the
        # prefill actually ran the trunk over
        self.stats_tokens = {"reused": 0, "computed": 0}
        from .. import observe

        observe.register_provider(self)
        # HBM ledger (observe/hbm.py): prefill K/V blocks are device
        # arrays — the tier's byte accounting is resident HBM, and the
        # byte budget is the exhaustion-ETA capacity
        from ..observe import hbm

        hbm.track(
            "cache", self, lambda c: {"prefill_blocks": c._tier.bytes}
        )
        hbm.track_resource(
            "prefill_cache_bytes",
            self,
            lambda c: c._tier.bytes,
            lambda c: c._tier.max_bytes,
        )

    @property
    def stats(self):
        return self._tier.stats

    def __len__(self) -> int:
        return len(self._tier)

    def clear(self) -> None:
        self._tier.clear()

    # -- lookup --------------------------------------------------------------
    def cacheable_blocks(self, n_real: int) -> int:
        """How many full blocks of a prompt with ``n_real`` real tokens
        are cacheable: full real blocks, minus one block if the prompt
        ends exactly on a boundary (the prefill must keep >= 1 real
        token to produce the first decode logits)."""
        n_blocks = n_real // self.block
        if n_blocks and n_blocks * self.block == n_real:
            n_blocks -= 1
        return n_blocks

    def bucket_tokens(self, n_matched: int) -> int:
        """Round a matched-prefix token count DOWN to a power-of-two
        block multiple.  The prefix split is a compile-shape dimension
        in every decode path (the batch KV decode's ``P`` and the
        continuous engine's per-join prefill) — bucketing keeps it at
        O(log) distinct values, so a mix of prompt families cannot
        compile one program per prefix length."""
        bucket = 0
        step = self.block
        while step <= int(n_matched):
            bucket = step
            step *= 2
        return bucket

    def match(
        self, ids_row: np.ndarray, n_real: int, deadline=None
    ) -> Tuple[int, List[Any], List[bytes]]:
        """Longest cached prefix of one prompt row: returns ``(n_tokens,
        blocks, keys)`` — the matched token count (a block multiple),
        the cached block values in order, and the chain keys of EVERY
        cacheable block (matched or not; the capture pass stores the
        missing tail under them)."""
        n_blocks = self.cacheable_blocks(int(n_real))
        keys = block_chain_keys(ids_row, n_blocks, self.block)
        blocks: List[Any] = []
        for key in keys:
            value = self._tier.get(key, deadline=deadline)
            if value is None:
                break
            blocks.append(value)
        return len(blocks) * self.block, blocks, keys

    # -- capture -------------------------------------------------------------
    def admit(
        self,
        keys: List[bytes],
        n_matched_blocks: int,
        get_block: Callable[[int], Any],
        deadline=None,
    ) -> int:
        """Store the blocks beyond the matched prefix.  ``get_block(j)``
        returns block ``j``'s K/V value (the generator slices it from
        the decode's returned buffers — an async device op, no fetch).
        Returns how many blocks were admitted."""
        admitted = 0
        for j in range(n_matched_blocks, len(keys)):
            try:
                value = get_block(j)
            except Exception:
                self._tier._count("failures")
                break
            nbytes = sum(
                int(getattr(part, "nbytes", 64)) for part in value
            )
            if self._tier.put(keys[j], value, nbytes=nbytes, deadline=deadline):
                admitted += 1
        return admitted

    def note_prefill(self, reused: int, computed: int) -> None:
        self.stats_tokens["reused"] += int(reused)
        self.stats_tokens["computed"] += int(computed)

    def observe_metrics(self):
        for kind, value in self.stats_tokens.items():
            yield (
                "counter",
                "pathway_cache_prefill_tokens_total",
                {**self._tier.labels, "kind": kind},
                value,
            )


def prefix_kv_cache_from_env() -> Optional[PrefixKVCache]:
    """Generator default: enabled unless ``PATHWAY_CACHE=0`` or
    ``PATHWAY_CACHE_KV=0`` (pure reuse of bit-reproducible K/V — the
    warm decode is bit-identical to the cold one, see
    models/generator.py)."""
    if not cache_enabled():
        return None
    if not config.get("cache.kv"):
        return None
    return PrefixKVCache()
