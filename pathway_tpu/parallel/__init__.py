"""pw.parallel — device mesh + sharding utilities.

The TPU-native replacement for the reference's worker topology
(PATHWAY_THREADS/PROCESSES over timely workers, src/engine/dataflow/
config.rs:88-121; exchange over shared-mem/TCP, external/timely-dataflow/
communication/): here parallelism is a ``jax.sharding.Mesh`` over TPU chips,
data placement is ``NamedSharding``, and the exchange is XLA collectives over
ICI (SURVEY.md §5.8).
"""

from . import distributed
from .exchange import ExchangePlane, gather_table_rows, get_plane
from .shards import FleetPartitionMap, ShardGroup, serve_shards
from .mesh import (
    current_mesh,
    data_axis_size,
    device_count,
    global_zeros,
    host_to_global,
    is_multiprocess,
    make_mesh,
    replicated,
    set_mesh,
    shard_cols,
    shard_rows,
)

__all__ = [
    "distributed",
    "FleetPartitionMap",
    "ShardGroup",
    "serve_shards",
    "ExchangePlane",
    "get_plane",
    "gather_table_rows",
    "make_mesh",
    "current_mesh",
    "set_mesh",
    "device_count",
    "data_axis_size",
    "shard_rows",
    "shard_cols",
    "replicated",
    "is_multiprocess",
    "host_to_global",
    "global_zeros",
]
