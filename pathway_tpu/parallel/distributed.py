"""Multi-process / multi-host execution — the comms backend.

Replaces the reference's timely TCP cluster (src/engine/dataflow/config.rs:
104-121 — PATHWAY_PROCESSES/PATHWAY_PROCESS_ID/PATHWAY_FIRST_PORT building a
``CommunicationConfig::Cluster``; zero-copy exchange in external/
timely-dataflow/communication/src/allocator/zero_copy/tcp.rs) with the
jax-native runtime: ``jax.distributed`` for process coordination (gRPC
coordination service hosted by process 0) and XLA collectives over ICI/DCN
for the data plane.

Execution model (the honest jax-native design, documented per-layer):

- **SPMD program, worker-sharded host plane.** Like the reference — where
  the user's script runs once per worker and each worker owns a shard
  (docs/2.developers/4.user-guide/80.advanced/10.worker-architecture.md:
  37-48) — every process builds the identical graph.  The host relational
  plane is SHARDED: each rank ingests its owned-key slice of every source
  (or its file split, for partitioned readers), stateful operators exchange
  rows by group/join key over the TCP exchange plane
  (``parallel/exchange.py``), and sinks gather to rank 0 for exactly-once
  output.  Commit timestamps are agreed per tick: ranks exchange
  (proposed_ts, moved, finished, stop) and deterministically adopt the max
  proposal (engine/executor.py ``_step_dist``).
- **Sharded device data plane.** Device-resident state (the KNN embedding
  matrix, model weights) lives on ONE global mesh spanning every process's
  devices (`global_mesh()`); each process addresses only its local shard.
  Exchange between shards is XLA collectives (all_gather/psum/ppermute)
  inside jit — the analog of timely's exchange channels — riding ICI within
  a slice and DCN across hosts, never the Python layer.  Operators that
  drive a multi-process mesh (external indexes) run REPLICATED on the host
  plane so every rank issues the same jit calls (SPMD discipline).

Topology env vars (set by ``pathway-tpu spawn`` — cli.py):
  PATHWAY_PROCESSES            total process count (default 1 — no-op)
  PATHWAY_PROCESS_ID           this process's rank
  PATHWAY_COORDINATOR_ADDRESS  host:port of process 0's coordination service

On CPU (tests / the virtual mesh) cross-process collectives use the gloo
backend; on TPU pods jax's default (device runtime over ICI/DCN) is used.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, Optional

import jax

from .. import config, observe
from ..robust import log_once
from ..robust import inject as _inject

__all__ = [
    "topology_from_env",
    "maybe_initialize",
    "is_distributed",
    "process_id",
    "process_count",
    "is_coordinator",
    "barrier",
    "broadcast_obj",
]

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_initialized = False


def topology_from_env() -> tuple[int, int, Optional[str]]:
    """(processes, process_id, coordinator_address) from PATHWAY_* env
    (reference: Config::from_env, src/engine/dataflow/config.rs:88-121)."""
    processes = config.get("parallel.processes")
    pid = config.get("parallel.process_id")
    addr = config.get("parallel.coordinator_address") or None
    if addr is None:
        first_port = config.get("parallel.first_port")
        if first_port:
            addr = f"127.0.0.1:{first_port}"
    return processes, pid, addr


def maybe_initialize() -> bool:
    """Join the process cluster if PATHWAY_PROCESSES > 1.  Idempotent; safe
    to call from ``pw.run()`` on every process.  Returns True when running
    distributed (after this call).

    Must run before the first jax backend touch in this process.  The TPU
    plugin registers at interpreter startup via sitecustomize, so when
    JAX_PLATFORMS=cpu is requested (tests, virtual meshes) the platform is
    also flipped through jax.config — env alone does not survive the
    pre-registration."""
    global _initialized
    with _lock:
        if _initialized:
            return True
        processes, pid, addr = topology_from_env()
        if processes <= 1:
            return False
        if addr is None:
            raise RuntimeError(
                "PATHWAY_PROCESSES > 1 but no PATHWAY_COORDINATOR_ADDRESS / "
                "PATHWAY_FIRST_PORT — launch via `pathway-tpu spawn` or set "
                "the topology env vars explicitly"
            )
        if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
            jax.config.update("jax_platforms", "cpu")
            # cross-process CPU collectives need an explicit implementation
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        jax.distributed.initialize(
            coordinator_address=addr,
            num_processes=processes,
            process_id=pid,
        )
        logger.info(
            "joined process cluster: rank %d/%d via %s", pid, processes, addr
        )
        _initialized = True
        return True


def is_distributed() -> bool:
    return jax.process_count() > 1


def process_id() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_coordinator() -> bool:
    return jax.process_index() == 0


def _client():
    from jax._src import distributed as _dist

    return _dist.global_state.client


# degraded control-plane operations, by site (barrier / broadcast):
# the coordination service timing out or faulting must cost AGREEMENT,
# never a hung serve — callers get a flagged local-only answer
_degraded_counters: Dict[str, observe.Counter] = {}


def _count_degraded(site: str) -> None:
    c = _degraded_counters.get(site)
    if c is None:
        c = _degraded_counters[site] = observe.counter(
            "pathway_dist_degraded_total", site=site
        )
    c.inc()


def barrier(name: str, timeout_ms: int = 60_000, deadline=None) -> bool:
    """Host-side control-plane barrier over the coordination service — the
    analog of timely's progress frontier sync at commit ticks (workers agree
    a timestamp is closed before results are emitted downstream).

    Returns True when every process reached the barrier, False when the
    sync DEGRADED (chaos site ``dist.barrier`` armed, coordination
    timeout, or service error): the caller proceeds on local knowledge
    with the degradation counted on
    ``pathway_dist_degraded_total{site="barrier"}`` — a serve tier must
    never hang on its own control plane."""
    try:
        _inject.fire("dist.barrier", deadline=deadline)
        if not is_distributed():
            return True
        client = _client()
        if client is None:  # pragma: no cover - initialize() always sets it
            raise RuntimeError("distributed runtime not initialized")
        client.wait_at_barrier(name, timeout_in_ms=timeout_ms)
        return True
    except Exception as exc:
        _count_degraded("barrier")
        log_once(
            f"dist.barrier:{type(exc).__name__}",
            "control-plane barrier %r degraded (%r); proceeding local-only",
            name,
            exc,
        )
        return False


def broadcast_obj(obj=None, *, name: str, timeout_ms: int = 60_000,
                  deadline=None):
    """Broadcast a small picklable control-plane object (config, rendezvous
    info, a per-tick chosen timestamp) from the coordinator to every process
    via the coordination service's KV store.  Call with ``obj`` on the
    coordinator and ``obj=None`` elsewhere; returns the coordinator's value
    everywhere.

    ``name`` must be unique per broadcast (include a tick/sequence number for
    repeated control-plane values: ``name=f"commit/{tick}"``) — the KV store
    rejects overwrites, which makes an accidental reuse fail loudly instead
    of silently serving a stale value to racing followers.

    Degrade semantics (chaos site ``dist.broadcast``, KV timeout, service
    error): returns the LOCAL ``obj`` — the coordinator's own value, or
    None on a follower — counted on
    ``pathway_dist_degraded_total{site="broadcast"}``.  Consumers (e.g.
    warm-state generation agreement, serve/warmstate.py) treat a local-only
    answer as flagged agreement, never as a reason to hang or fail."""
    try:
        _inject.fire("dist.broadcast", deadline=deadline)
        if not is_distributed():
            return obj
        import base64
        import pickle

        client = _client()
        key = f"pathway_tpu/bcast/{name}"
        if is_coordinator():
            client.key_value_set(
                key, base64.b64encode(pickle.dumps(obj)).decode()
            )
            return obj
        raw = client.blocking_key_value_get(key, timeout_ms)
        return pickle.loads(base64.b64decode(raw))
    except Exception as exc:
        _count_degraded("broadcast")
        log_once(
            f"dist.broadcast:{type(exc).__name__}",
            "control-plane broadcast %r degraded (%r); serving local value",
            name,
            exc,
        )
        return obj
