"""Mesh construction and sharding helpers.

Axes convention: ``("data", "model")`` — "data" shards index rows / batch
(the analog of the reference's per-worker key shard, src/engine/value.rs:38);
"model" shards large model weights (tensor parallelism).  Multi-host wires in
through ``jax.distributed.initialize`` + the same mesh spanning all hosts'
devices (DCN between hosts, ICI within a slice).
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import config

__all__ = [
    "make_mesh",
    "current_mesh",
    "set_mesh",
    "device_count",
    "data_axis_size",
    "shard_rows",
    "shard_cols",
    "replicated",
    "is_multiprocess",
    "host_to_global",
    "global_zeros",
]

_lock = threading.Lock()
_current_mesh: Optional[Mesh] = None


def device_count() -> int:
    return len(jax.devices())


def make_mesh(
    n_data: Optional[int] = None,
    n_model: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a ("data", "model") mesh over the available devices.

    Defaults: all devices on the data axis (index sharding), model axis 1.
    Env overrides: PATHWAY_TPU_DATA_SHARDS / PATHWAY_TPU_MODEL_SHARDS."""
    devices = list(devices if devices is not None else jax.devices())
    n_model = config.get("parallel.model_shards") or n_model
    if n_data is None:
        n_data = config.get("parallel.data_shards") or (
            len(devices) // n_model
        )
    needed = n_data * n_model
    if needed > len(devices):
        raise ValueError(
            f"mesh {n_data}x{n_model} needs {needed} devices, have {len(devices)}"
        )
    grid = np.array(devices[:needed]).reshape(n_data, n_model)
    return Mesh(grid, axis_names=("data", "model"))


def set_mesh(mesh: Optional[Mesh]) -> None:
    global _current_mesh
    with _lock:
        _current_mesh = mesh


def current_mesh(create: bool = True) -> Optional[Mesh]:
    """The process-wide mesh (created lazily over all devices)."""
    global _current_mesh
    with _lock:
        if _current_mesh is None and create:
            _current_mesh = make_mesh()
        return _current_mesh


def data_axis_size(mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or current_mesh()
    return mesh.shape["data"]


def shard_rows(mesh: Optional[Mesh] = None) -> NamedSharding:
    """Rows split across the data axis (index/embedding matrices)."""
    mesh = mesh or current_mesh()
    return NamedSharding(mesh, P("data", None))


def shard_cols(mesh: Optional[Mesh] = None) -> NamedSharding:
    mesh = mesh or current_mesh()
    return NamedSharding(mesh, P(None, "data"))


def replicated(mesh: Optional[Mesh] = None) -> NamedSharding:
    mesh = mesh or current_mesh()
    return NamedSharding(mesh, P())


def is_multiprocess(mesh: Mesh) -> bool:
    """True when the mesh spans devices of more than one host process (the
    multi-host path: jax.distributed initialized, devices not all
    addressable)."""
    me = jax.process_index()
    return any(d.process_index != me for d in mesh.devices.flat)


def host_to_global(value, mesh: Mesh, spec) -> jax.Array:
    """Put host data onto a (possibly multi-process) mesh.

    Single-process meshes use a plain device_put.  On a multi-process mesh
    ``device_put`` cannot target non-addressable devices, so the global array
    is assembled from per-process local data — SPMD replicas all hold the
    full host value (see parallel/distributed.py execution model) and each
    process contributes the shards it can address."""
    sharding = NamedSharding(mesh, spec)
    arr = np.asarray(value)
    if not is_multiprocess(mesh):
        return jax.device_put(arr, sharding)
    return jax.make_array_from_process_local_data(
        sharding, arr, global_shape=arr.shape
    )


def global_zeros(shape, dtype, mesh: Mesh, spec) -> jax.Array:
    """Allocate a zero-filled global array directly on the mesh (works on
    multi-process meshes, where host-side device_put cannot)."""
    sharding = NamedSharding(mesh, spec)
    import jax.numpy as jnp

    return jax.jit(
        lambda: jnp.zeros(shape, dtype=dtype), out_shardings=sharding
    )()
