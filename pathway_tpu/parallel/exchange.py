"""Host-side data-exchange plane for multi-process streaming.

The reference exchanges records between workers over timely's zero-copy TCP
allocator (external/timely-dataflow/communication/src/allocator/zero_copy/
{tcp,bytes_exchange}.rs) with the topology from CommunicationConfig::Cluster
(src/engine/dataflow/config.rs:72-82).  The jax-native build keeps the DEVICE
data plane on XLA collectives (parallel/distributed.py), but the host-side
relational engine still needs a record exchange: connector reads are split
across processes and rows must reach the process that owns their key
(reference ``reshard`` after ingest, src/engine/dataflow.rs:3314).

This module is that exchange: a full TCP mesh between the PATHWAY_PROCESSES
ranks, carrying pickled ``Delta`` shards as BSP collectives.  Every rank
executes the SAME sequence of collective calls per commit tick (the engine
sweeps operators in one global topological order — engine/graph.py), so each
call is identified by an ``(edge, seq)`` pair and deadlock is structurally
impossible; out-of-order arrivals park in an inbox keyed by that pair.

Rendezvous rides the jax coordination service's KV store (the ranks already
share it for jax.distributed), so no extra ports need configuring: each rank
publishes its listen address once at startup.

A peer dying mid-stream surfaces as a broken connection; a peer that HANGS
(SIGSTOP, network partition with the socket still open) is caught by the
heartbeat: every rank pings every peer each ``PATHWAY_EXCHANGE_HEARTBEAT``
seconds (default 2), and a collective waiting on a peer that has been silent
for ``PATHWAY_EXCHANGE_HEARTBEAT_TIMEOUT`` seconds (default 8) raises
``PeerLost`` instead of stalling for the full collective timeout.  Every
blocked collective then raises, aborting this rank's run too — the analog of
the reference's worker-panic propagation (src/engine/dataflow.rs:5667-5676).
Recovery is a cluster restart from persisted snapshots (per-rank input logs
+ offsets), mirroring docs/.../10.worker-architecture.md:58-61.

Transport hardening: the listener binds ONLY the advertised interface
(loopback for single-host clusters), and every connection must open with a
32-byte session secret minted by rank 0 and distributed over the jax
coordination KV — frames are pickled, so an unauthenticated listener would
hand arbitrary-code-execution to anyone who could reach the port.
"""

from __future__ import annotations

import errno
import hmac
import pickle
import secrets
import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Set, Tuple

from .. import config, observe
from ..robust import RetryPolicy, inject

__all__ = ["ExchangePlane", "FramedStream", "get_plane", "close_plane"]

_HDR = struct.Struct("!Q")
_TOKEN_LEN = 32
_HB_EDGE = "__hb__"
# clean-shutdown control frame: a rank leaving on purpose announces it,
# so its disconnect is goodbye, not PeerLost
_BYE_EDGE = "__bye__"

# socket errors that mean "try the same write again", NOT "the peer is
# gone": interrupted syscalls and transient kernel buffer exhaustion.
# Anything else (ECONNRESET, EPIPE, ...) stays fatal for the stream.
_TRANSIENT_ERRNOS = frozenset(
    {errno.EINTR, errno.EAGAIN, errno.EWOULDBLOCK, errno.ENOBUFS, errno.ENOMEM}
)
# pre-frame send retries (fault site "exchange.send"): safe only before
# the first byte of a frame is on the wire
_SEND_RETRY = RetryPolicy(attempts=3, base_delay_s=0.005, max_delay_s=0.1)


def _hb_interval() -> float:
    return config.get("parallel.exchange_heartbeat_s")


def _hb_timeout() -> float:
    return config.get("parallel.exchange_heartbeat_timeout_s")


class PeerLost(RuntimeError):
    """A cluster peer disconnected (crashed or exited early)."""


class FramedStream:
    """One token-authenticated, length-prefixed pickle stream — the
    point-to-point wire the serve fabric rides (serve/fabric.py), reusing
    this plane's framing discipline (``_HDR`` length prefix, 32-byte
    session secret checked with ``hmac.compare_digest`` BEFORE any
    ``pickle.loads``, ``_recv_exact`` chunked reads).

    Unlike the BSP mesh above, a ``FramedStream`` is a plain muxable
    duplex channel: any thread may ``send`` (serialized by an internal
    lock); exactly ONE thread should ``recv`` (the fabric's per-link
    receiver).  A broken or closed connection surfaces as ``PeerLost``;
    a recv timeout surfaces as ``socket.timeout`` so callers can poll."""

    __slots__ = ("_sock", "_send_lock", "_closed")

    def __init__(self, sock: socket.socket):
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        self._send_lock = threading.Lock()
        self._closed = False

    @classmethod
    def connect(
        cls, host: str, port: int, token: bytes, timeout: float = 5.0
    ) -> "FramedStream":
        """Dial a listener and present the session secret (client side)."""
        s = socket.create_connection((host, port), timeout=timeout)
        try:
            s.sendall(token)
        except OSError as exc:
            s.close()
            raise PeerLost(f"fabric connect to {host}:{port} failed: {exc!r}")
        s.settimeout(None)
        return cls(s)

    @classmethod
    def accept(
        cls, conn: socket.socket, token: bytes, timeout: float = 10.0
    ) -> "FramedStream":
        """Authenticate one accepted connection (server side): the first
        ``_TOKEN_LEN`` bytes must equal the session secret or the
        connection is closed before any frame is parsed."""
        try:
            conn.settimeout(timeout)
            offered = _recv_exact(conn, _TOKEN_LEN)
            if not hmac.compare_digest(offered, token):
                raise PermissionError("bad fabric token")
            conn.settimeout(None)
        except BaseException:
            try:
                conn.close()
            except OSError:
                pass
            raise
        return cls(conn)

    def send(self, obj: Any) -> None:
        """Pickle + frame + write ``obj`` (thread-safe)."""
        payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        frame = _HDR.pack(len(payload)) + payload
        try:
            with self._send_lock:
                self._sock.sendall(frame)
        except OSError as exc:
            raise PeerLost(f"fabric send failed: {exc!r}") from exc

    def recv(self, timeout: Optional[float] = None) -> Any:
        """Next frame, unpickled.  ``socket.timeout`` when ``timeout``
        elapses with no frame started; ``PeerLost`` on disconnect."""
        try:
            self._sock.settimeout(timeout)
            hdr = _recv_exact(self._sock, _HDR.size)
            # once a header landed the frame is in flight: finish it
            # without the poll timeout cutting a slow payload short
            self._sock.settimeout(None)
            (length,) = _HDR.unpack(hdr)
            return pickle.loads(_recv_exact(self._sock, length))
        except socket.timeout:
            raise
        except (OSError, ConnectionError, EOFError) as exc:
            raise PeerLost(f"fabric recv failed: {exc!r}") from exc

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


class ExchangePlane:
    """Full-mesh TCP exchange among ``nproc`` ranks with BSP semantics."""

    def __init__(self, rank: int, nproc: int, kv_set, kv_get, namespace: str = "0"):
        self.rank = rank
        self.nproc = nproc
        self._send: Dict[int, socket.socket] = {}
        self._send_locks: Dict[int, threading.Lock] = {}
        self._inbox: Dict[Tuple[str, int, int], Any] = {}
        self._cv = threading.Condition()
        self._dead: Optional[BaseException] = None
        self._closed = False
        # peers that announced clean shutdown (__bye__): their later
        # disconnect is expected, not PeerLost — but a collective still
        # WAITING on one of them fails immediately with a clear message
        self._peer_closed: Set[int] = set()
        self._recv_threads: List[threading.Thread] = []
        self._last_recv: Dict[int, float] = {}
        # flight-recorder accounting: per-peer wire traffic counters
        # (bumped inline on the send/recv paths — plain int adds) and
        # liveness gauges sampled at scrape time (pathway_exchange_*)
        self._bytes_in: Dict[int, int] = {}
        self._bytes_out: Dict[int, int] = {}
        self._chunks_in: Dict[int, int] = {}
        self._chunks_out: Dict[int, int] = {}
        self._observe_id = observe.next_id()
        observe.register_provider(self)

        # session secret: rank 0 mints it, everyone reads it from the jax
        # coordination KV (which only cluster members share).  Connections
        # that cannot present it are dropped before any pickle.loads runs.
        if rank == 0:
            self._token = secrets.token_bytes(_TOKEN_LEN)
            kv_set(f"pathway_tpu/exch/{namespace}/token", self._token.hex())
        else:
            self._token = bytes.fromhex(
                kv_get(f"pathway_tpu/exch/{namespace}/token")
            )

        # rendezvous: publish my listen addr, read everyone else's.  Bind
        # ONLY the advertised interface (loopback for single-host clusters,
        # the NIC that routes to the coordinator for multi-host) — frames
        # are pickled, so the listener must not face the open network.
        host = _advertise_host()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, 0))
        self._listener.listen(nproc)
        _, port = self._listener.getsockname()
        kv_set(f"pathway_tpu/exch/{namespace}/{rank}", f"{host}:{port}")
        addrs: Dict[int, Tuple[str, int]] = {}
        for peer in range(nproc):
            if peer == self.rank:
                continue
            raw = kv_get(f"pathway_tpu/exch/{namespace}/{peer}")
            h, p = raw.rsplit(":", 1)
            addrs[peer] = (h, int(p))

        # accept loop (peers dial me), started before dialing out.  Junk or
        # unauthenticated connections are closed and do not consume a slot.
        accepted: Dict[int, socket.socket] = {}
        accept_done = threading.Event()

        def _accept():
            deadline = time.monotonic() + 60
            try:
                while len(accepted) < nproc - 1 and time.monotonic() < deadline:
                    conn, _ = self._listener.accept()
                    try:
                        conn.settimeout(10)
                        peer_rank = _HDR.unpack(_recv_exact(conn, _HDR.size))[0]
                        offered = _recv_exact(conn, _TOKEN_LEN)
                        if not hmac.compare_digest(offered, self._token):
                            raise PermissionError("bad exchange token")
                        conn.settimeout(None)
                        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                        accepted[int(peer_rank)] = conn
                    except Exception:
                        try:
                            conn.close()
                        except OSError:
                            pass
            finally:
                accept_done.set()

        acceptor = threading.Thread(target=_accept, daemon=True, name="exch-accept")
        acceptor.start()
        for peer, (h, p) in addrs.items():
            s = socket.create_connection((h, p), timeout=60)
            # the 60s is a CONNECT timeout only: a permanent per-op timeout
            # would misread any >60s stall (peer inside a long jit compile
            # with full TCP buffers) as peer death and abort a healthy cluster
            s.settimeout(None)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            s.sendall(_HDR.pack(self.rank) + self._token)
            self._send[peer] = s
            self._send_locks[peer] = threading.Lock()
        if not accept_done.wait(timeout=60):  # pragma: no cover - rendezvous hang
            raise RuntimeError("exchange plane rendezvous timed out")
        acceptor.join()
        if len(accepted) != nproc - 1:  # pragma: no cover
            raise RuntimeError(
                f"exchange plane rendezvous incomplete: {sorted(accepted)}"
            )
        # the recv threads tick heartbeats inline from _deserialize, so the
        # ping frame and tick clock must exist BEFORE the first frame can
        # arrive — assigning them after the thread starts races an early
        # sender into an AttributeError-turned-PeerLost at startup
        ping = pickle.dumps((_HB_EDGE, 0, None), protocol=pickle.HIGHEST_PROTOCOL)
        self._ping_frame = _HDR.pack(len(ping)) + ping
        self._last_tick = time.monotonic()
        now = time.monotonic()
        for peer, conn in accepted.items():
            self._last_recv[peer] = now
            t = threading.Thread(
                target=self._recv_loop, args=(peer, conn), daemon=True,
                name=f"exch-recv-{peer}",
            )
            t.start()
            self._recv_threads.append(t)
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, daemon=True, name="exch-heartbeat"
        )
        self._hb_thread.start()

    # -- wire ---------------------------------------------------------------
    def _recv_loop(self, peer: int, conn: socket.socket) -> None:
        def alive() -> None:
            # refresh per CHUNK, not per frame: a frame larger than the
            # link can carry in hb_timeout seconds must still count as
            # liveness, or slow bulk transfers would read as a hung peer
            self._last_recv[peer] = time.monotonic()

        try:
            while True:
                hdr = _recv_exact(conn, _HDR.size, on_chunk=alive)
                (length,) = _HDR.unpack(hdr)
                payload = _recv_exact(conn, length, on_chunk=alive)
                self._bytes_in[peer] = (
                    self._bytes_in.get(peer, 0) + _HDR.size + length
                )
                self._chunks_in[peer] = self._chunks_in.get(peer, 0) + 1
                edge, seq, obj = self._deserialize(peer, payload)
                with self._cv:
                    self._last_recv[peer] = time.monotonic()
                    if edge == _BYE_EDGE:
                        # clean shutdown announced: the disconnect that
                        # follows is goodbye, not peer death
                        self._peer_closed.add(peer)
                    elif edge != _HB_EDGE:
                        self._inbox[(edge, seq, peer)] = obj
                    self._cv.notify_all()
        except BaseException as exc:  # noqa: BLE001 - any failure kills the run
            with self._cv:
                if (
                    not self._closed
                    and peer not in self._peer_closed
                    and self._dead is None
                ):
                    self._dead = PeerLost(
                        f"exchange peer {peer} disconnected: {exc!r}"
                    )
                self._cv.notify_all()

    def _send_to(self, peer: int, edge: str, seq: int, obj: Any) -> None:
        parts = self._serialize(edge, seq, obj)
        total = sum(len(p) for p in parts)
        # chaos fault site, fired before the first byte of the frame is
        # on the wire — the only point where a retry cannot desync the
        # stream.  Injected faults retry with backoff under _SEND_RETRY;
        # REAL transient socket errors are handled separately inside
        # _send_frame's slice loop (this site has no real work of its
        # own, so it deliberately bypasses retry_call — its retry
        # counters must never suggest production sends were retried
        # here).  An exhausted budget is a send failure: PeerLost.
        for attempt in range(_SEND_RETRY.attempts):
            try:
                inject.fire("exchange.send")
                break
            except Exception as exc:
                if attempt + 1 >= _SEND_RETRY.attempts:
                    raise PeerLost(
                        f"send to exchange peer {peer} failed: {exc!r}"
                    ) from exc
                time.sleep(_SEND_RETRY.delay_s("exchange.send", attempt + 1))
        try:
            with self._send_locks[peer]:
                # header + chunks as sequential writes under the one lock:
                # never joins the multi-hundred-MB payload into a single
                # buffer (the old dumps+concat peaked at ~3x payload RSS)
                self._send_frame(peer, _HDR.pack(total))
                for part in parts:
                    self._send_frame(peer, part)
                # one wire MESSAGE sent — the unit the receiver counts
                # too (_recv_loop's chunks_in), so in/out stay
                # comparable; under the send lock like the ping-path
                # increments, so concurrent senders cannot lose counts
                self._chunks_out[peer] = self._chunks_out.get(peer, 0) + 1
        except OSError as exc:
            raise PeerLost(f"send to exchange peer {peer} failed: {exc!r}") from exc

    def _serialize(self, edge: str, seq: int, obj: Any) -> List[bytes]:
        """Chunked pickling with INLINE heartbeat ticks.

        ``pickle.dumps`` of a multi-hundred-MB shard is one GIL-holding C
        call: the heartbeat thread cannot run for its whole duration, so a
        HEALTHY rank serializing for longer than the heartbeat timeout went
        silent and got declared PeerLost by its peers (ADVICE r5 #2 — a
        false positive that aborts a healthy cluster).  Streaming the
        pickle through a Python sink bounds each GIL-held stretch to one
        pickler frame (~64 KB) / one large-bytes write, and every chunk
        boundary pings the peers directly from THIS thread — liveness no
        longer depends on the starved heartbeat thread being scheduled.
        Returns the chunk list unjoined; ``_send_to`` streams it."""
        sink = _ChunkSink(self._hb_tick)
        pickle.Pickler(sink, protocol=pickle.HIGHEST_PROTOCOL).dump(
            (edge, seq, obj)
        )
        return sink.parts()

    def _deserialize(self, peer: int, payload: bytes) -> Any:
        """Recv-side mirror of ``_serialize`` (the same ADVICE r5 #2 false
        positive): one C-level ``pickle.loads`` of a multi-hundred-MB frame
        holds the GIL past the heartbeat timeout, so a healthy RECEIVING
        rank went silent mid-load and got declared PeerLost.  Unpickling
        through a Python source bounds each GIL-held stretch to one read;
        every read both pings the peers inline (from this recv thread) and
        refreshes the sending peer's liveness clock — its frame is still
        being processed, so the peer was alive when the bytes arrived and
        queued pings behind this frame must not read as its silence."""

        def tick() -> None:
            self._last_recv[peer] = time.monotonic()
            self._hb_tick()

        return pickle.Unpickler(_ChunkSource(payload, tick)).load()

    def _hb_tick(self) -> None:
        """Best-effort heartbeat pings issued inline from a busy thread
        (serialization chunk boundaries); rate-limited to half the
        heartbeat interval.  Skips peers whose send lock is held — an
        in-flight send to them already proves our liveness."""
        now = time.monotonic()
        if now - self._last_tick < _hb_interval() / 2:
            return
        self._last_tick = now
        with self._cv:
            if self._closed or self._dead is not None:
                return
        for peer, lock in self._send_locks.items():
            if lock.acquire(blocking=False):
                try:
                    if self._send_frame(peer, self._ping_frame, best_effort=True):
                        self._chunks_out[peer] = (
                            self._chunks_out.get(peer, 0) + 1
                        )
                except PeerLost as exc:
                    # a ping partially written and then stalled against a
                    # silent peer: the byte stream to it is corrupt past
                    # repair — surface it exactly like _heartbeat_loop
                    # does instead of letting the next send desync the
                    # receiver
                    with self._cv:
                        if not self._closed and self._dead is None:
                            self._dead = exc
                        self._cv.notify_all()
                    return
                except OSError:
                    pass  # recv loop surfaces the death with context
                finally:
                    lock.release()

    def _send_frame(self, peer: int, frame: bytes, best_effort: bool = False) -> bool:
        """Chunked send with stall detection (caller holds the send lock).

        A plain ``sendall`` with no timeout would block forever on a hung
        receiver with full TCP buffers — BEFORE this rank ever reaches
        ``_wait``'s heartbeat check.  Send in timed slices instead; a slice
        that makes no progress while the peer has ALSO been silent past the
        heartbeat timeout means the peer is hung, not merely slow (a slow
        but healthy peer keeps heartbeating us the whole time).

        ``best_effort`` (heartbeat pings): give up quietly if the socket
        won't take the first byte — data is queued, which proves our
        liveness to the peer anyway.  The first-byte probe is NON-blocking
        (inline ticks run on the serializing thread; one congested peer
        must not stall it for a socket timeout per tick).  Once a frame is
        partially written it MUST complete or the stream would corrupt."""
        s = self._send[peer]
        hb_timeout = _hb_timeout()
        view = memoryview(frame)
        ping_deadline: Optional[float] = None
        s.settimeout(max(0.5, _hb_interval()))
        try:
            if best_effort:
                s.settimeout(0.0)
                try:
                    sent = s.send(view)
                except (BlockingIOError, InterruptedError):
                    return False  # full buffer: skip this ping
                view = view[sent:]
                s.settimeout(max(0.5, _hb_interval()))
                if view:
                    # a data frame to a slow-but-alive peer may legitimately
                    # take long, but a peer that cannot drain a ping-sized
                    # frame for a whole heartbeat timeout has a wedged
                    # receive side even if ITS pings keep arriving — without
                    # this bound the half-written ping pins the calling
                    # (serializing) thread for as long as the peer stays
                    # congested
                    ping_deadline = time.monotonic() + hb_timeout
            transient = 0
            while view:
                try:
                    sent = s.send(view)
                except socket.timeout:
                    now = time.monotonic()
                    if now - self._last_recv.get(peer, 0.0) > hb_timeout:
                        raise PeerLost(
                            f"send to exchange peer {peer} stalled >{hb_timeout}s "
                            "with no heartbeat from it (hung or partitioned)"
                        )
                    if ping_deadline is not None and now > ping_deadline:
                        raise PeerLost(
                            f"exchange peer {peer} took none of a "
                            f"{len(frame)}-byte heartbeat frame for "
                            f">{hb_timeout}s (receive side wedged); the "
                            "partially written stream is unrecoverable"
                        )
                    continue
                except OSError as exc:
                    # TRANSIENT socket errors (EINTR, EAGAIN, ENOBUFS...)
                    # retry the SAME slice with a short backoff — they
                    # mean the kernel hiccuped, not that the peer died.
                    # The peer-silence bound above still applies: a peer
                    # that has ALSO stopped heartbeating is genuinely
                    # gone and the retry loop must not mask that.
                    if exc.errno not in _TRANSIENT_ERRNOS:
                        raise
                    transient += 1
                    now = time.monotonic()
                    if now - self._last_recv.get(peer, 0.0) > hb_timeout:
                        raise PeerLost(
                            f"send to exchange peer {peer} failing "
                            f"transiently ({exc!r}) with no heartbeat from "
                            f"it for >{hb_timeout}s (hung or partitioned)"
                        ) from exc
                    time.sleep(min(0.001 * (2.0 ** transient), 0.05))
                    continue
                view = view[sent:]
            self._bytes_out[peer] = self._bytes_out.get(peer, 0) + len(frame)
            return True
        finally:
            try:
                s.settimeout(None)
            except OSError:
                pass

    def _heartbeat_loop(self) -> None:
        """Ping every peer each interval so silence means the PEER stalled,
        not that traffic happens to be idle.  A busy data socket is fine:
        any frame (data or ping) refreshes the receiver's liveness clock.
        Skips peers whose send lock is held — a large in-flight send already
        proves this side is alive to them."""
        interval = _hb_interval()
        frame = self._ping_frame
        while True:
            time.sleep(interval)
            with self._cv:
                if self._closed or self._dead is not None:
                    return
            for peer, lock in self._send_locks.items():
                if lock.acquire(blocking=False):
                    try:
                        if self._send_frame(peer, frame, best_effort=True):
                            self._chunks_out[peer] = (
                                self._chunks_out.get(peer, 0) + 1
                            )
                    except PeerLost as exc:
                        # a ping that got partially written and then stalled
                        # against a silent peer: surface it to the engine
                        with self._cv:
                            if not self._closed and self._dead is None:
                                self._dead = exc
                            self._cv.notify_all()
                        return
                    except OSError:
                        pass  # recv loop surfaces the death with context
                    finally:
                        lock.release()

    def _wait(self, edge: str, seq: int, peers: List[int], timeout: float) -> Dict[int, Any]:
        out: Dict[int, Any] = {}
        hb_timeout = _hb_timeout()
        deadline = time.monotonic() + timeout
        with self._cv:
            while True:
                # drain BEFORE checking for death: a peer that sent its
                # final frame and exited cleanly must not abort a
                # collective whose data already arrived (TCP delivers the
                # frame before the EOF, so the inbox is authoritative)
                for p in peers:
                    if p not in out and (edge, seq, p) in self._inbox:
                        out[p] = self._inbox.pop((edge, seq, p))
                if len(out) == len(peers):
                    return out
                if self._dead is not None:
                    raise self._dead
                closed = [
                    p for p in peers if p not in out and p in self._peer_closed
                ]
                if closed:
                    # clean shutdown is NOT peer death — but a peer that
                    # said goodbye before sending this collective's part
                    # will never send it; fail this wait immediately and
                    # clearly WITHOUT poisoning the whole plane (other
                    # collectives may already hold their data)
                    raise PeerLost(
                        f"exchange {edge!r}#{seq}: peers {closed} closed "
                        "cleanly before sending (shutdown mid-collective)"
                    )
                now = time.monotonic()
                stalled = [
                    p
                    for p in peers
                    if p not in out and now - self._last_recv[p] > hb_timeout
                ]
                if stalled:
                    # hung-not-dead peer (SIGSTOP, partition with open
                    # socket): heartbeats stopped but TCP never reset.
                    # PeerLost (not TimeoutError) so run.py hard-aborts
                    # instead of unwinding into jax's shutdown barrier.
                    self._dead = PeerLost(
                        f"exchange {edge!r}#{seq}: peers {stalled} silent for "
                        f">{hb_timeout}s (heartbeat lost; stalled or partitioned)"
                    )
                    self._cv.notify_all()
                    raise self._dead
                if now >= deadline:
                    raise PeerLost(
                        f"exchange {edge!r}#{seq}: timed out waiting for "
                        f"{[p for p in peers if p not in out]}"
                    )
                self._cv.wait(timeout=min(1.0, hb_timeout / 4))

    # -- collectives --------------------------------------------------------
    def all_to_all(
        self, edge: str, seq: int, parts: List[Any], timeout: float = 600.0
    ) -> List[Any]:
        """Send ``parts[j]`` to rank j; return the nproc parts addressed to
        me (my own part included at position ``rank``)."""
        for peer in range(self.nproc):
            if peer != self.rank:
                self._send_to(peer, edge, seq, parts[peer])
        got = self._wait(
            edge, seq, [p for p in range(self.nproc) if p != self.rank], timeout
        )
        got[self.rank] = parts[self.rank]
        return [got[p] for p in range(self.nproc)]

    def gather(
        self, edge: str, seq: int, obj: Any, root: int = 0, timeout: float = 600.0
    ) -> Optional[List[Any]]:
        """Everyone sends to ``root``; root returns all parts, others None."""
        if self.rank != root:
            self._send_to(root, edge, seq, obj)
            return None
        got = self._wait(
            edge, seq, [p for p in range(self.nproc) if p != root], timeout
        )
        got[root] = obj
        return [got[p] for p in range(self.nproc)]

    def broadcast(
        self, edge: str, seq: int, obj: Any = None, root: int = 0, timeout: float = 600.0
    ) -> Any:
        if self.rank == root:
            for peer in range(self.nproc):
                if peer != root:
                    self._send_to(peer, edge, seq, obj)
            return obj
        return self._wait(edge, seq, [root], timeout)[root]

    def observe_metrics(self):
        """Scrape-time ``pathway_exchange_*`` samples (flight-recorder
        provider): per-peer liveness (``peer_up`` mirrors the heartbeat
        verdict: 1 while the peer has been heard from within the
        heartbeat timeout), silence age (seconds since the peer's last
        frame — the liveness clock ``_wait`` checks), and wire traffic
        counters.  The ``plane`` id label uniquifies concurrent planes
        (tests open several per process)."""
        base = {"rank": str(self.rank), "plane": str(self._observe_id)}
        now = time.monotonic()
        down = self._closed or self._dead is not None
        hb_timeout = _hb_timeout()
        for peer in sorted(self._send):
            labels = {**base, "peer": str(peer)}
            last = self._last_recv.get(peer)
            silence = max(0.0, now - last) if last is not None else None
            up = int(
                not down
                and peer not in self._peer_closed
                and silence is not None
                and silence <= hb_timeout
            )
            yield ("gauge", "pathway_exchange_peer_up", labels, up)
            if silence is not None:
                yield (
                    "gauge",
                    "pathway_exchange_heartbeat_silence_seconds",
                    labels,
                    silence,
                )
            for direction, store in (
                ("in", self._bytes_in),
                ("out", self._bytes_out),
            ):
                yield (
                    "counter",
                    "pathway_exchange_bytes_total",
                    {**labels, "direction": direction},
                    store.get(peer, 0),
                )
            for direction, store in (
                ("in", self._chunks_in),
                ("out", self._chunks_out),
            ):
                yield (
                    "counter",
                    "pathway_exchange_chunks_total",
                    {**labels, "direction": direction},
                    store.get(peer, 0),
                )
        yield (
            "gauge",
            "pathway_exchange_heartbeat_timeout_seconds",
            base,
            hb_timeout,
        )

    def close(self) -> None:
        """Clean shutdown: announce ``__bye__`` to every peer (so this
        rank's disconnect reads as goodbye, not ``PeerLost``), then close
        the sockets.  Idempotent; best-effort — a peer that is already
        gone just misses a goodbye it no longer needs."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._cv.notify_all()
        bye = pickle.dumps(
            (_BYE_EDGE, 0, None), protocol=pickle.HIGHEST_PROTOCOL
        )
        frame = _HDR.pack(len(bye)) + bye
        for peer, s in self._send.items():
            lock = self._send_locks[peer]
            # a short bounded wait: never let one wedged peer stall the
            # whole shutdown, and never interleave into an in-flight frame
            if not lock.acquire(timeout=1.0):
                continue
            try:
                s.settimeout(1.0)
                s.sendall(frame)
            except OSError:
                pass
            finally:
                lock.release()
        for s in self._send.values():
            try:
                s.close()
            except OSError:
                pass
        try:
            self._listener.close()
        except OSError:
            pass


class _ChunkSink:
    """File-like pickle sink collecting frames; calls ``tick`` at every
    chunk boundary so a long serialization keeps servicing heartbeats from
    the serializing thread itself (see ``ExchangePlane._serialize``)."""

    __slots__ = ("_parts", "_tick")

    def __init__(self, tick) -> None:
        self._parts: List[bytes] = []
        self._tick = tick

    def write(self, b) -> int:
        # the C pickler may hand a memoryview into its internal frame
        # buffer; copy before the buffer is reused
        self._parts.append(bytes(b))
        self._tick()
        return len(b)

    def parts(self) -> List[bytes]:
        return self._parts


class _ChunkSource:
    """File-like pickle source over a received frame; calls ``tick`` at
    every read so a long deserialization keeps servicing heartbeats from
    the receiving thread itself (see ``ExchangePlane._deserialize``)."""

    __slots__ = ("_view", "_pos", "_tick")

    def __init__(self, payload, tick) -> None:
        self._view = memoryview(payload)
        self._pos = 0
        self._tick = tick

    def read(self, n: int = -1) -> bytes:
        self._tick()
        pos = self._pos
        end = (
            len(self._view)
            if n is None or n < 0
            else min(pos + n, len(self._view))
        )
        self._pos = end
        return bytes(self._view[pos:end])

    def readline(self) -> bytes:
        # HIGHEST_PROTOCOL frames never hold newline-terminated opcodes,
        # but the Unpickler requires the method to exist
        self._tick()
        pos = self._pos
        nl = bytes(self._view[pos:]).find(b"\n")
        end = len(self._view) if nl < 0 else pos + nl + 1
        self._pos = end
        return bytes(self._view[pos:end])


def _advertise_host() -> str:
    """The address peers should dial for this rank's exchange listener.
    PATHWAY_EXCHANGE_HOST overrides; otherwise use the local interface that
    routes toward the cluster coordinator (loopback for single-host
    clusters, the reachable NIC for multi-host ones)."""
    override = config.get("parallel.exchange_host")
    if override:
        return override
    coord = config.get("parallel.coordinator_address")
    host = coord.rsplit(":", 1)[0] if ":" in coord else coord
    if host in ("", "localhost", "127.0.0.1", "0.0.0.0"):
        return "127.0.0.1"
    try:
        probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            probe.connect((host, 9))  # no packets sent; just picks the route
            return probe.getsockname()[0]
        finally:
            probe.close()
    except OSError:
        return socket.gethostbyname(socket.gethostname())


def _recv_exact(conn: socket.socket, n: int, on_chunk=None) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("exchange connection closed")
        buf += chunk
        if on_chunk is not None:
            on_chunk()
    return bytes(buf)


_plane: Optional[ExchangePlane] = None
_plane_lock = threading.Lock()
_plane_gen = 0


def get_plane() -> Optional[ExchangePlane]:
    """The process-wide exchange plane (created on first use when running
    distributed; None in single-process mode)."""
    global _plane, _plane_gen
    from . import distributed

    if not distributed.is_distributed():
        return None
    with _plane_lock:
        if _plane is None:
            client = distributed._client()
            gen = _plane_gen
            _plane_gen += 1
            _plane = ExchangePlane(
                distributed.process_id(),
                distributed.process_count(),
                kv_set=client.key_value_set,
                kv_get=lambda k: client.blocking_key_value_get(k, 60_000),
                namespace=str(gen),
            )
        return _plane


def close_plane() -> None:
    global _plane
    with _plane_lock:
        if _plane is not None:
            _plane.close()
            _plane = None


_user_seq = 0


def gather_table_rows(table):
    """Union of every rank's local rows for ``table`` — the cross-rank
    materialize (each rank holds only its shard of a distributed table's
    rows; reference users see the union through per-worker output
    connectors).  SPMD: every rank must call this in the same order.
    Single-process: identical to ``table._materialize()``."""
    global _user_seq
    keys, columns = table._materialize()
    plane = get_plane()
    if plane is None:
        return keys, columns
    seq = _user_seq
    _user_seq += 1
    got = plane.all_to_all(
        "gather_table", seq, [(keys, columns)] * plane.nproc
    )
    import numpy as np

    all_keys = np.concatenate([k for k, _c in got])
    names = list(columns.keys())
    merged = {}
    for n in names:
        cols = [c[n] for _k, c in got]
        if any(getattr(c, "dtype", None) == object for c in cols):
            cols = [np.asarray(c, dtype=object) for c in cols]
        merged[n] = np.concatenate(cols) if cols else columns[n]
    return all_keys, merged
