"""Serve-shard device groups: placement, routing, and per-shard health.

The single-host serving tier (rounds 5–10) is capped by one chip's HBM
and FLOPs no matter how many callers the scheduler coalesces; the
scale-out design (ROADMAP item 1, proven by the MULTICHIP_r05 dryrun:
fused serving over an 8-shard index with on-device global top-k merge at
~0% merge share) partitions the index by DOCUMENT across a device group
and fans the coalesced stage-1 batch out to every shard:

- ``ShardGroup`` resolves the serve device group (``PATHWAY_SERVE_SHARDS``
  or an explicit count, over the local devices) and owns the one routing
  rule — ``owner_of(key)`` — that the sharded IVF index (ops/ivf.py) and
  the sharded forward index (index/forward.py) both use, so a document's
  postings AND its compressed token rows live on the SAME shard and the
  late-interaction rerank never crosses shards for data it doesn't need;
- per-shard ``CircuitBreaker``s: a shard that keeps failing its stage-1
  dispatch is skipped (degradation rung ``shard_skipped`` — recall on
  its partition is lost, the request never is) and probed back in on the
  breaker's half-open schedule;
- ``shard_skips`` / breaker state export as ``pathway_serve_shard_*``
  on the one scrape surface via the flight-recorder provider registry.

Shards may outnumber physical devices (round-robin reuse): tier-1 runs
on CPU with ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` so
the shard axis is real in tests, and a 16-way logical sharding over 8
chips is a capacity-planning knob, not an error.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence

import jax

from .. import config, observe
from ..observe import trace
from ..robust import CircuitBreaker

__all__ = ["FleetPartitionMap", "ShardGroup", "serve_shards"]


def serve_shards(default: int = 0) -> int:
    """Shard count from ``serve.shards`` (0 = every local device)."""
    return config.get("serve.shards", fallback=default)


class FleetPartitionMap:
    """The ONE routing rule lifted to fleet scope: ``doc_key %
    n_partitions`` names the fabric HOST that owns a document, exactly
    as ``ShardGroup.owner_of`` names the device shard inside one host.
    The two compose — a fleet of H hosts each running an S-way
    ``ShardGroup`` places a document first by ``FleetPartitionMap``
    (which host's IVF resident/tail slabs and forward-index row bucket
    hold it) and then by the host's own ``ShardGroup`` (which local
    device) — and because both levels spell the same stable modulo
    rule, owner-routed absorb, scatter-gather serve, and per-partition
    warm snapshots all agree on placement with zero coordination.

    Deliberately device-free: the front-end process holds no
    accelerators, only host links.
    """

    def __init__(self, n_partitions: int):
        if int(n_partitions) < 1:
            raise ValueError(
                f"FleetPartitionMap needs >= 1 partition, got {n_partitions}"
            )
        self.n_partitions = int(n_partitions)

    def __len__(self) -> int:
        return self.n_partitions

    def owner_of(self, key: int) -> int:
        """Owning PARTITION (fabric host index) of a document key —
        the fleet-level spelling of the one routing rule."""
        return int(key) % self.n_partitions

    def route(self, keys: Sequence[int]):
        """Positions of ``keys`` grouped by owning partition (the same
        bucket-loop contract as ``ShardGroup.route``; iterate
        ``sorted(...)`` for deterministic per-partition batches)."""
        buckets: dict = {}
        for i, key in enumerate(keys):
            buckets.setdefault(self.owner_of(int(key)), []).append(i)
        return buckets


class ShardGroup:
    """One serve device group: ``n_shards`` logical shards mapped onto
    the local devices (round-robin when shards outnumber devices), the
    document→shard routing rule, and per-shard circuit breakers.

    A group is SHARED by every sharded structure serving one corpus
    (IVF index, forward index, any future posting tier): ``owner_of``
    is the single source of placement truth, so co-partitioned data
    stays co-resident by construction.
    """

    def __init__(
        self,
        n_shards: Optional[int] = None,
        devices: Optional[Sequence] = None,
        name: Optional[str] = None,
    ):
        self.devices = list(devices if devices is not None else jax.devices())
        if not self.devices:
            raise ValueError("ShardGroup needs at least one device")
        n = n_shards or serve_shards() or len(self.devices)
        self.n_shards = max(1, int(n))
        self.name = name or f"shards-{observe.next_id()}"
        self._lock = threading.Lock()
        # per-shard breakers: persistent stage-1 failures on one shard
        # open ITS breaker only — the other shards keep serving, and the
        # half-open probe heals it without operator action
        self._breakers: List[CircuitBreaker] = [
            CircuitBreaker(f"{self.name}.shard{s}")
            for s in range(self.n_shards)
        ]
        # skip accounting per shard (dead dispatch, open breaker): the
        # pathway_serve_shard_skips_total{shard=...} counter family
        self.skips: List[int] = [0] * self.n_shards
        observe.register_provider(self)

    def __len__(self) -> int:
        return self.n_shards

    def device(self, shard: int):
        """The device hosting ``shard`` (round-robin past the physical
        count)."""
        return self.devices[shard % len(self.devices)]

    def owner_of(self, key: int) -> int:
        """Owning shard of a document key — THE routing rule.  Stable
        modulo hash so IVF postings, forward rows, and absorb traffic
        for one document all land on one shard."""
        return int(key) % self.n_shards

    def route(self, keys: Sequence[int]):
        """Positions of ``keys`` grouped by owning shard — the one
        bucket loop every sharded structure's ingest/remove path uses
        (iterate ``sorted(...)`` for deterministic per-shard batches)."""
        buckets: dict = {}
        for i, key in enumerate(keys):
            buckets.setdefault(self.owner_of(int(key)), []).append(i)
        return buckets

    def breaker(self, shard: int) -> CircuitBreaker:
        return self._breakers[shard]

    def record_skip(self, shard: int) -> None:
        with self._lock:
            self.skips[shard] += 1
        # annotate the active trace: a kept slow/degraded serve shows
        # WHICH shard it lost, next to the per-shard dispatch spans
        t = trace.current()
        if t is not None:
            t.add_event("shard.skip", shard=int(shard))

    # -- flight-recorder provider ------------------------------------------
    def observe_metrics(self):
        labels = {"group": self.name}
        yield ("gauge", "pathway_serve_shard_count", labels, self.n_shards)
        for s in range(self.n_shards):
            shard_labels = {**labels, "shard": str(s)}
            yield (
                "counter",
                "pathway_serve_shard_skips_total",
                shard_labels,
                self.skips[s],
            )
            yield (
                "gauge",
                "pathway_serve_shard_breaker_open",
                shard_labels,
                0.0 if self._breakers[s].state == "closed" else 1.0,
            )
