"""pw.io.plaintext (reference: python/pathway/io/plaintext)."""

from __future__ import annotations

from ...internals.table import Table
from .. import fs as _fs

__all__ = ["read"]


def read(path: str, *, mode: str = "streaming", **kwargs) -> Table:
    return _fs.read(path, format="plaintext", mode=mode, **kwargs)
