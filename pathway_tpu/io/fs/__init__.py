"""pw.io.fs — filesystem connector: csv / json(lines) / plaintext / binary,
static or streaming (directory watching)
(reference: python/pathway/io/fs/__init__.py:31-275, scanner
src/connectors/scanner/filesystem.rs)."""

from __future__ import annotations

import csv as _csv
import glob as _glob
import json as _json
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Type

from ...internals import dtype as dt
from ...internals.schema import Schema, schema_from_types
from ...internals.table import Table
from .._connector import SessionWriter, jsonable, register_source

__all__ = ["read", "write", "CsvParserSettings"]


class CsvParserSettings:
    """DSV parser settings (reference: io/_utils.py:125 CsvParserSettings —
    delimiter/quote/escape/comments for the general-DSV format)."""

    def __init__(
        self,
        delimiter: str = ",",
        quote: str = '"',
        escape: Optional[str] = None,
        enable_double_quote_escapes: bool = True,
        enable_quoting: bool = True,
        comment_character: Optional[str] = None,
    ):
        self.delimiter = delimiter
        self.quote = quote
        self.escape = escape
        self.enable_double_quote_escapes = enable_double_quote_escapes
        self.enable_quoting = enable_quoting
        self.comment_character = comment_character


def _expand(path: str) -> List[str]:
    if os.path.isdir(path):
        out = []
        for root, _dirs, files in os.walk(path):
            for f in sorted(files):
                out.append(os.path.join(root, f))
        return out
    return sorted(_glob.glob(path)) or ([path] if os.path.exists(path) else [])


def _my_split(files: List[str]) -> List[str]:
    """Multi-process runs: each rank owns a deterministic hash-split of the
    file set (the reference's parallel readers: only workers with index <
    parallel_readers read a connector, src/engine/dataflow.rs:3317; here
    EVERY rank reads ITS files and rows are exchanged to their key owner).

    Topology comes from the env, NOT jax: reader threads must never race the
    main thread into first jax-backend initialization."""
    from ...parallel.distributed import topology_from_env

    nproc, rank, _addr = topology_from_env()
    if nproc <= 1:
        return files
    import zlib

    return [
        f
        for f in files
        if zlib.crc32(os.path.basename(f).encode()) % nproc == rank
    ]


def _parse_into(
    fpath: str,
    writer: SessionWriter,
    format: str,
    schema: Optional[Type[Schema]],
    with_metadata: bool = False,
    csv_settings=None,
) -> None:
    """Parse one local file into the session (shared by fs/s3/gdrive)."""
    columns = (
        [c for c in schema.columns().keys() if c != "_metadata"]
        if schema is not None
        else ["data"]
    )
    meta = None
    if with_metadata:
        st = os.stat(fpath)
        meta = {
            "path": fpath,
            "size": st.st_size,
            "modified_at": int(st.st_mtime),
            "created_at": int(st.st_ctime),
            "seen_at": int(time.time()),
        }

    # rows buffer into chunked bulk inserts (SessionWriter.insert_rows):
    # one session-lock acquisition per chunk, not per row
    _buf: List[Dict[str, Any]] = []

    def emit(values: Dict[str, Any]):
        if with_metadata:
            values = {**values, "_metadata": meta}
        _buf.append(values)
        if len(_buf) >= 8192:
            writer.insert_rows(_buf)
            _buf.clear()

    def flush():
        if _buf:
            writer.insert_rows(_buf)
            _buf.clear()

    emit_columns = None
    if not writer.track_value_deletions and not writer.session.upsert:
        if with_metadata:

            def emit_columns(cols, n):
                writer.insert_columns({**cols, "_metadata": [meta] * n}, n)

        else:

            def emit_columns(cols, n):
                writer.insert_columns(cols, n)

    try:
        _dispatch_format(
            fpath, format, columns, emit, csv_settings=csv_settings,
            emit_columns=emit_columns,
        )
    finally:
        # flush even when a malformed row raises mid-file, so every
        # successfully parsed row reaches the session (the pre-buffering
        # behavior); the exception still propagates to the caller
        flush()


def _dispatch_format(
    fpath, format, columns, emit, csv_settings=None, emit_columns=None
) -> None:

    if format == "csv" and csv_settings is not None:
        # general DSV: python csv module honouring the parser settings
        # (reference DsvParser, src/connectors/data_format.rs:500)
        with open(fpath, newline="") as f:
            reader = _csv.reader(
                f,
                delimiter=csv_settings.delimiter,
                quotechar=csv_settings.quote if csv_settings.enable_quoting else None,
                escapechar=csv_settings.escape,
                doublequote=csv_settings.enable_double_quote_escapes,
                quoting=_csv.QUOTE_MINIMAL
                if csv_settings.enable_quoting
                else _csv.QUOTE_NONE,
            )
            header = None
            comment = csv_settings.comment_character
            for row in reader:
                if not row or (comment and row[0].startswith(comment)):
                    continue
                if header is None:
                    header = row
                    idx = {
                        c: header.index(c) if c in header else None
                        for c in columns
                    }
                    continue
                emit(
                    {
                        c: (row[i] if i is not None and i < len(row) else None)
                        for c, i in idx.items()
                    }
                )
    elif format == "csv":
        # native C++ scanner (native/src/csv.cc) — columnar extents, one str
        # per cell; pure-Python fallback inside csv_rows when the library is
        # unavailable
        from ... import native as _native

        with open(fpath, "rb") as f:
            rows = _native.csv_rows(f.read())
        if rows:
            header = rows[0]
            idx = {c: header.index(c) if c in header else None for c in columns}
            body = rows[1:]
            if emit_columns is not None and body:
                # columnar hand-off: whole columns to the session in one
                # event — no per-row dicts/tuples on the hot path
                emit_columns(
                    {
                        c: (
                            [
                                (row[i] if i < len(row) else None)
                                for row in body
                            ]
                            if i is not None
                            else [None] * len(body)
                        )
                        for c, i in idx.items()
                    },
                    len(body),
                )
            else:
                for row in body:
                    emit(
                        {
                            c: (row[i] if i is not None and i < len(row) else None)
                            for c, i in idx.items()
                        }
                    )
    elif format in ("json", "jsonlines"):
        with open(fpath) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                obj = _json.loads(line)
                emit({c: obj.get(c) for c in columns})
    elif format in ("plaintext",):
        with open(fpath) as f:
            for line in f:
                emit({"data": line.rstrip("\n")})
    elif format == "plaintext_by_file":
        with open(fpath) as f:
            emit({"data": f.read()})
    elif format == "binary":
        with open(fpath, "rb") as f:
            emit({"data": f.read()})
    else:
        raise ValueError(f"unknown format {format!r}")


def _plaintext_schema():
    return schema_from_types(data=str)


def _binary_schema():
    return schema_from_types(data=bytes)


def read(
    path: str,
    *,
    format: str = "csv",
    schema: Optional[Type[Schema]] = None,
    mode: str = "streaming",
    csv_settings=None,
    json_field_paths=None,
    with_metadata: bool = False,
    autocommit_duration_ms: int = 100,
    name: str = "fs",
    poll_interval_s: float = 1.0,
    persistent_id: Optional[str] = None,
    **kwargs,
) -> Table:
    """Read files under ``path``.  ``mode="static"`` reads once;
    ``mode="streaming"`` keeps watching for new/modified files."""
    if format in ("plaintext", "plaintext_by_file"):
        schema = schema or _plaintext_schema()
    elif format == "binary":
        schema = schema or _binary_schema()
    elif schema is None:
        raise ValueError(f"schema is required for format={format!r}")
    if with_metadata:
        cols = dict(schema.columns())
        from ...internals.schema import ColumnSchema, _make_schema

        cols["_metadata"] = ColumnSchema(name="_metadata", dtype=dt.JSON)
        schema = _make_schema(schema.__name__ + "Meta", cols)

    columns = [c for c in schema.columns().keys() if c != "_metadata"]
    dtypes = schema.typehints()

    def parse_file(fpath: str, writer: SessionWriter):
        _parse_into(
            fpath,
            writer,
            format,
            schema,
            with_metadata=with_metadata,
            csv_settings=csv_settings,
        )

    # offsets for persistence = {path: mtime} of fully-ingested files; after
    # snapshot replay the runner seeks past them (reference seek semantics,
    # src/connectors/mod.rs ReadersQueryPurpose)
    if mode == "static":

        def runner(writer: SessionWriter):
            pers = writer.persistence
            seen: Dict[str, float] = dict((pers.offsets() or {}) if pers else {})
            for fpath in _my_split(_expand(path)):
                try:
                    mtime = os.path.getmtime(fpath)
                except OSError:
                    continue
                if seen.get(fpath) == mtime:
                    continue
                parse_file(fpath, writer)
                seen[fpath] = mtime
            writer.commit_offsets(seen)

        return register_source(
            schema,
            runner,
            mode="static",
            name=name,
            persistent_id=persistent_id,
            dist_mode="partitioned",
        )

    def runner(writer: SessionWriter):
        pers = writer.persistence
        seen: Dict[str, float] = dict((pers.offsets() or {}) if pers else {})
        while True:
            for fpath in _my_split(_expand(path)):
                try:
                    mtime = os.path.getmtime(fpath)
                except OSError:
                    continue
                if seen.get(fpath) == mtime:
                    continue
                # mark ingested only AFTER the parse completes, and hand the
                # persistence layer its own copy — a snapshot taken mid-parse
                # must not claim the file was fully read
                parse_file(fpath, writer)
                seen[fpath] = mtime
                writer.commit_offsets(seen)
            time.sleep(poll_interval_s)

    return register_source(
        schema,
        runner,
        mode="streaming",
        name=name,
        persistent_id=persistent_id,
        dist_mode="partitioned",
    )


def write(table: Table, filename: str, *, format: str = "csv", **kwargs) -> None:
    """Write the table's update stream to a file; csv/jsonlines rows carry
    ``time`` and ``diff`` columns (reference output format,
    src/connectors/data_format.rs DsvFormatter/JsonLinesFormatter).

    Multi-process runs: the sink's input edge gathers to rank 0, so ONLY
    rank 0 touches the file (exactly-once output); other ranks register the
    same operator (graph shapes must match across SPMD replicas) with no-op
    callbacks."""
    from ...parallel.distributed import topology_from_env
    from .._subscribe import subscribe

    processes, pid, _addr = topology_from_env()
    if processes > 1 and pid != 0:
        subscribe(table, on_change=None, on_time_end=None, on_end=None)
        return

    names = table.column_names
    f = open(filename, "w", newline="")
    state = {"writer": None}

    if format == "csv":
        w = _csv.writer(f)
        w.writerow(names + ["time", "diff"])

        def on_change(key, row, time, is_addition):
            w.writerow([row[n] for n in names] + [time, 1 if is_addition else -1])

    elif format in ("json", "jsonlines"):

        def on_change(key, row, time, is_addition):
            obj = {n: _jsonable(row[n]) for n in names}
            obj["time"] = time
            obj["diff"] = 1 if is_addition else -1
            f.write(_json.dumps(obj) + "\n")

    else:
        raise ValueError(f"unknown format {format!r}")

    def on_time_end(time):
        # flush once per commit tick: a crashed streaming job must not lose
        # rows of already-committed times to OS buffering (the recovery
        # contract, tests/test_recovery_e2e.py)
        f.flush()

    def on_end():
        f.flush()
        f.close()

    subscribe(table, on_change=on_change, on_time_end=on_time_end, on_end=on_end)


# shared JSON coercion lives in the connector runtime
_jsonable = jsonable
