"""pw.io.mongodb — MongoDB output connector
(reference: python/pathway/io/mongodb/__init__.py over MongoWriter,
src/connectors/data_storage.rs).  Gated on pymongo (not bundled).
"""

from __future__ import annotations

from ...internals.table import Table
from .._gated import require
from .._subscribe import subscribe

__all__ = ["write"]


def write(
    table: Table,
    connection_string: str,
    database: str,
    collection: str,
    *,
    max_batch_size: int = 1000,
    **kwargs,
) -> None:
    pymongo = require("pymongo", "mongodb")
    client = pymongo.MongoClient(connection_string)
    coll = client[database][collection]
    names = table.column_names
    buffer = []

    def on_change(key, row, time, is_addition):
        doc = {n: row[n] for n in names}
        doc["_pw_key"] = str(int(key))
        doc["time"] = time
        doc["diff"] = 1 if is_addition else -1
        buffer.append(doc)
        if len(buffer) >= max_batch_size:
            coll.insert_many(buffer)
            del buffer[:]

    def flush(ts=None):
        if buffer:
            coll.insert_many(buffer)
            del buffer[:]

    subscribe(table, on_change=on_change, on_time_end=flush, on_end=flush)
