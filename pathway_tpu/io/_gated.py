"""Helpers for connectors whose transport library is not bundled.

The connector modules are always importable (so ``pw.io.<name>`` exists and
documents its surface); the ImportError fires at call time with a clear
message, mirroring how the reference gates optional xpack deps.
"""

from __future__ import annotations

import importlib

__all__ = ["require"]


def require(module: str, connector: str, hint: str = ""):
    try:
        return importlib.import_module(module)
    except ImportError as e:
        msg = f"pw.io.{connector} requires the {module!r} package (not installed)"
        if hint:
            msg += f"; {hint}"
        raise ImportError(msg) from e
