"""pw.io.s3_csv — CSV-from-S3 convenience wrapper
(reference: python/pathway/io/s3_csv/__init__.py — delegates to the s3
reader with format="csv"; kept as its own module for API parity)."""

from __future__ import annotations

from typing import Optional, Type

from ...internals.schema import Schema
from ...internals.table import Table
from ..s3 import AwsS3Settings, read as s3_read

__all__ = ["read", "AwsS3Settings"]


def read(
    path: str,
    *,
    aws_s3_settings: Optional[AwsS3Settings] = None,
    schema: Optional[Type[Schema]] = None,
    csv_settings=None,
    mode: str = "streaming",
    persistent_id: Optional[str] = None,
    **kwargs,
) -> Table:
    """Read CSV objects under an S3 path prefix (reference signature)."""
    return s3_read(
        path,
        aws_s3_settings=aws_s3_settings,
        format="csv",
        schema=schema,
        csv_settings=csv_settings,
        mode=mode,
        persistent_id=persistent_id,
        name="s3_csv",
        **kwargs,
    )
