"""pw.io.subscribe — per-row change callbacks
(reference: python/pathway/io/_subscribe.py)."""

from __future__ import annotations

from typing import Any, Callable, Optional

from ..engine.graph import OutputCallbacks
from ..engine.operators.io import SubscribeOperator
from ..internals.keys import Pointer
from ..internals.parse_graph import G
from ..internals.table import Table

__all__ = ["subscribe"]


def subscribe(
    table: Table,
    on_change: Callable[..., None],
    on_end: Optional[Callable[[], None]] = None,
    on_time_end: Optional[Callable[[int], None]] = None,
) -> None:
    """on_change(key, row: dict, time: int, is_addition: bool)."""
    names = table.column_names
    engine_names = [table._column_mapping[n] for n in names]
    engine_table = table._engine_table
    col_idx = [engine_table.column_names.index(e) for e in engine_names]

    wrapped = None
    if on_change is not None:

        def wrapped(key, row_tuple, time, diff):
            row = {n: row_tuple[i] for n, i in zip(names, col_idx)}
            on_change(key=Pointer(key), row=row, time=time, is_addition=diff > 0)

    op = SubscribeOperator(
        engine_table,
        OutputCallbacks(
            on_change=wrapped, on_time_end=on_time_end, on_end=on_end
        ),
        name="subscribe",
    )
    G.engine_graph.add_operator(op)
