"""Shared connector runtime.

Reference: src/connectors/mod.rs:91-427 — one reader thread per connector
feeding parsed entries into an input session, committed on autocommit ticks.
Here the thread pushes rows into an ``InputSession``; the executor drains it
once per tick.  Static mode reads everything during the pre-run hook and
closes the session (batch semantics).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple, Type

import numpy as np

from ..engine.operators.io import InputSession, SourceOperator
from ..internals import dtype as dt
from ..internals.keys import ref_scalar, sequential_keys
from ..internals.parse_graph import G
from ..internals.schema import Schema
from ..internals.table import Table
from ..internals.universe import Universe

__all__ = ["SessionWriter", "register_source", "coerce_row_types", "jsonable"]


def jsonable(v):
    """Coerce engine values to JSON-encodable ones (shared by all writers)."""
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, bytes):
        return v.decode(errors="replace")
    return v


class SessionWriter:
    """Pushes keyed rows into an InputSession, deriving keys from primary-key
    columns (or a sequence counter) — the host analog of the reference's
    parser→session path (src/connectors/adaptors.rs)."""

    def __init__(
        self,
        session: InputSession,
        column_names: Sequence[str],
        primary_key: Optional[Sequence[str]],
        dtypes: Mapping[str, dt.DType],
        salt: int = 0,
        track_value_deletions: bool = False,
        name: str = "source",
    ):
        self.session = session
        self.column_names = list(column_names)
        self.primary_key = list(primary_key) if primary_key else None
        self.dtypes = dict(dtypes)
        self._counter = 0
        self._salt = salt
        self._lock = threading.Lock()
        # Without a primary key, deletions identify rows BY VALUE.  Keys are
        # DERIVED as hash(row-value-hash, occurrence-index), so matching a
        # deletion to its insert needs only a per-value LIVE COUNT — memory
        # is bounded by live distinct values (which the engine stores
        # anyway), not by ingest history, and keys are deterministic across
        # replays.  remove() cancels the most recent occurrence (LIFO).
        self.track_value_deletions = bool(track_value_deletions) and not self.primary_key
        self._live_counts: Dict[int, int] = {}
        # set by the PersistenceManager when a persistence config is active
        # (persistence/engine_state.py SourcePersistence)
        self.persistence = None
        # per-connector lag/offset stats, scraped by /metrics (io/_offsets.py)
        from ._offsets import ConnectorMonitor

        self.monitor = ConnectorMonitor(name)

    def key_of(self, values: Mapping[str, Any]) -> int:
        if self.primary_key:
            return self._pk_key(values)
        with self._lock:
            i = self._counter
            self._counter += 1
        return int(sequential_keys(i, 1, salt=self._salt)[0])

    def _pk_key(self, values: Mapping[str, Any]) -> int:
        return int(ref_scalar(*(values[c] for c in self.primary_key)))

    def _tracked_key(self, row: tuple) -> int:
        """Key for value-tracked rows: hash(value-hash, occurrence-index)."""
        vid = self._value_id(row)
        with self._lock:
            n = self._live_counts.get(vid, 0)
            self._live_counts[vid] = n + 1
        return int(ref_scalar(np.uint64(vid), n))

    def _value_id(self, row: tuple) -> int:
        return int(ref_scalar(*row))

    def insert(self, values: Mapping[str, Any], key: Optional[int] = None) -> None:
        values = coerce_row_types(values, self.dtypes)
        row = tuple(values.get(c) for c in self.column_names)
        if key is None:
            if self.track_value_deletions:
                key = self._tracked_key(row)
            else:
                key = self.key_of(values)
        self.session.insert(key, row)
        self.monitor.on_insert()

    def insert_rows(self, rows_values: Sequence[Mapping[str, Any]]) -> None:
        """Bulk insert: coerce + key a whole chunk, then hand it to the
        session in ONE ``insert_batch`` call — one session-lock acquisition
        per chunk instead of per row (the fs/csv readers parse thousands of
        rows per file; see InputSession.insert_batch)."""
        keys: List[Optional[int]] = []
        rows: List[tuple] = []
        for values in rows_values:
            values = coerce_row_types(values, self.dtypes)
            row = tuple(values.get(c) for c in self.column_names)
            if self.track_value_deletions:
                key: Optional[int] = self._tracked_key(row)
            elif self.primary_key:
                key = self._pk_key(values)
            else:
                key = None  # sequential, assigned in one counter bump below
            keys.append(key)
            rows.append(row)
        n_auto = sum(1 for k in keys if k is None)
        if n_auto:
            with self._lock:
                start = self._counter
                self._counter += n_auto
            auto = iter(sequential_keys(start, n_auto, salt=self._salt))
            keys = [int(next(auto)) if k is None else k for k in keys]
        self.session.insert_batch(keys, rows)
        self.monitor.on_insert(len(rows))

    def insert_columns(self, columns: Mapping[str, Any], n: Optional[int] = None) -> None:
        """Columnar bulk insert: whole columns (lists/arrays of equal
        length) go through vectorized coercion and ONE keying pass, then
        land in the session as a single columnar event — no per-row python
        tuples anywhere (the wordcount-shape hot path).  Falls back to
        insert_rows for sessions that need per-row treatment."""
        cols = {c: columns.get(c) for c in self.column_names}
        if n is None:
            present = [v for v in cols.values() if v is not None]
            if not present:
                raise ValueError(
                    "insert_columns: no schema column present and no n given"
                )
            n = len(present[0])
        if n == 0:
            return
        if self.track_value_deletions or self.session.upsert:
            # per-row semantics needed (upsert chains / value-tracked
            # deletions — primary-key schemas always open upsert sessions,
            # so PK keying happens in insert_rows)
            rows = [
                {c: (cols[c][i] if cols[c] is not None else None) for c in cols}
                for i in range(n)
            ]
            self.insert_rows(rows)
            return
        coerced = {
            c: _coerce_column(cols[c], self.dtypes.get(c), n)
            for c in self.column_names
        }
        with self._lock:
            start = self._counter
            self._counter += n
        keys = sequential_keys(start, n, salt=self._salt)
        self.session.insert_columnar(keys, coerced)
        self.monitor.on_insert(n)

    def remove(self, values: Mapping[str, Any], key: Optional[int] = None) -> None:
        values = coerce_row_types(values, self.dtypes)
        if key is None:
            if self.primary_key:
                key = self.key_of(values)
            elif self.track_value_deletions:
                row = tuple(values.get(c) for c in self.column_names)
                vid = self._value_id(row)
                with self._lock:
                    n = self._live_counts.get(vid, 0)
                    if n == 0:
                        raise KeyError(
                            f"remove: no live row matches {values!r} "
                            "(schema has no primary key; deletions match "
                            "previously inserted values)"
                        )
                    if n == 1:
                        del self._live_counts[vid]
                    else:
                        self._live_counts[vid] = n - 1
                key = int(ref_scalar(np.uint64(vid), n - 1))
            else:
                raise KeyError(
                    "remove: source does not track value deletions and the "
                    "schema has no primary key"
                )
        self.session.remove(key)
        self.monitor.on_delete()

    def commit_offsets(self, offsets: Mapping[Any, Any]):
        """Record committed per-partition read positions: persisted when a
        persistence config is active, and always folded into the connector
        monitor's offset antichain for lag/partition stats.  Returns the
        monitor's merged antichain — the same contract
        ``serve/ingest.py``'s ``IngestConnector.commit`` mirrors, so code
        bridging engine sources into the live indexes reads committed
        positions back from either."""
        from ._offsets import OffsetAntichain

        if self.persistence is not None:
            self.persistence.save_offsets(dict(offsets))
        self.monitor.on_commit(OffsetAntichain(dict(offsets)))
        return self.monitor.offsets

    def close(self) -> None:
        self.monitor.on_finish()
        self.session.close()


def _coerce_column(col, t: Optional[dt.DType], n: int) -> np.ndarray:
    """Vectorized flavor of coerce_row_types for one whole column."""
    if col is None:
        out = np.empty(n, dtype=object)
        return out
    t = dt.unoptionalize(t) if t is not None else None
    try:
        if t is dt.INT:
            arr = np.asarray(col)
            if np.issubdtype(arr.dtype, np.integer):
                return arr.astype(np.int64, copy=False)
            return arr.astype(np.int64)
        if t is dt.FLOAT:
            return np.asarray(col).astype(np.float64)
        if t is dt.STR:
            arr = np.asarray(col, dtype=object)
            # one full type scan — a first-element sample would let mixed
            # columns skip str() and hash/group differently than the row path
            if arr.size and not all(type(v) is str for v in arr.flat):
                return np.array(
                    [v if type(v) is str else str(v) for v in col],
                    dtype=object,
                )
            return arr
    except (ValueError, TypeError, OverflowError):
        # mixed/unparseable (numpy raises OverflowError for out-of-int64
        # values the row path keeps as python big ints): per-value below
        pass
    arr = np.empty(n, dtype=object)
    for i, v in enumerate(col):
        arr[i] = v
    dtypes = {"c": t} if t is not None else {}
    if t is not None:
        for i in range(n):
            arr[i] = coerce_row_types({"c": arr[i]}, dtypes)["c"]
    return arr


def coerce_row_types(
    values: Mapping[str, Any], dtypes: Mapping[str, dt.DType]
) -> Dict[str, Any]:
    out = dict(values)
    for c, t in dtypes.items():
        v = out.get(c)
        if v is None:
            continue
        t = dt.unoptionalize(t)
        try:
            if t is dt.INT and not isinstance(v, (int, np.integer)):
                out[c] = int(v)
            elif t is dt.FLOAT and not isinstance(v, (float, np.floating)):
                out[c] = float(v)
            elif t is dt.BOOL and not isinstance(v, (bool, np.bool_)):
                out[c] = str(v).lower() in ("1", "true", "yes", "on")
            elif t is dt.STR and not isinstance(v, str):
                out[c] = str(v)
        except (ValueError, TypeError):
            pass
    return out


_source_counter = [0]


def register_source(
    schema: Type[Schema],
    runner: Callable[[SessionWriter], None],
    *,
    mode: str = "streaming",
    upsert: bool = False,
    name: str = "source",
    persistent_id: Optional[str] = None,
    track_value_deletions: bool = False,
    atomic_batches: bool = False,
    dist_mode: str = "replicated",
    quiesce_check=None,
) -> Table:
    """Create the engine source + api table and schedule ``runner`` to feed it.

    ``mode="static"``: runner executes synchronously at run start, session
    closes after (batch).  ``mode="streaming"``: runner executes on a daemon
    thread; session closes when it returns.

    ``dist_mode`` (multi-process runs; reference ``parallel_readers``,
    src/engine/dataflow.rs:3317): "replicated" — every rank runs the runner
    and ingests identical events, the executor keeps each rank's owned-key
    slice; "partitioned" — ranks read DISJOINT splits (the runner consults
    ``parallel.distributed.process_id()``), rows are exchanged to their key
    owner; "broadcast" — one rank reads, every rank receives the full
    stream."""
    column_names = list(schema.columns().keys())
    dtypes = schema.typehints()
    _source_counter[0] += 1
    salt = _source_counter[0]
    # env topology, NOT jax.process_count(): graph build happens before
    # pw.run() joins the cluster, and touching the jax backend here would
    # break distributed.maybe_initialize()'s first-touch requirement
    from ..parallel.distributed import topology_from_env

    processes, pid, _addr = topology_from_env()
    if processes > 1:
        # collision-free distributed salt scheme: every source stretches its
        # counter by (processes+1); partitioned sources additionally fold in
        # the rank (disjoint splits both starting their row counter at 0
        # must never mint the same key), offset by +1 so a partitioned
        # source's rank-salts can never equal ANY source's stretched counter
        salt = salt * (processes + 1)
        if dist_mode == "partitioned":
            salt += pid + 1
    session = InputSession(
        upsert=upsert or schema.primary_key_columns() is not None,
        atomic_batches=atomic_batches,
    )
    writer = SessionWriter(
        session,
        column_names,
        schema.primary_key_columns(),
        dtypes,
        salt=salt,
        track_value_deletions=track_value_deletions,
        name=name,
    )
    et = G.engine_graph.add_table(column_names, name)
    op = G.engine_graph.add_operator(
        SourceOperator(et, session, dtypes, name=name)
    )
    op.persistent_id = persistent_id
    op.writer = writer
    op.dist_mode = dist_mode
    # loop-back sources (AsyncTransformer results re-entering the graph)
    # never close their session themselves; they count as drained for
    # batch-run termination when this callable reports no queued/in-flight
    # work (the feeding sources' liveness is checked separately by the
    # executor)
    op.quiesce_check = quiesce_check

    if mode == "static":

        def hook():
            try:
                runner(writer)
            finally:
                writer.close()

    else:

        def hook():
            def target():
                try:
                    runner(writer)
                except BaseException as exc:  # noqa: BLE001
                    # re-raised on the engine thread at the next drain —
                    # a crashed reader must fail the run, not end the stream
                    session.fail(exc)
                finally:
                    writer.close()

            thread = threading.Thread(target=target, daemon=True, name=f"connector-{name}")
            thread.start()

    G.pre_run_hooks.append(hook)
    return Table(et, dtypes, Universe(), short_name=name)
