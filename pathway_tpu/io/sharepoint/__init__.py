"""pw.io.sharepoint — SharePoint document-library input connector
(reference: the licensed xpack connector,
python/pathway/xpacks/connectors/sharepoint/, 376 LoC — lists a library
path, downloads new/changed files, emits bytes + metadata).  Gated on
Office365-REST-Python-Client (not bundled)."""

from __future__ import annotations

import time
from typing import Optional

from ...internals import dtype as dt
from ...internals.schema import ColumnSchema, _make_schema, schema_from_types
from ...internals.table import Table
from .._connector import SessionWriter, register_source
from .._gated import require

__all__ = ["read"]


def read(
    url: str,
    *,
    root_path: str,
    client_id: str,
    client_secret: Optional[str] = None,
    cert_path: Optional[str] = None,
    thumbprint: Optional[str] = None,
    tenant: Optional[str] = None,
    mode: str = "streaming",
    refresh_interval: int = 30,
    with_metadata: bool = False,
    recursive: bool = True,
    name: str = "sharepoint",
    persistent_id: Optional[str] = None,
    **kwargs,
) -> Table:
    """Stream files of a SharePoint document library folder.

    ``url`` is the site url (https://<org>.sharepoint.com/sites/<site>),
    ``root_path`` the server-relative folder ("Shared Documents/data").
    Auth: client credentials (client_id + client_secret) or certificate
    (client_id + cert_path + thumbprint + tenant)."""
    require(
        "office365",
        "sharepoint",
        "pip package Office365-REST-Python-Client",
    )
    if client_secret is None and not (cert_path and thumbprint and tenant):
        # validate HERE: in streaming mode the runner dies in a daemon
        # thread, which would leave an empty source and a buried traceback
        raise ValueError(
            "sharepoint auth needs client_secret or "
            "cert_path+thumbprint+tenant"
        )
    schema = schema_from_types(data=bytes)
    if with_metadata:
        cols = dict(schema.columns())
        cols["_metadata"] = ColumnSchema(name="_metadata", dtype=dt.JSON)
        schema = _make_schema("SharePointSchema", cols)

    def connect():
        from office365.runtime.auth.client_credential import (  # type: ignore
            ClientCredential,
        )
        from office365.sharepoint.client_context import ClientContext  # type: ignore

        ctx = ClientContext(url)
        if client_secret is not None:
            return ctx.with_credentials(
                ClientCredential(client_id, client_secret)
            )
        return ctx.with_client_certificate(
            tenant, client_id, thumbprint, cert_path
        )

    def list_files(ctx, folder_path):
        folder = ctx.web.get_folder_by_server_relative_url(folder_path)
        files = folder.files
        ctx.load(files)
        ctx.execute_query()
        out = [(f, folder_path) for f in files]
        if recursive:
            subs = folder.folders
            ctx.load(subs)
            ctx.execute_query()
            for sub in subs:
                out.extend(
                    list_files(ctx, f"{folder_path}/{sub.properties['Name']}")
                )
        return out

    def runner(writer: SessionWriter):
        ctx = connect()
        pers = writer.persistence
        seen = dict((pers.offsets() or {}) if pers else {})
        while True:
            for f, folder_path in list_files(ctx, root_path):
                props = f.properties
                rel = props.get("ServerRelativeUrl") or (
                    f"{folder_path}/{props['Name']}"
                )
                mtime = str(props.get("TimeLastModified", ""))
                if seen.get(rel) == mtime:
                    continue
                import io as _io

                buf = _io.BytesIO()
                f.download(buf).execute_query()
                values = {"data": buf.getvalue()}
                if with_metadata:
                    values["_metadata"] = {
                        "path": rel,
                        "name": props.get("Name"),
                        "modified_at": mtime,
                        "size": props.get("Length"),
                    }
                writer.insert(values)
                seen[rel] = mtime
                writer.commit_offsets(seen)
            if mode == "static":
                return
            time.sleep(refresh_interval)

    return register_source(
        schema, runner, mode=mode, name=name, persistent_id=persistent_id
    )
