"""pw.io.python — custom push sources
(reference: python/pathway/io/python/__init__.py:49 ConnectorSubject)."""

from __future__ import annotations

import json as _json
import queue
import threading
from typing import Any, Dict, Optional, Type

from ...internals.schema import Schema
from ...internals.table import Table
from .._connector import SessionWriter, register_source

__all__ = ["ConnectorSubject", "read"]


class ConnectorSubject:
    """Subclass and implement ``run()``; push rows with ``next(**kwargs)``
    (also next_json/next_str/next_bytes), delete with ``delete``."""

    _writer: Optional[SessionWriter] = None

    def __init__(self, datasource_name: str = "python"):
        self._datasource_name = datasource_name

    # -- to be implemented by user --------------------------------------
    def run(self) -> None:
        raise NotImplementedError

    def on_stop(self) -> None:
        pass

    # -- push API --------------------------------------------------------
    def next(self, **kwargs) -> None:
        assert self._writer is not None, "subject not started"
        self._writer.insert(kwargs)

    def next_json(self, message: Dict[str, Any]) -> None:
        self.next(**message)

    def next_str(self, message: str) -> None:
        self.next(data=message)

    def next_bytes(self, message: bytes) -> None:
        self.next(data=message)

    def delete(self, **kwargs) -> None:
        assert self._writer is not None
        self._writer.remove(kwargs)

    def commit(self) -> None:
        """Seal rows pushed so far into one atomic batch with its own commit
        tick (InputSession.mark_batch)."""
        if self._writer is not None:
            self._writer.session.mark_batch()

    def close(self) -> None:
        pass

    def start(self) -> None:
        try:
            self.run()
        finally:
            self.on_stop()


def read(
    subject: ConnectorSubject,
    *,
    schema: Type[Schema],
    autocommit_duration_ms: int = 100,
    name: str = "python",
    atomic_batches: bool = False,
    **kwargs,
) -> Table:
    """``autocommit_duration_ms`` is accepted for reference parity; batch
    boundaries are structural here — ``subject.commit()`` seals a batch and
    the engine assigns it its own commit tick (InputSession.mark_batch)."""

    def runner(writer: SessionWriter):
        subject._writer = writer
        subject.start()

    return register_source(
        schema,
        runner,
        mode="streaming",
        name=name,
        track_value_deletions=True,
        atomic_batches=atomic_batches,
    )
