"""pw.io.postgres — PostgreSQL output connector
(reference: python/pathway/io/postgres/__init__.py over PsqlWriter +
snapshot/updates formatters, src/connectors/data_format.rs PsqlUpdatesFormatter
/ PsqlSnapshotFormatter).  Gated on psycopg2/psycopg (not bundled).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

from ...internals.table import Table
from .._subscribe import subscribe

__all__ = ["write", "write_snapshot"]


def _connect(postgres_settings: Dict):
    try:
        import psycopg2  # type: ignore

        return psycopg2.connect(**postgres_settings)
    except ImportError:
        pass
    try:
        import psycopg  # type: ignore

        return psycopg.connect(**postgres_settings)
    except ImportError as e:
        raise ImportError(
            "pw.io.postgres requires psycopg2 or psycopg (not installed)"
        ) from e


def write(table: Table, postgres_settings: Dict, table_name: str, **kwargs) -> None:
    """Append the update stream: every change becomes an INSERT carrying
    time/diff columns (reference PsqlUpdatesFormatter)."""
    conn = _connect(postgres_settings)
    names = table.column_names
    cols = ", ".join(names + ["time", "diff"])
    ph = ", ".join(["%s"] * (len(names) + 2))
    cur = conn.cursor()

    def on_change(key, row, time, is_addition):
        cur.execute(
            f"INSERT INTO {table_name} ({cols}) VALUES ({ph})",  # noqa: S608
            [row[n] for n in names] + [time, 1 if is_addition else -1],
        )

    def on_time_end(ts):
        conn.commit()

    subscribe(table, on_change=on_change, on_time_end=on_time_end,
              on_end=lambda: (conn.commit(), conn.close()))


def write_snapshot(
    table: Table,
    postgres_settings: Dict,
    table_name: str,
    primary_key: Sequence[str],
    **kwargs,
) -> None:
    """Maintain a snapshot: upsert on insertion, delete on retraction
    (reference PsqlSnapshotFormatter)."""
    conn = _connect(postgres_settings)
    names = table.column_names
    cols = ", ".join(names)
    ph = ", ".join(["%s"] * len(names))
    keycond = " AND ".join(f"{c} = %s" for c in primary_key)
    updates = ", ".join(f"{c} = EXCLUDED.{c}" for c in names if c not in primary_key)
    pk = ", ".join(primary_key)
    cur = conn.cursor()

    def on_change(key, row, time, is_addition):
        if is_addition:
            cur.execute(
                f"INSERT INTO {table_name} ({cols}) VALUES ({ph}) "  # noqa: S608
                f"ON CONFLICT ({pk}) DO UPDATE SET {updates}",
                [row[n] for n in names],
            )
        else:
            cur.execute(
                f"DELETE FROM {table_name} WHERE {keycond}",  # noqa: S608
                [row[c] for c in primary_key],
            )

    subscribe(table, on_change=on_change,
              on_time_end=lambda ts: conn.commit(),
              on_end=lambda: (conn.commit(), conn.close()))
