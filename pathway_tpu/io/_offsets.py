"""Per-partition offsets + connector lag monitoring.

The reference tracks, per connector, an antichain of per-partition committed
offsets (src/connectors/offset.rs — OffsetAntichain) and per-connector
latency/lag stats consumed by the monitoring endpoint and dashboard
(src/connectors/monitoring.rs:237 ConnectorMonitor).  Here the antichain is
a partition -> max-offset map (total order within a partition, none across)
and the monitor keeps scrape-time counters surfaced at /metrics
(internals/metrics.py) and in the text dashboard.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Dict, Iterable, Optional, Tuple

__all__ = ["OffsetAntichain", "ConnectorMonitor", "connector_monitors"]


class OffsetAntichain:
    """Committed read positions, one per partition (file path, kafka
    partition id, shard, ...).  Offsets only advance; merging takes the
    per-partition max."""

    def __init__(self, positions: Optional[Dict[Any, Any]] = None):
        self._positions: Dict[Any, Any] = dict(positions or {})

    def advance(self, partition: Any, offset: Any) -> None:
        cur = self._positions.get(partition)
        if cur is None or offset > cur:
            self._positions[partition] = offset

    def get(self, partition: Any, default: Any = None) -> Any:
        return self._positions.get(partition, default)

    def merge(self, other: "OffsetAntichain") -> "OffsetAntichain":
        merged = OffsetAntichain(self._positions)
        for partition, offset in other._positions.items():
            merged.advance(partition, offset)
        return merged

    def dominates(self, other: "OffsetAntichain") -> bool:
        """True when every partition of ``other`` is at or behind ours."""
        for partition, offset in other._positions.items():
            cur = self._positions.get(partition)
            if cur is None or cur < offset:
                return False
        return True

    def items(self) -> Iterable[Tuple[Any, Any]]:
        return self._positions.items()

    def as_dict(self) -> Dict[Any, Any]:
        return dict(self._positions)

    @staticmethod
    def from_dict(raw: Optional[Dict[Any, Any]]) -> "OffsetAntichain":
        return OffsetAntichain(raw or {})

    def __len__(self) -> int:
        return len(self._positions)

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, OffsetAntichain)
            and self._positions == other._positions
        )

    def __repr__(self) -> str:  # pragma: no cover
        return f"OffsetAntichain({self._positions!r})"


_monitors: "weakref.WeakSet[ConnectorMonitor]" = weakref.WeakSet()


def connector_monitors():
    """Live connector monitors (scraped by /metrics and the dashboard)."""
    return list(_monitors)


class ConnectorMonitor:
    """Per-connector ingestion stats (reference ConnectorMonitor,
    src/connectors/monitoring.rs:237): row counters, last-activity clock for
    lag estimation, and the committed offset antichain."""

    _ids = 0

    def __init__(self, name: str):
        self.name = name
        ConnectorMonitor._ids += 1
        self.id = ConnectorMonitor._ids  # uniquifies metric labels
        self._lock = threading.Lock()
        self.rows_inserted = 0
        self.rows_deleted = 0
        self.commits = 0
        self.started_at = time.time()
        self.last_row_at: Optional[float] = None
        self.last_commit_at: Optional[float] = None
        self.offsets = OffsetAntichain()
        self.finished = False
        _monitors.add(self)

    def on_insert(self, n: int = 1) -> None:
        with self._lock:
            self.rows_inserted += n
            self.last_row_at = time.time()

    def on_delete(self, n: int = 1) -> None:
        with self._lock:
            self.rows_deleted += n
            self.last_row_at = time.time()

    def on_commit(self, offsets: Optional[OffsetAntichain] = None) -> None:
        with self._lock:
            self.commits += 1
            self.last_commit_at = time.time()
            if offsets is not None:
                self.offsets = self.offsets.merge(offsets)

    def on_finish(self) -> None:
        self.finished = True

    def lag_seconds(self) -> Optional[float]:
        """Seconds since the connector last produced a row (None before the
        first row; 0-ish while actively ingesting)."""
        if self.last_row_at is None:
            return None
        return max(0.0, time.time() - self.last_row_at)

    def stats(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "rows_inserted": self.rows_inserted,
            "rows_deleted": self.rows_deleted,
            "commits": self.commits,
            "lag_seconds": self.lag_seconds(),
            "last_commit_at": self.last_commit_at,
            "partitions": len(self.offsets),
            # the committed antichain itself: the live-ingest freshness
            # plane (serve/ingest.py) surfaces per-connector positions on
            # /serve_stats, and replaying a partition needs the positions
            # not just their count
            "offsets": self.offsets.as_dict(),
            "finished": self.finished,
        }
