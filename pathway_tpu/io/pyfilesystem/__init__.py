"""pw.io.pyfilesystem — read any PyFilesystem2 ``FS`` object as a table
(reference: python/pathway/io/pyfilesystem/__init__.py — snapshot-diff
polling over ``fs.walk``, upserting changed files and retracting deleted
ones, keyed by path).

Gated on the ``fs`` package (not bundled in this image); everything except
the ``FS`` calls is local, so the logic is fully testable with an in-memory
fake (tests/test_transport_fakes.py).
"""

from __future__ import annotations

import time
from typing import Optional

from ...internals.schema import Schema, column_definition
from ...internals.table import Table
from ..python import ConnectorSubject, read as python_read

__all__ = ["read"]

STATIC_MODE_NAME = "static"


class _FileSchema(Schema):
    path: str = column_definition(primary_key=True)
    data: bytes
    _metadata: Optional[dict] = column_definition(default_value=None)


class _PyFilesystemSubject(ConnectorSubject):
    def __init__(self, source, *, path, mode, refresh_interval, with_metadata):
        super().__init__(datasource_name="pyfilesystem")
        self.source = source
        self.path = path
        self.mode = mode
        self.refresh_interval = refresh_interval
        self.with_metadata = with_metadata
        self._modify_times: dict = {}

    def run(self) -> None:
        while True:
            started = time.time()
            changed, deleted = self._snapshot_update()
            for p in changed:
                try:
                    data = self.source.readbytes(p)
                except Exception:  # noqa: BLE001 - deleted between walk and read
                    deleted.append(p)
                    continue
                row = {"path": p, "data": data}
                if self.with_metadata:
                    row["_metadata"] = self._metadata_for(p)
                self.next(**row)
            for p in deleted:
                self._modify_times.pop(p, None)
                self.delete(path=p, data=b"")
            self.commit()
            if self.mode == STATIC_MODE_NAME:
                return
            elapsed = time.time() - started
            if elapsed < self.refresh_interval:
                time.sleep(self.refresh_interval - elapsed)

    def _metadata_for(self, p: str) -> dict:
        try:
            info = self.source.getinfo(p, namespaces=["basic", "details"])
        except Exception:  # noqa: BLE001 - racing deletion
            return {"path": p, "seen_at": int(time.time())}

        def ts(dt):
            return None if dt is None else int(dt.timestamp())

        return {
            "created_at": ts(getattr(info, "created", None)),
            "modified_at": ts(getattr(info, "modified", None)),
            "accessed_at": ts(getattr(info, "accessed", None)),
            "seen_at": int(time.time()),
            "size": getattr(info, "size", None),
            "name": getattr(info, "name", p),
            "path": p,
        }

    def _snapshot_update(self):
        changed: list = []
        existing: set = set()
        for p in self.source.walk.files(path=self.path):
            existing.add(p)
            try:
                info = self.source.getinfo(p, namespaces=["details"])
                modified = getattr(info, "modified", None)
            except Exception:  # noqa: BLE001
                continue
            if self._modify_times.get(p) != modified:
                self._modify_times[p] = modified
                changed.append(p)
        deleted = [p for p in self._modify_times if p not in existing]
        return changed, deleted


def read(
    source,
    *,
    path: str = "",
    mode: str = "streaming",
    refresh_interval: float = 30.0,
    with_metadata: bool = False,
    name: str = "pyfilesystem",
    **kwargs,
) -> Table:
    """Read a PyFilesystem ``FS`` (reference signature: source FS + path +
    mode + refresh_interval + with_metadata; rows are keyed by path and
    upserted as files change, retracted when files disappear).

    ``source`` accepts any object with the ``FS`` surface used here
    (``walk.files``, ``readbytes``, ``getinfo``) — e.g.
    ``fs.open_fs("mem://")``, an S3FS, or a zip/tar FS."""
    subject = _PyFilesystemSubject(
        source,
        path=path,
        mode=mode,
        refresh_interval=refresh_interval,
        with_metadata=with_metadata,
    )
    return python_read(subject, schema=_FileSchema, name=name, **kwargs)
