"""pw.io.debezium — Debezium CDC format
(reference: python/pathway/io/debezium/__init__.py over the DebeziumDB parser,
src/connectors/data_format.rs — parses {payload: {op, before, after}} change
messages; op c/r=insert, u=update (retract before + insert after), d=delete).

The reference consumes Debezium through Kafka; here ``read`` accepts either a
Kafka topic (when the kafka backend is available) or any stream of raw JSON
message strings — e.g. a jsonlines file/directory (each line one Debezium
envelope), which is also how the tests drive it.
"""

from __future__ import annotations

import json
from typing import Optional, Type

from ...internals.schema import Schema
from ...internals.table import Table
from .._connector import SessionWriter, register_source

__all__ = ["read", "parse_message"]


def parse_message(raw, columns):
    """Decode one Debezium envelope -> (op, before_values, after_values)."""
    if isinstance(raw, (bytes, bytearray)):
        raw = raw.decode()
    msg = json.loads(raw) if isinstance(raw, str) else raw
    payload = msg.get("payload", msg)
    op = payload.get("op", "c")
    before = payload.get("before")
    after = payload.get("after")

    def project(obj):
        if obj is None:
            return None
        return {c: obj.get(c) for c in columns}

    return op, project(before), project(after)


def read(
    rdkafka_settings=None,
    topic_name: Optional[str] = None,
    *,
    schema: Type[Schema],
    input_dir: Optional[str] = None,
    mode: str = "streaming",
    autocommit_duration_ms: int = 100,
    name: str = "debezium",
    persistent_id: Optional[str] = None,
    **kwargs,
) -> Table:
    """Read a Debezium change stream.

    Exactly one transport: ``rdkafka_settings``+``topic_name`` (Kafka) or
    ``input_dir`` (directory of jsonlines files with one envelope per line).
    """
    columns = list(schema.columns().keys())

    def apply_message(writer: SessionWriter, raw) -> None:
        try:
            op, before, after = parse_message(raw, columns)
        except (ValueError, KeyError):
            return
        if op in ("c", "r") and after is not None:
            writer.insert(after)
        elif op == "u":
            if before is not None:
                writer.remove(before)
            if after is not None:
                writer.insert(after)
        elif op == "d" and before is not None:
            writer.remove(before)

    if input_dir is not None:
        import os
        import time as _time

        def runner(writer: SessionWriter):
            pers = writer.persistence
            seen = dict((pers.offsets() or {}) if pers else {})

            def scan_once():
                changed = False
                try:
                    files = sorted(os.listdir(input_dir))
                except FileNotFoundError:
                    return False
                for fname in files:
                    fpath = os.path.join(input_dir, fname)
                    if not os.path.isfile(fpath):
                        continue
                    pos = seen.get(fpath, 0)
                    with open(fpath) as f:
                        f.seek(pos)
                        for line in f:
                            line = line.strip()
                            if line:
                                apply_message(writer, line)
                        newpos = f.tell()
                    if newpos != pos:
                        seen[fpath] = newpos
                        changed = True
                if changed and pers is not None:
                    pers.save_offsets(dict(seen))
                return changed

            if mode == "static":
                scan_once()
                return
            while True:
                scan_once()
                _time.sleep(0.2)

        return register_source(
            schema,
            runner,
            mode=mode,
            name=name,
            persistent_id=persistent_id,
            track_value_deletions=True,  # CDC update/delete envelopes
        )

    if topic_name is None:
        raise ValueError("debezium.read needs topic_name+rdkafka_settings or input_dir")

    from ..kafka import _consume_raw  # gated on a kafka client library

    def runner(writer: SessionWriter):
        for _partition, _offset, raw in _consume_raw(rdkafka_settings, topic_name):
            apply_message(writer, raw)

    return register_source(
        schema,
        runner,
        mode="streaming",
        name=name,
        persistent_id=persistent_id,
        track_value_deletions=True,  # CDC update/delete envelopes
    )
