"""pw.io.airbyte — Airbyte sources via the Airbyte protocol
(reference: python/pathway/io/airbyte/__init__.py + vendored
airbyte_serverless — 300+ SaaS sources through connector images).

The Airbyte protocol itself is just JSONL on stdout: a source process emits
``{"type": "RECORD", ...}`` / ``{"type": "STATE", ...}`` messages.  This
connector runs any source — a docker image (``docker run -i <image> read
...``), a pip-installed ``source-<name>`` entry point, or an arbitrary
``exec_command`` — and turns RECORD messages into rows and STATE messages
into committed offsets (so persistence resumes incremental syncs).  No
vendored runner library needed."""

from __future__ import annotations

import json
import os
import shlex
import shutil
import subprocess
import tempfile
import threading
import time
from typing import Dict, List, Optional, Sequence

from ...internals.schema import schema_from_types
from ...internals.table import Table
from .._connector import SessionWriter, register_source

__all__ = ["read"]


def _source_command(
    config_path: str,
    catalog_path: str,
    state_path: Optional[str],
    *,
    image: Optional[str],
    exec_command: Optional[str],
    env_vars: Optional[Dict[str, str]],
) -> List[str]:
    if exec_command:
        cmd = shlex.split(exec_command)
    elif image:
        if shutil.which("docker") is None:
            raise RuntimeError(
                "pw.io.airbyte with a connector image needs docker on PATH; "
                "alternatively pass exec_command for a locally installed "
                "source"
            )
        mounts = []
        for p in (config_path, catalog_path, state_path):
            if p:
                mounts += ["-v", f"{os.path.abspath(p)}:{os.path.abspath(p)}:ro"]
        envs = []
        for k, v in (env_vars or {}).items():
            envs += ["-e", f"{k}={v}"]
        cmd = ["docker", "run", "--rm", "-i", *mounts, *envs, image]
    else:
        raise ValueError("pw.io.airbyte needs `image` or `exec_command`")
    # absolute paths: a docker container resolves relative paths against its
    # own workdir, not the host cwd the mounts were built from
    cmd += [
        "read",
        "--config", os.path.abspath(config_path),
        "--catalog", os.path.abspath(catalog_path),
    ]
    if state_path:
        cmd += ["--state", os.path.abspath(state_path)]
    return cmd


def _configured_catalog(streams: Sequence[str]) -> dict:
    return {
        "streams": [
            {
                "stream": {
                    "name": s,
                    "json_schema": {},
                    "supported_sync_modes": ["full_refresh", "incremental"],
                },
                "sync_mode": "incremental",
                "destination_sync_mode": "append",
            }
            for s in streams
        ]
    }


def read(
    config: Optional[dict] = None,
    streams: Optional[List[str]] = None,
    *,
    config_file_path: Optional[str] = None,
    image: Optional[str] = None,
    exec_command: Optional[str] = None,
    env_vars: Optional[Dict[str, str]] = None,
    mode: str = "streaming",
    refresh_interval_ms: int = 60000,
    name: str = "airbyte",
    persistent_id: Optional[str] = None,
    **kwargs,
) -> Table:
    """Rows: ``stream`` (str), ``data`` (the record JSON).

    ``config``/``config_file_path``: the source's connection config.
    ``image``: an Airbyte source docker image (e.g.
    ``airbyte/source-github``); ``exec_command``: a locally installed source
    binary instead.  STATE messages commit as offsets, so incremental syncs
    resume across restarts under a persistence config."""
    if not streams:
        raise ValueError("pw.io.airbyte requires the list of streams to sync")
    schema = schema_from_types(stream=str, data=dict)

    def runner(writer: SessionWriter):
        # mkdtemp is 0700; removed in the finally so source credentials in
        # config.json never outlive the run
        workdir = tempfile.mkdtemp(prefix="pw-airbyte-")
        try:
            _run_source(writer, workdir)
        finally:
            shutil.rmtree(workdir, ignore_errors=True)

    def _run_source(writer: SessionWriter, workdir: str):
        from ...internals.keys import ref_scalar

        config_path = config_file_path
        if config_path is None:
            config_path = os.path.join(workdir, "config.json")
            with open(config_path, "w") as f:
                json.dump(config or {}, f)
        catalog_path = os.path.join(workdir, "catalog.json")
        with open(catalog_path, "w") as f:
            json.dump(_configured_catalog(streams), f)

        pers = writer.persistence
        state = (pers.offsets() or {}).get("state") if pers else None
        while True:
            state_path = None
            if state is not None:
                state_path = os.path.join(workdir, "state.json")
                with open(state_path, "w") as f:
                    json.dump(state, f)
            cmd = _source_command(
                config_path,
                catalog_path,
                state_path,
                image=image,
                exec_command=exec_command,
                env_vars=env_vars,
            )
            proc = subprocess.Popen(
                cmd,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
            assert proc.stdout is not None

            # drain stderr concurrently: a chatty source would fill the OS
            # pipe buffer and deadlock against our stdout read
            err_tail: List[str] = []

            def _drain(stream=proc.stderr):
                for err_line in stream:
                    err_tail.append(err_line)
                    del err_tail[:-50]

            drainer = threading.Thread(target=_drain, daemon=True)
            drainer.start()
            for line in proc.stdout:
                line = line.strip()
                if not line:
                    continue
                try:
                    message = json.loads(line)
                except json.JSONDecodeError:
                    continue  # sources also log plain text to stdout
                mtype = message.get("type")
                if mtype == "RECORD":
                    record = message.get("record", {})
                    stream_name = record.get("stream", "")
                    data = record.get("data", {})
                    # content-derived key + upsert session: a full-refresh
                    # source re-emitting its dataset each cycle lands on the
                    # same keys instead of duplicating rows every refresh
                    key = int(
                        ref_scalar(
                            stream_name, json.dumps(data, sort_keys=True)
                        )
                    )
                    writer.insert(
                        {"stream": stream_name, "data": data}, key=key
                    )
                elif mtype == "STATE":
                    state = message.get("state")
                    writer.commit_offsets({"state": state})
            rc = proc.wait()
            drainer.join(timeout=5)
            if rc != 0:
                err = "".join(err_tail)[-2000:]
                raise RuntimeError(f"airbyte source exited rc={rc}:\n{err}")
            if mode == "static":
                return
            time.sleep(refresh_interval_ms / 1000.0)

    def dist_runner(writer: SessionWriter) -> None:
        # distributed: ONE rank runs the external source (a docker/exec
        # Airbyte connector per rank would duplicate reads and side
        # effects); rows are disjoint-by-construction and re-scatter to
        # their key owners via the partitioned source exchange
        from ...parallel.distributed import topology_from_env

        processes, pid, _addr = topology_from_env()
        if processes > 1 and pid != 0:
            return
        runner(writer)

    return register_source(
        schema,
        dist_runner,
        mode=mode,
        upsert=True,
        name=name,
        persistent_id=persistent_id,
        dist_mode="partitioned",
    )
