"""pw.io.airbyte — Airbyte sources
(reference: python/pathway/io/airbyte/__init__.py + vendored
airbyte_serverless — 300+ SaaS sources via Airbyte connector docker images /
pypi packages).  Gated: requires an airbyte runner (docker or
airbyte-serverless), neither bundled."""

from __future__ import annotations

from typing import Dict, List, Optional

from ...internals.table import Table

__all__ = ["read"]


def read(
    config_file_path: str,
    streams: List[str],
    *,
    mode: str = "streaming",
    refresh_interval_ms: int = 60000,
    **kwargs,
) -> Table:
    raise ImportError(
        "pw.io.airbyte requires an Airbyte source runner (docker or the "
        "airbyte-serverless package), which is not installed in this "
        "environment; ingest via pw.io.kafka / pw.io.fs instead"
    )
