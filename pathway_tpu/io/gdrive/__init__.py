"""pw.io.gdrive — Google Drive input connector
(reference: python/pathway/io/gdrive/__init__.py, 401 LoC — lists a folder
via the Drive v3 API, downloads new/changed objects, emits file bytes).
Gated on google-api-python-client (not bundled)."""

from __future__ import annotations

import time
from typing import Optional

from ...internals.schema import schema_from_types
from ...internals.table import Table
from .._connector import SessionWriter, register_source
from .._gated import require

__all__ = ["read"]


def read(
    object_id: str,
    *,
    mode: str = "streaming",
    refresh_interval: int = 30,
    service_user_credentials_file: str,
    with_metadata: bool = False,
    name: str = "gdrive",
    persistent_id: Optional[str] = None,
    **kwargs,
) -> Table:
    require("googleapiclient", "gdrive", "pip package google-api-python-client")
    schema = schema_from_types(data=bytes)

    def runner(writer: SessionWriter):
        from google.oauth2.service_account import Credentials  # type: ignore
        from googleapiclient.discovery import build  # type: ignore

        creds = Credentials.from_service_account_file(
            service_user_credentials_file,
            scopes=["https://www.googleapis.com/auth/drive.readonly"],
        )
        service = build("drive", "v3", credentials=creds)
        pers = writer.persistence
        seen = dict((pers.offsets() or {}) if pers else {})
        while True:
            resp = (
                service.files()
                .list(
                    q=f"'{object_id}' in parents and trashed = false",
                    fields="files(id, name, modifiedTime)",
                )
                .execute()
            )
            for f in resp.get("files", []):
                fid, mtime = f["id"], f.get("modifiedTime", "")
                if seen.get(fid) == mtime:
                    continue
                data = service.files().get_media(fileId=fid).execute()
                writer.insert({"data": data})
                seen[fid] = mtime
                if pers is not None:
                    pers.save_offsets(dict(seen))
            if mode == "static":
                return
            time.sleep(refresh_interval)

    return register_source(
        schema, runner, mode=mode, name=name, persistent_id=persistent_id
    )
