"""pw.io.sqlite — SQLite connector
(reference: python/pathway/io/sqlite/__init__.py over SqliteReader,
src/connectors/data_storage.rs — snapshot reads of a table with rowid-based
change detection).

``read``: static mode loads the table once; streaming mode polls, treating
the table as an upsert stream keyed by the schema's primary key (or rowid) —
new/changed rows upsert, disappeared keys retract.
``write``: maintains a mirror table of the output stream.
"""

from __future__ import annotations

import sqlite3
import threading
import time
from typing import Optional, Type

from ...internals.keys import ref_scalar
from ...internals.schema import Schema
from ...internals.table import Table
from .._connector import SessionWriter, register_source

__all__ = ["read", "write"]


def read(
    path: str,
    table_name: str,
    schema: Type[Schema],
    *,
    mode: str = "streaming",
    poll_interval_s: float = 0.2,
    name: str = "sqlite",
    persistent_id: Optional[str] = None,
) -> Table:
    columns = list(schema.columns().keys())
    pkey = schema.primary_key_columns()
    col_sql = ", ".join(columns)
    query = f"SELECT rowid, {col_sql} FROM {table_name}"  # noqa: S608 (local file db)

    def snapshot(conn):
        rows = {}
        for row in conn.execute(query):
            rowid, values = row[0], row[1:]
            rec = dict(zip(columns, values))
            if pkey:
                key = tuple(rec[c] for c in pkey)
            else:
                key = rowid
            rows[key] = rec
        return rows

    if mode == "static":

        def runner(writer: SessionWriter):
            conn = sqlite3.connect(path)
            try:
                for rec in snapshot(conn).values():
                    writer.insert(rec)
            finally:
                conn.close()

        return register_source(
            schema, runner, mode="static", name=name, upsert=bool(pkey),
            persistent_id=persistent_id,
        )

    def runner(writer: SessionWriter):
        conn = sqlite3.connect(path)
        previous = {}

        def engine_key(ident):
            # without a primary key, rowid is the stable row identity —
            # derive the engine key from it so updates retract the right row
            if pkey:
                return None  # writer derives the key from the pkey columns
            return int(ref_scalar("_sqlite_rowid", ident))

        try:
            while True:
                current = snapshot(conn)
                for ident, rec in current.items():
                    if previous.get(ident) != rec:
                        writer.insert(rec, key=engine_key(ident))
                for ident, rec in previous.items():
                    if ident not in current:
                        writer.remove(rec, key=engine_key(ident))
                previous = current
                time.sleep(poll_interval_s)
        finally:
            conn.close()

    return register_source(
        schema, runner, mode="streaming", name=name, upsert=True,
        persistent_id=persistent_id,
    )


def write(table: Table, path: str, table_name: str) -> None:
    """Mirror the table's update stream into a SQLite table (insert on +1,
    delete on -1; the mirror converges to the live table contents)."""
    from .._subscribe import subscribe

    names = table.column_names
    cols_sql = ", ".join(f'"{c}"' for c in names)
    qmarks = ", ".join("?" for _ in names)
    lock = threading.Lock()
    conn = sqlite3.connect(path, check_same_thread=False)
    conn.execute(
        f'CREATE TABLE IF NOT EXISTS "{table_name}" '
        f"({cols_sql}, _pw_key INTEGER)"
    )
    conn.commit()

    def on_change(key, row, time, is_addition):
        skey = int(key) - (1 << 63)  # sqlite INTEGER is signed 64-bit
        with lock:
            if is_addition:
                conn.execute(
                    f'INSERT INTO "{table_name}" ({cols_sql}, _pw_key) '
                    f"VALUES ({qmarks}, ?)",
                    [_sqlite_value(row[c]) for c in names] + [skey],
                )
            else:
                conn.execute(
                    f'DELETE FROM "{table_name}" WHERE _pw_key = ?', (skey,)
                )

    def on_time_end(ts):
        with lock:
            conn.commit()

    def on_end():
        with lock:
            conn.commit()
            conn.close()

    subscribe(table, on_change=on_change, on_time_end=on_time_end, on_end=on_end)


def _sqlite_value(v):
    import numpy as np

    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, (np.bool_, bool)):
        return int(v)
    if isinstance(v, np.ndarray):
        return v.tobytes()
    return v
