"""pw.io.csv (reference: python/pathway/io/csv/__init__.py → io/fs)."""

from __future__ import annotations

from typing import Optional, Type

from ...internals.schema import Schema
from ...internals.table import Table
from .. import fs as _fs

# re-export the DSV settings next to the reader, like the reference
from ..fs import CsvParserSettings  # noqa: F401

__all__ = ["read", "write", "CsvParserSettings"]


def read(
    path: str,
    *,
    schema: Optional[Type[Schema]] = None,
    mode: str = "streaming",
    **kwargs,
) -> Table:
    return _fs.read(path, format="csv", schema=schema, mode=mode, **kwargs)


def write(table: Table, filename: str, **kwargs) -> None:
    _fs.write(table, filename, format="csv", **kwargs)
