"""pw.io.kafka — Kafka connector
(reference: python/pathway/io/kafka/__init__.py, 686 LoC, over KafkaReader /
KafkaWriter, src/connectors/data_storage.rs).

Gated on a Python Kafka client (``kafka-python`` or ``confluent_kafka`` —
neither is bundled in this image); all parsing/formatting logic is local so
only the transport needs the client library.
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, Optional, Type

from ...internals.schema import Schema, schema_from_types
from ...internals.table import Table
from .._connector import SessionWriter, register_source

__all__ = ["read", "write", "simple_read"]


def _make_consumer(rdkafka_settings: Dict, topic: str):
    try:
        from kafka import KafkaConsumer  # type: ignore

        return KafkaConsumer(
            topic,
            bootstrap_servers=rdkafka_settings.get("bootstrap.servers"),
            group_id=rdkafka_settings.get("group.id"),
            auto_offset_reset=rdkafka_settings.get("auto.offset.reset", "earliest"),
        )
    except ImportError:
        pass
    try:
        from confluent_kafka import Consumer  # type: ignore

        consumer = Consumer(rdkafka_settings)
        consumer.subscribe([topic])
        return consumer
    except ImportError as e:
        raise ImportError(
            "pw.io.kafka requires a Kafka client library (kafka-python or "
            "confluent_kafka); neither is installed"
        ) from e


def _consume_raw(rdkafka_settings: Dict, topic: str):
    """Yield ``(partition, offset, payload)``; partition/offset are None
    only for clients that do not expose them (both real clients do)."""
    consumer = _make_consumer(rdkafka_settings or {}, topic)
    if hasattr(consumer, "poll") and not hasattr(consumer, "subscription"):
        # confluent_kafka style
        while True:
            msg = consumer.poll(0.2)
            if msg is None or msg.error():
                continue
            yield msg.partition(), msg.offset(), msg.value()
    else:  # kafka-python style iterator
        for msg in consumer:
            yield (
                getattr(msg, "partition", None),
                getattr(msg, "offset", None),
                msg.value,
            )


def read(
    rdkafka_settings: Dict,
    topic: Optional[str] = None,
    *,
    schema: Optional[Type[Schema]] = None,
    format: str = "json",
    autocommit_duration_ms: int = 100,
    name: str = "kafka",
    persistent_id: Optional[str] = None,
    **kwargs,
) -> Table:
    """Consume a topic as a stream of rows (json / plaintext / raw)."""
    if format in ("plaintext", "raw"):
        schema = schema or schema_from_types(data=(str if format == "plaintext" else bytes))
    elif schema is None:
        raise ValueError(f"schema is required for format={format!r}")
    columns = list(schema.columns().keys())
    has_pk = schema.primary_key_columns() is not None

    # distributed placement depends on the consumer-group config: WITH a
    # group.id, brokers hand each rank a DISJOINT partition subset —
    # partitioned, true parallel consumption.  WITHOUT one, every rank's
    # consumer reads ALL partitions (identical streams) — replicated, the
    # engine keeps each rank's owned-key slice.  Replicated mode only works
    # if every rank mints the SAME key for the same record, but brokers
    # interleave partitions nondeterministically, so per-rank sequential
    # keys would diverge — keys for non-PK rows are instead derived from
    # (topic, partition, offset), which is order-independent (the analog of
    # the reference's offset-based snapshot identity, src/connectors/offset.rs).
    has_group = bool((rdkafka_settings or {}).get("group.id"))

    from ...internals.keys import ref_scalar
    from ...parallel.distributed import topology_from_env

    nproc, _rank, _addr = topology_from_env()
    replicated_multiproc = (not has_group) and nproc > 1
    # per-read() ordinal, identical across ranks (same script, same build
    # order): folded into the derived key so two no-PK reads of the SAME
    # topic stay key-disjoint (concat-safe), like the per-source salt does
    # for sequential keys.  Scoped to the graph, not the module, so a
    # rank that happens to have built an earlier graph in-process does not
    # drift from fresh ranks.
    from ...internals.parse_graph import G

    ordinal = G.claim_io_ordinal("kafka")

    def runner(writer: SessionWriter):
        for partition, offset, raw in _consume_raw(rdkafka_settings, topic):
            key = None
            if not has_pk and partition is not None and offset is not None:
                key = int(
                    ref_scalar(
                        "kafka", ordinal, topic or "", int(partition), int(offset)
                    )
                )
            elif key is None and not has_pk and replicated_multiproc:
                raise ValueError(
                    "pw.io.kafka: replicated (group-id-less) consumption in a "
                    "multi-process run needs deterministic record identity, "
                    "but this client exposes no partition/offset — set a "
                    "group.id (partitioned mode) or add a primary key"
                )
            if format == "raw":
                writer.insert({"data": raw}, key=key)
            elif format == "plaintext":
                writer.insert({"data": raw.decode(errors="replace")}, key=key)
            else:
                try:
                    obj = json.loads(raw)
                except ValueError:
                    continue
                writer.insert({c: obj.get(c) for c in columns}, key=key)
    return register_source(
        schema,
        runner,
        mode="streaming",
        name=name,
        persistent_id=persistent_id,
        dist_mode="partitioned" if has_group else "replicated",
    )


def simple_read(server: str, topic: str, *, format: str = "raw", **kwargs) -> Table:
    return read(
        {"bootstrap.servers": server, "group.id": f"pathway-{topic}"},
        topic,
        format=format,
        **kwargs,
    )


def write(
    table: Table,
    rdkafka_settings: Dict,
    topic_name: str,
    *,
    format: str = "json",
    **kwargs,
) -> None:
    """Produce the table's update stream to a topic (json rows with
    time/diff fields, matching the reference's output format)."""
    try:
        from kafka import KafkaProducer  # type: ignore

        producer = KafkaProducer(
            bootstrap_servers=rdkafka_settings.get("bootstrap.servers")
        )

        def send(payload: bytes):
            producer.send(topic_name, payload)

        def flush():
            producer.flush()

    except ImportError:
        try:
            from confluent_kafka import Producer  # type: ignore

            producer = Producer(rdkafka_settings)

            def send(payload: bytes):
                producer.produce(topic_name, payload)

            def flush():
                producer.flush()

        except ImportError as e:
            raise ImportError(
                "pw.io.kafka requires a Kafka client library (kafka-python or "
                "confluent_kafka); neither is installed"
            ) from e

    from .._connector import jsonable as _jsonable
    from .._subscribe import subscribe

    names = table.column_names

    def on_change(key, row, time, is_addition):
        obj = {n: _jsonable(row[n]) for n in names}
        obj["time"] = time
        obj["diff"] = 1 if is_addition else -1
        send(json.dumps(obj).encode())

    subscribe(table, on_change=on_change, on_time_end=lambda ts: flush())
