"""pw.io.logstash — Logstash output connector
(reference: python/pathway/io/logstash/__init__.py — posts the update stream
to Logstash's http input plugin).  Uses ``requests`` (bundled)."""

from __future__ import annotations

import json

from ...internals.table import Table
from .._subscribe import subscribe

__all__ = ["write"]


def write(table: Table, endpoint: str, n_retries: int = 0, **kwargs) -> None:
    import requests

    names = table.column_names
    session = requests.Session()

    def on_change(key, row, time, is_addition):
        obj = {n: _plain(row[n]) for n in names}
        obj["time"] = time
        obj["diff"] = 1 if is_addition else -1
        last_err = None
        for _ in range(n_retries + 1):
            try:
                resp = session.post(
                    endpoint,
                    data=json.dumps(obj),
                    headers={"Content-Type": "application/json"},
                )
                resp.raise_for_status()
                return
            except requests.RequestException as e:  # pragma: no cover
                last_err = e
        if last_err is not None:
            raise last_err

    subscribe(table, on_change=on_change)


from .._connector import jsonable as _plain  # noqa: E402
