"""pw.io.elasticsearch — Elasticsearch output connector
(reference: python/pathway/io/elasticsearch/__init__.py over ElasticSearchWriter,
src/connectors/data_storage.rs).  Implemented over the REST bulk API with
``requests`` (bundled) — no elasticsearch client library needed.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, Optional

from ...internals.table import Table
from .._subscribe import subscribe

__all__ = ["write", "ElasticSearchAuth"]


class ElasticSearchAuth:
    """Auth settings (reference ElasticSearchAuth: basic / apikey / bearer)."""

    def __init__(self, kind: str, **params):
        self.kind = kind
        self.params = params

    @classmethod
    def basic(cls, username: str, password: str) -> "ElasticSearchAuth":
        return cls("basic", username=username, password=password)

    @classmethod
    def apikey(cls, apikey: str) -> "ElasticSearchAuth":
        return cls("apikey", apikey=apikey)

    def headers(self) -> Dict[str, str]:
        if self.kind == "apikey":
            return {"Authorization": f"ApiKey {self.params['apikey']}"}
        return {}

    def requests_auth(self):
        if self.kind == "basic":
            return (self.params["username"], self.params["password"])
        return None


def write(
    table: Table,
    host: str,
    auth: Optional[ElasticSearchAuth] = None,
    index_name: str = "pathway",
    *,
    batch_size: int = 500,
    **kwargs,
) -> None:
    """Index the table's update stream; insertions index documents (doc id =
    row key), deletions delete them — the index converges to the table."""
    import requests

    names = table.column_names
    lock = threading.Lock()
    buffer = []
    session = requests.Session()
    if auth is not None:
        session.headers.update(auth.headers())
        a = auth.requests_auth()
        if a:
            session.auth = a

    def flush_locked():
        if not buffer:
            return
        payload = "\n".join(buffer) + "\n"
        del buffer[:]
        resp = session.post(
            f"{host.rstrip('/')}/_bulk",
            data=payload,
            headers={"Content-Type": "application/x-ndjson"},
        )
        resp.raise_for_status()
        # _bulk returns HTTP 200 even when individual items fail
        body = resp.json()
        if body.get("errors"):
            failed = [
                item
                for item in body.get("items", [])
                for op in item.values()
                if op.get("error")
            ]
            raise RuntimeError(
                f"Elasticsearch bulk rejected {len(failed)} item(s): "
                f"{failed[:3]!r}"
            )

    def on_change(key, row, time, is_addition):
        doc_id = str(int(key))
        with lock:
            if is_addition:
                buffer.append(json.dumps({"index": {"_index": index_name, "_id": doc_id}}))
                buffer.append(json.dumps({n: _jsonable(row[n]) for n in names}))
            else:
                buffer.append(json.dumps({"delete": {"_index": index_name, "_id": doc_id}}))
            if len(buffer) >= batch_size:
                flush_locked()

    def on_time_end(ts):
        with lock:
            flush_locked()

    subscribe(table, on_change=on_change, on_time_end=on_time_end,
              on_end=lambda: on_time_end(None))


from .._connector import jsonable as _jsonable  # noqa: E402
