"""pw.io.s3 / s3_csv / minio — object-store connectors
(reference: python/pathway/io/s3/__init__.py over the S3 scanner,
src/connectors/scanner/s3.rs — posix-like listing + object reads).
Gated on boto3 (not bundled); parsing reuses the fs format stack.
"""

from __future__ import annotations

import hashlib
import os
import zlib
import tempfile
import time
from typing import Optional, Type

from ...internals.schema import Schema
from ...internals.table import Table
from .._connector import SessionWriter, register_source
from .._gated import require

__all__ = ["read", "AwsS3Settings"]


class AwsS3Settings:
    """(reference AwsS3Settings: bucket, region, access keys, endpoint)"""

    def __init__(
        self,
        bucket_name: Optional[str] = None,
        access_key: Optional[str] = None,
        secret_access_key: Optional[str] = None,
        region: Optional[str] = None,
        endpoint: Optional[str] = None,
        with_path_style: bool = False,
    ):
        self.bucket_name = bucket_name
        self.access_key = access_key
        self.secret_access_key = secret_access_key
        self.region = region
        self.endpoint = endpoint
        self.with_path_style = with_path_style

    def create_client(self):
        boto3 = require("boto3", "s3")
        kwargs = {}
        if self.access_key:
            kwargs["aws_access_key_id"] = self.access_key
        if self.secret_access_key:
            kwargs["aws_secret_access_key"] = self.secret_access_key
        if self.region:
            kwargs["region_name"] = self.region
        if self.endpoint:
            kwargs["endpoint_url"] = self.endpoint
        return boto3.client("s3", **kwargs)


def read(
    path: str,
    *,
    aws_s3_settings: Optional[AwsS3Settings] = None,
    format: str = "csv",
    schema: Optional[Type[Schema]] = None,
    mode: str = "streaming",
    poll_interval_s: float = 5.0,
    name: str = "s3",
    persistent_id: Optional[str] = None,
    csv_settings=None,
    **kwargs,
) -> Table:
    """Read objects under ``s3://bucket/prefix`` (or ``path`` as prefix with
    settings.bucket_name), parsing like pw.io.fs."""
    settings = aws_s3_settings or AwsS3Settings()
    bucket, prefix = _split_path(path, settings)
    client = settings.create_client()
    # objects are downloaded (etag-versioned) into a temp dir, then parsed by
    # the shared fs format stack
    tmpdir = tempfile.mkdtemp(prefix="pw_s3_")

    def runner(writer: SessionWriter):
        pers = writer.persistence
        seen = dict((pers.offsets() or {}) if pers else {})
        from ..fs import _parse_into  # shared single-file parser

        from ...parallel.distributed import topology_from_env

        nproc, rank, _addr = topology_from_env()
        while True:
            paginator = client.get_paginator("list_objects_v2")
            for page in paginator.paginate(Bucket=bucket, Prefix=prefix):
                for obj in page.get("Contents", []):
                    key, etag = obj["Key"], obj.get("ETag", "")
                    if nproc > 1 and (
                        zlib.crc32(key.encode()) % nproc != rank
                    ):
                        continue  # another rank owns this object (parallel readers)
                    if seen.get(key) == etag:
                        continue
                    # hash-suffixed cache name: '/'-flattening alone is not
                    # injective ('a/b' vs 'a__b')
                    digest = hashlib.sha1(key.encode()).hexdigest()[:12]
                    local = os.path.join(
                        tmpdir, f"{os.path.basename(key)}.{digest}"
                    )
                    client.download_file(bucket, key, local)
                    _parse_into(
                        local, writer, format, schema, csv_settings=csv_settings
                    )
                    seen[key] = etag
                    if pers is not None:
                        pers.save_offsets(dict(seen))
            if mode == "static":
                return
            time.sleep(poll_interval_s)

    return register_source(
        schema,
        runner,
        mode=mode,
        name=name,
        persistent_id=persistent_id,
        dist_mode="partitioned",
    )


def _split_path(path: str, settings: AwsS3Settings):
    if path.startswith("s3://"):
        rest = path[5:]
        bucket, _, prefix = rest.partition("/")
        return bucket, prefix
    if settings.bucket_name is None:
        raise ValueError("bucket not given (use s3://bucket/prefix or settings)")
    return settings.bucket_name, path.lstrip("/")
