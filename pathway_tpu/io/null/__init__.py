"""pw.io.null — sink that discards output (reference: python/pathway/io/null)."""

from __future__ import annotations

from ...internals.table import Table
from .._subscribe import subscribe

__all__ = ["write"]


def write(table: Table, **kwargs) -> None:
    subscribe(table, on_change=lambda **kw: None)
