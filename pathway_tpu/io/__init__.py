"""pw.io — connectors
(reference inventory: python/pathway/io/ — fs, csv, jsonlines, plaintext,
kafka, s3, http, python, debezium, postgres, elasticsearch, … — SURVEY.md
§2.8).  Implemented natively here: fs/csv/jsonlines/plaintext/binary, python
subjects, http (REST server), subscribe, null; service-backed connectors
(kafka, s3, postgres, …) arrive as optional backends behind the same
Reader/Writer split."""

from __future__ import annotations

from . import csv, fs, jsonlines, null, plaintext, python
from ._subscribe import subscribe

# http imported lazily (aiohttp); kept importable as pw.io.http
from . import http  # noqa: E402

__all__ = [
    "csv",
    "fs",
    "jsonlines",
    "null",
    "plaintext",
    "python",
    "http",
    "subscribe",
]
