"""pw.io — connectors
(reference inventory: python/pathway/io/ — SURVEY.md §2.8).

Natively implemented: fs/csv/jsonlines/plaintext/binary, python subjects,
http (REST server), subscribe, null, sqlite, debezium (file transport),
elasticsearch/logstash/slack (REST via requests), bigquery (bundled client).
Service-library-gated (import succeeds, transport errors with a clear
message at call time): kafka, redpanda, nats, s3/minio, deltalake, postgres,
mongodb, pubsub, gdrive, airbyte.
"""

from __future__ import annotations

from . import (
    airbyte,
    bigquery,
    csv,
    debezium,
    deltalake,
    elasticsearch,
    fs,
    gdrive,
    jsonlines,
    kafka,
    logstash,
    minio,
    mongodb,
    nats,
    null,
    plaintext,
    postgres,
    pubsub,
    pyfilesystem,
    python,
    redpanda,
    s3,
    s3_csv,
    sharepoint,
    slack,
    sqlite,
)
from ._subscribe import subscribe

# http imported lazily (aiohttp); kept importable as pw.io.http
from . import http  # noqa: E402

__all__ = [
    "airbyte",
    "bigquery",
    "csv",
    "debezium",
    "deltalake",
    "elasticsearch",
    "fs",
    "gdrive",
    "http",
    "jsonlines",
    "kafka",
    "logstash",
    "minio",
    "mongodb",
    "nats",
    "null",
    "plaintext",
    "postgres",
    "pubsub",
    "pyfilesystem",
    "python",
    "redpanda",
    "s3",
    "s3_csv",
    "sharepoint",
    "slack",
    "sqlite",
    "subscribe",
]
