"""pw.io.http — REST server connector + HTTP client writers
(reference: python/pathway/io/http/_server.py:126-624 — PathwayWebserver,
rest_connector, EndpointDocumentation; the serving path of every RAG/QA
template)."""

from __future__ import annotations

import asyncio
import json as _json
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple, Type

import numpy as np

from ...internals import dtype as dt
from ...internals.keys import Pointer
from ...internals.parse_graph import G
from ...internals.schema import Schema, schema_from_types
from ...internals.table import Table
from .._connector import SessionWriter, register_source
from .._subscribe import subscribe

__all__ = [
    "PathwayWebserver",
    "rest_connector",
    "EndpointDocumentation",
    "RestServerSubject",
]


@dataclass
class EndpointDocumentation:
    """OpenAPI metadata for a route (reference: _server.py:126)."""

    summary: Optional[str] = None
    description: Optional[str] = None
    tags: Optional[Sequence[str]] = None
    method_types: Optional[Sequence[str]] = None


def _jsonable(v: Any) -> Any:
    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, np.floating):
        return float(v)
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, bytes):
        return v.decode(errors="replace")
    if isinstance(v, Pointer):
        return str(v)
    if isinstance(v, tuple):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_jsonable(x) for x in v]
    return v


class PathwayWebserver:
    """aiohttp server running on its own thread + event loop
    (reference: _server.py:329).  Routes are added by rest_connector before
    ``pw.run``; the server starts in a pre-run hook."""

    def __init__(self, host: str = "0.0.0.0", port: int = 8080, with_cors: bool = False):
        self.host = host
        self.port = port
        self.with_cors = with_cors
        self._routes: List[Tuple[str, Sequence[str], Any, EndpointDocumentation]] = []
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._started = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._runner = None
        self._registered_hook = False

    def _register_start_hook(self):
        if not self._registered_hook:
            self._registered_hook = True
            G.pre_run_hooks.append(self.start)
            G.post_run_hooks.append(self.stop)

    def add_route(self, route: str, methods: Sequence[str], handler, documentation=None):
        self._routes.append(
            (route, methods, handler, documentation or EndpointDocumentation())
        )
        self._register_start_hook()

    @property
    def loop(self) -> asyncio.AbstractEventLoop:
        assert self._loop is not None, "webserver not started"
        return self._loop

    def openapi_description_json(self) -> Dict[str, Any]:
        paths: Dict[str, Any] = {}
        for route, methods, _handler, doc in self._routes:
            entry = {}
            for m in methods:
                entry[m.lower()] = {
                    "summary": doc.summary or route,
                    "description": doc.description or "",
                    "tags": list(doc.tags or []),
                    "responses": {"200": {"description": "success"}},
                }
            paths[route] = entry
        return {
            "openapi": "3.0.3",
            "info": {"title": "pathway_tpu app", "version": "1.0"},
            "paths": paths,
        }

    def start(self) -> None:
        if self._thread is not None:
            return

        from aiohttp import web

        def run_loop():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop

            app = web.Application()
            for route, methods, handler, _doc in self._routes:
                for m in methods:
                    app.router.add_route(m, route, handler)

            async def openapi_handler(request):
                return web.json_response(self.openapi_description_json())

            app.router.add_route("GET", "/_schema", openapi_handler)

            if self.with_cors:

                @web.middleware
                async def cors_middleware(request, handler):
                    if request.method == "OPTIONS":
                        resp = web.Response()
                    else:
                        resp = await handler(request)
                    resp.headers["Access-Control-Allow-Origin"] = "*"
                    resp.headers["Access-Control-Allow-Headers"] = "*"
                    return resp

                app.middlewares.append(cors_middleware)

            runner = web.AppRunner(app)
            loop.run_until_complete(runner.setup())
            site = web.TCPSite(runner, self.host, self.port)
            loop.run_until_complete(site.start())
            self._runner = runner
            self._started.set()
            loop.run_forever()

        self._thread = threading.Thread(target=run_loop, daemon=True, name="webserver")
        self._thread.start()
        self._started.wait(timeout=10)

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)


class RestServerSubject:
    """Bridges HTTP requests into the queries table and resolves responses
    (reference: _server.py:490)."""

    def __init__(
        self,
        webserver: PathwayWebserver,
        route: str,
        methods: Sequence[str],
        schema: Type[Schema],
        delete_completed_queries: bool,
        request_validator=None,
        documentation: Optional[EndpointDocumentation] = None,
    ):
        self.webserver = webserver
        self.route = route
        self.methods = methods
        self.schema = schema
        self.delete_completed_queries = delete_completed_queries
        self.request_validator = request_validator
        self._writer: Optional[SessionWriter] = None
        self._futures: Dict[int, asyncio.Future] = {}
        self._lock = threading.Lock()
        webserver.add_route(route, methods, self._handle, documentation)

    def attach_writer(self, writer: SessionWriter) -> None:
        self._writer = writer

    async def _handle(self, request):
        from aiohttp import web

        if request.method in ("POST", "PUT", "PATCH"):
            try:
                payload = await request.json()
            except Exception:
                payload = {}
        else:
            payload = dict(request.query)
        if self.request_validator is not None:
            try:
                self.request_validator(payload)
            except Exception as e:
                return web.json_response({"error": str(e)}, status=400)
        columns = list(self.schema.columns().keys())
        defaults = self.schema.default_values()
        values = {}
        for c in columns:
            if c in payload:
                values[c] = payload[c]
            elif c in defaults:
                values[c] = defaults[c]
            else:
                values[c] = None
        assert self._writer is not None
        key = self._writer.key_of({**values, "_request_seq": id(request)})
        future = asyncio.get_event_loop().create_future()
        with self._lock:
            self._futures[int(key)] = future
        self._writer.insert(values, key=key)
        try:
            result = await asyncio.wait_for(future, timeout=120)
        except asyncio.TimeoutError:
            return web.json_response({"error": "timeout"}, status=504)
        finally:
            with self._lock:
                self._futures.pop(int(key), None)
            if self.delete_completed_queries:
                self._writer.session.remove(int(key))
        from ...internals.error_value import is_error

        if is_error(result):
            return web.json_response(
                {"error": getattr(result, "message", "") or "computation failed"},
                status=500,
            )
        return web.json_response(_jsonable(result))

    def resolve(self, key: int, value: Any) -> None:
        with self._lock:
            future = self._futures.get(int(key))
        if future is not None and not future.done():
            self.webserver.loop.call_soon_threadsafe(
                lambda: future.set_result(value) if not future.done() else None
            )


class _ResponseWriter:
    def __init__(self, subject: Optional[RestServerSubject]):
        # subject is None on non-frontend cluster ranks: the subscriber edge
        # gathers response rows to rank 0, so only rank 0 resolves futures —
        # but every rank must register the SAME operator (SPMD graph shape)
        self.subject = subject

    def __call__(self, response_table: Table) -> None:
        names = response_table.column_names
        if self.subject is None:
            subscribe(response_table, on_change=None)
            return

        def on_change(key, row, time, is_addition):
            if not is_addition:
                return
            if "result" in row:
                value = row["result"]
            elif len(names) == 1:
                value = row[names[0]]
            else:
                value = row
            self.subject.resolve(int(key), value)

        subscribe(response_table, on_change=on_change)


def rest_connector(
    host: Optional[str] = None,
    port: Optional[int] = None,
    *,
    webserver: Optional[PathwayWebserver] = None,
    route: str = "/",
    schema: Optional[Type[Schema]] = None,
    methods: Sequence[str] = ("POST",),
    autocommit_duration_ms: int = 50,
    keep_queries: Optional[bool] = None,
    delete_completed_queries: bool = True,
    request_validator=None,
    documentation: Optional[EndpointDocumentation] = None,
) -> Tuple[Table, Any]:
    """Expose a REST endpoint as a (queries_table, response_writer) pair
    (reference: io/http/_server.py:624).

    Multi-process runs: rank 0 binds the HTTP frontend; incoming query rows
    BROADCAST to every rank (source dist_mode="broadcast"), so replicated
    pipelines — including device-mesh retrieval, whose jit calls must stay
    SPMD across processes — serve the query on the whole cluster, and the
    response stream gathers back to rank 0 where the HTTP futures resolve."""
    if schema is None:
        schema = schema_from_types(query=str)
    if keep_queries is not None:
        delete_completed_queries = not keep_queries

    from ...parallel.distributed import topology_from_env

    processes, pid, _addr = topology_from_env()
    frontend = processes <= 1 or pid == 0
    stop_event = threading.Event()

    if frontend:
        if webserver is None:
            webserver = PathwayWebserver(
                host=host or "0.0.0.0", port=port or 8080
            )
        subject = RestServerSubject(
            webserver,
            route,
            methods,
            schema,
            delete_completed_queries,
            request_validator,
            documentation,
        )

        def runner(writer: SessionWriter):
            subject.attach_writer(writer)
            # keep the session open for the lifetime of the run
            stop_event.wait()

    else:
        # non-frontend rank: same graph shape (source + subscriber must line
        # up across SPMD replicas), no socket; rows arrive via the broadcast
        subject = None

        def runner(writer: SessionWriter):
            stop_event.wait()

    G.post_run_hooks.append(stop_event.set)
    table = register_source(
        schema,
        runner,
        mode="streaming",
        name=f"rest{route.replace('/', '_')}",
        dist_mode="broadcast",
    )
    return table, _ResponseWriter(subject)
