"""pw.io.pubsub — Google Pub/Sub output connector
(reference: python/pathway/io/pubsub/__init__.py).  Gated on
google-cloud-pubsub (not bundled)."""

from __future__ import annotations

import json

from ...internals.table import Table
from .._gated import require
from .._subscribe import subscribe

__all__ = ["write"]


def write(table: Table, publisher, project_id: str, topic_id: str, **kwargs) -> None:
    """Publish the update stream; ``publisher`` is a
    google.cloud.pubsub_v1.PublisherClient (passed in, as in the reference)."""
    if publisher is None:
        pubsub = require("google.cloud.pubsub_v1", "pubsub")
        publisher = pubsub.PublisherClient()
    topic_path = publisher.topic_path(project_id, topic_id)
    names = table.column_names
    futures = []

    def on_change(key, row, time, is_addition):
        obj = {n: _plain(row[n]) for n in names}
        attrs = {"time": str(time), "diff": str(1 if is_addition else -1)}
        futures.append(
            publisher.publish(topic_path, json.dumps(obj).encode(), **attrs)
        )

    def flush(ts=None):
        for f in futures:
            f.result()
        del futures[:]

    subscribe(table, on_change=on_change, on_time_end=flush, on_end=flush)


from .._connector import jsonable as _plain  # noqa: E402
