"""pw.io.bigquery — BigQuery output connector
(reference: python/pathway/io/bigquery/__init__.py — streams the update
stream into a table via the google-cloud-bigquery client, which IS bundled
in this image)."""

from __future__ import annotations

from typing import Optional

from ...internals.table import Table
from .._subscribe import subscribe

__all__ = ["write"]


def write(
    table: Table,
    dataset_name: str,
    table_name: str,
    *,
    service_user_credentials_file: Optional[str] = None,
    max_batch_size: int = 500,
    **kwargs,
) -> None:
    from google.cloud import bigquery  # bundled

    if service_user_credentials_file:
        client = bigquery.Client.from_service_account_json(
            service_user_credentials_file
        )
    else:
        client = bigquery.Client()
    table_ref = f"{client.project}.{dataset_name}.{table_name}"
    names = table.column_names
    buffer = []

    def on_change(key, row, time, is_addition):
        rec = {n: _plain(row[n]) for n in names}
        rec["time"] = time
        rec["diff"] = 1 if is_addition else -1
        buffer.append(rec)
        if len(buffer) >= max_batch_size:
            flush()

    def flush(ts=None):
        if not buffer:
            return
        batch = list(buffer)
        errors = client.insert_rows_json(table_ref, batch)
        if errors:
            # insert_rows_json reports per-row failures; rows not listed were
            # inserted, so keep ONLY the failed rows for the retry — leaving
            # the whole batch buffered would re-insert the successful rows.
            # If any error entry lacks a usable row index (request-level
            # errors), fall back to retrying the whole batch: duplicates beat
            # silent loss (at-least-once).
            idxs = [e.get("index") for e in errors]
            if all(isinstance(i, int) and 0 <= i < len(batch) for i in idxs):
                failed_idx = sorted(set(idxs))
                buffer[:] = [batch[i] for i in failed_idx]
            raise RuntimeError(f"BigQuery insert errors: {errors}")
        del buffer[:]

    subscribe(table, on_change=on_change, on_time_end=flush, on_end=flush)


from .._connector import jsonable as _plain  # noqa: E402
