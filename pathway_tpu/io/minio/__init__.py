"""pw.io.minio — MinIO connector (S3-compatible; reference:
python/pathway/io/minio/__init__.py — thin wrapper over the s3 reader with a
custom endpoint)."""

from __future__ import annotations

from typing import Optional, Type

from ...internals.schema import Schema
from ...internals.table import Table
from ..s3 import AwsS3Settings
from ..s3 import read as _s3_read

__all__ = ["read", "MinIOSettings"]


class MinIOSettings:
    def __init__(
        self,
        endpoint: str,
        bucket_name: str,
        access_key: str,
        secret_access_key: str,
        *,
        with_path_style: bool = True,
        region: Optional[str] = None,
    ):
        self.endpoint = endpoint
        self.bucket_name = bucket_name
        self.access_key = access_key
        self.secret_access_key = secret_access_key
        self.with_path_style = with_path_style
        self.region = region

    def create_aws_settings(self) -> AwsS3Settings:
        endpoint = self.endpoint
        if not endpoint.startswith("http"):
            endpoint = f"https://{endpoint}"
        return AwsS3Settings(
            bucket_name=self.bucket_name,
            access_key=self.access_key,
            secret_access_key=self.secret_access_key,
            region=self.region,
            endpoint=endpoint,
            with_path_style=self.with_path_style,
        )


def read(
    path: str,
    minio_settings: MinIOSettings,
    *,
    format: str = "csv",
    schema: Optional[Type[Schema]] = None,
    **kwargs,
) -> Table:
    return _s3_read(
        path,
        aws_s3_settings=minio_settings.create_aws_settings(),
        format=format,
        schema=schema,
        **kwargs,
    )
