"""pw.io.slack — Slack notifications output
(reference: python/pathway/xpacks/connectors/ slack send_alerts usage /
io surface).  Posts one message per insertion via chat.postMessage
(``requests``, bundled)."""

from __future__ import annotations

from ...internals.table import Table
from .._subscribe import subscribe

__all__ = ["send_alerts"]


def send_alerts(alerts: Table, slack_channel_id: str, slack_token: str) -> None:
    import requests

    names = alerts.column_names
    message_col = names[0]

    def on_change(key, row, time, is_addition):
        if not is_addition:
            return
        resp = requests.post(
            "https://slack.com/api/chat.postMessage",
            json={"channel": slack_channel_id, "text": str(row[message_col])},
            headers={"Authorization": f"Bearer {slack_token}"},
        )
        resp.raise_for_status()

    subscribe(alerts, on_change=on_change)
