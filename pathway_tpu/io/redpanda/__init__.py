"""pw.io.redpanda — Redpanda connector (Kafka-API compatible; reference:
python/pathway/io/redpanda/__init__.py re-exports the kafka connector)."""

from __future__ import annotations

from ..kafka import read, write  # noqa: F401

__all__ = ["read", "write"]
