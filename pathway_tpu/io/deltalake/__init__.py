"""pw.io.deltalake — Delta Lake connector
(reference: python/pathway/io/deltalake/__init__.py over DeltaTableReader /
DeltaBatchWriter, src/connectors/data_storage.rs).  Gated on the deltalake
package (not bundled).
"""

from __future__ import annotations

import time
from typing import Optional, Type

from ...internals.schema import Schema
from ...internals.table import Table
from .._connector import SessionWriter, register_source
from .._gated import require
from .._subscribe import subscribe

__all__ = ["read", "write"]


def read(
    uri: str,
    *,
    schema: Type[Schema],
    mode: str = "streaming",
    poll_interval_s: float = 1.0,
    name: str = "deltalake",
    persistent_id: Optional[str] = None,
    **kwargs,
) -> Table:
    """Read a Delta table; streaming mode tails new versions (CDC-style)."""
    require("deltalake", "deltalake")
    columns = list(schema.columns().keys())

    pkey = schema.primary_key_columns()

    def runner(writer: SessionWriter):
        from deltalake import DeltaTable  # type: ignore

        from ...internals.keys import ref_scalar

        pers = writer.persistence
        version = -1
        previous = {}
        while True:
            dt = DeltaTable(uri)
            current = dt.version()
            if current > version:
                # snapshot-diff against the previous version: upserts for
                # new/changed identities, retractions for removed ones.
                # Without a primary key, identity = row content + occurrence
                # number (stable across versions for unchanged rows).
                rows = {}
                occurrence: dict = {}
                for rec in dt.to_pyarrow_table().to_pylist():
                    projected = {c: rec.get(c) for c in columns}
                    if pkey:
                        ident = tuple(projected[c] for c in pkey)
                    else:
                        content = tuple(projected[c] for c in columns)
                        n = occurrence.get(content, 0)
                        occurrence[content] = n + 1
                        ident = (content, n)
                    rows[ident] = projected

                def engine_key(ident):
                    if pkey:
                        return None  # writer derives the key from pkey columns
                    content, n = ident
                    return int(ref_scalar("_delta_row", n, *map(str, content)))

                for ident, rec in rows.items():
                    if previous.get(ident) != rec:
                        writer.insert(rec, key=engine_key(ident))
                for ident, rec in previous.items():
                    if ident not in rows:
                        writer.remove(rec, key=engine_key(ident))
                previous = rows
                version = current
                if pers is not None:
                    pers.save_offsets(version)
            if mode == "static":
                return
            time.sleep(poll_interval_s)

    return register_source(
        schema,
        runner,
        mode=mode,
        name=name,
        upsert=schema.primary_key_columns() is not None,
        persistent_id=persistent_id,
    )


def write(table: Table, uri: str, *, min_commit_frequency=60_000, **kwargs) -> None:
    """Append the update stream (rows + time/diff) as Delta commits."""
    require("deltalake", "deltalake")
    import pyarrow as pa  # type: ignore
    from deltalake import write_deltalake  # type: ignore

    names = table.column_names
    buffer = []

    def on_change(key, row, time, is_addition):
        rec = {n: row[n] for n in names}
        rec["time"] = time
        rec["diff"] = 1 if is_addition else -1
        buffer.append(rec)

    def flush(ts=None):
        if not buffer:
            return
        batch = pa.Table.from_pylist(buffer)
        del buffer[:]
        write_deltalake(uri, batch, mode="append")

    subscribe(table, on_change=on_change, on_time_end=flush, on_end=flush)
