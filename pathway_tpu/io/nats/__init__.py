"""pw.io.nats — NATS connector
(reference: python/pathway/io/nats/__init__.py over NatsReader/NatsWriter,
src/connectors/data_storage.rs).  Gated on nats-py (not bundled).
"""

from __future__ import annotations

import json
from typing import Optional, Type

from ...internals.schema import Schema, schema_from_types
from ...internals.table import Table
from .._connector import SessionWriter, register_source
from .._gated import require
from .._subscribe import subscribe

__all__ = ["read", "write"]


def read(
    uri: str,
    topic: str,
    *,
    schema: Optional[Type[Schema]] = None,
    format: str = "json",
    name: str = "nats",
    persistent_id: Optional[str] = None,
    **kwargs,
) -> Table:
    require("nats", "nats")
    if format in ("plaintext", "raw"):
        schema = schema or schema_from_types(
            data=(str if format == "plaintext" else bytes)
        )
    elif schema is None:
        raise ValueError("schema is required for json format")
    columns = list(schema.columns().keys())

    def runner(writer: SessionWriter):
        import asyncio

        import nats  # type: ignore

        async def consume():
            nc = await nats.connect(uri)
            sub = await nc.subscribe(topic)
            async for msg in sub.messages:
                raw = msg.data
                if format == "raw":
                    writer.insert({"data": raw})
                elif format == "plaintext":
                    writer.insert({"data": raw.decode(errors="replace")})
                else:
                    try:
                        obj = json.loads(raw)
                    except ValueError:
                        continue
                    writer.insert({c: obj.get(c) for c in columns})

        asyncio.run(consume())

    return register_source(
        schema, runner, mode="streaming", name=name, persistent_id=persistent_id
    )


def write(table: Table, uri: str, topic: str, *, format: str = "json", **kwargs) -> None:
    require("nats", "nats")
    import asyncio
    import threading

    import nats  # type: ignore

    names = table.column_names
    loop = asyncio.new_event_loop()
    ready = threading.Event()
    state = {}

    def loop_main():
        asyncio.set_event_loop(loop)

        async def setup():
            state["nc"] = await nats.connect(uri)
            ready.set()

        loop.run_until_complete(setup())
        loop.run_forever()

    threading.Thread(target=loop_main, daemon=True).start()
    if not ready.wait(10):
        raise ConnectionError(f"could not connect to NATS at {uri!r} within 10s")

    def on_change(key, row, time, is_addition):
        obj = {n: _plain(row[n]) for n in names}
        obj["time"] = time
        obj["diff"] = 1 if is_addition else -1
        payload = json.dumps(obj).encode()
        asyncio.run_coroutine_threadsafe(
            state["nc"].publish(topic, payload), loop
        ).result()

    subscribe(table, on_change=on_change)


from .._connector import jsonable as _plain  # noqa: E402
