"""Debug helpers: static tables and synchronous computation
(reference: python/pathway/debug/__init__.py:207-496 — table_from_markdown /
table_from_pandas / compute_and_print / compute_and_print_update_stream)."""

from __future__ import annotations

import re
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple, Type

import numpy as np

from ..engine.graph import OutputCallbacks
from ..engine.operators.io import SubscribeOperator
from ..internals import dtype as dt
from ..internals.keys import Pointer, ref_scalar, sequential_keys
from ..internals.parse_graph import G
from ..internals.run import run as _run
from ..internals.schema import Schema, schema_from_types
from ..internals.table import Table

__all__ = [
    "table_from_rows",
    "table_from_markdown",
    "table_from_pandas",
    "table_to_pandas",
    "table_to_dicts",
    "compute_and_print",
    "compute_and_print_update_stream",
    "parse_to_table",
]


def table_from_rows(
    schema: Type[Schema],
    rows: Sequence[Tuple],
    unsafe_trusted_ids: bool = False,
    is_stream: bool = False,
) -> Table:
    names = list(schema.columns().keys())
    dict_rows = [dict(zip(names, row)) for row in rows]
    return Table.from_rows(dict_rows, schema, name="debug_rows")


def _parse_value(text: str) -> Any:
    text = text.strip()
    if text in ("", "None"):
        return None
    if text in ("True", "true"):
        return True
    if text in ("False", "false"):
        return False
    try:
        return int(text)
    except ValueError:
        pass
    try:
        return float(text)
    except ValueError:
        pass
    return text


def table_from_markdown(
    txt: str,
    *,
    schema: Optional[Type[Schema]] = None,
    unsafe_trusted_ids: bool = False,
    **kwargs,
) -> Table:
    """Parse a markdown-ish table (reference: debug/__init__.py:429).

    First unnamed column (before the first ``|``) is the row id if present."""
    lines = [l for l in txt.strip().splitlines() if l.strip()]
    header = lines[0]
    has_id = header.lstrip().startswith("|")
    col_names = [c.strip() for c in header.split("|") if c.strip()]
    rows: List[Dict[str, Any]] = []
    explicit_keys: List[int] = []
    for line in lines[1:]:
        if re.match(r"^[\s|:-]+$", line):
            continue
        parts = line.split("|")
        if has_id:
            id_part = parts[0].strip()
            values = parts[1:]
            if id_part:
                explicit_keys.append(int(ref_scalar(int(id_part))))
        else:
            values = parts
        vals = [_parse_value(v) for v in values[: len(col_names)]]
        while len(vals) < len(col_names):
            vals.append(None)
        rows.append(dict(zip(col_names, vals)))
    keys = explicit_keys if has_id and len(explicit_keys) == len(rows) else None
    return Table.from_rows(rows, schema, keys=keys, name="markdown")


# reference alias
parse_to_table = table_from_markdown


def table_from_pandas(
    df,
    *,
    schema: Optional[Type[Schema]] = None,
    unsafe_trusted_ids: bool = False,
    **kwargs,
) -> Table:
    rows = df.to_dict("records")
    keys = None
    try:
        if df.index.dtype.kind in "iu":
            keys = [int(ref_scalar(int(i))) for i in df.index]
    except Exception:
        keys = None
    return Table.from_rows(rows, schema, keys=keys, name="pandas")


def _ensure_ran():
    _run(monitoring_level=None)


def table_to_dicts(table: Table):
    _ensure_ran()
    keys, columns = table._materialize()
    return [Pointer(k) for k in keys], {
        name: {Pointer(k): col[i] for i, k in enumerate(keys)}
        for name, col in columns.items()
    }


def table_to_pandas(table: Table, include_id: bool = True):
    import pandas as pd

    _ensure_ran()
    keys, columns = table._materialize()
    df = pd.DataFrame({name: list(col) for name, col in columns.items()})
    if include_id:
        df.index = [Pointer(k) for k in keys]
    return df


def compute_and_print(
    table: Table,
    *,
    include_id: bool = True,
    short_pointers: bool = True,
    n_rows: Optional[int] = None,
    **kwargs,
) -> None:
    _ensure_ran()
    keys, columns = table._materialize()
    names = list(columns.keys())
    order = np.argsort(keys)
    header = (["id"] if include_id else []) + names
    rows = []
    for i in order[: n_rows if n_rows is not None else len(order)]:
        row = []
        if include_id:
            p = Pointer(int(keys[i]))
            row.append(f"^{int(p) % 0xFFFFFF:X}" if short_pointers else repr(p))
        row.extend(str(columns[c][i]) for c in names)
        rows.append(row)
    widths = [
        max(len(header[c]), *(len(r[c]) for r in rows)) if rows else len(header[c])
        for c in range(len(header))
    ]
    print(" | ".join(h.ljust(w) for h, w in zip(header, widths)))
    for r in rows:
        print(" | ".join(v.ljust(w) for v, w in zip(r, widths)))


def compute_and_print_update_stream(table: Table, **kwargs) -> None:
    events: List[Tuple[int, int, Tuple]] = []

    def on_change(key, row, time, diff):
        events.append((time, diff, row))

    op = SubscribeOperator(
        table._engine_table, OutputCallbacks(on_change=on_change), name="debug_stream"
    )
    G.engine_graph.add_operator(op)
    _ensure_ran()
    names = table.column_names
    print("time | diff | " + " | ".join(names))
    for time, diff, row in events:
        print(f"{time} | {diff:+d} | " + " | ".join(str(v) for v in row))
