"""Debug helpers: static tables and synchronous computation
(reference: python/pathway/debug/__init__.py:207-496 — table_from_markdown /
table_from_pandas / compute_and_print / compute_and_print_update_stream)."""

from __future__ import annotations

import re
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple, Type

import numpy as np

from ..engine.graph import OutputCallbacks
from ..engine.operators.io import SubscribeOperator
from ..internals import dtype as dt
from ..internals.keys import Pointer, ref_scalar, sequential_keys
from ..internals.parse_graph import G
from ..internals.run import run as _run
from ..internals.schema import Schema, schema_from_types
from ..internals.table import Table

__all__ = [
    "table_from_rows",
    "table_from_markdown",
    "table_from_pandas",
    "table_to_pandas",
    "table_to_dicts",
    "compute_and_print",
    "compute_and_print_update_stream",
    "parse_to_table",
    "StreamGenerator",
]


def table_from_rows(
    schema: Type[Schema],
    rows: Sequence[Tuple],
    unsafe_trusted_ids: bool = False,
    is_stream: bool = False,
) -> Table:
    names = list(schema.columns().keys())
    dict_rows = [dict(zip(names, row)) for row in rows]
    return Table.from_rows(dict_rows, schema, name="debug_rows")


def _parse_value(text: str) -> Any:
    text = text.strip()
    if text in ("", "None"):
        return None
    if text in ("True", "true"):
        return True
    if text in ("False", "false"):
        return False
    try:
        return int(text)
    except ValueError:
        pass
    try:
        return float(text)
    except ValueError:
        pass
    return text


def _parse_markdown_rows(txt: str) -> Tuple[List[Dict[str, Any]], Optional[List[int]]]:
    """Shared markdown grammar: returns (rows, explicit_keys_or_None).

    First unnamed column (before the first ``|``) is the row id if present."""
    lines = [l for l in txt.strip().splitlines() if l.strip()]
    header = lines[0]
    has_id = header.lstrip().startswith("|")
    col_names = [c.strip() for c in header.split("|") if c.strip()]
    rows: List[Dict[str, Any]] = []
    explicit_keys: List[int] = []
    for line in lines[1:]:
        if re.match(r"^[\s|:-]+$", line):
            continue
        parts = line.split("|")
        if has_id:
            id_part = parts[0].strip()
            values = parts[1:]
            if id_part:
                explicit_keys.append(int(ref_scalar(int(id_part))))
        else:
            values = parts
        vals = [_parse_value(v) for v in values[: len(col_names)]]
        while len(vals) < len(col_names):
            vals.append(None)
        rows.append(dict(zip(col_names, vals)))
    keys = explicit_keys if has_id and len(explicit_keys) == len(rows) else None
    return rows, keys


def table_from_markdown(
    txt: str,
    *,
    schema: Optional[Type[Schema]] = None,
    unsafe_trusted_ids: bool = False,
    **kwargs,
) -> Table:
    """Parse a markdown-ish table (reference: debug/__init__.py:429)."""
    rows, keys = _parse_markdown_rows(txt)
    return Table.from_rows(rows, schema, keys=keys, name="markdown")


# reference alias
parse_to_table = table_from_markdown


def table_from_pandas(
    df,
    *,
    schema: Optional[Type[Schema]] = None,
    unsafe_trusted_ids: bool = False,
    **kwargs,
) -> Table:
    rows = df.to_dict("records")
    keys = None
    try:
        if df.index.dtype.kind in "iu":
            keys = [int(ref_scalar(int(i))) for i in df.index]
    except Exception:
        keys = None
    return Table.from_rows(rows, schema, keys=keys, name="pandas")


def _ensure_ran():
    _run(monitoring_level=None)


def table_to_dicts(table: Table):
    _ensure_ran()
    keys, columns = table._materialize()
    return [Pointer(k) for k in keys], {
        name: {Pointer(k): col[i] for i, k in enumerate(keys)}
        for name, col in columns.items()
    }


def table_to_pandas(table: Table, include_id: bool = True):
    import pandas as pd

    _ensure_ran()
    keys, columns = table._materialize()
    df = pd.DataFrame({name: list(col) for name, col in columns.items()})
    if include_id:
        df.index = [Pointer(k) for k in keys]
    return df


def compute_and_print(
    table: Table,
    *,
    include_id: bool = True,
    short_pointers: bool = True,
    n_rows: Optional[int] = None,
    **kwargs,
) -> None:
    _ensure_ran()
    keys, columns = table._materialize()
    names = list(columns.keys())
    order = np.argsort(keys)
    header = (["id"] if include_id else []) + names
    rows = []
    for i in order[: n_rows if n_rows is not None else len(order)]:
        row = []
        if include_id:
            p = Pointer(int(keys[i]))
            row.append(f"^{int(p) % 0xFFFFFF:X}" if short_pointers else repr(p))
        row.extend(str(columns[c][i]) for c in names)
        rows.append(row)
    widths = [
        max(len(header[c]), *(len(r[c]) for r in rows)) if rows else len(header[c])
        for c in range(len(header))
    ]
    print(" | ".join(h.ljust(w) for h, w in zip(header, widths)))
    for r in rows:
        print(" | ".join(v.ljust(w) for v, w in zip(r, widths)))


class StreamGenerator:
    """Builds artificial streams with controlled batch boundaries for tests
    (reference: debug/__init__.py:496 StreamGenerator — snapshot-event
    replay per worker).  The TPU engine is single-host SPMD, so per-worker
    splits collapse into batch order; each batch is sealed atomically
    (``subject.commit()`` → InputSession.mark_batch) and gets its own commit
    tick structurally — no timing dependence."""

    def table_from_list_of_batches(
        self, batches: Sequence[Sequence[Mapping[str, Any]]], schema: Type[Schema]
    ) -> Table:
        from ..io.python import ConnectorSubject, read

        class _Gen(ConnectorSubject):
            def run(self) -> None:
                for batch in batches:
                    for row in batch:
                        self.next(**row)
                    self.commit()

        return read(
            _Gen(),
            schema=schema,
            name="debug.stream-generator",
            atomic_batches=True,
        )

    def table_from_list_of_batches_by_workers(
        self,
        batches: Sequence[Mapping[int, Sequence[Mapping[str, Any]]]],
        schema: Type[Schema],
    ) -> Table:
        flattened = [
            [row for worker in sorted(batch) for row in batch[worker]]
            for batch in batches
        ]
        return self.table_from_list_of_batches(flattened, schema)

    def table_from_pandas(
        self, df, *, schema: Optional[Type[Schema]] = None, **kwargs
    ) -> Table:
        """``_time`` column splits rows into batches; ``_diff`` of -1 emits a
        deletion; ``_worker`` is accepted and ignored (single-host)."""
        from ..io.python import ConnectorSubject, read

        records = df.to_dict("records")
        value_cols = [
            c for c in df.columns if c not in ("_time", "_diff", "_worker")
        ]
        if schema is None:
            sample = records[0] if records else {}
            schema = schema_from_types(
                **{c: type(sample.get(c, "")) for c in value_cols}
            )
        def time_of(rec) -> int:
            t = rec.get("_time", 2)
            try:
                import math

                if t is None or (isinstance(t, float) and math.isnan(t)):
                    return 2
            except TypeError:
                pass
            return int(t)

        by_time: Dict[int, List[Mapping[str, Any]]] = {}
        for rec in records:
            by_time.setdefault(time_of(rec), []).append(rec)

        class _Gen(ConnectorSubject):
            def run(self) -> None:
                for t in sorted(by_time):
                    for rec in by_time[t]:
                        values = {c: rec[c] for c in value_cols}
                        if int(rec.get("_diff", 1)) >= 0:
                            self.next(**values)
                        else:
                            self.delete(**values)
                    self.commit()

        return read(
            _Gen(),
            schema=schema,
            name="debug.stream-generator",
            atomic_batches=True,
        )

    def table_from_markdown(self, table: str, **kwargs) -> Table:
        """Markdown rows with optional ``_time``/``_diff`` columns become a
        stream with those batch boundaries (same grammar as the module-level
        ``table_from_markdown``)."""
        import pandas as pd

        rows, _keys = _parse_markdown_rows(table)
        return self.table_from_pandas(pd.DataFrame(rows), **kwargs)


def compute_and_print_update_stream(table: Table, **kwargs) -> None:
    events: List[Tuple[int, int, Tuple]] = []

    def on_change(key, row, time, diff):
        events.append((time, diff, row))

    op = SubscribeOperator(
        table._engine_table, OutputCallbacks(on_change=on_change), name="debug_stream"
    )
    G.engine_graph.add_operator(op)
    _ensure_ran()
    names = table.column_names
    print("time | diff | " + " | ".join(names))
    for time, diff, row in events:
        print(f"{time} | {diff:+d} | " + " | ".join(str(v) for v in row))
