"""WordPiece tokenizer — real subword vocabularies, fully offline.

Loads a standard BERT-style ``vocab.txt`` (one token per line, ``##``
continuation prefix, [PAD]/[UNK]/[CLS]/[SEP] specials) and implements the
greedy longest-match-first WordPiece algorithm with BERT basic
tokenization (lowercase + punctuation splitting).  Byte-compatible with
``transformers.BertTokenizer`` on the same vocab (tests/test_hf_import.py
asserts parity), so checkpoints exported from sentence-transformers bring
their own vocab and tokenize identically here — no network, no HF runtime
in the serving path.

Reference counterpart: the tiktoken/HF tokenizers the reference downloads
at runtime (xpacks/llm/splitters.py:13, embedders.py:270-330).
"""

from __future__ import annotations

import os
import unicodedata
from typing import List, Sequence, Tuple

import numpy as np

__all__ = ["WordPieceTokenizer"]


def _is_punctuation(ch: str) -> bool:
    cp = ord(ch)
    if (33 <= cp <= 47) or (58 <= cp <= 64) or (91 <= cp <= 96) or (123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_cjk(cp: int) -> bool:
    """CJK unified ideograph ranges (BERT tokenizes these per character)."""
    return (
        (0x4E00 <= cp <= 0x9FFF)
        or (0x3400 <= cp <= 0x4DBF)
        or (0x20000 <= cp <= 0x2A6DF)
        or (0x2A700 <= cp <= 0x2B73F)
        or (0x2B740 <= cp <= 0x2B81F)
        or (0x2B820 <= cp <= 0x2CEAF)
        or (0xF900 <= cp <= 0xFAFF)
        or (0x2F800 <= cp <= 0x2FA1F)
    )


def _clean(text: str) -> str:
    """BERT text cleanup: tab/newline/CR become spaces, other control chars
    and NUL are dropped, CJK chars get space-isolated so they tokenize per
    character (mirrors BertTokenizer's _clean_text + CJK handling)."""
    out = []
    for ch in text:
        cp = ord(ch)
        if ch in ("\t", "\n", "\r"):
            out.append(" ")
            continue
        if cp == 0 or cp == 0xFFFD or unicodedata.category(ch).startswith("C"):
            continue
        if _is_cjk(cp):
            out.append(f" {ch} ")
        else:
            out.append(ch)
    return "".join(out)


def _basic_tokenize(text: str, lowercase: bool) -> List[str]:
    """BERT basic tokenizer: control-char cleanup + CJK isolation,
    whitespace split, punctuation isolation, optional lowercasing with
    accent stripping."""
    out: List[str] = []
    for word in _clean(text).strip().split():
        if lowercase:
            word = word.lower()
            word = unicodedata.normalize("NFD", word)
            word = "".join(c for c in word if unicodedata.category(c) != "Mn")
        current = ""
        for ch in word:
            if _is_punctuation(ch):
                if current:
                    out.append(current)
                    current = ""
                out.append(ch)
            else:
                current += ch
        if current:
            out.append(current)
    return out


class WordPieceTokenizer:
    def __init__(
        self,
        vocab_file: str,
        max_length: int = 128,
        lowercase: bool = True,
        unk_token: str = "[UNK]",
        pad_token: str = "[PAD]",
        cls_token: str = "[CLS]",
        sep_token: str = "[SEP]",
        max_chars_per_word: int = 100,
    ):
        if not os.path.exists(vocab_file):
            raise FileNotFoundError(vocab_file)
        self.vocab: dict = {}
        with open(vocab_file, encoding="utf-8") as f:
            for i, line in enumerate(f):
                token = line.rstrip("\n")
                if token:
                    self.vocab[token] = i
        self.max_length = max_length
        self.lowercase = lowercase
        self.max_chars_per_word = max_chars_per_word
        self.UNK = self.vocab[unk_token]
        self.PAD = self.vocab[pad_token]
        self.CLS = self.vocab[cls_token]
        self.SEP = self.vocab[sep_token]
        self.vocab_size = max(self.vocab.values()) + 1

    def _wordpiece(self, word: str) -> List[int]:
        if len(word) > self.max_chars_per_word:
            return [self.UNK]
        ids: List[int] = []
        start = 0
        while start < len(word):
            end = len(word)
            piece_id = None
            while start < end:
                piece = word[start:end]
                if start > 0:
                    piece = "##" + piece
                if piece in self.vocab:
                    piece_id = self.vocab[piece]
                    break
                end -= 1
            if piece_id is None:
                return [self.UNK]  # whole word becomes UNK, as in BERT
            ids.append(piece_id)
            start = end
        return ids

    def tokenize(self, text: str) -> List[int]:
        ids: List[int] = []
        for word in _basic_tokenize(str(text), self.lowercase):
            ids.extend(self._wordpiece(word))
        return ids

    def count_tokens(self, text: str) -> int:
        return len(self.tokenize(text))

    def encode(
        self, text: str, pair: str | None = None, max_length: int | None = None
    ) -> List[int]:
        max_length = max_length or self.max_length
        if pair is None:
            ids = [self.CLS] + self.tokenize(text)
            return ids[: max_length - 1] + [self.SEP]
        # sentence pairs truncate longest-first (HF semantics): both segments
        # keep tokens, so an over-long query can't silently evict the whole
        # document and collapse every pair to the same score
        a = self.tokenize(text)
        b = self.tokenize(pair)
        budget = max(max_length - 3, 2)
        while len(a) + len(b) > budget:
            if len(a) >= len(b) and len(a) > 1:
                a.pop()
            elif len(b) > 1:
                b.pop()
            else:
                break
        return [self.CLS] + a + [self.SEP] + b + [self.SEP]

    def encode_batch(
        self,
        texts: Sequence[str],
        pairs: Sequence[str] | None = None,
        max_length: int | None = None,
        pad_to: int | None = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Returns (ids [B, L], mask [B, L]) padded to a shared length —
        same contract as HashTokenizer.encode_batch (length rounded to a
        multiple of 16 to bound jit shape variants)."""
        max_length = max_length or self.max_length
        encoded = [
            self.encode(t, pairs[i] if pairs is not None else None, max_length)
            for i, t in enumerate(texts)
        ]
        longest = max((len(e) for e in encoded), default=1)
        L = pad_to or min(max_length, ((longest + 15) // 16) * 16)
        ids = np.full((len(encoded), L), self.PAD, dtype=np.int32)
        mask = np.zeros((len(encoded), L), dtype=np.int32)
        for i, e in enumerate(encoded):
            e = e[:L]
            ids[i, : len(e)] = e
            mask[i, : len(e)] = 1
        return ids, mask
