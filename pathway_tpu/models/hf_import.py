"""HF checkpoint import: safetensors -> jax BERT encoder, fully offline.

The reference serves real sentence-transformers models through the HF
runtime (xpacks/llm/embedders.py:270-330, ``model.encode`` per string).
Here a BERT-family checkpoint directory (``config.json`` +
``model.safetensors`` + ``vocab.txt``, the standard sentence-transformers
export) loads straight into a jax forward implemented in this module —
numerically matching ``transformers.BertModel`` (tests/test_hf_import.py
asserts parity against a torch reference) — and runs batched on TPU with
mean pooling.  No torch and no HF runtime in the serving path.

Supported surface: BERT/MiniLM-style post-LayerNorm encoders (the
architecture of all-MiniLM-L6-v2 and friends, the reference templates'
default embedder).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["BertConfig", "BertEncoderModule", "load_bert_checkpoint"]


@dataclass
class BertConfig:
    vocab_size: int
    hidden_size: int
    num_hidden_layers: int
    num_attention_heads: int
    intermediate_size: int
    max_position_embeddings: int
    layer_norm_eps: float = 1e-12

    @staticmethod
    def from_json(path: str) -> "BertConfig":
        with open(path) as f:
            raw = json.load(f)
        return BertConfig(
            vocab_size=raw["vocab_size"],
            hidden_size=raw["hidden_size"],
            num_hidden_layers=raw["num_hidden_layers"],
            num_attention_heads=raw["num_attention_heads"],
            intermediate_size=raw["intermediate_size"],
            max_position_embeddings=raw["max_position_embeddings"],
            layer_norm_eps=raw.get("layer_norm_eps", 1e-12),
        )


def _layer_norm(x, gamma, beta, eps):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean((x - mean) ** 2, axis=-1, keepdims=True)
    return (x - mean) / jnp.sqrt(var + eps) * gamma + beta


def bert_forward(
    params: Dict[str, Any],
    ids: jnp.ndarray,
    mask: jnp.ndarray,
    cfg: BertConfig,
    type_ids: jnp.ndarray = None,
) -> jnp.ndarray:
    """HF-BERT-equivalent forward (eval mode): returns the last hidden state
    [B, L, H].  Post-LN blocks, exact (erf) GELU, additive attention mask.
    ``type_ids`` segments sentence pairs (cross-encoders); defaults to 0s."""
    emb = params["embeddings"]
    B, L = ids.shape
    if type_ids is None:
        type_ids = jnp.zeros((B, L), jnp.int32)
    h = (
        emb["word"][ids]
        + emb["position"][jnp.arange(L)][None, :, :]
        + emb["token_type"][type_ids]
    )
    h = _layer_norm(h, emb["ln_gamma"], emb["ln_beta"], cfg.layer_norm_eps)

    n_heads = cfg.num_attention_heads
    head_dim = cfg.hidden_size // n_heads
    neg = jnp.asarray(-1e9, h.dtype)
    attn_bias = jnp.where(mask[:, None, None, :] > 0, 0.0, neg)  # [B,1,1,L]

    for layer in params["layers"]:
        q = h @ layer["q_w"] + layer["q_b"]
        k = h @ layer["k_w"] + layer["k_b"]
        v = h @ layer["v_w"] + layer["v_b"]

        def split(x):
            return x.reshape(B, L, n_heads, head_dim).transpose(0, 2, 1, 3)

        scores = split(q) @ split(k).transpose(0, 1, 3, 2)
        scores = scores / jnp.sqrt(jnp.asarray(head_dim, h.dtype)) + attn_bias
        probs = jax.nn.softmax(scores, axis=-1)
        ctx = (probs @ split(v)).transpose(0, 2, 1, 3).reshape(B, L, cfg.hidden_size)
        attn_out = ctx @ layer["o_w"] + layer["o_b"]
        h = _layer_norm(
            h + attn_out, layer["attn_ln_gamma"], layer["attn_ln_beta"],
            cfg.layer_norm_eps,
        )
        ffn = jax.nn.gelu(h @ layer["ffn_in_w"] + layer["ffn_in_b"], approximate=False)
        ffn = ffn @ layer["ffn_out_w"] + layer["ffn_out_b"]
        h = _layer_norm(
            h + ffn, layer["ffn_ln_gamma"], layer["ffn_ln_beta"], cfg.layer_norm_eps
        )
    return h


def mean_pool(hidden: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """sentence-transformers mean pooling: masked token average [B, H]."""
    m = mask[:, :, None].astype(hidden.dtype)
    return jnp.sum(hidden * m, axis=1) / jnp.maximum(jnp.sum(m, axis=1), 1e-9)


class BertEncoderModule:
    """Duck-typed stand-in for a flax module inside SentenceEncoder:
    ``apply({"params": params}, ids, mask)`` -> mean-pooled [B, H]."""

    def __init__(self, cfg: BertConfig):
        self.cfg = cfg

    def apply(self, variables, ids, mask):
        hidden = bert_forward(variables["params"], ids, mask, self.cfg)
        return mean_pool(hidden, mask)


def _t(x: np.ndarray) -> np.ndarray:
    """torch Linear stores weight [out, in]; jax matmul wants [in, out]."""
    return np.ascontiguousarray(x.T)


def _load_tensors(path: str):
    """One safetensors read; a leading ``bert.`` prefix (full-model exports)
    is stripped."""
    from safetensors.numpy import load_file

    raw = load_file(os.path.join(path, "model.safetensors"))
    return {
        (name[5:] if name.startswith("bert.") else name): value
        for name, value in raw.items()
    }


def load_bert_checkpoint(path: str, _tensors=None):
    """Load an HF BERT-style checkpoint directory -> (BertConfig, params).

    ``path`` must contain ``config.json`` and ``model.safetensors`` (the
    standard ``save_pretrained`` layout).  Tensor names follow HF BertModel."""
    cfg = BertConfig.from_json(os.path.join(path, "config.json"))
    tensors = _tensors if _tensors is not None else _load_tensors(path)

    def get(name: str) -> np.ndarray:
        if name not in tensors:
            raise KeyError(
                f"checkpoint at {path} lacks tensor {name!r} — "
                "only BERT-family encoders are supported"
            )
        return tensors[name]

    params: Dict[str, Any] = {
        "embeddings": {
            "word": get("embeddings.word_embeddings.weight"),
            "position": get("embeddings.position_embeddings.weight"),
            "token_type": get("embeddings.token_type_embeddings.weight"),
            "ln_gamma": get("embeddings.LayerNorm.weight"),
            "ln_beta": get("embeddings.LayerNorm.bias"),
        },
        "layers": [],
    }
    for i in range(cfg.num_hidden_layers):
        p = f"encoder.layer.{i}."
        params["layers"].append(
            {
                "q_w": _t(get(p + "attention.self.query.weight")),
                "q_b": get(p + "attention.self.query.bias"),
                "k_w": _t(get(p + "attention.self.key.weight")),
                "k_b": get(p + "attention.self.key.bias"),
                "v_w": _t(get(p + "attention.self.value.weight")),
                "v_b": get(p + "attention.self.value.bias"),
                "o_w": _t(get(p + "attention.output.dense.weight")),
                "o_b": get(p + "attention.output.dense.bias"),
                "attn_ln_gamma": get(p + "attention.output.LayerNorm.weight"),
                "attn_ln_beta": get(p + "attention.output.LayerNorm.bias"),
                "ffn_in_w": _t(get(p + "intermediate.dense.weight")),
                "ffn_in_b": get(p + "intermediate.dense.bias"),
                "ffn_out_w": _t(get(p + "output.dense.weight")),
                "ffn_out_b": get(p + "output.dense.bias"),
                "ffn_ln_gamma": get(p + "output.LayerNorm.weight"),
                "ffn_ln_beta": get(p + "output.LayerNorm.bias"),
            }
        )
    params = jax.tree_util.tree_map(jnp.asarray, params)
    return cfg, params


def load_bert_cross_encoder(path: str):
    """Load an HF ``BertForSequenceClassification`` checkpoint (the
    architecture of sentence-transformers cross-encoders like
    ms-marco-MiniLM) -> (BertConfig, params incl. pooler + classifier).
    Forward: encoder -> [CLS] -> pooler dense+tanh -> classifier logits."""
    tensors = _load_tensors(path)
    cfg, params = load_bert_checkpoint(path, _tensors=tensors)
    if "classifier.weight" not in tensors:
        raise KeyError(
            f"checkpoint at {path} has no classification head "
            "(classifier.weight) — it is an encoder/embedder export, not a "
            "cross-encoder; use SentenceEncoder(checkpoint_path=...) for it"
        )
    extra = {
        "classifier": {
            "w": jnp.asarray(_t(tensors["classifier.weight"])),
            "b": jnp.asarray(tensors["classifier.bias"]),
        }
    }
    if "pooler.dense.weight" in tensors:
        extra["pooler"] = {
            "w": jnp.asarray(_t(tensors["pooler.dense.weight"])),
            "b": jnp.asarray(tensors["pooler.dense.bias"]),
        }
    params.update(extra)
    return cfg, params


def bert_classify(
    params: Dict[str, Any],
    ids: jnp.ndarray,
    mask: jnp.ndarray,
    cfg: BertConfig,
    type_ids: jnp.ndarray = None,
) -> jnp.ndarray:
    """Sequence-classification logits [B, n_labels] (HF
    BertForSequenceClassification semantics: pooler(tanh) over [CLS], then
    the classifier head; without a head, returns the pooled [CLS])."""
    hidden = bert_forward(params, ids, mask, cfg, type_ids)
    cls = hidden[:, 0, :]
    if "pooler" in params:
        cls = jnp.tanh(cls @ params["pooler"]["w"] + params["pooler"]["b"])
    if "classifier" in params:
        return cls @ params["classifier"]["w"] + params["classifier"]["b"]
    return cls


class BertCrossEncoderModule:
    """Duck-typed module for CrossEncoderModel: ``apply`` -> [B] scores
    (single-logit heads squeeze; multi-label heads return logit 0)."""

    def __init__(self, cfg: BertConfig):
        self.cfg = cfg

    def apply(self, variables, ids, mask, type_ids=None):
        logits = bert_classify(
            variables["params"], ids, mask, self.cfg, type_ids
        )
        return logits[:, 0]


def load_hf_text_model(path: str, max_length: int, dtype, cross: bool = False):
    """Shared SentenceEncoder/CrossEncoderModel HF initialisation: one
    place for the config clamp, tokenizer lookup, and module choice.
    Returns (module, params, transformer_config, tokenizer)."""
    from .transformer import TransformerConfig
    from .wordpiece import WordPieceTokenizer

    hf_cfg, params = (
        load_bert_cross_encoder(path) if cross else load_bert_checkpoint(path)
    )
    max_length = min(max_length, hf_cfg.max_position_embeddings)
    config = TransformerConfig(
        vocab_size=hf_cfg.vocab_size,
        d_model=hf_cfg.hidden_size,
        n_heads=hf_cfg.num_attention_heads,
        n_layers=hf_cfg.num_hidden_layers,
        d_ff=hf_cfg.intermediate_size,
        max_len=max_length,
        dtype=dtype,
        pool="mean",
    )
    vocab_file = os.path.join(path, "vocab.txt")
    if not os.path.exists(vocab_file):
        # trained weights + hash-derived token ids = silently garbage
        # embeddings/scores; fail loudly instead
        raise FileNotFoundError(
            f"{path} has model weights but no vocab.txt — export the "
            "tokenizer vocab alongside the checkpoint "
            "(tokenizer.save_vocabulary) so token ids match the weights"
        )
    tokenizer = WordPieceTokenizer(vocab_file, max_length=max_length)
    module = (
        BertCrossEncoderModule(hf_cfg) if cross else BertEncoderModule(hf_cfg)
    )
    return module, params, config, tokenizer


def is_hf_checkpoint(path) -> bool:
    return (
        isinstance(path, str)
        and os.path.isdir(path)
        and os.path.exists(os.path.join(path, "config.json"))
        and os.path.exists(os.path.join(path, "model.safetensors"))
    )
