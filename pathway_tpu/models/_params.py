"""Parameter pytree helpers (unboxing flax logical-partitioning metadata)."""

from __future__ import annotations

import flax.linen as nn

__all__ = ["unbox"]


def unbox(params):
    """Strip flax Partitioned/LogicallyPartitioned boxes so params are plain
    arrays (sharding is applied via jit shardings / device_put instead)."""
    return nn.meta.unbox(params)
