"""SentenceEncoder — batched text -> embedding on TPU.

The TPU-native replacement for the reference's SentenceTransformerEmbedder
hot path (xpacks/llm/embedders.py:270-330, which calls ``model.encode`` one
string at a time): batches are tokenized once, padded to bucketed shapes,
and run through one jitted flax forward per micro-batch.  Params can shard
over the mesh "model" axis; batches shard over "data".
"""

from __future__ import annotations

# pathway: serve-path  (hidden-sync lint applies: no implicit host round trips)

import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import observe
from ..observe import hbm, profile, trace
from ..ops.recompile_guard import RecompileTripwire
from ..robust import retry_call
from ._params import unbox as _unbox

from .tokenizer import HashTokenizer
from .transformer import TransformerConfig, TransformerEncoder, resolve_heads

__all__ = ["SentenceEncoder"]

# flight recorder: submit→ready latency of a blocking encode (dispatch
# through host fetch) + batch occupancy per dispatch
_H_READY = observe.histogram("pathway_serve_model_seconds", model="encoder")

_BATCH_BUCKETS = (1, 4, 16, 64, 256)


def _bucket(n: int, buckets=_BATCH_BUCKETS) -> int:
    for b in buckets:
        if n <= b:
            return b
    return ((n + 255) // 256) * 256


class SentenceEncoder:
    def __init__(
        self,
        model: str = "pathway-mini",
        dimension: int = 384,
        n_layers: int = 6,
        n_heads: int = 6,
        max_length: int = 128,
        vocab_size: int = 32768,
        seed: int = 0,
        checkpoint_path: Optional[str] = None,
        mesh=None,
        dtype=jnp.bfloat16,
        normalize: bool = True,
    ):
        self.model_name = model
        self.normalize = normalize
        self.mesh = mesh
        self._lock = threading.Lock()
        self._fns: Dict[tuple, Any] = {}
        # optional tier-1 embedding cache (pathway_tpu/cache): per-row
        # reuse on the plain encode path, keyed on token ids — opt-in
        # via set_embed_cache (ingest/QA re-embeds of hot text); the
        # fused serve path carries its OWN tier on FusedEncodeSearch
        self.embed_cache = None
        # recompile tripwire: every new compile shape is counted; past the
        # budget it warns (fails under tests) — see ops/recompile_guard.py
        self._tripwire = RecompileTripwire(f"SentenceEncoder[{model}]")

        from .hf_import import is_hf_checkpoint

        if is_hf_checkpoint(checkpoint_path):
            # real-weights path: HF BERT-family safetensors + WordPiece vocab
            # (models/hf_import.py)
            from .hf_import import load_hf_text_model

            self.module, self.params, self.config, self.tokenizer = (
                load_hf_text_model(checkpoint_path, max_length, dtype)
            )
        else:
            self.config = TransformerConfig(
                vocab_size=vocab_size,
                d_model=dimension,
                n_heads=resolve_heads(dimension, n_heads),
                n_layers=n_layers,
                d_ff=dimension * 4,
                max_len=max_length,
                dtype=dtype,
                pool="mean",
            )
            self.tokenizer = HashTokenizer(
                vocab_size=vocab_size, max_length=max_length
            )
            self.module = TransformerEncoder(self.config)
            if checkpoint_path and os.path.exists(checkpoint_path):
                self.params = self._load_checkpoint(checkpoint_path)
            else:
                ids = jnp.zeros((1, 16), jnp.int32)
                mask = jnp.ones((1, 16), jnp.int32)
                self.params = self.module.init(
                    jax.random.PRNGKey(seed), ids, mask
                )["params"]
        self.params = _unbox(self.params)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            self.params = jax.device_put(
                self.params, NamedSharding(mesh, P())
            )
        # HBM ledger (observe/hbm.py): the parameter tree is usually the
        # single largest resident allocation — without it the ledger's
        # device cross-check cannot balance
        hbm.track_params("encoder", self)

    def _load_checkpoint(self, path: str):
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        return ckptr.restore(os.path.abspath(path))

    def save_checkpoint(self, path: str) -> None:
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        ckptr.save(os.path.abspath(path), self.params)
        ckptr.wait_until_finished()

    def get_embedding_dimension(self) -> int:
        return self.config.d_model

    def _forward_fn(self, batch: int, length: int):
        key = (batch, length)
        fn = self._fns.get(key)
        if fn is None:
            self._tripwire.observe(key)
            module = self.module
            normalize = self.normalize
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                data_sharding = NamedSharding(self.mesh, P("data", None))

                @jax.jit
                def fn(params, ids, mask):
                    ids = jax.lax.with_sharding_constraint(ids, data_sharding)
                    out = module.apply({"params": params}, ids, mask)
                    if normalize:
                        out = out / jnp.maximum(
                            jnp.linalg.norm(out, axis=-1, keepdims=True), 1e-9
                        )
                    return out

            else:

                @jax.jit
                def fn(params, ids, mask):
                    out = module.apply({"params": params}, ids, mask)
                    if normalize:
                        out = out / jnp.maximum(
                            jnp.linalg.norm(out, axis=-1, keepdims=True), 1e-9
                        )
                    return out

            # device-time attribution (observe/profile.py)
            fn = profile.wrap("encoder.forward", fn)
            self._fns[key] = fn
        return self._fns[key]

    def set_embed_cache(self, cache) -> None:
        """Arm the tier-1 embedding cache on the plain encode path
        (``EmbeddingCache`` or None).  Cached rows are the encoder's own
        previous outputs, device-resident — a hit skips the trunk
        forward for that row and never crosses the host link."""
        self.embed_cache = cache

    def _cached_encode_rows(self, ids, mask, n: int):
        """Cache wrapper for ``encode_to_device``: per-row lookup keyed
        on token ids, ONE bucketed forward for the misses, device-side
        composition.  The dispatch here is the plain encode's own launch
        (same ``encoder.dispatch`` retry/fault site), guarded by the
        cache lookup — the analyzer's cache-wrapper convention.  Twin of
        ``ops/serving.py _cached_embeddings`` (the serve-batch contract:
        [B, d] incl. pad rows, deadline-plumbed, serve.dispatch site) —
        kept parallel rather than shared so the dispatch stays lexically
        visible to the analyzer; fix cache-path bugs in BOTH."""
        cache = self.embed_cache
        ids = np.asarray(ids)
        mask = np.asarray(mask)
        # value-space signature: this path stores rows under the
        # encoder's own normalize contract — partitioned from the serve
        # path's metric-normalized space even on a shared tier
        rows, misses, row_keys = cache.lookup_rows(
            ids, mask, n, space=f"encode:{int(self.normalize)}"
        )
        fresh: Dict[int, Any] = {}
        if misses:
            n_miss = len(misses)
            bm = _bucket(n_miss)
            L = ids.shape[1]
            ids_m = ids[misses]
            mask_m = mask[misses]
            if bm > n_miss:
                ids_m = np.concatenate(
                    [ids_m, np.zeros((bm - n_miss, L), ids.dtype)]
                )
                mask_m = np.concatenate(
                    [mask_m, np.zeros((bm - n_miss, L), mask.dtype)]
                )
            with self._lock:
                fn = self._forward_fn(bm, L)
            observe.record_occupancy("encoder", n_miss, bm)
            out_m = retry_call(
                "encoder.dispatch", fn, self.params,
                jnp.asarray(ids_m), jnp.asarray(mask_m),
            )
            for j, i in enumerate(misses):
                row = out_m[j]
                fresh[i] = row
                cache.put_row(row_keys[i], row)
        return jnp.stack(
            [rows[i] if rows[i] is not None else fresh[i] for i in range(n)]
        )

    def encode_to_device(self, texts: Sequence[str]):
        """Batch encode with the result left in HBM ([B, d] jax array) —
        feed ``DeviceKnnIndex.add_from_device`` for device-to-device ingest
        with no host round trip (the SURVEY §7.6 pipeline shape)."""
        texts = ["" if t is None else str(t) for t in texts]
        n = len(texts)
        if n == 0:
            return jnp.zeros((0, self.config.d_model), jnp.float32)
        # tokenize + pad OFF the lock: the tokenizer is stateless, so
        # concurrent encoders overlap their host prep instead of
        # serializing behind one thread's lock hold; the lock covers
        # only the compiled-fn cache lookup
        b = _bucket(n)
        padded = list(texts) + [""] * (b - n)
        ids, mask = self.tokenizer.encode_batch(padded)
        if self.embed_cache is not None:
            # tier-1 reuse: known rows skip the forward; misses encode
            # in one bucketed launch and compose on device
            return self._cached_encode_rows(ids, mask, n)
        with self._lock:
            fn = self._forward_fn(ids.shape[0], ids.shape[1])
        # dispatch OFF the lock (lock-discipline): params/fn are stable
        # refs, so the launch needs no lock — holding it would serialize
        # concurrent encoders behind one device queue push.  Transient
        # dispatch failures retry under the "encoder.dispatch" site
        # budget (also the chaos-suite fault site — robust/inject.py).
        observe.record_occupancy("encoder", n, ids.shape[0])
        out = retry_call(
            "encoder.dispatch", fn, self.params, jnp.asarray(ids), jnp.asarray(mask)
        )
        return out[:n]

    def encode(self, texts: Sequence[str]) -> np.ndarray:
        """Batch encode: [B] strings -> [B, d] float32."""
        out = self.encode_to_device(texts)
        # submit→ready clock starts AFTER the dispatch is enqueued — the
        # same semantics as every other pathway_serve_model_seconds
        # series (host prep/tokenize time is not device latency)
        t0 = time.perf_counter_ns()
        host = np.asarray(out, dtype=np.float32)
        t_ready = time.perf_counter_ns()
        _H_READY.observe_ns(t_ready - t0)
        _t = trace.current()
        if _t is not None:
            _t.add_span(
                "model.encoder", t0, t_ready, exemplar=_H_READY,
                texts=len(texts),
            )
        return host

    # -- sequence packing ---------------------------------------------------
    def _pack(self, texts: Sequence[str], max_docs_per_row: int = 8):
        """Best-fit-decreasing packing of tokenized docs into rows of
        ``max_len`` tokens (layout shared with the cross-encoder:
        models/packing.py).  Returns (ids [R, L], mask, segments,
        positions, doc_slots, n_seg) where doc_slots[i] = (row, segment-1)
        of input doc i; segments are 1-based per row, positions restart per
        document (so positional embeddings match unpacked encoding)."""
        from .packing import pack_rows

        L = self.config.max_len
        # tokenize through the NATIVE batch path, then strip padding —
        # per-doc python tokenization was the original ingest bottleneck
        ids_b, mask_b = self.tokenizer.encode_batch(texts)
        ids_b = np.asarray(ids_b)
        lens = np.minimum(np.asarray(mask_b).sum(axis=1), L).astype(np.int64)
        return pack_rows(ids_b, lens, L, max_docs_per_row)

    def encode_packed_to_device(self, texts: Sequence[str]):
        """Encode with SEQUENCE PACKING: short documents share rows with
        block-diagonal attention, so the MXU sees full-length matmuls
        regardless of the corpus length distribution (the variable-length
        ingest hot path; plain per-doc batching starves the MXU below
        ~64 tokens).  Returns a [B, d] device array aligned with
        ``texts`` — same contract as ``encode_to_device``."""
        if not isinstance(self.module, TransformerEncoder):
            # HF-imported modules don't take segment inputs; packing is a
            # shape optimization, so fall back to the plain path
            return self.encode_to_device(texts)
        # tokenize + pack OFF the lock (stateless host prep, same reason
        # as encode_to_device); the lock covers only the compiled-fn cache
        texts = ["" if t is None else str(t) for t in texts]
        n = len(texts)
        if n == 0:
            return jnp.zeros((0, self.config.d_model), jnp.float32)
        from .packing import pad_packed_rows, seg_bucket

        ids, mask, segments, positions, doc_slots, n_seg = self._pack(texts)
        # bucket the row count and segment width: few compile shapes
        rows_real = ids.shape[0]
        Rb = _bucket(rows_real)
        observe.record_occupancy("encoder_packed", rows_real, Rb)
        ids, segments, positions = pad_packed_rows(
            ids, segments, positions, Rb
        )
        Sb = seg_bucket(n_seg)
        with self._lock:
            fn = self._packed_fn(Rb, ids.shape[1], Sb)
        # dispatch OFF the lock, same as encode_to_device (and the same
        # "encoder.dispatch" retry/fault site)
        # no separate mask transfer: segments>0 IS the token mask in
        # the packed forward
        pooled = retry_call(
            "encoder.dispatch",
            fn,
            self.params,
            jnp.asarray(ids),
            jnp.asarray(segments),
            jnp.asarray(positions),
        )  # [Rb, Sb, d]
        flat_ix = np.asarray(
            [r * Sb + s for r, s in doc_slots], np.int32
        )
        nb = _bucket(n)
        if nb > n:
            flat_ix = np.concatenate(
                [flat_ix, np.repeat(flat_ix[-1:], nb - n)]
            )
        out = jnp.take(
            pooled.reshape(Rb * Sb, -1), jnp.asarray(flat_ix), axis=0
        )
        return out[:n]

    # -- token-state export (forward-index ingest) --------------------------
    def _token_fn(self, batch: int, length: int):
        """Compiled doc-side TOKEN-STATE forward: ``(params, ids, mask) ->
        [B, L, d]`` per-token hidden states (post final layer norm,
        L2-normalized per token) — the doc-side export the late-interaction
        forward index stores at ingest (pathway_tpu/index).  Runs the SAME
        trunk params through a pool-free twin of the module, so stored doc
        tokens live in exactly the space the serve-time query tokens come
        from."""
        key = ("tokens", batch, length)
        fn = self._fns.get(key)
        if fn is None:
            self._tripwire.observe(key)
            if not isinstance(self.module, TransformerEncoder):
                raise NotImplementedError(
                    "token-state export needs the in-framework "
                    "TransformerEncoder trunk (HF-imported modules pool "
                    "internally)"
                )
            from .transformer import normalized_token_states, token_state_trunk

            trunk = token_state_trunk(self.config)

            @jax.jit
            def fn(params, ids, mask):
                hidden = trunk.apply({"params": params}, ids, mask)
                return normalized_token_states(hidden, mask)

            # device-time attribution (observe/profile.py)
            fn = profile.wrap("encoder.token_states", fn)
            self._fns[key] = fn
        return self._fns[key]

    def encode_token_states(self, texts: Sequence[str]):
        """Batch encode to PER-TOKEN states, device-resident: returns
        ``(tokens [B, L, d] f32 jax array, mask [B, L] np, n_real)`` with
        pad rows/tokens zeroed.  ``L`` is pinned to ``max_len`` so the
        ingest path compiles one shape per batch bucket (ingest batches
        are maintenance-path work; one wide shape beats a /16 shape
        ladder).  Feeds ``index.forward.ForwardIndex`` ingest — the token
        states never cross the host link."""
        texts = ["" if t is None else str(t) for t in texts]
        n = len(texts)
        L = self.config.max_len
        if n == 0:
            return jnp.zeros((0, L, self.config.d_model), jnp.float32), (
                np.zeros((0, L), np.int32)
            ), 0
        b = _bucket(n)
        padded = list(texts) + [""] * (b - n)
        ids, mask = self.tokenizer.encode_batch(padded, pad_to=L)
        ids = np.asarray(ids)
        mask = np.asarray(mask)
        with self._lock:
            fn = self._token_fn(ids.shape[0], ids.shape[1])
        # dispatch OFF the lock, like encode_to_device (same retry/fault
        # site: a doc-side token encode is still an encoder dispatch)
        out = retry_call(
            "encoder.dispatch", fn, self.params, jnp.asarray(ids), jnp.asarray(mask)
        )
        return out, mask, n

    def _packed_fn(self, R: int, L: int, S: int):
        key = ("packed", R, L, S)
        fn = self._fns.get(key)
        if fn is None:
            self._tripwire.observe(key)
            module = self.module
            normalize = self.normalize

            @jax.jit
            def fn(params, ids, segments, positions):
                out = module.apply(
                    {"params": params},
                    ids,
                    segments > 0,  # the packed forward masks via segments
                    segments=segments,
                    positions=positions,
                    n_segments=S,
                )
                if normalize:
                    out = out / jnp.maximum(
                        jnp.linalg.norm(out, axis=-1, keepdims=True), 1e-9
                    )
                return out

            fn = profile.wrap("encoder.packed", fn)
            self._fns[key] = fn
        return self._fns[key]

    def __call__(self, texts: Sequence[str]) -> np.ndarray:
        return self.encode(texts)
