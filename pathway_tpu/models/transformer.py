"""Flax transformer encoder — the shared trunk for embedders/rerankers.

Designed for the MXU: all matmuls batched, static shapes, bf16 activations,
and flax logical-axis annotations so large configs shard over the mesh
"model" axis via tensor parallelism (SURVEY.md §7.6; the parallel module
turns logical axes into NamedSharding)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "KVTransformerDecoder",
    "SlotKVDecoder",
    "TransformerConfig",
    "TransformerEncoder",
    "normalized_token_states",
    "resolve_heads",
    "token_state_trunk",
]


def token_state_trunk(config: "TransformerConfig") -> "TransformerEncoder":
    """A pool-free twin of a trunk config — applies the SAME params (no
    pooling layer carries weights) and returns raw [B, L, d] hidden
    states.  The one constructor for every token-state export site."""
    from dataclasses import replace

    return TransformerEncoder(replace(config, pool="none"))


def normalized_token_states(hidden, mask):
    """Canonical token-state post-processing for late interaction
    (traced fragment): f32 cast, per-token L2 normalization (1e-9
    floor), pad tokens zeroed.  Doc-side ingest export
    (models/encoder.py) and query-side serve export (ops/serving.py)
    BOTH go through this one function — MaxSim is only meaningful if
    stored doc tokens and serve-time query tokens live in the identical
    vector space, so the math must not be able to drift between them."""
    hidden = hidden.astype(jnp.float32)
    hidden = hidden / jnp.maximum(
        jnp.linalg.norm(hidden, axis=-1, keepdims=True), 1e-9
    )
    return hidden * mask[:, :, None].astype(jnp.float32)


def resolve_heads(d_model: int, requested: int) -> int:
    """Largest head count <= requested that divides d_model (so arbitrary
    embedder dimensions work without manual head tuning)."""
    for h in range(min(requested, d_model), 0, -1):
        if d_model % h == 0:
            return h
    return 1


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32768
    d_model: int = 384
    n_heads: int = 6
    n_layers: int = 6
    d_ff: int = 1536
    max_len: int = 512
    dtype: Any = jnp.bfloat16
    pool: str = "mean"  # mean | cls | none
    causal: bool = False
    # long-context: shard the sequence dim over this mesh axis and attend
    # via ring attention (ops/ring_attention.py) — O(L/n) activation memory
    # per device, K/V rotated over ICI neighbor links
    mesh: Any = None
    sequence_axis: Optional[str] = None


class MlpBlock(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        h = nn.Dense(
            cfg.d_ff,
            dtype=cfg.dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.xavier_uniform(), ("embed", "mlp")
            ),
        )(x)
        h = nn.gelu(h)
        return nn.Dense(
            cfg.d_model,
            dtype=cfg.dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.xavier_uniform(), ("mlp", "embed")
            ),
        )(h)


class SelfAttention(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x, mask, segments=None):
        cfg = self.config
        B, L, D = x.shape
        head_dim = cfg.d_model // cfg.n_heads

        def proj(name, logical):
            return nn.Dense(
                cfg.d_model,
                dtype=cfg.dtype,
                name=name,
                kernel_init=nn.with_logical_partitioning(
                    nn.initializers.xavier_uniform(), logical
                ),
            )

        q = proj("query", ("embed", "heads"))(x)
        k = proj("key", ("embed", "heads"))(x)
        v = proj("value", ("embed", "heads"))(x)
        q = q.reshape(B, L, cfg.n_heads, head_dim)
        k = k.reshape(B, L, cfg.n_heads, head_dim)
        v = v.reshape(B, L, cfg.n_heads, head_dim)
        if cfg.sequence_axis is not None and cfg.mesh is not None:
            # sequence packing and sequence sharding are mutually
            # exclusive: the ring walks one logical sequence, and packed
            # rows would attend across document boundaries undetected
            assert segments is None, (
                "packed (segments) forward is not supported with "
                "ring/sequence-parallel attention"
            )
            from ..ops.ring_attention import ring_attention_sharded

            positions = jnp.broadcast_to(jnp.arange(L)[None, :], (B, L))
            out = ring_attention_sharded(
                cfg.mesh,
                q,
                k,
                v,
                mask.astype(bool),
                positions,
                axis=cfg.sequence_axis,
                causal=cfg.causal,
            ).reshape(B, L, cfg.d_model)
            return proj("out", ("heads", "embed"))(out)
        scores = jnp.einsum("blhd,bmhd->bhlm", q, k) / np.sqrt(head_dim)
        big_neg = jnp.finfo(jnp.float32).min
        if segments is not None:
            # PACKED rows: token l attends token m iff both belong to the
            # SAME nonzero segment (block-diagonal attention) — several
            # short documents share one row with exact per-doc semantics
            same = segments[:, None, :, None] == segments[:, None, None, :]
            attn_mask = same & (segments[:, None, None, :] > 0)
        else:
            attn_mask = mask[:, None, None, :]  # [B,1,1,L] key mask
        if cfg.causal:
            causal = jnp.tril(jnp.ones((L, L), dtype=bool))
            attn_mask = attn_mask * causal[None, None, :, :]
        scores = jnp.where(attn_mask > 0, scores, big_neg)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(cfg.dtype)
        out = jnp.einsum("bhlm,bmhd->blhd", probs, v).reshape(B, L, cfg.d_model)
        return proj("out", ("heads", "embed"))(out)


class EncoderBlock(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x, mask, segments=None):
        cfg = self.config
        h = nn.LayerNorm(dtype=cfg.dtype)(x)
        x = x + SelfAttention(cfg)(h, mask, segments)
        h = nn.LayerNorm(dtype=cfg.dtype)(x)
        x = x + MlpBlock(cfg)(h)
        return x


class KVSelfAttention(nn.Module):
    """Params-compatible incremental twin of ``SelfAttention``: attends
    ``Ln`` NEW tokens against a persistent K/V buffer instead of
    re-projecting the whole sequence.  The new tokens' K/V are inserted
    at ``write_pos`` (per row) and the updated buffers returned — the
    caller (``KVTransformerDecoder``) threads them through the decode.

    Numerics are kept LINE-FOR-LINE with ``SelfAttention`` (same
    projection names/dtypes, same ``big_neg`` masking, f32 softmax):
    under causal attention a position's K/V depends only on tokens at or
    before it, so for real query positions the score rows here are
    bit-identical to the full re-attend — the parity test in
    tests/test_serve_cache.py holds token-for-token.

    ``quant=True`` (ops/kv_quant.py): the cache buffers are int8 with
    per-(head, channel) stored scales — new K/V quantize at the write
    and EVERY read dequantizes inside this kernel, so prefill and
    decode attend identical values and warm joins stay deterministic."""

    config: TransformerConfig
    quant: bool = False

    @nn.compact
    def __call__(
        self, x, k_cache, v_cache, write_pos, q_pos,
        k_scales=None, v_scales=None,
    ):
        cfg = self.config
        B, Ln, D = x.shape
        T = k_cache.shape[1]
        head_dim = cfg.d_model // cfg.n_heads

        def proj(name, logical):
            return nn.Dense(
                cfg.d_model,
                dtype=cfg.dtype,
                name=name,
                kernel_init=nn.with_logical_partitioning(
                    nn.initializers.xavier_uniform(), logical
                ),
            )

        q = proj("query", ("embed", "heads"))(x)
        k_new = proj("key", ("embed", "heads"))(x)
        v_new = proj("value", ("embed", "heads"))(x)
        q = q.reshape(B, Ln, cfg.n_heads, head_dim)
        k_new = k_new.reshape(B, Ln, cfg.n_heads, head_dim)
        v_new = v_new.reshape(B, Ln, cfg.n_heads, head_dim)
        if self.quant:
            from ..ops.kv_quant import dequantize_kv, quantize_kv

            k_new = quantize_kv(k_new, k_scales)
            v_new = quantize_kv(v_new, v_scales)
        # insert the new tokens' K/V at each row's write position (rows
        # decode at different offsets: prompts have different lengths)
        insert = jax.vmap(
            lambda buf, new, p: jax.lax.dynamic_update_slice(
                buf, new, (p, 0, 0)
            )
        )
        k_cache = insert(k_cache, k_new, write_pos)
        v_cache = insert(v_cache, v_new, write_pos)
        if self.quant:
            k_att = dequantize_kv(k_cache, k_scales, cfg.dtype)
            v_att = dequantize_kv(v_cache, v_scales, cfg.dtype)
        else:
            k_att, v_att = k_cache, v_cache
        scores = jnp.einsum("blhd,bmhd->bhlm", q, k_att) / np.sqrt(head_dim)
        big_neg = jnp.finfo(jnp.float32).min
        # query at global position q_pos[b, l] attends key slot t iff
        # t <= q_pos — slots past the write frontier are either unwritten
        # (zeros) or stale pad K/V, and both are masked to exact zero
        # probability, so they can never perturb the output
        key_pos = jnp.arange(T, dtype=jnp.int32)
        attn_mask = key_pos[None, None, :] <= q_pos[:, :, None]
        scores = jnp.where(attn_mask[:, None, :, :], scores, big_neg)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(cfg.dtype)
        out = jnp.einsum("bhlm,bmhd->blhd", probs, v_att).reshape(
            B, Ln, cfg.d_model
        )
        return proj("out", ("heads", "embed"))(out), k_cache, v_cache


class KVEncoderBlock(nn.Module):
    """Params-compatible incremental twin of ``EncoderBlock`` — explicit
    submodule names pin the param tree to the trunk's layout."""

    config: TransformerConfig
    quant: bool = False

    @nn.compact
    def __call__(
        self, x, k_cache, v_cache, write_pos, q_pos,
        k_scales=None, v_scales=None,
    ):
        cfg = self.config
        h = nn.LayerNorm(dtype=cfg.dtype, name="LayerNorm_0")(x)
        attn, k_cache, v_cache = KVSelfAttention(
            cfg, name="SelfAttention_0", quant=self.quant
        )(h, k_cache, v_cache, write_pos, q_pos, k_scales, v_scales)
        x = x + attn
        h = nn.LayerNorm(dtype=cfg.dtype, name="LayerNorm_1")(x)
        x = x + MlpBlock(cfg, name="MlpBlock_0")(h)
        return x, k_cache, v_cache


class KVTransformerDecoder(nn.Module):
    """Incremental causal decode over the SAME params as a causal
    ``TransformerEncoder`` (the generator trunk): forward ``Ln`` new
    tokens against per-layer K/V buffers ``[B, n_layers, T, H, hd]``,
    returning the final-LN hidden states for those tokens plus the
    updated buffers.  One module serves both phases of a KV decode:

    - **prefill**: ``Ln`` = the prompt suffix, ``write_pos`` = the
      cached-prefix length (0 cold);
    - **decode step**: ``Ln = 1``, ``write_pos`` = the row's current
      token count.

    This is what turns the generator's O(steps × L²) re-attend decode
    into O(steps × L) — and, with the prefix cache
    (pathway_tpu/cache/prefix.py), lets prompts sharing a prefix skip
    its prefill entirely.

    ``quant=True``: the per-layer buffers are int8 and ``k_scales``/
    ``v_scales`` ``[n_layers, H, hd]`` must be passed — each layer's
    attention quantizes its writes and dequantizes its reads."""

    config: TransformerConfig
    quant: bool = False

    @nn.compact
    def __call__(
        self, ids_new, positions, k_caches, v_caches, write_pos, q_pos,
        k_scales=None, v_scales=None,
    ):
        cfg = self.config
        tok = nn.Embed(
            cfg.vocab_size,
            cfg.d_model,
            dtype=cfg.dtype,
            embedding_init=nn.with_logical_partitioning(
                nn.initializers.normal(0.02), ("vocab", "embed")
            ),
            name="tok_embed",
        )(ids_new)
        pos = nn.Embed(
            cfg.max_len,
            cfg.d_model,
            dtype=cfg.dtype,
            embedding_init=nn.with_logical_partitioning(
                nn.initializers.normal(0.02), ("pos", "embed")
            ),
            name="pos_embed",
        )(positions)
        x = tok + pos
        new_k = []
        new_v = []
        for i in range(cfg.n_layers):
            x, ki, vi = KVEncoderBlock(
                cfg, name=f"block_{i}", quant=self.quant
            )(
                x, k_caches[:, i], v_caches[:, i], write_pos, q_pos,
                None if k_scales is None else k_scales[i],
                None if v_scales is None else v_scales[i],
            )
            new_k.append(ki)
            new_v.append(vi)
        x = nn.LayerNorm(dtype=cfg.dtype, name="final_ln")(x)
        return x, jnp.stack(new_k, axis=1), jnp.stack(new_v, axis=1)


class SlotSelfAttention(nn.Module):
    """Params-compatible slot-pool twin of ``KVSelfAttention``: the
    batch dimension is a pool of persistent SLOTS and only ACTIVE lanes
    may move their K/V.  The freeze is applied at the WRITE, not with a
    post-hoc full-buffer select: the inserted value is the new token's
    K/V for active lanes and the buffer's EXISTING value for inactive
    ones — a single [S, Ln, H, hd] mask instead of two [S, T, H, hd]
    copies per layer per step, which keeps the per-step scatter
    in-place-friendly for XLA's loop optimizer.  For active lanes the
    inserted values (and therefore scores, probs, outputs) are
    line-for-line ``KVSelfAttention``'s — the twin relation the
    token-identity tests pin down.

    ``quant=True``: int8 pool with per-(head, channel) stored scales —
    same write-masking over int8 values, reads dequantized in-kernel
    (ops/kv_quant.py)."""

    config: TransformerConfig
    quant: bool = False

    @nn.compact
    def __call__(
        self, x, k_cache, v_cache, write_pos, q_pos, active,
        k_scales=None, v_scales=None,
    ):
        cfg = self.config
        B, Ln, D = x.shape
        T = k_cache.shape[1]
        head_dim = cfg.d_model // cfg.n_heads

        def proj(name, logical):
            return nn.Dense(
                cfg.d_model,
                dtype=cfg.dtype,
                name=name,
                kernel_init=nn.with_logical_partitioning(
                    nn.initializers.xavier_uniform(), logical
                ),
            )

        q = proj("query", ("embed", "heads"))(x)
        k_new = proj("key", ("embed", "heads"))(x)
        v_new = proj("value", ("embed", "heads"))(x)
        q = q.reshape(B, Ln, cfg.n_heads, head_dim)
        k_new = k_new.reshape(B, Ln, cfg.n_heads, head_dim)
        v_new = v_new.reshape(B, Ln, cfg.n_heads, head_dim)
        if self.quant:
            from ..ops.kv_quant import dequantize_kv, quantize_kv

            k_new = quantize_kv(k_new, k_scales)
            v_new = quantize_kv(v_new, v_scales)
        # masked write: inactive lanes re-insert what the buffer already
        # holds at their write position — their K/V is bit-frozen
        read = jax.vmap(
            lambda buf, p: jax.lax.dynamic_slice(
                buf, (p, 0, 0), (Ln, cfg.n_heads, head_dim)
            )
        )
        sel = active[:, None, None, None]
        k_ins = jnp.where(sel, k_new, read(k_cache, write_pos))
        v_ins = jnp.where(sel, v_new, read(v_cache, write_pos))
        insert = jax.vmap(
            lambda buf, new, p: jax.lax.dynamic_update_slice(
                buf, new, (p, 0, 0)
            )
        )
        k_cache = insert(k_cache, k_ins, write_pos)
        v_cache = insert(v_cache, v_ins, write_pos)
        if self.quant:
            k_att = dequantize_kv(k_cache, k_scales, cfg.dtype)
            v_att = dequantize_kv(v_cache, v_scales, cfg.dtype)
        else:
            k_att, v_att = k_cache, v_cache
        scores = jnp.einsum("blhd,bmhd->bhlm", q, k_att) / np.sqrt(head_dim)
        big_neg = jnp.finfo(jnp.float32).min
        key_pos = jnp.arange(T, dtype=jnp.int32)
        attn_mask = key_pos[None, None, :] <= q_pos[:, :, None]
        scores = jnp.where(attn_mask[:, None, :, :], scores, big_neg)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(cfg.dtype)
        out = jnp.einsum("bhlm,bmhd->blhd", probs, v_att).reshape(
            B, Ln, cfg.d_model
        )
        return proj("out", ("heads", "embed"))(out), k_cache, v_cache


class SlotEncoderBlock(nn.Module):
    """Slot-pool twin of ``KVEncoderBlock`` — explicit submodule names
    pin the param tree to the trunk's layout."""

    config: TransformerConfig
    quant: bool = False

    @nn.compact
    def __call__(
        self, x, k_cache, v_cache, write_pos, q_pos, active,
        k_scales=None, v_scales=None,
    ):
        cfg = self.config
        h = nn.LayerNorm(dtype=cfg.dtype, name="LayerNorm_0")(x)
        attn, k_cache, v_cache = SlotSelfAttention(
            cfg, name="SelfAttention_0", quant=self.quant
        )(h, k_cache, v_cache, write_pos, q_pos, active, k_scales, v_scales)
        x = x + attn
        h = nn.LayerNorm(dtype=cfg.dtype, name="LayerNorm_1")(x)
        x = x + MlpBlock(cfg, name="MlpBlock_0")(h)
        return x, k_cache, v_cache


class SlotKVDecoder(nn.Module):
    """Slot-indexed twin of ``KVTransformerDecoder`` for the continuous
    decode engine (serve/decode.py): the batch dimension is a pool of
    ``S`` persistent SLOTS whose K/V buffers ``[S, n_layers, T, H, hd]``
    outlive any one request, and the step advances only ACTIVE slots.

    Requests JOIN a slot mid-flight (prefill writes their prompt K/V)
    and LEAVE at EOS; the pool buffers are then reused by the next
    request.  Two properties make the in-flight mixing safe:

    - **inactive slots do not move**: each layer's K/V write is masked
      per lane (``SlotSelfAttention`` re-inserts the existing value for
      inactive lanes), so an idle or finished slot's K/V is bit-frozen
      no matter what garbage its lane computed.  For active slots the
      buffers and hidden states are exactly what
      ``KVTransformerDecoder`` would have produced — the twin relation
      the token-identity tests pin down;
    - **stale K/V cannot leak**: the attention masks every key slot
      past a row's ``q_pos`` to exact-zero probability, and a joining
      request's prefill (re)writes every position it will ever attend —
      so a reused slot can never see its previous occupant.

    ``quant=True``: int8 pool + ``[n_layers, H, hd]`` stored scales
    (ops/kv_quant.py).  ``layers=D`` runs only the FIRST ``D`` trunk
    blocks (plus ``final_ln``) over the same param tree — the reduced-
    layer DRAFT trunk of the speculative decode path: its pool slice is
    ``[S, D, T, H, hd]`` and its proposals need no second model."""

    config: TransformerConfig
    quant: bool = False
    layers: Optional[int] = None

    @nn.compact
    def __call__(
        self, ids_new, positions, k_pool, v_pool, write_pos, q_pos, active,
        k_scales=None, v_scales=None,
    ):
        cfg = self.config
        tok = nn.Embed(
            cfg.vocab_size,
            cfg.d_model,
            dtype=cfg.dtype,
            embedding_init=nn.with_logical_partitioning(
                nn.initializers.normal(0.02), ("vocab", "embed")
            ),
            name="tok_embed",
        )(ids_new)
        pos = nn.Embed(
            cfg.max_len,
            cfg.d_model,
            dtype=cfg.dtype,
            embedding_init=nn.with_logical_partitioning(
                nn.initializers.normal(0.02), ("pos", "embed")
            ),
            name="pos_embed",
        )(positions)
        x = tok + pos
        new_k = []
        new_v = []
        n_layers = cfg.n_layers if self.layers is None else self.layers
        for i in range(n_layers):
            x, ki, vi = SlotEncoderBlock(
                cfg, name=f"block_{i}", quant=self.quant
            )(
                x, k_pool[:, i], v_pool[:, i], write_pos, q_pos, active,
                None if k_scales is None else k_scales[i],
                None if v_scales is None else v_scales[i],
            )
            new_k.append(ki)
            new_v.append(vi)
        x = nn.LayerNorm(dtype=cfg.dtype, name="final_ln")(x)
        return x, jnp.stack(new_k, axis=1), jnp.stack(new_v, axis=1)


class TransformerEncoder(nn.Module):
    """Token ids + mask -> pooled embedding (or full hidden states)."""

    config: TransformerConfig

    @nn.compact
    def __call__(self, ids, mask, segments=None, positions=None, n_segments=0):
        """Unpacked: ``(ids, mask) -> [B, d]`` pooled embeddings.

        PACKED (sequence packing — several short documents share one row,
        the TPU-idiomatic answer to variable-length corpora): pass
        ``segments`` [B, L] (0 = pad, 1..n_segments = document within the
        row), ``positions`` [B, L] (restarting per document so positional
        embeddings match the unpacked encoding), and static
        ``n_segments``; returns ``[B, n_segments, d]`` per-document
        embeddings (zero rows for absent segments).  Attention is
        block-diagonal per segment, so results equal the unpacked
        forward up to dtype accumulation order."""
        cfg = self.config
        B, L = ids.shape
        tok = nn.Embed(
            cfg.vocab_size,
            cfg.d_model,
            dtype=cfg.dtype,
            embedding_init=nn.with_logical_partitioning(
                nn.initializers.normal(0.02), ("vocab", "embed")
            ),
            name="tok_embed",
        )(ids)
        if positions is None:
            positions = jnp.arange(L)[None, :]
        pos = nn.Embed(
            cfg.max_len,
            cfg.d_model,
            dtype=cfg.dtype,
            embedding_init=nn.with_logical_partitioning(
                nn.initializers.normal(0.02), ("pos", "embed")
            ),
            name="pos_embed",
        )(positions)
        x = tok + pos
        for i in range(cfg.n_layers):
            x = EncoderBlock(cfg, name=f"block_{i}")(x, mask, segments)
        x = nn.LayerNorm(dtype=cfg.dtype, name="final_ln")(x)
        if segments is not None:
            # per-segment masked mean pool as ONE matmul per row:
            # onehot [B, L, S] x hidden [B, L, d] -> [B, S, d]
            assert n_segments > 0, "packed forward needs static n_segments"
            assert cfg.pool == "mean", (
                f"packed forward implements mean pooling only (pool="
                f"{cfg.pool!r} would silently change semantics)"
            )
            seg_ids = jnp.arange(1, n_segments + 1)
            onehot = (segments[:, :, None] == seg_ids[None, None, :]).astype(
                x.dtype
            )
            summed = jnp.einsum("bls,bld->bsd", onehot, x)
            counts = jnp.maximum(jnp.sum(onehot, axis=1), 1.0)[:, :, None]
            return (summed / counts).astype(jnp.float32)
        if cfg.pool == "none":
            return x
        if cfg.pool == "cls":
            return x[:, 0, :].astype(jnp.float32)
        # masked mean pool
        m = mask[:, :, None].astype(x.dtype)
        summed = jnp.sum(x * m, axis=1)
        counts = jnp.maximum(jnp.sum(m, axis=1), 1.0)
        return (summed / counts).astype(jnp.float32)
