"""Cross-encoder pair scorer — batched (query, doc) -> relevance score.

TPU-native replacement for sentence_transformers CrossEncoder
(reference: xpacks/llm/rerankers.py:186 CrossEncoderReranker): both texts in
one sequence separated by [SEP], encoder trunk + regression head, one jitted
forward per padded batch."""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ._params import unbox as _unbox

from .tokenizer import HashTokenizer
from .transformer import TransformerConfig, TransformerEncoder, resolve_heads

__all__ = ["CrossEncoderModel"]


class _CrossEncoderModule(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, ids, mask):
        pooled = TransformerEncoder(self.config, name="trunk")(ids, mask)
        h = nn.Dense(self.config.d_model, name="head_dense")(pooled)
        h = nn.tanh(h)
        return nn.Dense(1, name="head_out")(h)[:, 0]


class CrossEncoderModel:
    def __init__(
        self,
        model: str = "pathway-mini-cross",
        dimension: int = 256,
        n_layers: int = 4,
        n_heads: int = 4,
        max_length: int = 256,
        vocab_size: int = 32768,
        seed: int = 1,
        checkpoint_path: Optional[str] = None,
        dtype=jnp.bfloat16,
    ):
        from .hf_import import is_hf_checkpoint

        self._lock = threading.Lock()
        self._fns: Dict[tuple, Any] = {}
        self._hf = is_hf_checkpoint(checkpoint_path)
        if self._hf:
            # real-weights path: HF BertForSequenceClassification (the
            # sentence-transformers cross-encoder export; hf_import.py)
            from .hf_import import load_hf_text_model

            self.module, self.params, self.config, self.tokenizer = (
                load_hf_text_model(
                    checkpoint_path, max_length, dtype, cross=True
                )
            )
            return
        self.config = TransformerConfig(
            vocab_size=vocab_size,
            d_model=dimension,
            n_heads=resolve_heads(dimension, n_heads),
            n_layers=n_layers,
            d_ff=dimension * 4,
            max_len=max_length,
            dtype=dtype,
            pool="mean",
        )
        self.tokenizer = HashTokenizer(vocab_size=vocab_size, max_length=max_length)
        self.module = _CrossEncoderModule(self.config)
        ids = jnp.zeros((1, 16), jnp.int32)
        mask = jnp.ones((1, 16), jnp.int32)
        self.params = self.module.init(jax.random.PRNGKey(seed), ids, mask)["params"]
        self.params = _unbox(self.params)

    def _forward_fn(self, shape):
        fn = self._fns.get(shape)
        if fn is None:
            if self._hf:
                fn = jax.jit(
                    lambda params, ids, mask, type_ids: self.module.apply(
                        {"params": params}, ids, mask, type_ids
                    )
                )
            else:
                fn = jax.jit(
                    lambda params, ids, mask: self.module.apply(
                        {"params": params}, ids, mask
                    )
                )
            self._fns[shape] = fn
        return fn

    def predict(self, pairs: Sequence[Tuple[str, str]]) -> np.ndarray:
        """[(query, doc)] -> scores [B] float32."""
        with self._lock:
            n = len(pairs)
            if n == 0:
                return np.zeros((0,), np.float32)
            from .encoder import _bucket

            b = _bucket(n)
            qs = [str(p[0]) for p in pairs] + [""] * (b - n)
            ds = [str(p[1]) for p in pairs] + [""] * (b - n)
            ids, mask = self.tokenizer.encode_batch(qs, pairs=ds)
            fn = self._forward_fn(ids.shape)
            if self._hf:
                # BERT pair segments: tokens after the first [SEP] are type 1
                first_sep = np.argmax(ids == self.tokenizer.SEP, axis=1)
                type_ids = (
                    (np.arange(ids.shape[1])[None, :] > first_sep[:, None])
                    & (mask > 0)
                ).astype(np.int32)
                out = fn(
                    self.params,
                    jnp.asarray(ids),
                    jnp.asarray(mask),
                    jnp.asarray(type_ids),
                )
            else:
                out = fn(self.params, jnp.asarray(ids), jnp.asarray(mask))
            return np.asarray(out, dtype=np.float32)[:n]
