"""Cross-encoder pair scorer — batched (query, doc) -> relevance score.

TPU-native replacement for sentence_transformers CrossEncoder
(reference: xpacks/llm/rerankers.py:186 CrossEncoderReranker): both texts in
one sequence separated by [SEP], encoder trunk + regression head, one jitted
forward per padded batch."""

from __future__ import annotations

# pathway: serve-path  (hidden-sync lint applies: no implicit host round trips)

import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from .. import observe
from ..observe import hbm, profile, trace
from ..ops.recompile_guard import RecompileTripwire
from ..robust import Deadline, inject, retry_call
from ._params import unbox as _unbox

from .tokenizer import HashTokenizer
from .transformer import TransformerConfig, TransformerEncoder, resolve_heads

__all__ = ["CrossEncoderModel"]

# flight recorder: submit→ready latency (dispatch through the completion
# fetch) + per-dispatch batch occupancy
_H_READY = observe.histogram("pathway_serve_model_seconds", model="cross_encoder")


class _CrossEncoderModule(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, ids, mask, segments=None, positions=None, n_segments=0):
        """Unpacked: ``(ids, mask) -> [B]`` pair scores.  PACKED (several
        short (query, doc) pairs share one row under block-diagonal
        segment attention — models/transformer.py): pass ``segments`` /
        ``positions`` / static ``n_segments`` and the per-segment pooled
        states come back as ``[B, n_segments, d]``, so the regression head
        scores every packed pair in the same two matmuls."""
        pooled = TransformerEncoder(self.config, name="trunk")(
            ids, mask, segments=segments, positions=positions,
            n_segments=n_segments,
        )
        h = nn.Dense(self.config.d_model, name="head_dense")(pooled)
        h = nn.tanh(h)
        return nn.Dense(1, name="head_out")(h)[..., 0]


class CrossEncoderModel:
    def __init__(
        self,
        model: str = "pathway-mini-cross",
        dimension: int = 256,
        n_layers: int = 4,
        n_heads: int = 4,
        max_length: int = 256,
        vocab_size: int = 32768,
        seed: int = 1,
        checkpoint_path: Optional[str] = None,
        dtype=jnp.bfloat16,
    ):
        from .hf_import import is_hf_checkpoint

        self._lock = threading.Lock()
        self._fns: Dict[tuple, Any] = {}
        # recompile tripwire (ops/recompile_guard.py): counts compile
        # shapes, warns past budget, fails under tests
        self._tripwire = RecompileTripwire(f"CrossEncoderModel[{model}]")
        self._hf = is_hf_checkpoint(checkpoint_path)
        if self._hf:
            # real-weights path: HF BertForSequenceClassification (the
            # sentence-transformers cross-encoder export; hf_import.py)
            from .hf_import import load_hf_text_model

            self.module, self.params, self.config, self.tokenizer = (
                load_hf_text_model(
                    checkpoint_path, max_length, dtype, cross=True
                )
            )
            return
        self.config = TransformerConfig(
            vocab_size=vocab_size,
            d_model=dimension,
            n_heads=resolve_heads(dimension, n_heads),
            n_layers=n_layers,
            d_ff=dimension * 4,
            max_len=max_length,
            dtype=dtype,
            pool="mean",
        )
        self.tokenizer = HashTokenizer(vocab_size=vocab_size, max_length=max_length)
        self.module = _CrossEncoderModule(self.config)
        ids = jnp.zeros((1, 16), jnp.int32)
        mask = jnp.ones((1, 16), jnp.int32)
        self.params = self.module.init(jax.random.PRNGKey(seed), ids, mask)["params"]
        self.params = _unbox(self.params)
        # HBM ledger (observe/hbm.py): parameter tree bytes
        hbm.track_params("cross_encoder", self)

    def _forward_fn(self, shape):
        fn = self._fns.get(shape)
        if fn is None:
            self._tripwire.observe(shape)
            if self._hf:
                fn = jax.jit(
                    lambda params, ids, mask, type_ids: self.module.apply(
                        {"params": params}, ids, mask, type_ids
                    )
                )
            else:
                fn = jax.jit(
                    lambda params, ids, mask: self.module.apply(
                        {"params": params}, ids, mask
                    )
                )
            # device-time attribution (observe/profile.py)
            fn = profile.wrap("cross_encoder.forward", fn)
            self._fns[shape] = fn
        return fn

    def predict(
        self, pairs: Sequence[Tuple[str, str]], packed: Optional[bool] = None
    ) -> np.ndarray:
        """[(query, doc)] -> scores [B] float32.

        ``packed=None`` (default) picks sequence packing whenever the
        module supports it (the in-framework trunk; HF-imported modules
        take no segment inputs): short pairs share rows under
        block-diagonal attention instead of each padding to
        ``max_length``, identical scores up to dtype accumulation order.
        ``packed=False`` forces the one-pair-per-row reference path (the
        parity oracle for the packed one)."""
        return self.submit(pairs, packed=packed)()

    def submit(
        self,
        pairs: Sequence[Tuple[str, str]],
        packed: Optional[bool] = None,
        deadline: Optional[Deadline] = None,
    ):
        """Dispatch one scoring batch WITHOUT waiting; returns a zero-arg
        callable completing it (same submit/complete pattern as
        ``FusedEncodeSearch.submit``, so a serving pipeline can overlap
        cross-encoder rescoring with the next call's retrieval).
        ``deadline`` bounds the dispatch retry budget and is re-checked
        before the completion blocks on the fetch — a spent budget raises
        ``DeadlineExceeded`` for the caller's degradation ladder."""
        n = len(pairs)
        if n == 0:
            return lambda: np.zeros((0,), np.float32)
        if packed is None:
            packed = not self._hf
        if packed and not self._hf:
            return self._submit_packed(pairs, deadline=deadline)
        return self._submit_unpacked(pairs, deadline=deadline)

    def _submit_unpacked(
        self,
        pairs: Sequence[Tuple[str, str]],
        deadline: Optional[Deadline] = None,
    ):
        """One pair per padded row — the HF path and the parity reference
        for the packed path.  Tokenization runs OFF the lock (stateless
        host prep: concurrent rerank callers overlap it); the lock covers
        only the compiled-fn cache, and the dispatch launches OFF it too
        (lock-discipline: concurrent rerank callers must not serialize
        behind one thread's enqueue)."""
        from .encoder import _bucket

        n = len(pairs)
        b = _bucket(n)
        qs = [str(p[0]) for p in pairs] + [""] * (b - n)
        ds = [str(p[1]) for p in pairs] + [""] * (b - n)
        ids, mask = self.tokenizer.encode_batch(qs, pairs=ds)
        with self._lock:
            fn = self._forward_fn(ids.shape)
        if self._hf:
            # BERT pair segments: tokens after the first [SEP] are type 1
            first_sep = np.argmax(ids == self.tokenizer.SEP, axis=1)
            type_ids = (
                (np.arange(ids.shape[1])[None, :] > first_sep[:, None])
                & (mask > 0)
            ).astype(np.int32)
            out = retry_call(
                "cross_encoder.dispatch",
                fn,
                self.params,
                jnp.asarray(ids),
                jnp.asarray(mask),
                jnp.asarray(type_ids),
                deadline=deadline,
            )
        else:
            out = retry_call(
                "cross_encoder.dispatch",
                fn,
                self.params,
                jnp.asarray(ids),
                jnp.asarray(mask),
                deadline=deadline,
            )
        if hasattr(out, "copy_to_host_async"):
            out.copy_to_host_async()
        t_dispatch = time.perf_counter_ns()
        observe.record_occupancy("cross_encoder", n, b)

        def complete() -> np.ndarray:
            inject.fire("cross_encoder.fetch", deadline=deadline)
            if deadline is not None:
                deadline.check("cross_encoder.fetch")
            scores = np.asarray(out, dtype=np.float32)[:n]
            t_ready = time.perf_counter_ns()
            _H_READY.observe_ns(t_ready - t_dispatch)
            _t = trace.current()
            if _t is not None:
                _t.add_span(
                    "model.cross_encoder", t_dispatch, t_ready,
                    exemplar=_H_READY, pairs=n,
                )
            return scores

        return complete

    # -- sequence packing ---------------------------------------------------
    def _pack_pairs(self, pairs: Sequence[Tuple[str, str]]):
        """Tokenize (query, doc) pairs and pack them into length-bucketed
        rows (models/packing.py): the row width is the smallest bucket
        holding the longest pair, so a 20-token pair never burns a full
        ``max_length``-token row of MXU work.  Returns (ids, segments,
        positions, doc_slots, n_seg) with doc_slots[i] = (row, seg-1) of
        pair i."""
        from .packing import pack_rows, row_length_bucket

        qs = [str(p[0]) for p in pairs]
        ds = [str(p[1]) for p in pairs]
        ids_b, mask_b = self.tokenizer.encode_batch(qs, pairs=ds)
        ids_b = np.asarray(ids_b)
        lens = np.asarray(mask_b).sum(axis=1).astype(np.int64)
        L = row_length_bucket(int(lens.max()), self.config.max_len)
        lens = np.minimum(lens, L)
        ids, _mask, segments, positions, doc_slots, n_seg = pack_rows(
            ids_b, lens, L
        )
        return ids, segments, positions, doc_slots, n_seg

    def _packed_fn(self, R: int, L: int, S: int):
        key = ("packed", R, L, S)
        fn = self._fns.get(key)
        if fn is None:
            self._tripwire.observe(key)
            module = self.module

            @jax.jit
            def fn(params, ids, segments, positions):
                return module.apply(
                    {"params": params},
                    ids,
                    segments > 0,  # the packed forward masks via segments
                    segments=segments,
                    positions=positions,
                    n_segments=S,
                )  # [R, S] per-segment pair scores

            fn = profile.wrap("cross_encoder.packed", fn)
            self._fns[key] = fn
        return self._fns[key]

    def _submit_packed(
        self,
        pairs: Sequence[Tuple[str, str]],
        deadline: Optional[Deadline] = None,
    ):
        """Packed async scoring: pack, dispatch ONE forward over the packed
        rows, return a completion that gathers the per-pair scores back
        into input order.  Tokenize + pack run OFF the lock (stateless
        host prep — concurrent rerank callers overlap it); the lock
        covers only the compiled-fn cache, and the dispatch launches OFF
        it too (lock-discipline)."""
        from .encoder import _bucket
        from .packing import pad_packed_rows, seg_bucket

        n = len(pairs)
        ids, segments, positions, doc_slots, n_seg = self._pack_pairs(pairs)
        rows_real = ids.shape[0]
        Rb = _bucket(rows_real)
        ids, segments, positions = pad_packed_rows(
            ids, segments, positions, Rb
        )
        Sb = seg_bucket(n_seg)
        with self._lock:
            fn = self._packed_fn(Rb, ids.shape[1], Sb)
        out = retry_call(
            "cross_encoder.dispatch",
            fn,
            self.params,
            jnp.asarray(ids),
            jnp.asarray(segments),
            jnp.asarray(positions),
            deadline=deadline,
        )
        if hasattr(out, "copy_to_host_async"):
            out.copy_to_host_async()
        t_dispatch = time.perf_counter_ns()
        observe.record_occupancy("cross_encoder_packed", rows_real, Rb)
        flat_ix = np.asarray([r * Sb + s for r, s in doc_slots], np.int64)

        def complete() -> np.ndarray:
            inject.fire("cross_encoder.fetch", deadline=deadline)
            if deadline is not None:
                deadline.check("cross_encoder.fetch")
            arr = np.asarray(out, dtype=np.float32).reshape(-1)
            t_ready = time.perf_counter_ns()
            _H_READY.observe_ns(t_ready - t_dispatch)
            _t = trace.current()
            if _t is not None:
                _t.add_span(
                    "model.cross_encoder", t_dispatch, t_ready,
                    exemplar=_H_READY, pairs=n, packed=True,
                )
            return arr[flat_ix][:n]

        return complete
