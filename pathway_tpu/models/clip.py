"""ClipModel — dual text/image encoder for multimodal retrieval
(BASELINE.json config 3: multimodal CLIP streaming index; the reference uses
API/torch CLIP via its embedder UDFs).  Patchified image transformer + text
transformer projected into one space; both batched jit forwards."""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from .. import observe
from ..robust import retry_call
from ._params import unbox as _unbox

from .tokenizer import HashTokenizer
from .transformer import TransformerConfig, TransformerEncoder, resolve_heads

__all__ = ["ClipModel"]

# flight recorder: submit→ready latency (dispatch through host fetch)
# per modality + batch occupancy per dispatch
_H_TEXT = observe.histogram("pathway_serve_model_seconds", model="clip_text")
_H_IMAGE = observe.histogram("pathway_serve_model_seconds", model="clip_image")


class _ImageEncoder(nn.Module):
    config: TransformerConfig
    patch: int = 16
    image_size: int = 64

    @nn.compact
    def __call__(self, images):  # [B, H, W, C] float32 in [0,1]
        cfg = self.config
        B = images.shape[0]
        x = nn.Conv(
            cfg.d_model,
            kernel_size=(self.patch, self.patch),
            strides=(self.patch, self.patch),
            dtype=cfg.dtype,
            name="patchify",
        )(images.astype(cfg.dtype))
        x = x.reshape(B, -1, cfg.d_model)
        L = x.shape[1]
        pos = nn.Embed(L, cfg.d_model, dtype=cfg.dtype, name="pos")(
            jnp.arange(L)[None, :]
        )
        x = x + pos
        mask = jnp.ones((B, L), jnp.int32)
        from .transformer import EncoderBlock

        for i in range(cfg.n_layers):
            x = EncoderBlock(cfg, name=f"block_{i}")(x, mask)
        x = nn.LayerNorm(dtype=cfg.dtype)(x)
        return jnp.mean(x, axis=1).astype(jnp.float32)


class _ClipModule(nn.Module):
    config: TransformerConfig
    image_size: int
    patch: int
    proj_dim: int

    @nn.compact
    def __call__(self, ids, mask, images):
        text = TransformerEncoder(self.config, name="text")(ids, mask)
        image = _ImageEncoder(
            self.config, patch=self.patch, image_size=self.image_size, name="image"
        )(images)
        tproj = nn.Dense(self.proj_dim, name="text_proj")(text)
        iproj = nn.Dense(self.proj_dim, name="image_proj")(image)
        return tproj, iproj


class ClipModel:
    def __init__(
        self,
        model: str = "pathway-mini-clip",
        dimension: int = 256,
        proj_dim: int = 256,
        n_layers: int = 4,
        n_heads: int = 4,
        image_size: int = 64,
        patch: int = 16,
        max_length: int = 64,
        vocab_size: int = 32768,
        seed: int = 3,
        dtype=jnp.bfloat16,
    ):
        self.config = TransformerConfig(
            vocab_size=vocab_size,
            d_model=dimension,
            n_heads=resolve_heads(dimension, n_heads),
            n_layers=n_layers,
            d_ff=dimension * 4,
            max_len=max_length,
            dtype=dtype,
            pool="mean",
        )
        self.image_size = image_size
        self.proj_dim = proj_dim
        self.tokenizer = HashTokenizer(vocab_size=vocab_size, max_length=max_length)
        self.module = _ClipModule(self.config, image_size, patch, proj_dim)
        self._lock = threading.Lock()
        self._text_fns: Dict[tuple, Any] = {}
        self._image_fns: Dict[tuple, Any] = {}
        # recompile tripwire (ops/recompile_guard.py): text batches bucket
        # via _bucket and image batches have one shape, so the compile
        # census is small; a leak warns (fails under tests)
        from ..ops.recompile_guard import RecompileTripwire

        self._tripwire = RecompileTripwire(f"ClipModel[{model}]")
        ids = jnp.zeros((1, 16), jnp.int32)
        mask = jnp.ones((1, 16), jnp.int32)
        imgs = jnp.zeros((1, image_size, image_size, 3), jnp.float32)
        self.params = self.module.init(jax.random.PRNGKey(seed), ids, mask, imgs)[
            "params"
        ]
        self.params = _unbox(self.params)

    def get_embedding_dimension(self) -> int:
        return self.proj_dim

    def encode_text(self, texts: Sequence[str]) -> np.ndarray:
        with self._lock:
            n = len(texts)
            if n == 0:
                return np.zeros((0, self.proj_dim), np.float32)
            from .encoder import _bucket

            b = _bucket(n)
            padded = [str(t) for t in texts] + [""] * (b - n)
            ids, mask = self.tokenizer.encode_batch(padded)
            key = ids.shape
            fn = self._text_fns.get(key)
            if fn is None:
                self._tripwire.observe(("text",) + tuple(key))
                module = self.module
                image_size = self.image_size

                @jax.jit
                def fn(params, ids, mask):
                    dummy = jnp.zeros((ids.shape[0], image_size, image_size, 3), jnp.float32)
                    t, _ = module.apply({"params": params}, ids, mask, dummy)
                    return t / jnp.maximum(jnp.linalg.norm(t, axis=-1, keepdims=True), 1e-9)

                self._text_fns[key] = fn
        # dispatch + fetch OFF the lock (the round-5 lock-discipline class:
        # holding it across the device round trip serialized every
        # concurrent encode for the full latency); the lock only guards
        # tokenization and the compiled-fn cache
        t0 = time.perf_counter_ns()
        observe.record_occupancy("clip_text", n, b)
        out = retry_call(
            "clip.dispatch", fn, self.params, jnp.asarray(ids), jnp.asarray(mask)
        )
        host = np.asarray(out)[:n]  # pathway: allow(value-flow): encode_text's contract is synchronous host rows (the serve path goes through submit/complete, which books its fetch)
        _H_TEXT.observe_ns(time.perf_counter_ns() - t0)
        return host

    def encode_image(self, images: Sequence[np.ndarray]) -> np.ndarray:
        with self._lock:
            n = len(images)
            if n == 0:
                return np.zeros((0, self.proj_dim), np.float32)
            from .encoder import _bucket

            b = _bucket(n)
            S = self.image_size
            batch = np.zeros((b, S, S, 3), np.float32)
            for i, img in enumerate(images):
                img = np.asarray(img, dtype=np.float32)
                if img.ndim == 2:
                    img = np.stack([img] * 3, axis=-1)
                h, w = img.shape[:2]
                hh, ww = min(h, S), min(w, S)
                batch[i, :hh, :ww, :] = img[:hh, :ww, :3]
            key = (b,)
            fn = self._image_fns.get(key)
            if fn is None:
                self._tripwire.observe(("image",) + key)
                module = self.module

                @jax.jit
                def fn(params, imgs):
                    ids = jnp.zeros((imgs.shape[0], 16), jnp.int32)
                    mask = jnp.ones((imgs.shape[0], 16), jnp.int32)
                    _, im = module.apply({"params": params}, ids, mask, imgs)
                    return im / jnp.maximum(
                        jnp.linalg.norm(im, axis=-1, keepdims=True), 1e-9
                    )

                self._image_fns[key] = fn
        # dispatch + fetch off-lock, same as encode_text (and the same
        # "clip.dispatch" retry/fault site)
        t0 = time.perf_counter_ns()
        observe.record_occupancy("clip_image", n, b)
        out = retry_call("clip.dispatch", fn, self.params, jnp.asarray(batch))
        host = np.asarray(out)[:n]  # pathway: allow(value-flow): encode_image's contract is synchronous host rows, same as encode_text
        _H_IMAGE.observe_ns(time.perf_counter_ns() - t0)
        return host
