"""Deterministic hashing tokenizer.

Offline-friendly replacement for downloaded vocabularies (the reference
relies on HF/tiktoken tokenizers, xpacks/llm/splitters.py:13): words and
char-trigram fallbacks hash into a fixed id space with xxh3.  Embeddings
trained in-framework are consistent because the mapping is deterministic.
"""

from __future__ import annotations

import re
from typing import List, Sequence, Tuple

import numpy as np
import xxhash

__all__ = ["HashTokenizer"]

_WORD_RE = re.compile(r"[\w']+|[^\w\s]")


class HashTokenizer:
    PAD = 0
    CLS = 1
    SEP = 2
    UNK = 3
    _RESERVED = 8

    def __init__(self, vocab_size: int = 32768, max_length: int = 128):
        self.vocab_size = vocab_size
        self.max_length = max_length

    def _word_id(self, word: str) -> int:
        h = xxhash.xxh3_64_intdigest(word.lower().encode())
        return self._RESERVED + (h % (self.vocab_size - self._RESERVED))

    def tokenize(self, text: str) -> List[int]:
        return [self._word_id(w) for w in _WORD_RE.findall(str(text))]

    def count_tokens(self, text: str) -> int:
        return len(_WORD_RE.findall(str(text)))

    def encode(
        self, text: str, pair: str | None = None, max_length: int | None = None
    ) -> List[int]:
        max_length = max_length or self.max_length
        if pair is None:
            ids = [self.CLS] + self.tokenize(text)
            return ids[: max_length - 1] + [self.SEP]
        # sentence pairs truncate longest-first (HF semantics): both segments
        # keep tokens, so an over-long query can't silently evict the whole
        # document and collapse every pair to the same score
        a = self.tokenize(text)
        b = self.tokenize(pair)
        budget = max(max_length - 3, 2)
        while len(a) + len(b) > budget:
            if len(a) >= len(b) and len(a) > 1:
                a.pop()
            elif len(b) > 1:
                b.pop()
            else:
                break
        return [self.CLS] + a + [self.SEP] + b + [self.SEP]

    def encode_batch(
        self,
        texts: Sequence[str],
        pairs: Sequence[str] | None = None,
        max_length: int | None = None,
        pad_to: int | None = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Returns (ids [B, L], mask [B, L]) padded to a shared length."""
        max_length = max_length or self.max_length
        encoded = [
            self.encode(t, pairs[i] if pairs is not None else None, max_length)
            for i, t in enumerate(texts)
        ]
        longest = max((len(e) for e in encoded), default=1)
        # pad length to a multiple of 16 to bound jit shape variants
        L = pad_to or min(max_length, ((longest + 15) // 16) * 16)
        ids = np.full((len(encoded), L), self.PAD, dtype=np.int32)
        mask = np.zeros((len(encoded), L), dtype=np.int32)
        for i, e in enumerate(encoded):
            e = e[:L]
            ids[i, : len(e)] = e
            mask[i, : len(e)] = 1
        return ids, mask
