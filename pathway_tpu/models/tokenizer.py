"""Deterministic hashing tokenizer.

Offline-friendly replacement for downloaded vocabularies (the reference
relies on HF/tiktoken tokenizers, xpacks/llm/splitters.py:13): words and
char-trigram fallbacks hash into a fixed id space with xxh3.  Embeddings
trained in-framework are consistent because the mapping is deterministic.
"""

from __future__ import annotations

import re
from typing import List, Sequence, Tuple

import numpy as np
import xxhash

__all__ = ["HashTokenizer"]

_WORD_RE = re.compile(r"[\w']+|[^\w\s]")


class HashTokenizer:
    PAD = 0
    CLS = 1
    SEP = 2
    UNK = 3
    _RESERVED = 8

    def __init__(self, vocab_size: int = 32768, max_length: int = 128):
        self.vocab_size = vocab_size
        self.max_length = max_length

    def _word_id(self, word: str) -> int:
        h = xxhash.xxh3_64_intdigest(word.lower().encode())
        return self._RESERVED + (h % (self.vocab_size - self._RESERVED))

    def tokenize(self, text: str) -> List[int]:
        return [self._word_id(w) for w in _WORD_RE.findall(str(text))]

    def count_tokens(self, text: str) -> int:
        return len(_WORD_RE.findall(str(text)))

    def encode(
        self, text: str, pair: str | None = None, max_length: int | None = None
    ) -> List[int]:
        max_length = max_length or self.max_length
        if pair is None:
            ids = [self.CLS] + self.tokenize(text)
            return ids[: max_length - 1] + [self.SEP]
        # sentence pairs truncate longest-first (HF semantics): both segments
        # keep tokens, so an over-long query can't silently evict the whole
        # document and collapse every pair to the same score
        a = self.tokenize(text)
        b = self.tokenize(pair)
        budget = max(max_length - 3, 2)
        while len(a) + len(b) > budget:
            if len(a) >= len(b) and len(a) > 1:
                a.pop()
            elif len(b) > 1:
                b.pop()
            else:
                break
        return [self.CLS] + a + [self.SEP] + b + [self.SEP]

    def encode_batch(
        self,
        texts: Sequence[str],
        pairs: Sequence[str] | None = None,
        max_length: int | None = None,
        pad_to: int | None = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Returns (ids [B, L], mask [B, L]) padded to a shared length."""
        max_length = max_length or self.max_length
        if pairs is None:
            fast = self._encode_batch_native(texts, max_length, pad_to)
            if fast is not None:
                return fast
        encoded = [
            self.encode(t, pairs[i] if pairs is not None else None, max_length)
            for i, t in enumerate(texts)
        ]
        longest = max((len(e) for e in encoded), default=1)
        # pad length to a multiple of 16 to bound jit shape variants
        L = pad_to or min(max_length, ((longest + 15) // 16) * 16)
        ids = np.full((len(encoded), L), self.PAD, dtype=np.int32)
        mask = np.zeros((len(encoded), L), dtype=np.int32)
        for i, e in enumerate(encoded):
            e = e[:L]
            ids[i, : len(e)] = e
            mask[i, : len(e)] = 1
        return ids, mask

    def _encode_batch_native(
        self, texts: Sequence[str], max_length: int, pad_to: int | None
    ) -> Tuple[np.ndarray, np.ndarray] | None:
        """Whole-batch tokenization through the C++ scanner
        (native/src/tokenizer.cc — bit-identical ids for ASCII input), with
        vectorised CLS/SEP framing and padding.  The per-word Python loop
        was the ingest bottleneck: the TPU encoder consumes docs >10x
        faster than the host could tokenize them.  Returns None (caller
        keeps the Python path) for non-ASCII batches or without the native
        library."""
        n = len(texts)
        if n == 0:
            return None
        texts_s = [t if isinstance(t, str) else str(t) for t in texts]
        joined = "".join(texts_s)
        if not joined.isascii():
            return None
        from .. import native as _native

        lens = np.fromiter(map(len, texts_s), dtype=np.int64, count=n)
        offsets = np.empty(n + 1, dtype=np.int64)
        offsets[0] = 0
        np.cumsum(lens, out=offsets[1:])
        out = _native.tokenize_hash(
            joined.encode(), offsets, self.vocab_size, self._RESERVED
        )
        if out is None:
            return None
        tok_ids, tok_off = out
        counts = np.diff(tok_off)
        trunc = np.minimum(counts, max_length - 2)
        longest = int(trunc.max()) + 2 if n else 1
        L = pad_to or min(max_length, ((longest + 15) // 16) * 16)
        trunc = np.minimum(trunc, L - 2)
        ids = np.full((n, L), self.PAD, dtype=np.int32)
        total = int(trunc.sum())
        if total:
            starts = np.cumsum(trunc) - trunc
            pos = np.arange(total, dtype=np.int64) - np.repeat(starts, trunc)
            src = np.repeat(tok_off[:-1], trunc) + pos
            ids[np.repeat(np.arange(n), trunc), pos + 1] = tok_ids[src]
        ids[:, 0] = self.CLS
        ids[np.arange(n), trunc + 1] = self.SEP
        mask = (
            np.arange(L, dtype=np.int64)[None, :] < (trunc + 2)[:, None]
        ).astype(np.int32)
        return ids, mask
