"""TextGenerator — local causal LM for chat-style generation.

TPU-native analog of the reference's HFPipelineChat local generator
(xpacks/llm/llms.py:441).  Decoding is a real **KV-cache decode**: one
jitted function runs the prompt prefill (suffix only, when the prefix
cache below has the leading blocks) and then ``lax.scan``s single-token
steps against persistent per-layer K/V buffers — O(steps × L) attention
instead of the old full re-attend's O(steps × L²), still with no
per-token python round trips (ONE dispatch per generate call, as
before).

**Prefix/KV reuse** (pathway_tpu/cache/prefix.py): prompt token ids are
content-addressed in fixed blocks under a hash chain, and the K/V of
every full block is captured device-resident after the decode.  RAG
prompts sharing a system-prompt + retrieved-chunk prefix prefill only
their tails — prefill cost across a shared-prefix prompt set is
sub-linear, measured by the ``serve_cache`` bench phase via the
``pathway_cache_prefill_tokens_total{kind=reused|computed}`` counters.

Bit-reproducibility: the KV twin (models/transformer.py
``KVTransformerDecoder``) keeps the attention math line-for-line with
the trunk, the K/V buffer width is constant across prefix splits, and
masked slots carry exactly-zero probability — so warm (cached-prefix)
decodes emit the SAME tokens as cold ones, and the KV path matches the
legacy full re-attend decode token-for-token (tests/test_serve_cache.py
parity tests).  ``PATHWAY_GENERATOR_KV=0`` falls back to the legacy
decode.

With random-init weights the output is noise; with a trained checkpoint
it generates — either way the serving path, batching, caching and
compile behavior are the product."""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import config, observe
from ..observe import hbm, profile
from ..robust import retry_call
from ._params import unbox as _unbox

from .tokenizer import HashTokenizer
from .transformer import (
    KVTransformerDecoder,
    SlotKVDecoder,
    TransformerConfig,
    TransformerEncoder,
    resolve_heads,
)

__all__ = [
    "TextGenerator",
    "decode_draft_layers",
    "decode_draft_source",
    "decode_kv_quant",
    "decode_spec_k",
    "decode_step_bucket",
    "eos_id_from_env",
]

# flight recorder: submit→ready latency of a full decode (dispatch
# through host fetch) + batch occupancy per dispatch
_H_READY = observe.histogram("pathway_serve_model_seconds", model="generator")

# sentinel: "use the instance default" for per-call eos_id overrides
_UNSET = object()


def decode_step_bucket() -> int:
    """Decode-step chunk size from ``decode.step_bucket`` (default 8,
    tuner-adjustable): how many single-token decode steps one compiled
    chunk dispatch advances.  Shared by the legacy EOS-chunked decode and
    the continuous engine (serve/decode.py) — ONE knob, one compile shape."""
    return config.get("decode.step_bucket")


def decode_spec_k() -> int:
    """Speculation depth from ``PATHWAY_DECODE_SPEC_K`` (default 0 =
    speculation OFF): how many positions one verify dispatch scores per
    active slot — 1 committed token + up to ``k-1`` accepted draft
    tokens per round.  ``k <= 1`` is the plain one-token-per-step
    continuous decode; the ceiling keeps the verify forward (an
    ``Ln = k`` attention) from dwarfing the steps it replaces."""
    return config.get("decode.spec_k")


def decode_kv_quant() -> str:
    """Slot-pool K/V storage from ``PATHWAY_DECODE_KV_QUANT``: ``bf16``
    (default, bit-identical to solo decode) or ``int8`` (per-(layer,
    head, channel) stored scales, 2x slots×context at fixed HBM,
    bounded token drift — ops/kv_quant.py)."""
    return config.get("decode.kv_quant")


def decode_draft_source() -> str:
    """Draft proposal source from ``PATHWAY_DECODE_DRAFT``: ``auto``
    (default: n-gram mining first, reduced-layer trunk when the n-gram
    well runs dry), ``ngram`` (mining only — lanes without a match
    advance one token per round), or ``trunk`` (always the reduced-
    layer draft dispatch)."""
    return config.get("decode.draft")


def decode_draft_layers(n_layers: int) -> int:
    """Reduced-layer draft-trunk depth from
    ``PATHWAY_DECODE_DRAFT_LAYERS`` (default 0 = half the trunk,
    minimum 1): the draft forwards only the FIRST ``D`` blocks of the
    same params — cheap proposals, exactness restored by the verify."""
    d = config.get("decode.draft_layers")
    if d <= 0:
        d = max(1, n_layers // 2)
    return min(d, n_layers)


def eos_id_from_env() -> Optional[int]:
    """``PATHWAY_GENERATOR_EOS`` (a token id, e.g. 2 for the tokenizer's
    SEP) — unset/empty means no EOS handling, byte-for-byte the
    pre-EOS decode behavior."""
    raw = config.get("generator.eos").strip()
    if not raw or raw in ("0", "none", "off"):
        return None
    try:
        return int(raw)
    except ValueError:
        return None


class TextGenerator:
    def __init__(
        self,
        model: str = "pathway-mini-lm",
        dimension: int = 256,
        n_layers: int = 4,
        n_heads: int = 4,
        max_length: int = 256,
        vocab_size: int = 32768,
        seed: int = 2,
        checkpoint_path: Optional[str] = None,
        dtype=jnp.bfloat16,
        kv_cache: Any = "env",
        eos_id: Any = "env",
    ):
        self.config = TransformerConfig(
            vocab_size=vocab_size,
            d_model=dimension,
            n_heads=resolve_heads(dimension, n_heads),
            n_layers=n_layers,
            d_ff=dimension * 4,
            max_len=max_length,
            dtype=dtype,
            pool="none",
            causal=True,
        )
        self.tokenizer = HashTokenizer(vocab_size=vocab_size, max_length=max_length)
        self.module = TransformerEncoder(self.config)
        self._kv_module = KVTransformerDecoder(self.config)
        self._slot_module = SlotKVDecoder(self.config)
        # int8 twins (same params; ops/kv_quant.py scales as operands)
        self._kv_module_q = KVTransformerDecoder(self.config, quant=True)
        self._slot_module_q = SlotKVDecoder(self.config, quant=True)
        self._kv_scales = None  # lazy (params exist below)
        # EOS handling: a row that emits this token is FINISHED — further
        # sampling work is masked to PAD and the legacy decode returns as
        # soon as every row has finished (chunked dispatch).  None (the
        # env default when PATHWAY_GENERATOR_EOS is unset) preserves the
        # single-dispatch always-`steps` decode exactly.
        if eos_id == "env":
            eos_id = eos_id_from_env()
        if eos_id is not None and int(eos_id) == self.tokenizer.PAD:
            raise ValueError("eos_id must differ from the PAD token id")
        self.eos_id = None if eos_id is None else int(eos_id)
        # decode steps actually executed by the last generate() call —
        # the EOS early-exit regression hook (a batch of short answers
        # must not pay the full `steps` budget)
        self.last_decode_steps = 0
        self._lock = threading.Lock()
        self._fns: Dict[tuple, Any] = {}
        # recompile tripwire (ops/recompile_guard.py): decode shapes are
        # (batch bucket, padded length, prefix bucket, steps); a leak
        # fails under tests
        from ..ops.recompile_guard import RecompileTripwire

        self._tripwire = RecompileTripwire(f"TextGenerator[{model}]")
        ids = jnp.zeros((1, 16), jnp.int32)
        mask = jnp.ones((1, 16), jnp.int32)
        self.params = self.module.init(jax.random.PRNGKey(seed), ids, mask)["params"]
        self.params = _unbox(self.params)
        # weight-tied readout: logits = h @ tok_embed.T
        self._vocab_table = None
        # tier-2 prefix/KV cache (pathway_tpu/cache): per-generator —
        # K/V blocks are only meaningful against this instance's params
        if kv_cache == "env":
            from ..cache import prefix_kv_cache_from_env

            kv_cache = prefix_kv_cache_from_env()
        self.kv_cache = kv_cache
        self._use_kv = config.get("generator.kv")
        # HBM ledger (observe/hbm.py): parameter tree bytes
        hbm.track_params("generator", self)

    # -- legacy full re-attend decode (parity reference / fallback) ----------
    def _decode_fn(self, B: int, L: int, steps: int):
        """Compiled decode CHUNK of ``steps`` single-token iterations:
        ``(params, ids, mask, pos, temperature, rng, finished, eos) ->
        (tokens [B, steps], ids, mask, pos, rng, finished)``.  The carry
        is explicit so ``generate`` can thread it across chunk dispatches
        and return as soon as every row has finished; with EOS disabled
        (``eos = -1``) one chunk of the full budget reproduces the
        original single-dispatch decode token-for-token.  Per-row
        ``finished`` masks every write/advance (the row is bit-frozen)
        and an all-finished batch skips the forward pass entirely via
        ``lax.cond`` — post-EOS sampling work is zeroed, not just
        discarded."""
        key = (B, L, steps)
        fn = self._fns.get(key)
        if fn is None:
            self._tripwire.observe(key)
            module = self.module
            PAD = self.tokenizer.PAD

            def decode(params, ids, mask, pos, temperature, rng, finished, eos):
                emb = params["tok_embed"]["embedding"]

                def live(carry):
                    ids_c, mask_c, pos, rng_c, fin = carry
                    hidden = module.apply({"params": params}, ids_c, mask_c)
                    logits = jnp.einsum(
                        "bld,vd->blv", hidden.astype(jnp.float32), emb.astype(jnp.float32)
                    )
                    # logits at last real position of each row
                    last = jnp.take_along_axis(
                        logits, (pos - 1)[:, None, None], axis=1
                    )[:, 0, :]
                    rng_c, sub = jax.random.split(rng_c)
                    greedy = jnp.argmax(last, axis=-1)
                    sampled = jax.random.categorical(sub, last / jnp.maximum(temperature, 1e-4))
                    nxt = jnp.where(temperature <= 0.0, greedy, sampled).astype(jnp.int32)
                    nxt = jnp.where(fin, PAD, nxt)
                    ids_c = jnp.take_along_axis(
                        ids_c, jnp.arange(ids_c.shape[1])[None, :], axis=1
                    )
                    ids_w = jax.vmap(lambda row, p, t: row.at[p].set(t))(
                        ids_c, pos, nxt
                    )
                    mask_w = jax.vmap(lambda row, p: row.at[p].set(1))(mask_c, pos)
                    # finished rows are frozen: no ids/mask write, no
                    # position advance — their history stays exactly the
                    # prefix that ended in EOS.  The row emitting EOS
                    # THIS step still writes and advances (the original
                    # unconditional behavior), then freezes.
                    keep = fin[:, None]
                    ids_c = jnp.where(keep, ids_c, ids_w)
                    mask_c = jnp.where(keep, mask_c, mask_w)
                    pos = jnp.where(fin, pos, pos + 1)
                    fin = fin | (nxt == eos)
                    return (ids_c, mask_c, pos, rng_c, fin), nxt

                def dead(carry):
                    return carry, jnp.full((B,), PAD, jnp.int32)

                def step(carry, _):
                    return jax.lax.cond(jnp.all(carry[4]), dead, live, carry)

                (ids_f, mask_f, pos_f, rng_f, fin_f), toks = jax.lax.scan(
                    step, (ids, mask, pos, rng, finished), None, length=steps
                )
                return toks.T, ids_f, mask_f, pos_f, rng_f, fin_f

            # device-time attribution (observe/profile.py)
            fn = profile.wrap("generator.decode", jax.jit(decode))
            self._fns[key] = fn
        return fn

    # -- KV-cache decode -----------------------------------------------------
    def _kv_fn(self, B: int, L_sfx: int, P: int, steps: int):
        """Compiled prefill+decode: ``(params, suffix_ids, n_lens,
        prefix_k, prefix_v, temperature, rng) -> (tokens [B, steps],
        k_buf, v_buf)``.  ``P`` is the static cached-prefix split (the
        batch-min match, bucketed to power-of-two block multiples by
        ``_cached_prefix``) — the K/V buffer width is ``P + L_sfx +
        steps`` == the legacy decode's constant attention width, which
        is what makes warm and cold decodes bit-identical.
        The returned buffers stay device-resident; the capture pass
        slices prompt blocks out of them for the prefix cache."""
        key = ("kv", B, L_sfx, P, steps)
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        self._tripwire.observe(key)
        cfg = self.config
        decoder = self._kv_module
        PAD = self.tokenizer.PAD
        H = cfg.n_heads
        hd = cfg.d_model // H
        T = P + L_sfx + steps

        def run(
            params, suffix_ids, n_lens, prefix_k, prefix_v, temperature,
            rng, eos, fin0,
        ):
            emb = params["tok_embed"]["embedding"]
            kbuf = jnp.zeros((B, cfg.n_layers, T, H, hd), cfg.dtype)
            vbuf = jnp.zeros((B, cfg.n_layers, T, H, hd), cfg.dtype)
            if P:
                kbuf = jax.lax.dynamic_update_slice(
                    kbuf, prefix_k.astype(cfg.dtype), (0, 0, 0, 0, 0)
                )
                vbuf = jax.lax.dynamic_update_slice(
                    vbuf, prefix_v.astype(cfg.dtype), (0, 0, 0, 0, 0)
                )
            # prefill: the suffix tokens sit at global positions
            # [P, P + L_sfx); every row shares the static split point
            positions = jnp.broadcast_to(
                (P + jnp.arange(L_sfx, dtype=jnp.int32))[None, :], (B, L_sfx)
            )
            write_pos = jnp.full((B,), P, jnp.int32)
            hidden, kbuf, vbuf = decoder.apply(
                {"params": params}, suffix_ids, positions, kbuf, vbuf,
                write_pos, positions,
            )
            logits = jnp.einsum(
                "bld,vd->blv", hidden.astype(jnp.float32), emb.astype(jnp.float32)
            )
            # first decode logits: the last REAL prompt position, in
            # suffix-local coordinates (the prefix cache always leaves
            # >= 1 real suffix token, so n - 1 - P >= 0 on real rows)
            last0 = jnp.take_along_axis(
                logits,
                jnp.maximum(n_lens - 1 - P, 0)[:, None, None],
                axis=1,
            )[:, 0, :]

            def step(carry, _):
                kbuf_c, vbuf_c, last, pos, rng_c, fin = carry
                greedy = jnp.argmax(last, axis=-1)

                def sample(rng_c):
                    rng2, sub = jax.random.split(rng_c)
                    return rng2, jax.random.categorical(
                        sub, last / jnp.maximum(temperature, 1e-4)
                    )

                def greedy_only(rng_c):
                    # temperature 0: the B×V gumbel draw would be
                    # discarded by the where below — skip it
                    return rng_c, greedy

                rng_c, sampled = jax.lax.cond(
                    temperature <= 0.0, greedy_only, sample, rng_c
                )
                nxt = jnp.where(temperature <= 0.0, greedy, sampled).astype(
                    jnp.int32
                )
                # per-row finished mask: a row that emitted EOS samples
                # PAD from here on; once EVERY row is done the forward
                # pass is skipped outright (lax.cond) — further work is
                # zeroed inside the single decode dispatch
                nxt = jnp.where(fin, PAD, nxt)
                fin_next = fin | (nxt == eos)

                def fwd(args):
                    kbuf_c, vbuf_c, nxt, pos = args
                    h1, kbuf_n, vbuf_n = decoder.apply(
                        {"params": params}, nxt[:, None], pos[:, None],
                        kbuf_c, vbuf_c, pos, pos[:, None],
                    )
                    return kbuf_n, vbuf_n, jnp.einsum(
                        "bld,vd->blv",
                        h1.astype(jnp.float32),
                        emb.astype(jnp.float32),
                    )[:, 0, :]

                def skip(args):
                    kbuf_c, vbuf_c, _nxt, _pos = args
                    return kbuf_c, vbuf_c, last

                kbuf_c, vbuf_c, logits1 = jax.lax.cond(
                    jnp.all(fin_next), skip, fwd, (kbuf_c, vbuf_c, nxt, pos)
                )
                pos = jnp.where(fin, pos, pos + 1)
                return (kbuf_c, vbuf_c, logits1, pos, rng_c, fin_next), nxt

            (kbuf, vbuf, _, _, _, _), toks = jax.lax.scan(
                step, (kbuf, vbuf, last0, n_lens, rng, fin0), None, length=steps
            )
            return toks.T, kbuf, vbuf  # toks [B, steps]

        fn = profile.wrap("generator.kv_decode", jax.jit(run))
        self._fns[key] = fn
        return fn

    def _cached_prefix(self, ids: np.ndarray, n_lens: np.ndarray, n: int):
        """Cache wrapper for the prefix tier: per-row longest cached
        block chain, batched at the row MINIMUM (the static split point
        every row shares — the RAG shape is many prompts over one
        system+chunks prefix, where the minimum IS the shared prefix),
        then rounded DOWN to a power-of-two block multiple
        (``PrefixKVCache.bucket_tokens``) so the split point (a
        compile-shape dimension) takes O(log) values instead of one per
        distinct prefix length — a mix of prompt families must not
        compile one decode program each.  Returns ``(P, matches)``;
        pure host + cache work, no dispatch."""
        matches = [
            self.kv_cache.match(ids[i], int(n_lens[i])) for i in range(n)
        ]
        P = min((m[0] for m in matches), default=0)
        return self.kv_cache.bucket_tokens(P), matches

    # -- continuous-decode slot pool (serve/decode.py) -----------------------
    def kv_pool_scales(self):
        """Per-(layer, head, channel) int8 K/V scales ``[L, H, hd]``
        for THIS generator's params (ops/kv_quant.py) — computed once,
        shared by every quantized pool over the instance."""
        if self._kv_scales is None:
            from ..ops.kv_quant import kv_pool_scales

            # compute OFF the lock (device math must never run under
            # it); the assignment races benignly — both winners hold
            # identical values derived from the same params
            scales = kv_pool_scales(self.params, self.config)
            with self._lock:
                if self._kv_scales is None:
                    self._kv_scales = scales
        return self._kv_scales

    def _slot_prefill_fn(
        self, S: int, T: int, B: int, L_sfx: int, P: int, quant: bool = False
    ):
        """Compiled JOIN batch for ``B`` slots of a ``[S, L, H, T, d]``
        K/V pool: ``(params, pool_k, pool_v, slots [B], suffix_ids
        [B, L_sfx], n_len [B], prefix_k, prefix_v, rngs [B, 2],
        temps [B]) -> (pool_k, pool_v, first_tokens [B], rngs')``.
        Prefills each row's prompt suffix (cached prefix blocks land at
        positions [0, P)) into fresh width-``T`` buffers, samples each
        row's FIRST generated token from its last real prompt position —
        per-row rng chains, consuming each request's first split, the
        same chain position the solo decode uses — and scatters every
        row into the pool at its slot, wiping the previous occupants.
        Joins arriving together batch into ONE dispatch (``B`` bucketed
        to powers of two; pad rows scatter to an out-of-bounds slot
        index and are dropped).  ``T`` is the POOL width:
        masked attention is width-invariant (extra key slots carry
        exact-zero probability), which is what keeps a pooled decode
        bit-identical to a solo one whose buffer is exactly
        prompt+steps wide.

        ``quant=True`` (int8 pool): the fn takes two trailing operands
        ``k_scales``/``v_scales`` ``[L, H, hd]``, prefills through the
        quant KV twin — every attention read is dequant(int8), the SAME
        values a later warm join will read back, which is what keeps
        warm and cold int8 joins deterministic — and scatters int8
        rows; the bf16 prefix rows passed in are (re)quantized on
        insert (idempotent: ops/kv_quant.py)."""
        key = ("slot_prefill_q" if quant else "slot_prefill", S, T, B, L_sfx, P)
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        self._tripwire.observe(key)
        cfg = self.config
        decoder = self._kv_module_q if quant else self._kv_module
        H = cfg.n_heads
        hd = cfg.d_model // H
        buf_dtype = jnp.int8 if quant else cfg.dtype

        def prefill(
            params, pool_k, pool_v, slots, suffix_ids, n_len,
            prefix_k, prefix_v, rngs, temps, k_scales=None, v_scales=None,
        ):
            from ..ops.kv_quant import quantize_kv

            emb = params["tok_embed"]["embedding"]
            kbuf = jnp.zeros((B, cfg.n_layers, T, H, hd), buf_dtype)
            vbuf = jnp.zeros((B, cfg.n_layers, T, H, hd), buf_dtype)
            if P:
                pfx_k = (
                    quantize_kv(prefix_k, k_scales)
                    if quant else prefix_k.astype(cfg.dtype)
                )
                pfx_v = (
                    quantize_kv(prefix_v, v_scales)
                    if quant else prefix_v.astype(cfg.dtype)
                )
                kbuf = jax.lax.dynamic_update_slice(
                    kbuf, pfx_k, (0, 0, 0, 0, 0)
                )
                vbuf = jax.lax.dynamic_update_slice(
                    vbuf, pfx_v, (0, 0, 0, 0, 0)
                )
            positions = jnp.broadcast_to(
                (P + jnp.arange(L_sfx, dtype=jnp.int32))[None, :], (B, L_sfx)
            )
            write_pos = jnp.full((B,), P, jnp.int32)
            hidden, kbuf, vbuf = decoder.apply(
                {"params": params}, suffix_ids, positions, kbuf, vbuf,
                write_pos, positions, k_scales, v_scales,
            )
            logits = jnp.einsum(
                "bld,vd->blv", hidden.astype(jnp.float32), emb.astype(jnp.float32)
            )
            last0 = jnp.take_along_axis(
                logits,
                jnp.maximum(n_len - 1 - P, 0)[:, None, None],
                axis=1,
            )[:, 0, :]
            greedy = jnp.argmax(last0, axis=-1)

            def sample(rngs):
                pairs = jax.vmap(jax.random.split)(rngs)
                drawn = jax.vmap(jax.random.categorical)(
                    pairs[:, 1], last0 / jnp.maximum(temps, 1e-4)[:, None]
                )
                return pairs[:, 0], jnp.where(temps <= 0.0, greedy, drawn)

            def greedy_only(rngs):
                return rngs, greedy

            rngs, toks = jax.lax.cond(
                jnp.all(temps <= 0.0), greedy_only, sample, rngs
            )
            # ONE scatter per buffer: row i lands at pool slot
            # ``slots[i]``; pad rows carry an out-of-bounds index and
            # are DROPPED by the scatter (jax's default out-of-bounds
            # scatter semantics), so padding can never clobber a slot
            pool_k = pool_k.at[slots].set(kbuf)
            pool_v = pool_v.at[slots].set(vbuf)
            return pool_k, pool_v, toks.astype(jnp.int32), rngs

        fn = profile.wrap("generator.slot_prefill", jax.jit(prefill))
        self._fns[key] = fn
        return fn

    def _slot_step_fn(self, S: int, T: int, chunk: int, quant: bool = False):
        """Compiled decode-step CHUNK over the whole slot pool:
        ``(params, pool_k, pool_v, tok [S], pos [S], active [S],
        left [S], rngs [S, 2], temps [S], eos [S]) -> (pool_k, pool_v,
        rngs, emitted [chunk, S])``.  Each of the ``chunk`` scan
        iterations forwards every slot's current token one position
        (``SlotKVDecoder`` — inactive slots' K/V bit-frozen), samples
        the next token PER SLOT with that slot's own rng chain (the solo
        chain: requests are batch-composition-independent), emits ``-1``
        for inactive lanes, and retires lanes that emit their EOS or
        exhaust their budget.  ONE compile signature per engine — the
        shapes are (S, T, chunk), all static per pool.

        ``quant=True``: int8 pool, trailing ``k_scales``/``v_scales``
        operands, reads dequantized in-kernel (ops/kv_quant.py)."""
        key = ("slot_step_q" if quant else "slot_step", S, T, chunk)
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        self._tripwire.observe(key)
        decoder = self._slot_module_q if quant else self._slot_module

        def run(
            params, pool_k, pool_v, tok, pos, active, left, rngs, temps, eos,
            k_scales=None, v_scales=None,
        ):
            emb = params["tok_embed"]["embedding"]

            def one(carry, _):
                pool_k, pool_v, tok, pos, act, left, rngs = carry
                live = act & (left > 0)
                h, pool_k, pool_v = decoder.apply(
                    {"params": params}, tok[:, None], pos[:, None],
                    pool_k, pool_v, pos, pos[:, None], live,
                    k_scales, v_scales,
                )
                logits = jnp.einsum(
                    "bld,vd->blv", h.astype(jnp.float32), emb.astype(jnp.float32)
                )[:, 0, :]
                greedy = jnp.argmax(logits, axis=-1)

                def sample(rngs):
                    # sampling lanes: one split per step per lane (the
                    # solo chain), per-lane categorical over [V]
                    pairs = jax.vmap(jax.random.split)(rngs)
                    subs = pairs[:, 1]
                    drawn = jax.vmap(jax.random.categorical)(
                        subs, logits / jnp.maximum(temps, 1e-4)[:, None]
                    )
                    return pairs[:, 0], jnp.where(
                        temps <= 0.0, greedy, drawn
                    )

                def greedy_only(rngs):
                    # all-greedy pool: tokens are rng-independent, so
                    # the S×V gumbel draw (the dominant per-step cost at
                    # small models) is skipped outright
                    return rngs, greedy

                rngs2, nxt = jax.lax.cond(
                    jnp.all(temps <= 0.0), greedy_only, sample, rngs
                )
                nxt = nxt.astype(jnp.int32)
                emitted = jnp.where(live, nxt, -1)
                act2 = live & (nxt != eos)
                pos2 = jnp.where(live, pos + 1, pos)
                left2 = jnp.where(live, left - 1, left)
                tok2 = jnp.where(live, nxt, tok)
                # rng chains advance only for live lanes: a finished
                # lane's chain state is frozen where the solo decode's
                # chain was when it emitted that request's last token
                rngs3 = jnp.where(live[:, None], rngs2, rngs)
                return (pool_k, pool_v, tok2, pos2, act2, left2, rngs3), emitted

            (pool_k, pool_v, _, _, _, _, rngs), em = jax.lax.scan(
                one, (pool_k, pool_v, tok, pos, active, left, rngs),
                None, length=chunk,
            )
            return pool_k, pool_v, rngs, em

        fn = profile.wrap("generator.slot_step", jax.jit(run))
        self._fns[key] = fn
        return fn

    def _slot_verify_fn(self, S: int, T: int, k: int, quant: bool = False):
        """Compiled speculative VERIFY over the whole slot pool — the
        single batched dispatch that scores all ``k`` draft positions at
        once: ``(params, pool_k, pool_v, toks [S, k], pos [S],
        active [S], left [S], rngs [S, 2], temps [S], eos [S]) ->
        (pool_k, pool_v, rngs, emitted [k, S])``.

        ``toks[:, 0]`` is each lane's last emitted token (what a plain
        step would forward) and ``toks[:, 1:]`` its k-1 draft proposals.
        One ``SlotKVDecoder`` forward with ``Ln = k`` writes K/V for all
        k positions and yields logits at each; an in-kernel scan then
        walks the positions replaying EXACTLY the plain-step sampling
        (same per-lane rng chain, one split per EMITTED token, the
        pool-level all-greedy gate) and accepts while the sampled token
        agrees with the next forwarded input.  On the first disagreement
        the lane's own sampled token is still emitted (it was drawn from
        the true distribution at a position whose K/V is valid — the
        prefix up to it matched), and later positions emit ``-1``.
        Greedy and temperature>0 are both EXACT: acceptance only keeps
        tokens the plain chain would have drawn with the same splits, so
        spec-on == spec-off == solo bit-for-bit.  Rejected positions'
        K/V rows are garbage but UNREACHABLE: the pool is
        write-before-read (next dispatch re-writes position ``pos``
        before anything attends it) and masked attention zeroes keys
        past each row's ``q_pos``.

        ``quant=True``: int8 pool + trailing scales operands, same as
        the step fn."""
        key = ("slot_verify_q" if quant else "slot_verify", S, T, k)
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        self._tripwire.observe(key)
        decoder = self._slot_module_q if quant else self._slot_module

        def run(
            params, pool_k, pool_v, toks, pos, active, left, rngs, temps, eos,
            k_scales=None, v_scales=None,
        ):
            emb = params["tok_embed"]["embedding"]
            live0 = active & (left > 0)
            positions = pos[:, None] + jnp.arange(k, dtype=jnp.int32)[None, :]
            # ONE forward for all k positions pool-wide; write_pos=pos
            # so the k rows land at [pos, pos+k) (inactive lanes'
            # writes are masked off by ``live0`` as in the plain step)
            h, pool_k, pool_v = decoder.apply(
                {"params": params}, toks, positions,
                pool_k, pool_v, pos, positions, live0,
                k_scales, v_scales,
            )
            logits = jnp.einsum(
                "bld,vd->blv", h.astype(jnp.float32), emb.astype(jnp.float32)
            )  # [S, k, V]
            # follow[:, i] = the token forwarded at position i+1 — what
            # the sampled token at i must equal for acceptance to
            # continue; -1 (never a vocab id) past the last draft
            follow = jnp.concatenate(
                [toks[:, 1:], jnp.full((S, 1), -1, jnp.int32)], axis=1
            )

            def one(carry, xs):
                acc, pos_c, left_c, rngs = carry
                lg, fol = xs
                live = acc & (left_c > 0)
                greedy = jnp.argmax(lg, axis=-1)

                def sample(rngs):
                    pairs = jax.vmap(jax.random.split)(rngs)
                    drawn = jax.vmap(jax.random.categorical)(
                        pairs[:, 1], lg / jnp.maximum(temps, 1e-4)[:, None]
                    )
                    return pairs[:, 0], jnp.where(temps <= 0.0, greedy, drawn)

                def greedy_only(rngs):
                    return rngs, greedy

                rngs2, nxt = jax.lax.cond(
                    jnp.all(temps <= 0.0), greedy_only, sample, rngs
                )
                nxt = nxt.astype(jnp.int32)
                emitted = jnp.where(live, nxt, -1)
                # keep accepting only while the draw agrees with the
                # next forwarded draft AND the lane didn't just finish
                acc2 = live & (nxt != eos) & (nxt == fol)
                pos2 = jnp.where(live, pos_c + 1, pos_c)
                left2 = jnp.where(live, left_c - 1, left_c)
                # one split per EMITTED token — the solo chain position
                rngs3 = jnp.where(live[:, None], rngs2, rngs)
                return (acc2, pos2, left2, rngs3), emitted

            xs = (jnp.swapaxes(logits, 0, 1), follow.T)
            (_, _, _, rngs), em = jax.lax.scan(
                one, (live0, pos, left, rngs), xs
            )
            return pool_k, pool_v, rngs, em

        fn = profile.wrap("generator.slot_verify", jax.jit(run))
        self._fns[key] = fn
        return fn

    def _slot_draft_fn(
        self, S: int, T: int, k_draft: int, D: int, quant: bool = False
    ):
        """Compiled reduced-layer TRUNK draft — the fallback proposer
        when a lane's n-gram well runs dry: ``(params, pool_k, pool_v,
        tok [S], pos [S], active [S]) -> drafts [S, k_draft]``.  Runs
        only the first ``D`` trunk blocks (plus ``final_ln``) over the
        SAME params — no second model — greedily rolling ``k_draft``
        tokens forward on a sliced ``[S, D, T, H, hd]`` view of the
        pool.  The slice is a functional copy: the real pool is NEVER
        written (drafts are proposals; the verify dispatch is what
        commits K/V), so a wrong draft can't poison anything.  Greedy
        on purpose — drafts only seed verification, and the verify
        scan's exact sampling decides acceptance, so draft quality
        affects speed, never tokens."""
        key = ("slot_draft_q" if quant else "slot_draft", S, T, k_draft, D)
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        self._tripwire.observe(key)
        cfg = self.config
        decoder = SlotKVDecoder(cfg, quant=quant, layers=D)

        def run(
            params, pool_k, pool_v, tok, pos, active,
            k_scales=None, v_scales=None,
        ):
            emb = params["tok_embed"]["embedding"]
            pk = pool_k[:, :D]
            pv = pool_v[:, :D]
            ks = None if k_scales is None else k_scales[:D]
            vs = None if v_scales is None else v_scales[:D]

            def one(carry, _):
                pk, pv, tok, pos_c = carry
                h, pk, pv = decoder.apply(
                    {"params": params}, tok[:, None], pos_c[:, None],
                    pk, pv, pos_c, pos_c[:, None], active,
                    ks, vs,
                )
                logits = jnp.einsum(
                    "bld,vd->blv", h.astype(jnp.float32),
                    emb.astype(jnp.float32),
                )[:, 0, :]
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                pos2 = jnp.where(active, pos_c + 1, pos_c)
                return (pk, pv, nxt, pos2), nxt

            (_, _, _, _), toks = jax.lax.scan(
                one, (pk, pv, tok, pos), None, length=k_draft
            )
            return jnp.swapaxes(toks, 0, 1)

        fn = profile.wrap("generator.slot_draft", jax.jit(run))
        self._fns[key] = fn
        return fn

    def _generate_kv(
        self,
        prompts: Sequence[str],
        max_new_tokens: int,
        temperature: float,
        seed: int,
        eos: Optional[int] = None,
    ) -> List[str]:
        cfg = self.config
        n = len(prompts)
        # tokenize + pad OFF the lock (the tokenizer is stateless), same
        # discipline as the serve/encode paths: concurrent generates
        # overlap their host prep; the lock covers only the compiled-fn
        # cache below
        from .encoder import _bucket

        b = _bucket(n)
        texts = [str(p) for p in prompts] + [""] * (b - n)
        L_budget = cfg.max_len - max_new_tokens
        ids, mask = self.tokenizer.encode_batch(texts, max_length=L_budget)
        ids = np.asarray(ids)
        mask = np.asarray(mask)
        n_lens = mask.sum(axis=1).astype(np.int32)
        # tier-2 lookup OFF the lock (cache traffic, incl. chaos sites,
        # must never stall a concurrent generate)
        P, matches = (0, [])
        if self.kv_cache is not None:
            P, matches = self._cached_prefix(ids, n_lens, n)
        L_sfx = ids.shape[1] - P
        H = cfg.n_heads
        hd = cfg.d_model // H
        if P:
            n_pblk = P // self.kv_cache.block
            rows_k = []
            rows_v = []
            for i in range(b):
                if i < n:
                    blocks = matches[i][1][:n_pblk]
                    rows_k.append(jnp.concatenate([blk[0] for blk in blocks], axis=1))
                    rows_v.append(jnp.concatenate([blk[1] for blk in blocks], axis=1))
                else:
                    rows_k.append(jnp.zeros((cfg.n_layers, P, H, hd), cfg.dtype))
                    rows_v.append(jnp.zeros((cfg.n_layers, P, H, hd), cfg.dtype))
            prefix_k = jnp.stack(rows_k)
            prefix_v = jnp.stack(rows_v)
        else:
            prefix_k = jnp.zeros((b, cfg.n_layers, 0, H, hd), cfg.dtype)
            prefix_v = jnp.zeros((b, cfg.n_layers, 0, H, hd), cfg.dtype)
        with self._lock:
            fn = self._kv_fn(b, L_sfx, P, max_new_tokens)
        t0 = time.perf_counter_ns()
        observe.record_occupancy("generator", n, b)
        # "generator.dispatch" is the retry/fault site: a generator that
        # stays down raises out of here, and the QA layer's ladder rung
        # answers extractively from the retrieved passages instead
        toks, kbuf, vbuf = retry_call(
            "generator.dispatch",
            fn,
            self.params,
            jnp.asarray(ids[:, P:]),
            jnp.asarray(n_lens),
            prefix_k,
            prefix_v,
            jnp.float32(temperature),
            jax.random.PRNGKey(seed),
            jnp.int32(-1 if eos is None else eos),
            # padding rows start finished (output discarded) so the
            # in-scan all-finished compute skip can fire on real batches
            jnp.asarray(np.arange(b) >= n)
            if eos is not None
            else jnp.zeros((b,), bool),
        )
        toks = np.asarray(toks)[:n]
        self.last_decode_steps = max_new_tokens
        _H_READY.observe_ns(time.perf_counter_ns() - t0)
        # capture: admit the prompt's uncached full blocks as async
        # device slices of the returned buffers (prompt region only —
        # block j covers buffer positions [j*blk, (j+1)*blk), identical
        # in global and buffer coordinates since the prefix sits at 0)
        if self.kv_cache is not None:
            blk = self.kv_cache.block
            for i in range(n):
                matched, _blocks, chain = matches[i]
                self.kv_cache.admit(
                    chain,
                    matched // blk,
                    lambda j, row=i: (
                        kbuf[row, :, j * blk : (j + 1) * blk],
                        vbuf[row, :, j * blk : (j + 1) * blk],
                    ),
                )
                self.kv_cache.note_prefill(
                    reused=P, computed=int(n_lens[i]) - P
                )
        return [self.render_tokens(row) for row in toks]

    def render_tokens(self, row: Sequence[int]) -> str:
        """Canonical token-id rendering (the hashing tokenizer is not
        invertible) — shared by every decode path, including the
        continuous engine (serve/decode.py), so per-request token
        identity is comparable as plain strings."""
        return " ".join(
            f"<{int(t)}>" for t in row if int(t) != self.tokenizer.PAD
        )

    def generate(
        self,
        prompts: Sequence[str],
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        seed: int = 0,
        use_kv: Optional[bool] = None,
        eos_id: Any = _UNSET,
    ) -> List[str]:
        """Generate up to ``max_new_tokens`` per prompt.  ``use_kv``
        overrides the decode path (None = the ``PATHWAY_GENERATOR_KV``
        default): the KV path and the legacy full re-attend emit
        identical tokens — the legacy path survives as the parity oracle
        and fallback.  ``eos_id`` (default: the instance's
        ``PATHWAY_GENERATOR_EOS`` setting) marks rows finished when they
        emit it: post-EOS sampling is masked to PAD on both paths, and
        the legacy path runs its decode in ``PATHWAY_DECODE_STEP_BUCKET``
        chunks so the call RETURNS as soon as every row has finished
        instead of paying the full ``steps`` budget."""
        if not prompts:
            return []
        eos = self.eos_id if eos_id is _UNSET else eos_id
        if eos is not None and int(eos) == self.tokenizer.PAD:
            raise ValueError("eos_id must differ from the PAD token id")
        if use_kv if use_kv is not None else self._use_kv:
            return self._generate_kv(
                prompts, max_new_tokens, temperature, seed, eos=eos
            )
        with self._lock:
            n = len(prompts)
            from .encoder import _bucket

            b = _bucket(n)
            texts = [str(p) for p in prompts] + [""] * (b - n)
            L_budget = self.config.max_len - max_new_tokens
            ids, mask = self.tokenizer.encode_batch(texts, max_length=L_budget)
            pad = np.zeros((ids.shape[0], max_new_tokens), np.int32)
            ids = np.concatenate([ids, pad], axis=1)
            mask_full = np.concatenate([mask, pad], axis=1)
            # without EOS the whole budget is ONE chunk (the original
            # single-dispatch decode, unchanged); with EOS the budget is
            # split into step-bucket chunks so the host can stop as soon
            # as the finished mask covers every row
            chunk = (
                max_new_tokens if eos is None
                else min(max_new_tokens, decode_step_bucket())
            )
        # dispatch + fetch OFF the lock (lock-discipline: holding it across
        # the decode round trip serialized concurrent generates for the
        # full device latency); the lock only guards tokenization and the
        # compiled-fn cache
        t0 = time.perf_counter_ns()
        observe.record_occupancy("generator", n, b)
        ids_d = jnp.asarray(ids)
        mask_d = jnp.asarray(mask_full)
        pos_d = jnp.asarray(mask.sum(axis=1).astype(np.int32))
        rng = jax.random.PRNGKey(seed)
        # bucket-padding rows start FINISHED: their output is discarded,
        # and leaving them live would keep the all-finished early exit
        # from ever firing on a real EOS-heavy batch
        fin = jnp.asarray(np.arange(ids.shape[0]) >= n) if eos is not None \
            else jnp.zeros((ids.shape[0],), bool)
        eos_t = jnp.int32(-1 if eos is None else eos)
        temp_t = jnp.float32(temperature)
        out_chunks: List[np.ndarray] = []
        steps_run = 0
        while steps_run < max_new_tokens:
            # the tail chunk is sized EXACTLY to the remaining budget
            # (one extra compile signature per distinct remainder, both
            # bounded by the step bucket) — the decode never runs, nor
            # reports, more steps than max_new_tokens
            c = min(chunk, max_new_tokens - steps_run)
            with self._lock:
                fn = self._decode_fn(ids.shape[0], ids.shape[1], c)
            toks_c, ids_d, mask_d, pos_d, rng, fin = retry_call(
                "generator.dispatch",
                fn,
                self.params,
                ids_d,
                mask_d,
                pos_d,
                temp_t,
                rng,
                fin,
                eos_t,
            )
            out_chunks.append(np.asarray(toks_c))
            steps_run += c
            # EOS early-exit: every row finished — the remaining budget
            # would be all-PAD no-op iterations, so return now
            if eos is not None and bool(np.asarray(fin).all()):
                break
        self.last_decode_steps = steps_run
        toks = np.concatenate(out_chunks, axis=1)[:n, :max_new_tokens]
        _H_READY.observe_ns(time.perf_counter_ns() - t0)
        return [self.render_tokens(row) for row in toks]

    def __call__(self, prompts: Sequence[str], **kwargs) -> List[str]:
        return self.generate(prompts, **kwargs)
