"""TextGenerator — local causal LM for chat-style generation.

TPU-native analog of the reference's HFPipelineChat local generator
(xpacks/llm/llms.py:441).  Decoding is a real **KV-cache decode**: one
jitted function runs the prompt prefill (suffix only, when the prefix
cache below has the leading blocks) and then ``lax.scan``s single-token
steps against persistent per-layer K/V buffers — O(steps × L) attention
instead of the old full re-attend's O(steps × L²), still with no
per-token python round trips (ONE dispatch per generate call, as
before).

**Prefix/KV reuse** (pathway_tpu/cache/prefix.py): prompt token ids are
content-addressed in fixed blocks under a hash chain, and the K/V of
every full block is captured device-resident after the decode.  RAG
prompts sharing a system-prompt + retrieved-chunk prefix prefill only
their tails — prefill cost across a shared-prefix prompt set is
sub-linear, measured by the ``serve_cache`` bench phase via the
``pathway_cache_prefill_tokens_total{kind=reused|computed}`` counters.

Bit-reproducibility: the KV twin (models/transformer.py
``KVTransformerDecoder``) keeps the attention math line-for-line with
the trunk, the K/V buffer width is constant across prefix splits, and
masked slots carry exactly-zero probability — so warm (cached-prefix)
decodes emit the SAME tokens as cold ones, and the KV path matches the
legacy full re-attend decode token-for-token (tests/test_serve_cache.py
parity tests).  ``PATHWAY_GENERATOR_KV=0`` falls back to the legacy
decode.

With random-init weights the output is noise; with a trained checkpoint
it generates — either way the serving path, batching, caching and
compile behavior are the product."""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import observe
from ..robust import retry_call
from ._params import unbox as _unbox

from .tokenizer import HashTokenizer
from .transformer import (
    KVTransformerDecoder,
    TransformerConfig,
    TransformerEncoder,
    resolve_heads,
)

__all__ = ["TextGenerator"]

# flight recorder: submit→ready latency of a full decode (dispatch
# through host fetch) + batch occupancy per dispatch
_H_READY = observe.histogram("pathway_serve_model_seconds", model="generator")


class TextGenerator:
    def __init__(
        self,
        model: str = "pathway-mini-lm",
        dimension: int = 256,
        n_layers: int = 4,
        n_heads: int = 4,
        max_length: int = 256,
        vocab_size: int = 32768,
        seed: int = 2,
        checkpoint_path: Optional[str] = None,
        dtype=jnp.bfloat16,
        kv_cache: Any = "env",
    ):
        self.config = TransformerConfig(
            vocab_size=vocab_size,
            d_model=dimension,
            n_heads=resolve_heads(dimension, n_heads),
            n_layers=n_layers,
            d_ff=dimension * 4,
            max_len=max_length,
            dtype=dtype,
            pool="none",
            causal=True,
        )
        self.tokenizer = HashTokenizer(vocab_size=vocab_size, max_length=max_length)
        self.module = TransformerEncoder(self.config)
        self._kv_module = KVTransformerDecoder(self.config)
        self._lock = threading.Lock()
        self._fns: Dict[tuple, Any] = {}
        # recompile tripwire (ops/recompile_guard.py): decode shapes are
        # (batch bucket, padded length, prefix bucket, steps); a leak
        # fails under tests
        from ..ops.recompile_guard import RecompileTripwire

        self._tripwire = RecompileTripwire(f"TextGenerator[{model}]")
        ids = jnp.zeros((1, 16), jnp.int32)
        mask = jnp.ones((1, 16), jnp.int32)
        self.params = self.module.init(jax.random.PRNGKey(seed), ids, mask)["params"]
        self.params = _unbox(self.params)
        # weight-tied readout: logits = h @ tok_embed.T
        self._vocab_table = None
        # tier-2 prefix/KV cache (pathway_tpu/cache): per-generator —
        # K/V blocks are only meaningful against this instance's params
        if kv_cache == "env":
            from ..cache import prefix_kv_cache_from_env

            kv_cache = prefix_kv_cache_from_env()
        self.kv_cache = kv_cache
        self._use_kv = os.environ.get("PATHWAY_GENERATOR_KV", "1") not in (
            "0", "false", "off",
        )

    # -- legacy full re-attend decode (parity reference / fallback) ----------
    def _decode_fn(self, B: int, L: int, steps: int):
        key = (B, L, steps)
        fn = self._fns.get(key)
        if fn is None:
            self._tripwire.observe(key)
            module = self.module

            def decode(params, ids, mask, temperature, rng):
                emb = params["tok_embed"]["embedding"]

                def step(carry, _):
                    ids_c, mask_c, pos, rng_c = carry
                    hidden = module.apply({"params": params}, ids_c, mask_c)
                    logits = jnp.einsum(
                        "bld,vd->blv", hidden.astype(jnp.float32), emb.astype(jnp.float32)
                    )
                    # logits at last real position of each row
                    last = jnp.take_along_axis(
                        logits, (pos - 1)[:, None, None], axis=1
                    )[:, 0, :]
                    rng_c, sub = jax.random.split(rng_c)
                    greedy = jnp.argmax(last, axis=-1)
                    sampled = jax.random.categorical(sub, last / jnp.maximum(temperature, 1e-4))
                    nxt = jnp.where(temperature <= 0.0, greedy, sampled).astype(jnp.int32)
                    ids_c = jnp.take_along_axis(
                        ids_c, jnp.arange(ids_c.shape[1])[None, :], axis=1
                    )
                    ids_c = jax.vmap(lambda row, p, t: row.at[p].set(t))(
                        ids_c, pos, nxt
                    )
                    mask_c = jax.vmap(lambda row, p: row.at[p].set(1))(mask_c, pos)
                    return (ids_c, mask_c, pos + 1, rng_c), nxt

                (ids_f, _, _, _), toks = jax.lax.scan(
                    step, (ids, mask, jnp.sum(mask, axis=1), rng), None, length=steps
                )
                return toks.T  # [B, steps]

            fn = jax.jit(decode)
            self._fns[key] = fn
        return fn

    # -- KV-cache decode -----------------------------------------------------
    def _kv_fn(self, B: int, L_sfx: int, P: int, steps: int):
        """Compiled prefill+decode: ``(params, suffix_ids, n_lens,
        prefix_k, prefix_v, temperature, rng) -> (tokens [B, steps],
        k_buf, v_buf)``.  ``P`` is the static cached-prefix split (the
        batch-min match, bucketed to power-of-two block multiples by
        ``_cached_prefix``) — the K/V buffer width is ``P + L_sfx +
        steps`` == the legacy decode's constant attention width, which
        is what makes warm and cold decodes bit-identical.
        The returned buffers stay device-resident; the capture pass
        slices prompt blocks out of them for the prefix cache."""
        key = ("kv", B, L_sfx, P, steps)
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        self._tripwire.observe(key)
        cfg = self.config
        decoder = self._kv_module
        H = cfg.n_heads
        hd = cfg.d_model // H
        T = P + L_sfx + steps

        def run(params, suffix_ids, n_lens, prefix_k, prefix_v, temperature, rng):
            emb = params["tok_embed"]["embedding"]
            kbuf = jnp.zeros((B, cfg.n_layers, T, H, hd), cfg.dtype)
            vbuf = jnp.zeros((B, cfg.n_layers, T, H, hd), cfg.dtype)
            if P:
                kbuf = jax.lax.dynamic_update_slice(
                    kbuf, prefix_k.astype(cfg.dtype), (0, 0, 0, 0, 0)
                )
                vbuf = jax.lax.dynamic_update_slice(
                    vbuf, prefix_v.astype(cfg.dtype), (0, 0, 0, 0, 0)
                )
            # prefill: the suffix tokens sit at global positions
            # [P, P + L_sfx); every row shares the static split point
            positions = jnp.broadcast_to(
                (P + jnp.arange(L_sfx, dtype=jnp.int32))[None, :], (B, L_sfx)
            )
            write_pos = jnp.full((B,), P, jnp.int32)
            hidden, kbuf, vbuf = decoder.apply(
                {"params": params}, suffix_ids, positions, kbuf, vbuf,
                write_pos, positions,
            )
            logits = jnp.einsum(
                "bld,vd->blv", hidden.astype(jnp.float32), emb.astype(jnp.float32)
            )
            # first decode logits: the last REAL prompt position, in
            # suffix-local coordinates (the prefix cache always leaves
            # >= 1 real suffix token, so n - 1 - P >= 0 on real rows)
            last0 = jnp.take_along_axis(
                logits,
                jnp.maximum(n_lens - 1 - P, 0)[:, None, None],
                axis=1,
            )[:, 0, :]

            def step(carry, _):
                kbuf_c, vbuf_c, last, pos, rng_c = carry
                rng_c, sub = jax.random.split(rng_c)
                greedy = jnp.argmax(last, axis=-1)
                sampled = jax.random.categorical(
                    sub, last / jnp.maximum(temperature, 1e-4)
                )
                nxt = jnp.where(temperature <= 0.0, greedy, sampled).astype(
                    jnp.int32
                )
                h1, kbuf_c, vbuf_c = decoder.apply(
                    {"params": params}, nxt[:, None], pos[:, None],
                    kbuf_c, vbuf_c, pos, pos[:, None],
                )
                logits1 = jnp.einsum(
                    "bld,vd->blv",
                    h1.astype(jnp.float32),
                    emb.astype(jnp.float32),
                )[:, 0, :]
                return (kbuf_c, vbuf_c, logits1, pos + 1, rng_c), nxt

            (kbuf, vbuf, _, _, _), toks = jax.lax.scan(
                step, (kbuf, vbuf, last0, n_lens, rng), None, length=steps
            )
            return toks.T, kbuf, vbuf  # toks [B, steps]

        fn = jax.jit(run)
        self._fns[key] = fn
        return fn

    def _cached_prefix(self, ids: np.ndarray, n_lens: np.ndarray, n: int):
        """Cache wrapper for the prefix tier: per-row longest cached
        block chain, batched at the row MINIMUM (the static split point
        every row shares — the RAG shape is many prompts over one
        system+chunks prefix, where the minimum IS the shared prefix),
        then rounded DOWN to a power-of-two block multiple so the split
        point (a compile-shape dimension) takes O(log) values instead of
        one per distinct prefix length — a mix of prompt families must
        not compile one decode program each.  Returns ``(P, matches)``;
        pure host + cache work, no dispatch."""
        matches = [
            self.kv_cache.match(ids[i], int(n_lens[i])) for i in range(n)
        ]
        P = min((m[0] for m in matches), default=0)
        blk = self.kv_cache.block
        bucket = 0
        step = blk
        while step <= P:
            bucket = step
            step *= 2
        return bucket, matches

    def _generate_kv(
        self,
        prompts: Sequence[str],
        max_new_tokens: int,
        temperature: float,
        seed: int,
    ) -> List[str]:
        cfg = self.config
        n = len(prompts)
        # tokenize + pad OFF the lock (the tokenizer is stateless), same
        # discipline as the serve/encode paths: concurrent generates
        # overlap their host prep; the lock covers only the compiled-fn
        # cache below
        from .encoder import _bucket

        b = _bucket(n)
        texts = [str(p) for p in prompts] + [""] * (b - n)
        L_budget = cfg.max_len - max_new_tokens
        ids, mask = self.tokenizer.encode_batch(texts, max_length=L_budget)
        ids = np.asarray(ids)
        mask = np.asarray(mask)
        n_lens = mask.sum(axis=1).astype(np.int32)
        # tier-2 lookup OFF the lock (cache traffic, incl. chaos sites,
        # must never stall a concurrent generate)
        P, matches = (0, [])
        if self.kv_cache is not None:
            P, matches = self._cached_prefix(ids, n_lens, n)
        L_sfx = ids.shape[1] - P
        H = cfg.n_heads
        hd = cfg.d_model // H
        if P:
            n_pblk = P // self.kv_cache.block
            rows_k = []
            rows_v = []
            for i in range(b):
                if i < n:
                    blocks = matches[i][1][:n_pblk]
                    rows_k.append(jnp.concatenate([blk[0] for blk in blocks], axis=1))
                    rows_v.append(jnp.concatenate([blk[1] for blk in blocks], axis=1))
                else:
                    rows_k.append(jnp.zeros((cfg.n_layers, P, H, hd), cfg.dtype))
                    rows_v.append(jnp.zeros((cfg.n_layers, P, H, hd), cfg.dtype))
            prefix_k = jnp.stack(rows_k)
            prefix_v = jnp.stack(rows_v)
        else:
            prefix_k = jnp.zeros((b, cfg.n_layers, 0, H, hd), cfg.dtype)
            prefix_v = jnp.zeros((b, cfg.n_layers, 0, H, hd), cfg.dtype)
        with self._lock:
            fn = self._kv_fn(b, L_sfx, P, max_new_tokens)
        t0 = time.perf_counter_ns()
        observe.record_occupancy("generator", n, b)
        # "generator.dispatch" is the retry/fault site: a generator that
        # stays down raises out of here, and the QA layer's ladder rung
        # answers extractively from the retrieved passages instead
        toks, kbuf, vbuf = retry_call(
            "generator.dispatch",
            fn,
            self.params,
            jnp.asarray(ids[:, P:]),
            jnp.asarray(n_lens),
            prefix_k,
            prefix_v,
            jnp.float32(temperature),
            jax.random.PRNGKey(seed),
        )
        toks = np.asarray(toks)[:n]
        _H_READY.observe_ns(time.perf_counter_ns() - t0)
        # capture: admit the prompt's uncached full blocks as async
        # device slices of the returned buffers (prompt region only —
        # block j covers buffer positions [j*blk, (j+1)*blk), identical
        # in global and buffer coordinates since the prefix sits at 0)
        if self.kv_cache is not None:
            blk = self.kv_cache.block
            for i in range(n):
                matched, _blocks, chain = matches[i]
                self.kv_cache.admit(
                    chain,
                    matched // blk,
                    lambda j, row=i: (
                        kbuf[row, :, j * blk : (j + 1) * blk],
                        vbuf[row, :, j * blk : (j + 1) * blk],
                    ),
                )
                self.kv_cache.note_prefill(
                    reused=P, computed=int(n_lens[i]) - P
                )
        # hashing tokenizer is not invertible; render token ids
        return [
            " ".join(f"<{int(t)}>" for t in row if t != self.tokenizer.PAD)
            for row in toks
        ]

    def generate(
        self,
        prompts: Sequence[str],
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        seed: int = 0,
        use_kv: Optional[bool] = None,
    ) -> List[str]:
        """Generate ``max_new_tokens`` per prompt.  ``use_kv`` overrides
        the decode path (None = the ``PATHWAY_GENERATOR_KV`` default):
        the KV path and the legacy full re-attend emit identical tokens
        — the legacy path survives as the parity oracle and fallback."""
        if not prompts:
            return []
        if use_kv if use_kv is not None else self._use_kv:
            return self._generate_kv(
                prompts, max_new_tokens, temperature, seed
            )
        with self._lock:
            n = len(prompts)
            from .encoder import _bucket

            b = _bucket(n)
            texts = [str(p) for p in prompts] + [""] * (b - n)
            L_budget = self.config.max_len - max_new_tokens
            ids, mask = self.tokenizer.encode_batch(texts, max_length=L_budget)
            pad = np.zeros((ids.shape[0], max_new_tokens), np.int32)
            ids = np.concatenate([ids, pad], axis=1)
            mask_full = np.concatenate([mask, pad], axis=1)
            fn = self._decode_fn(ids.shape[0], ids.shape[1], max_new_tokens)
        # dispatch + fetch OFF the lock (lock-discipline: holding it across
        # the decode round trip serialized concurrent generates for the
        # full device latency); the lock only guards tokenization and the
        # compiled-fn cache
        t0 = time.perf_counter_ns()
        observe.record_occupancy("generator", n, b)
        toks = retry_call(
            "generator.dispatch",
            fn,
            self.params,
            jnp.asarray(ids),
            jnp.asarray(mask_full),
            jnp.float32(temperature),
            jax.random.PRNGKey(seed),
        )
        toks = np.asarray(toks)[:n]
        _H_READY.observe_ns(time.perf_counter_ns() - t0)
        # hashing tokenizer is not invertible; render token ids
        return [
            " ".join(f"<{int(t)}>" for t in row if t != self.tokenizer.PAD)
            for row in toks
        ]

    def __call__(self, prompts: Sequence[str], **kwargs) -> List[str]:
        return self.generate(prompts, **kwargs)
