"""TextGenerator — local causal LM for chat-style generation.

TPU-native analog of the reference's HFPipelineChat local generator
(xpacks/llm/llms.py:441).  Greedy/temperature decoding runs as a
``lax.scan`` over a fixed-size token buffer inside one jit — no per-token
python round trips.  With random-init weights the output is noise; with a
trained checkpoint it generates — either way the serving path, batching and
compile behavior are the product."""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import observe
from ..robust import retry_call
from ._params import unbox as _unbox

from .tokenizer import HashTokenizer
from .transformer import TransformerConfig, TransformerEncoder, resolve_heads

__all__ = ["TextGenerator"]

# flight recorder: submit→ready latency of a full decode (dispatch
# through host fetch) + batch occupancy per dispatch
_H_READY = observe.histogram("pathway_serve_model_seconds", model="generator")


class TextGenerator:
    def __init__(
        self,
        model: str = "pathway-mini-lm",
        dimension: int = 256,
        n_layers: int = 4,
        n_heads: int = 4,
        max_length: int = 256,
        vocab_size: int = 32768,
        seed: int = 2,
        checkpoint_path: Optional[str] = None,
        dtype=jnp.bfloat16,
    ):
        self.config = TransformerConfig(
            vocab_size=vocab_size,
            d_model=dimension,
            n_heads=resolve_heads(dimension, n_heads),
            n_layers=n_layers,
            d_ff=dimension * 4,
            max_len=max_length,
            dtype=dtype,
            pool="none",
            causal=True,
        )
        self.tokenizer = HashTokenizer(vocab_size=vocab_size, max_length=max_length)
        self.module = TransformerEncoder(self.config)
        self._lock = threading.Lock()
        self._fns: Dict[tuple, Any] = {}
        # recompile tripwire (ops/recompile_guard.py): decode shapes are
        # (batch bucket, padded length, steps); a leak fails under tests
        from ..ops.recompile_guard import RecompileTripwire

        self._tripwire = RecompileTripwire(f"TextGenerator[{model}]")
        ids = jnp.zeros((1, 16), jnp.int32)
        mask = jnp.ones((1, 16), jnp.int32)
        self.params = self.module.init(jax.random.PRNGKey(seed), ids, mask)["params"]
        self.params = _unbox(self.params)
        # weight-tied readout: logits = h @ tok_embed.T
        self._vocab_table = None

    def _decode_fn(self, B: int, L: int, steps: int):
        key = (B, L, steps)
        fn = self._fns.get(key)
        if fn is None:
            self._tripwire.observe(key)
            module = self.module

            def decode(params, ids, mask, temperature, rng):
                emb = params["tok_embed"]["embedding"]

                def step(carry, _):
                    ids_c, mask_c, pos, rng_c = carry
                    hidden = module.apply({"params": params}, ids_c, mask_c)
                    logits = jnp.einsum(
                        "bld,vd->blv", hidden.astype(jnp.float32), emb.astype(jnp.float32)
                    )
                    # logits at last real position of each row
                    last = jnp.take_along_axis(
                        logits, (pos - 1)[:, None, None], axis=1
                    )[:, 0, :]
                    rng_c, sub = jax.random.split(rng_c)
                    greedy = jnp.argmax(last, axis=-1)
                    sampled = jax.random.categorical(sub, last / jnp.maximum(temperature, 1e-4))
                    nxt = jnp.where(temperature <= 0.0, greedy, sampled).astype(jnp.int32)
                    ids_c = jnp.take_along_axis(
                        ids_c, jnp.arange(ids_c.shape[1])[None, :], axis=1
                    )
                    ids_c = jax.vmap(lambda row, p, t: row.at[p].set(t))(
                        ids_c, pos, nxt
                    )
                    mask_c = jax.vmap(lambda row, p: row.at[p].set(1))(mask_c, pos)
                    return (ids_c, mask_c, pos + 1, rng_c), nxt

                (ids_f, _, _, _), toks = jax.lax.scan(
                    step, (ids, mask, jnp.sum(mask, axis=1), rng), None, length=steps
                )
                return toks.T  # [B, steps]

            fn = jax.jit(decode)
            self._fns[key] = fn
        return fn

    def generate(
        self,
        prompts: Sequence[str],
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        seed: int = 0,
    ) -> List[str]:
        with self._lock:
            n = len(prompts)
            if n == 0:
                return []
            from .encoder import _bucket

            b = _bucket(n)
            texts = [str(p) for p in prompts] + [""] * (b - n)
            L_budget = self.config.max_len - max_new_tokens
            ids, mask = self.tokenizer.encode_batch(texts, max_length=L_budget)
            pad = np.zeros((ids.shape[0], max_new_tokens), np.int32)
            ids = np.concatenate([ids, pad], axis=1)
            mask_full = np.concatenate([mask, pad], axis=1)
            fn = self._decode_fn(ids.shape[0], ids.shape[1], max_new_tokens)
        # dispatch + fetch OFF the lock (lock-discipline: holding it across
        # the decode round trip serialized concurrent generates for the
        # full device latency); the lock only guards tokenization and the
        # compiled-fn cache
        t0 = time.perf_counter_ns()
        observe.record_occupancy("generator", n, b)
        # "generator.dispatch" is the retry/fault site: a generator that
        # stays down raises out of here, and the QA layer's ladder rung
        # answers extractively from the retrieved passages instead
        toks = retry_call(
            "generator.dispatch",
            fn,
            self.params,
            jnp.asarray(ids),
            jnp.asarray(mask_full),
            jnp.float32(temperature),
            jax.random.PRNGKey(seed),
        )
        toks = np.asarray(toks)[:n]
        _H_READY.observe_ns(time.perf_counter_ns() - t0)
        # hashing tokenizer is not invertible; render token ids
        return [
            " ".join(f"<{int(t)}>" for t in row if t != self.tokenizer.PAD)
            for row in toks
        ]

    def __call__(self, prompts: Sequence[str], **kwargs) -> List[str]:
        return self.generate(prompts, **kwargs)
