"""Sequence-packing row layout — shared by the embedder and cross-encoder.

Best-fit-decreasing bin packing of tokenized sequences into fixed-length
rows for block-diagonal segment attention (models/transformer.py): several
short sequences share one row, so the MXU sees full-length matmuls
regardless of the input length distribution.  Split out of
``SentenceEncoder._pack`` so the cross-encoder's (query, doc) pair scoring
packs through the exact same layout code.
"""

from __future__ import annotations

import bisect
from typing import List, Tuple

import numpy as np

__all__ = ["pack_rows", "pad_packed_rows", "row_length_bucket", "seg_bucket"]

_ROW_LEN_BUCKETS = (32, 64, 128, 256, 512)


def seg_bucket(n_seg: int) -> int:
    """Segment width is a compile dimension: bucket it (8 wide, then /4
    steps) so every packed consumer compiles the same handful of shapes."""
    return 8 if n_seg <= 8 else max(1, ((n_seg + 3) // 4) * 4)


def pad_packed_rows(
    ids: np.ndarray,
    segments: np.ndarray,
    positions: np.ndarray,
    rows: int,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Zero-pad the packed [R, L] layout arrays up to ``rows`` rows (pad
    rows carry segment 0 everywhere = fully masked)."""
    R, L = ids.shape
    if rows > R:
        pad = np.zeros((rows - R, L), np.int32)
        ids = np.concatenate([ids, pad])
        segments = np.concatenate([segments, pad])
        positions = np.concatenate([positions, pad])
    return ids, segments, positions


def row_length_bucket(longest: int, max_len: int) -> int:
    """Length-bucketed row width: the smallest power-of-two bucket that
    holds the longest sequence, capped at ``max_len`` — short micro-batches
    compile a handful of (R, L) shapes instead of one per input length,
    and an all-short batch never pays a ``max_len``-wide forward."""
    for b in _ROW_LEN_BUCKETS:
        if b >= max_len:
            return max_len
        if longest <= b:
            return b
    return max_len


def pack_rows(
    ids_b: np.ndarray,
    lens: np.ndarray,
    L: int,
    max_docs_per_row: int = 8,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, List[Tuple[int, int]], int]:
    """Pack ``n`` tokenized sequences (``ids_b`` [n, L_tok] padded, ``lens``
    [n] real token counts, already clipped to ``L``) into rows of ``L``
    tokens.  Returns (ids [R, L], mask, segments, positions, doc_slots,
    n_seg) where doc_slots[i] = (row, segment-1) of input sequence i;
    segments are 1-based per row, positions restart per sequence (so
    positional embeddings match the unpacked encoding)."""
    n = int(ids_b.shape[0])
    lens = np.asarray(lens, np.int64)
    order = np.argsort(-lens, kind="stable")
    # best-fit-decreasing via a capacity-sorted open-row list: O(log R)
    # placement per doc (a naive scan-all-rows loop measured 68 ms per
    # 2.5k-doc chunk — more than the device forward it feeds).  The
    # per-row doc cap keeps the segment width (a compile dimension)
    # small and stable across chunks.
    open_caps: list = []  # ascending (cap_left, row_id)
    row_of = np.empty(n, np.int64)
    seg_of = np.empty(n, np.int64)
    off_of = np.empty(n, np.int64)
    row_fill: list = []  # tokens used per row
    row_count: list = []  # docs per row
    for i in order.tolist():
        need = int(lens[i])
        j = bisect.bisect_left(open_caps, (need, -1))
        if j < len(open_caps):
            cap_left, rid = open_caps.pop(j)
            row_of[i] = rid
            seg_of[i] = row_count[rid]
            off_of[i] = row_fill[rid]
            row_count[rid] += 1
            row_fill[rid] += need
            new_cap = cap_left - need
            if row_count[rid] < max_docs_per_row and new_cap >= 2:
                bisect.insort(open_caps, (new_cap, rid))
        else:
            rid = len(row_fill)
            row_of[i] = rid
            seg_of[i] = 0
            off_of[i] = 0
            row_fill.append(need)
            row_count.append(1)
            if max_docs_per_row > 1 and L - need >= 2:
                bisect.insort(open_caps, (L - need, rid))
    R = len(row_fill)
    n_seg = max(row_count) if row_count else 1
    # vectorized assembly: one flat scatter for all token positions
    total = int(lens.sum())
    within = np.arange(total) - np.repeat(
        np.concatenate([[0], np.cumsum(lens)[:-1]]), lens
    )
    src = np.repeat(np.arange(n) * ids_b.shape[1], lens) + within
    dest = np.repeat(row_of * L + off_of, lens) + within
    ids = np.zeros(R * L, np.int32)
    mask = np.zeros(R * L, np.int32)
    segments = np.zeros(R * L, np.int32)
    positions = np.zeros(R * L, np.int32)
    ids[dest] = ids_b.reshape(-1)[src]
    mask[dest] = 1
    segments[dest] = np.repeat(seg_of + 1, lens)
    positions[dest] = within
    doc_slots = list(zip(row_of.tolist(), seg_of.tolist()))
    return (
        ids.reshape(R, L),
        mask.reshape(R, L),
        segments.reshape(R, L),
        positions.reshape(R, L),
        doc_slots,
        n_seg,
    )
