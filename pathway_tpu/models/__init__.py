"""pw.models — TPU-native model zoo backing the LLM xpack.

The reference delegates local inference to torch libraries
(sentence_transformers SentenceTransformer/CrossEncoder, transformers
pipeline — xpacks/llm/embedders.py:270, rerankers.py:186, llms.py:441).
Here the equivalents are flax modules compiled by XLA and batched by
construction; weights load from a local checkpoint directory when given and
fall back to deterministic random init (useful for benchmarks and tests —
this environment has zero egress, so nothing downloads)."""

from .tokenizer import HashTokenizer
from .transformer import TransformerConfig, TransformerEncoder
from .encoder import SentenceEncoder
from .cross_encoder import CrossEncoderModel
from .generator import TextGenerator
from .clip import ClipModel

__all__ = [
    "HashTokenizer",
    "TransformerConfig",
    "TransformerEncoder",
    "SentenceEncoder",
    "CrossEncoderModel",
    "TextGenerator",
    "ClipModel",
]
