"""REST servers for RAG apps (reference: xpacks/llm/servers.py:16-291 —
BaseRestServer, QARestServer, QASummaryRestServer, DocumentStoreServer,
serve_callable)."""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional, Type

from ...internals import run as run_mod
from ...internals.schema import Schema, schema_from_types
from ...io.http import EndpointDocumentation, PathwayWebserver, rest_connector

__all__ = [
    "BaseRestServer",
    "QARestServer",
    "QASummaryRestServer",
    "DocumentStoreServer",
    "serve_callable",
]


class BaseRestServer:
    def __init__(self, host: str, port: int, with_cors: bool = False, **kwargs):
        self.webserver = PathwayWebserver(host=host, port=port, with_cors=with_cors)

    def serve(
        self,
        route: str,
        schema: Type[Schema],
        handler: Callable,
        documentation: Optional[EndpointDocumentation] = None,
        methods=("POST",),
        **kwargs,
    ) -> None:
        """Wire route -> handler(queries_table) -> response writer
        (reference: servers.py:25-90)."""
        queries, writer = rest_connector(
            webserver=self.webserver,
            route=route,
            schema=schema,
            methods=methods,
            delete_completed_queries=True,
            documentation=documentation,
        )
        writer(handler(queries))

    def run(
        self,
        threaded: bool = False,
        with_cache: bool = True,
        cache_backend=None,
        terminate_on_error: bool = False,
        **kwargs,
    ):
        """Start the engine (and so the server).  threaded=True runs the
        dataflow on a daemon thread (reference: run_server(threaded=True))."""
        if threaded:
            t = threading.Thread(
                target=lambda: run_mod.run(monitoring_level=None), daemon=True
            )
            t.start()
            return t
        run_mod.run(monitoring_level=None)


class QARestServer(BaseRestServer):
    """(reference: servers.py:92) — routes for a BaseRAGQuestionAnswerer."""

    def __init__(self, host: str, port: int, rag_question_answerer, **kwargs):
        super().__init__(host, port, **kwargs)
        self.serve(
            "/v1/pw_ai_answer",
            rag_question_answerer.AnswerQuerySchema,
            rag_question_answerer.answer_query,
            EndpointDocumentation(summary="Answer a question over the live index"),
        )
        self.serve(
            "/v1/retrieve",
            rag_question_answerer.RetrieveQuerySchema,
            rag_question_answerer.retrieve,
            EndpointDocumentation(summary="Retrieve documents"),
        )
        self.serve(
            "/v1/statistics",
            rag_question_answerer.StatisticsQuerySchema,
            rag_question_answerer.statistics,
            EndpointDocumentation(summary="Indexed-document statistics"),
        )
        self.serve(
            "/v1/pw_list_documents",
            rag_question_answerer.InputsQuerySchema,
            rag_question_answerer.list_documents,
            EndpointDocumentation(summary="List indexed input documents"),
        )


class QASummaryRestServer(QARestServer):
    """(reference: servers.py:140) — adds the summarize route."""

    def __init__(self, host: str, port: int, rag_question_answerer, **kwargs):
        super().__init__(host, port, rag_question_answerer, **kwargs)
        self.serve(
            "/v1/pw_ai_summary",
            rag_question_answerer.SummarizeQuerySchema,
            rag_question_answerer.summarize_query,
            EndpointDocumentation(summary="Summarize a list of texts"),
        )


class DocumentStoreServer(BaseRestServer):
    """(reference: servers.py:193) — REST facade over a DocumentStore."""

    def __init__(self, host: str, port: int, document_store, **kwargs):
        super().__init__(host, port, **kwargs)
        self.serve(
            "/v1/retrieve",
            document_store.RetrieveQuerySchema,
            document_store.retrieve_query,
            EndpointDocumentation(summary="Retrieve documents"),
        )
        self.serve(
            "/v1/statistics",
            document_store.StatisticsQuerySchema,
            document_store.statistics_query,
            EndpointDocumentation(summary="Index statistics"),
        )
        self.serve(
            "/v1/inputs",
            document_store.InputsQuerySchema,
            document_store.inputs_query,
            EndpointDocumentation(summary="List input documents"),
        )


def serve_callable(
    route: str,
    schema: Type[Schema],
    host: str = "0.0.0.0",
    port: int = 8080,
    callable_func: Optional[Callable] = None,
    **kwargs,
):
    """Expose an ad-hoc python callable as a REST endpoint
    (reference: servers.py:227).  Use as a decorator or pass callable_func."""

    def decorate(func: Callable):
        from ...internals import udfs
        from ...internals.thisclass import this

        server = BaseRestServer(host, port, **kwargs)
        udf_obj = udfs.udf(func)

        def handler(queries):
            cols = {c: getattr(this, c) for c in schema.column_names()}
            return queries.select(result=udf_obj(**cols))

        server.serve(route, schema, handler)
        return server

    if callable_func is not None:
        return decorate(callable_func)
    return decorate
