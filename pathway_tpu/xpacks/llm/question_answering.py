"""RAG question answering
(reference: xpacks/llm/question_answering.py — BaseRAGQuestionAnswerer :314,
AdaptiveRAGQuestionAnswerer :622, answer_with_geometric_rag_strategy
:97/:162 — geometric document-count growth bounds LLM token cost)."""

from __future__ import annotations

import asyncio
import inspect
import json
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from ...internals import dtype as dt
from ...internals.expression import ApplyExpression
from ...internals.schema import Schema, column_definition
from ...internals.table import Table
from ...internals.thisclass import this
from ...robust import (
    EXTRACTIVE_ANSWER,
    RERANK_SKIPPED,
    RetryPolicy,
    breaker as robust_breaker,
    extractive_answer,
    inject,
    log_once,
    record_degraded,
    retry_call,
)

# the wrapped reranker's predict() owns its own dispatch retries when it
# is a CrossEncoderModel (the "cross_encoder.dispatch" site): one outer
# attempt keeps the "qa.rerank" breaker gate + fault site without
# multiplying attempt budgets or triple-counting breaker failures
_QA_RERANK_RETRY = RetryPolicy(attempts=1)
from .document_store import DocumentStore
from .prompts import prompt_qa, prompt_qa_geometric_rag, prompt_summarize

__all__ = [
    "BaseQuestionAnswerer",
    "BaseRAGQuestionAnswerer",
    "AdaptiveRAGQuestionAnswerer",
    "DeckRetriever",
    "RAGClient",
    "answer_with_geometric_rag_strategy",
    "answer_with_geometric_rag_strategy_from_index",
]

NO_ANSWER = "No information found."


def _call_chat(llm, prompt) -> str:
    """Call a chat UDF's underlying function synchronously — ``prompt`` is
    either a plain string or a prepared messages list (vision parsers pass
    multi-part content through here too)."""
    fn = llm.func
    messages = (
        prompt
        if isinstance(prompt, list)
        else [{"role": "user", "content": prompt}]
    )
    if inspect.iscoroutinefunction(fn):
        return str(asyncio.run(fn(messages)))
    if getattr(llm, "batched", False):
        arr = np.empty(1, dtype=object)
        arr[0] = messages
        return str(fn(arr)[0])
    return str(fn(messages))


def answer_with_geometric_rag_strategy(
    question: str,
    documents: Sequence[str],
    llm,
    n_starting_documents: int = 2,
    factor: int = 2,
    max_iterations: int = 4,
    strict_prompt: bool = False,
) -> str:
    """Ask with 2, 4, 8, ... docs until the model finds an answer
    (reference: question_answering.py:97 — the Adaptive RAG loop giving ~4x
    token-cost reduction, docs/.adaptive-rag/article.py:28)."""
    documents = list(documents)
    n = n_starting_documents
    for _ in range(max_iterations):
        docs = documents[:n]
        prompt = prompt_qa_geometric_rag(
            question, docs, information_not_found_response=NO_ANSWER
        )
        answer = _call_chat(llm, prompt)
        if answer and NO_ANSWER.lower() not in answer.lower():
            return answer
        if n >= len(documents):
            break
        n *= factor
    return NO_ANSWER


def answer_with_geometric_rag_strategy_from_index(
    question_column,
    index,
    documents_column_name: str,
    llm,
    n_starting_documents: int = 2,
    factor: int = 2,
    max_iterations: int = 4,
    **kwargs,
):
    """(reference: question_answering.py:162) — retrieve max docs once, then
    run the geometric loop per row."""
    max_docs = n_starting_documents * factor ** (max_iterations - 1)
    result = index.query_as_of_now(question_column, number_of_matches=max_docs)
    docs_table = result.select(
        _pw_question=question_column,
        _pw_docs=getattr(index.data_table, documents_column_name),
    )
    return docs_table.select(
        result=ApplyExpression(
            lambda q, docs: answer_with_geometric_rag_strategy(
                q, list(docs or ()), llm, n_starting_documents, factor, max_iterations
            ),
            dt.STR,
            args=(this._pw_question, this._pw_docs),
        )
    )


class BaseQuestionAnswerer:
    AnswerQuerySchema: type
    RetrieveQuerySchema: type
    StatisticsQuerySchema: type
    InputsQuerySchema: type


class BaseRAGQuestionAnswerer(BaseQuestionAnswerer):
    """(reference: question_answering.py:314) — answer/summarize/retrieve
    endpoints over a DocumentStore + chat model."""

    class AnswerQuerySchema(Schema):
        prompt: str
        filters: Optional[str] = column_definition(default_value=None)
        model: Optional[str] = column_definition(default_value=None)
        return_context_docs: bool = column_definition(default_value=False)

    class SummarizeQuerySchema(Schema):
        text_list: Any
        model: Optional[str] = column_definition(default_value=None)

    RetrieveQuerySchema = DocumentStore.RetrieveQuerySchema
    StatisticsQuerySchema = DocumentStore.StatisticsQuerySchema
    InputsQuerySchema = DocumentStore.InputsQuerySchema

    def __init__(
        self,
        llm,
        indexer: DocumentStore,
        *,
        default_llm_name: Optional[str] = None,
        search_topk: int = 6,
        prompt_template: Callable[[str, Sequence[str]], str] = prompt_qa,
        reranker=None,
        rerank_candidates: Optional[int] = None,
        coalesce_rerank: Optional[bool] = None,
    ):
        """``reranker`` plugs a second ranking stage between retrieval and
        the LLM prompt (the multi-stage ranking architecture from
        PAPERS.md): a ``CrossEncoderModel`` (or anything with
        ``predict(pairs) -> scores``, e.g. a sentence_transformers
        CrossEncoder) or a ``CrossEncoderReranker`` UDF.  Retrieval then
        over-fetches ``rerank_candidates`` docs (default 4x ``search_topk``)
        and the reranker's packed pair scoring keeps the best
        ``search_topk`` — the same retrieve→rerank shape the fused
        ``ops.RetrieveRerankPipeline`` serves at two device round trips.

        ``coalesce_rerank`` (default: ``PATHWAY_QA_RERANK_COALESCE`` env,
        off) routes the per-row pair scoring through a
        ``serve.SharedBatcher``: concurrent QA rows' (question, doc)
        pairs coalesce into ONE packed cross-encoder dispatch inside the
        ``PATHWAY_SERVE_COALESCE_US`` window instead of each row paying
        its own device round trip — the same continuous cross-request
        batching the serve scheduler applies to retrieval."""
        self.llm = llm
        self.indexer = indexer
        self.search_topk = search_topk
        self.prompt_template = prompt_template
        self.reranker = reranker
        # resolve the predict-capable object ONCE: a constructor-time error
        # beats an AttributeError per row deep inside the dataflow UDF
        if reranker is None:
            self._rerank_model = None
        else:
            model = (
                reranker
                if callable(getattr(reranker, "predict", None))
                else getattr(reranker, "_model", None)
            )
            if not callable(getattr(model, "predict", None)):
                raise ValueError(
                    "reranker must expose predict(pairs) -> scores (a "
                    "CrossEncoderModel, a sentence_transformers "
                    "CrossEncoder, or a CrossEncoderReranker wrapping one)"
                    f"; got {type(reranker).__name__}"
                )
            self._rerank_model = model
        # a CrossEncoderReranker carries an explicit packed= choice; honor
        # it here too, not just on its own dataflow scoring path (non-None
        # only when the wrapped model's predict takes packed)
        self._rerank_packed = getattr(reranker, "_predict_packed", None)
        # cross-request rerank coalescing: concurrent QA rows share one
        # packed cross-encoder dispatch through a SharedBatcher fronting
        # the model's submit/complete contract
        if coalesce_rerank is None:
            from ... import config

            coalesce_rerank = config.get("qa.rerank_coalesce")
        self._rerank_batcher = None
        if (
            coalesce_rerank
            and self._rerank_model is not None
            and callable(getattr(self._rerank_model, "submit", None))
        ):
            from ... import observe
            from ...serve import SharedBatcher

            model = self._rerank_model
            packed = self._rerank_packed
            if packed is None:
                submit_fn = model.submit
            else:
                def submit_fn(items, deadline=None, _m=model, _p=packed):
                    return _m.submit(items, packed=_p, deadline=deadline)

            # per-instance name: two QA answerers must not collide into
            # one Prometheus label set (duplicate samples fail the scrape)
            self._rerank_batcher = SharedBatcher(
                submit_fn, name=f"qa-rerank-{observe.next_id()}"
            )
        # without a reranker there is no second stage to over-fetch for:
        # retrieval stays at search_topk even if rerank_candidates is set
        self.rerank_candidates = (
            (rerank_candidates or 4 * search_topk)
            if reranker is not None
            else search_topk
        )
        # per-model circuit breakers (robust/retry.py), shared process-
        # wide: the "cross_encoder" breaker is the same one the fused
        # RetrieveRerankPipeline feeds, so a reranker persistently down
        # under EITHER surface fast-paths both to the rerank_skipped
        # rung; the "generator" breaker gates the LLM chat calls
        self._rerank_breaker = robust_breaker("cross_encoder")
        self._llm_breaker = robust_breaker("generator")
        self.server = None

    def _rerank_docs(
        self,
        question: str,
        docs: list,
        keep: Optional[int] = None,
        flags: Optional[list] = None,
    ) -> list:
        """Reorder retrieved doc dicts by cross-encoder pair score and keep
        the best ``keep`` (default ``search_topk``); no-op without a
        reranker.

        Degradation ladder: a reranker failure (after its retry budget,
        or an open circuit) serves the RETRIEVAL ordering instead —
        flagged through ``flags``, counted on
        ``pathway_serve_degraded_total{reason="rerank_skipped"}`` — and
        never sinks the answer."""
        if self._rerank_model is None or not docs:
            return docs
        model = self._rerank_model
        pairs = [(question, str(d.get("text", ""))) for d in docs]
        try:
            if self._rerank_batcher is not None:
                # coalesced path: this row's pairs ride a shared packed
                # dispatch with every other row in the window (a batch
                # failure re-raises here and lands on the same ladder)
                raw = retry_call(
                    "qa.rerank", self._rerank_batcher.score, pairs,
                    policy=_QA_RERANK_RETRY,
                    breaker=self._rerank_breaker,
                )
            elif self._rerank_packed is None:
                raw = retry_call(
                    "qa.rerank", model.predict, pairs,
                    policy=_QA_RERANK_RETRY,
                    breaker=self._rerank_breaker,
                )
            else:
                raw = retry_call(
                    "qa.rerank", model.predict, pairs,
                    packed=self._rerank_packed,
                    policy=_QA_RERANK_RETRY,
                    breaker=self._rerank_breaker,
                )
            scores = np.asarray(raw, dtype=np.float64)
        except Exception as exc:
            log_once(
                f"qa.rerank:{type(exc).__name__}",
                "QA reranker failed (%r); serving retrieval order flagged "
                "rerank_skipped",
                exc,
            )
            record_degraded(RERANK_SKIPPED)
            if flags is not None:
                flags.append(RERANK_SKIPPED)
            return docs[: keep or self.search_topk]
        order = np.argsort(-scores, kind="stable")[: keep or self.search_topk]
        out = []
        for j in order:
            d = dict(docs[int(j)])
            d["rerank_score"] = float(scores[int(j)])
            out.append(d)
        return out

    def _chat_or_extract(
        self, question: str, doc_texts: Sequence[str], chat, flags=None
    ) -> str:
        """Run ``chat()`` (the LLM call) under the "generator" circuit
        breaker.  Generator down / circuit open ⇒ the ladder's last
        answer-bearing rung: an extractive answer from the top retrieved
        passages, flagged + counted — the QA surface keeps answering
        with grounded text instead of erroring."""
        b = self._llm_breaker
        if b.allow():
            try:
                inject.fire("generator.chat")
                response = chat()
            except Exception as exc:
                b.record_failure()
                log_once(
                    f"generator.chat:{type(exc).__name__}",
                    "LLM chat failed (%r); answering extractively from the "
                    "retrieved passages",
                    exc,
                )
            else:
                b.record_success()
                return response
        record_degraded(EXTRACTIVE_ANSWER)
        if flags is not None:
            flags.append(EXTRACTIVE_ANSWER)
        return extractive_answer(question, list(doc_texts))

    # -- dataflow endpoints -------------------------------------------------
    def answer_query(self, queries: Table) -> Table:
        """prompt -> retrieve -> (rerank) -> build prompt -> chat -> answer."""
        topk = self.rerank_candidates
        store = self.indexer
        enriched = queries.select(
            query=this.prompt,
            k=ApplyExpression(lambda *_: topk, dt.INT, args=()),
            metadata_filter=this.filters,
            filepath_globpattern=ApplyExpression(lambda *_: None, dt.ANY, args=()),
        )
        retrieved = store.retrieve_query(enriched)
        llm = self.llm
        template = self.prompt_template
        rerank = self._rerank_docs
        chat_or_extract = self._chat_or_extract

        def answer(prompt, docs, return_docs):
            flags: list = []
            docs = rerank(prompt, list(docs or []), flags=flags)
            doc_texts = [d["text"] for d in docs]
            response = chat_or_extract(
                prompt,
                doc_texts,
                lambda: _call_chat(llm, template(prompt, doc_texts)),
                flags=flags,
            )
            if return_docs:
                out = {"response": response, "context_docs": docs}
                if flags:
                    # ladder visibility: which degraded rungs served this
                    # answer (rerank_skipped / extractive_answer)
                    out["degraded"] = flags
                return out
            return response

        combined = queries.select(
            _pw_prompt=this.prompt,
            _pw_return=this.return_context_docs,
            _pw_docs=retrieved.result,
        )
        return combined.select(
            result=ApplyExpression(
                answer, dt.ANY, args=(this._pw_prompt, this._pw_docs, this._pw_return)
            )
        )

    def summarize_query(self, queries: Table) -> Table:
        llm = self.llm

        def summarize(text_list):
            if isinstance(text_list, str):
                text_list = [text_list]
            return _call_chat(llm, prompt_summarize(list(text_list or [])))

        return queries.select(
            result=ApplyExpression(summarize, dt.STR, args=(this.text_list,))
        )

    def retrieve(self, queries: Table) -> Table:
        return self.indexer.retrieve_query(queries)

    def statistics(self, queries: Table) -> Table:
        return self.indexer.statistics_query(queries)

    def list_documents(self, queries: Table) -> Table:
        return self.indexer.inputs_query(queries)

    # -- serving ------------------------------------------------------------
    def build_server(self, host: str, port: int, **kwargs) -> None:
        """(reference: question_answering.py build_server)"""
        from .servers import QASummaryRestServer

        self.server = QASummaryRestServer(host, port, self, **kwargs)

    def run_server(self, threaded: bool = False, with_cache: bool = True, **kwargs):
        if self.server is None:
            raise RuntimeError("call build_server(host, port) first")
        return self.server.run(threaded=threaded, with_cache=with_cache, **kwargs)


class AdaptiveRAGQuestionAnswerer(BaseRAGQuestionAnswerer):
    """(reference: question_answering.py:622) — geometric context growth."""

    def __init__(
        self,
        llm,
        indexer: DocumentStore,
        *,
        n_starting_documents: int = 2,
        factor: int = 2,
        max_iterations: int = 4,
        strict_prompt: bool = False,
        **kwargs,
    ):
        super().__init__(llm, indexer, **kwargs)
        self.n_starting_documents = n_starting_documents
        self.factor = factor
        self.max_iterations = max_iterations
        self.strict_prompt = strict_prompt

    def answer_query(self, queries: Table) -> Table:
        max_docs = self.n_starting_documents * self.factor ** (
            self.max_iterations - 1
        )
        store = self.indexer
        enriched = queries.select(
            query=this.prompt,
            k=ApplyExpression(lambda *_: max_docs, dt.INT, args=()),
            metadata_filter=this.filters,
            filepath_globpattern=ApplyExpression(lambda *_: None, dt.ANY, args=()),
        )
        retrieved = store.retrieve_query(enriched)
        llm = self.llm
        n0, factor, iters = self.n_starting_documents, self.factor, self.max_iterations
        rerank = self._rerank_docs
        chat_or_extract = self._chat_or_extract

        def answer(prompt, docs):
            # rerank BEFORE the geometric loop: adaptive RAG answers from
            # the first n docs, so cross-encoder ordering directly buys
            # one-round answers (reorder only — the loop needs the full
            # candidate list to grow into)
            docs = rerank(prompt, list(docs or []), keep=len(docs or []))
            doc_texts = [d["text"] for d in docs]
            return chat_or_extract(
                prompt,
                doc_texts,
                lambda: answer_with_geometric_rag_strategy(
                    prompt, doc_texts, llm, n0, factor, iters
                ),
            )

        combined = queries.select(
            _pw_prompt=this.prompt, _pw_docs=retrieved.result
        )
        return combined.select(
            result=ApplyExpression(
                answer, dt.STR, args=(this._pw_prompt, this._pw_docs)
            )
        )


class DeckRetriever(BaseQuestionAnswerer):
    """Slide-deck search server (reference: question_answering.py:738) —
    ``answer_query`` returns the top slides for a prompt instead of an LLM
    answer; serves the same QA REST surface so clients and templates treat
    it like any question answerer."""

    excluded_response_metadata = ["b64_image", "image"]

    class AnswerQuerySchema(Schema):
        prompt: str
        filters: Optional[str] = column_definition(default_value=None)

    RetrieveQuerySchema = DocumentStore.RetrieveQuerySchema
    StatisticsQuerySchema = DocumentStore.StatisticsQuerySchema
    InputsQuerySchema = DocumentStore.InputsQuerySchema

    def __init__(self, indexer: DocumentStore, *, search_topk: int = 6):
        self.indexer = indexer
        self.search_topk = search_topk
        self.server = None

    def answer_query(self, queries: Table) -> Table:
        """Return slides matching the prompt (no LLM in the loop)."""
        topk = self.search_topk
        store = self.indexer
        enriched = queries.select(
            query=this.prompt,
            k=ApplyExpression(lambda *_: topk, dt.INT, args=()),
            metadata_filter=this.filters,
            filepath_globpattern=ApplyExpression(lambda *_: None, dt.ANY, args=()),
        )
        retrieved = store.retrieve_query(enriched)
        drop = set(self.excluded_response_metadata)

        def strip(docs):
            out = []
            for d in docs or []:
                d = dict(d)
                meta = d.get("metadata")
                if isinstance(meta, dict):
                    d["metadata"] = {
                        k: v for k, v in meta.items() if k not in drop
                    }
                out.append(d)
            return out

        return retrieved.select(
            result=ApplyExpression(strip, dt.ANY, args=(this.result,))
        )

    def retrieve(self, queries: Table) -> Table:
        return self.indexer.retrieve_query(queries)

    def statistics(self, queries: Table) -> Table:
        return self.indexer.statistics_query(queries)

    def list_documents(self, queries: Table) -> Table:
        return self.indexer.inputs_query(queries)

    def build_server(self, host: str, port: int, **kwargs) -> None:
        from .servers import QARestServer

        self.server = QARestServer(host, port, self, **kwargs)

    def run_server(self, threaded: bool = False, with_cache: bool = True, **kwargs):
        if self.server is None:
            raise RuntimeError("call build_server(host, port) first")
        return self.server.run(threaded=threaded, with_cache=with_cache, **kwargs)


class RAGClient:
    """HTTP client for the QA servers (reference: question_answering.py RAGClient)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8000, url: Optional[str] = None):
        self.url = url or f"http://{host}:{port}"

    def _post(self, route: str, payload: dict):
        import requests

        resp = requests.post(self.url + route, json=payload, timeout=120)
        resp.raise_for_status()
        return resp.json()

    def answer(self, prompt: str, filters: Optional[str] = None, **kwargs):
        return self._post(
            "/v1/pw_ai_answer", {"prompt": prompt, "filters": filters, **kwargs}
        )

    pw_ai_answer = answer

    def summarize(self, text_list: List[str], **kwargs):
        return self._post("/v1/pw_ai_summary", {"text_list": text_list, **kwargs})

    pw_ai_summary = summarize

    def retrieve(self, query: str, k: int = 3, metadata_filter=None, filepath_globpattern=None):
        return self._post(
            "/v1/retrieve",
            {
                "query": query,
                "k": k,
                "metadata_filter": metadata_filter,
                "filepath_globpattern": filepath_globpattern,
            },
        )

    def statistics(self):
        return self._post("/v1/statistics", {})

    def list_documents(self, metadata_filter=None, filepath_globpattern=None):
        return self._post(
            "/v1/pw_list_documents",
            {
                "metadata_filter": metadata_filter,
                "filepath_globpattern": filepath_globpattern,
            },
        )
