"""RAG answer-quality evaluation harness (reference:
integration_tests/rag_evals/{evaluator.py,test_eval.py,connector.py:31} —
spin up the QA app, query it over HTTP with a labeled QA set, score the
answers; the reference's headline chart is accuracy vs supporting-document
count for the adaptive strategy, docs/.adaptive-rag/article.py:85).

Fully offline design: the reference scores a remote GPT with RAGAS; this
harness instead separates WHAT the RAG loop controls (retrieval, context
growth, prompt plumbing, stop-when-answered) from raw LLM quality by using
a deterministic EXTRACTIVE reader as the chat model: given the prompt our
QA pipeline builds, it answers correctly iff the supporting fact is among
the supplied context documents, and says "No information found." otherwise.
Accuracy at n documents then measures exactly what the adaptive loop
varies — whether n documents of context contain the answer — and the
adaptive run's documents-used distribution measures its token savings, the
two numbers the reference's chart reports.

Scoring mirrors the reference's lenient comparator
(evaluator.py compare_sim_with_date): normalized exact-match OR
SequenceMatcher similarity above a threshold.
"""

from __future__ import annotations

import random
import re
from dataclasses import dataclass, field
from difflib import SequenceMatcher
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "EvalCase",
    "EvalResult",
    "ExtractiveReaderChat",
    "make_fact_corpus",
    "score_answer",
    "run_eval",
    "accuracy_vs_doc_count",
]

NO_ANSWER = "No information found."

_ENTITIES = [
    "Freedonia", "Sylvania", "Osterlich", "Marxville", "Duckburg",
    "Grandview", "Ambrosia", "Borduria", "Syldavia", "Latveria",
    "Elbonia", "Genosha", "Krakozhia", "Molvania", "Petoria",
    "Brutopia", "Glubbdubdrib", "Laputa", "Lilliput", "Blefuscu",
    "Vulgaria", "Zubrowka", "Panem", "Wadiya",
]
_ATTRIBUTES = ["capital", "currency", "anthem", "flower"]
_VALUES = {
    "capital": ["Fredville", "Sylvan City", "Osterburg", "Marxton",
                "Duckfort", "Granditon", "Ambroton", "Bordopolis"],
    "currency": ["crown", "florin", "thaler", "ducat", "guilder",
                 "mark", "peso", "dinar"],
    "anthem": ["Hail Progress", "Onward Rivers", "Golden Dawn",
               "Mountain Song", "Steel Hymn", "Harbor Call",
               "Sunrise March", "Valley Chorus"],
    "flower": ["edelweiss", "tulip", "orchid", "lotus", "poppy",
               "iris", "dahlia", "aster"],
}
_FILLER = (
    "The region is known for its rolling hills and busy markets. "
    "Travelers praise the railways and the long summer festivals. "
    "Local historians debate the founding era at great length. "
)


@dataclass
class EvalCase:
    question: str
    label: str
    file: str


@dataclass
class EvalResult:
    accuracy: float
    cases: int
    correct: int
    avg_docs_used: Optional[float] = None
    answered_with_one_doc: Optional[float] = None
    records: List[dict] = field(default_factory=list)


def make_fact_corpus(
    out_dir: str, n_docs: int = 24, seed: int = 0, distractors: bool = True
) -> List[EvalCase]:
    """Write ``n_docs`` fact documents (each planting ONE unique fact
    inside filler prose) and return the QA set asking for every fact.

    ``distractors=True`` additionally writes one decoy per entity that
    uses the SAME entity and attribute words without stating the fact —
    so top-1 retrieval is genuinely contested and the accuracy-vs-doc-
    count curve has the reference chart's growing shape instead of being
    trivially flat (docs/.adaptive-rag/article.py:85)."""
    import os

    rng = random.Random(seed)
    cases: List[EvalCase] = []
    os.makedirs(out_dir, exist_ok=True)
    for i in range(n_docs):
        entity = _ENTITIES[i % len(_ENTITIES)]
        attribute = _ATTRIBUTES[i % len(_ATTRIBUTES)]
        value = rng.choice(_VALUES[attribute])
        fname = f"doc_{i:03d}.txt"
        fact = f"The {attribute} of {entity} is {value}."
        body = (
            f"Notes on {entity}. {_FILLER}{fact} {_FILLER}"
            f"Scholars continue to study {entity} closely."
        )
        with open(os.path.join(out_dir, fname), "w") as f:
            f.write(body)
        if distractors:
            # half the decoys are lexically STRONG (outrank the fact doc
            # at top-1), half weak — so the curve starts mid-range and
            # climbs with n like the reference chart, instead of sitting
            # at either extreme
            if i % 2 == 0:
                decoy = (
                    f"Travel guide for {entity}. The {attribute} question of "
                    f"{entity} fascinates visitors; every tour of {entity} "
                    f"debates the {attribute} at length, but the {attribute} "
                    f"itself is recorded in the registry of {entity}. {_FILLER}"
                )
            else:
                decoy = (
                    f"Travel guide for {entity}. Visitors ask about the "
                    f"{attribute} of {entity}, which this guide does not "
                    f"cover. {_FILLER}The registry holds such records. "
                    f"{_FILLER}"
                )
            with open(os.path.join(out_dir, f"decoy_{i:03d}.txt"), "w") as f:
                f.write(decoy)
        cases.append(
            EvalCase(
                question=f"What is the {attribute} of {entity}?",
                label=value,
                file=fname,
            )
        )
    return cases


class ExtractiveReaderChat:
    """Deterministic reader standing in for the chat model: extracts the
    asked-for fact from the CONTEXT EMBEDDED IN THE PROMPT (the same prompt
    our QA pipeline sends a real LLM), or refuses with the configured
    no-answer phrase — which is what drives the adaptive loop to widen."""

    batched = False

    def __init__(self):
        self.calls = 0
        self.func = self._reply  # chat-UDF surface (_call_chat uses .func)

    def _reply(self, messages) -> str:
        self.calls += 1
        prompt = messages[-1]["content"] if isinstance(messages, list) else str(messages)
        if not isinstance(prompt, str):
            prompt = str(prompt)
        q = re.search(r"Question: What is the (\w+) of (\w+)\?", prompt)
        if not q:
            return NO_ANSWER
        attribute, entity = q.group(1), q.group(2)
        m = re.search(
            rf"The {re.escape(attribute)} of {re.escape(entity)} is ([^.\n]+)\.",
            prompt,
        )
        return m.group(1).strip() if m else NO_ANSWER


def _normalize(s: str) -> str:
    return "".join(c for c in s.lower() if c.isalnum())


def score_answer(pred: str, label: str, min_similarity: float = 0.68) -> bool:
    """Lenient match (reference evaluator.py compare_sim_with_date):
    normalized containment or SequenceMatcher similarity."""
    a, b = _normalize(str(pred)), _normalize(str(label))
    if not b:
        return NO_ANSWER.lower() in str(pred).lower()
    if b in a:
        return True
    return SequenceMatcher(None, a, b).ratio() > min_similarity


def run_eval(answer_fn, cases: Sequence[EvalCase]) -> EvalResult:
    """Score ``answer_fn(question) -> answer`` over the QA set."""
    records = []
    correct = 0
    for case in cases:
        pred = answer_fn(case.question)
        ok = score_answer(pred, case.label)
        correct += ok
        records.append(
            {"question": case.question, "label": case.label,
             "pred": str(pred), "correct": bool(ok)}
        )
    return EvalResult(
        accuracy=correct / max(len(cases), 1),
        cases=len(cases),
        correct=correct,
        records=records,
    )


def accuracy_vs_doc_count(
    retrieve_fn,
    llm,
    cases: Sequence[EvalCase],
    doc_counts: Sequence[int] = (1, 2, 4, 8),
) -> Dict[int, float]:
    """The reference's headline chart (docs/.adaptive-rag/article.py:85):
    answer every question with a FIXED number of context documents and
    report accuracy per count.  ``retrieve_fn(question, k) -> [doc_text]``."""
    from .prompts import prompt_qa_geometric_rag
    from .question_answering import _call_chat

    curve: Dict[int, float] = {}
    for n in doc_counts:
        correct = 0
        for case in cases:
            docs = retrieve_fn(case.question, n)
            prompt = prompt_qa_geometric_rag(
                case.question, docs, information_not_found_response=NO_ANSWER
            )
            pred = _call_chat(llm, prompt)
            correct += score_answer(pred, case.label)
        curve[n] = correct / max(len(cases), 1)
    return curve
