"""DocumentStore — live document ingestion + retrieval pipeline
(reference: xpacks/llm/document_store.py:32 DocumentStore, :286
build_pipeline, :426 retrieve_query; query schemas mirror the REST API of
the reference's DocumentStoreServer)."""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ...internals import dtype as dt
from ...internals.expression import ApplyExpression, ColumnReference
from ...internals.schema import Schema, column_definition, schema_from_dict
from ...internals.table import Table
from ...internals.thisclass import this
from ...internals.udfs import UDF
from ...stdlib.indexing.data_index import DataIndex, InnerIndex
from ...stdlib.indexing.nearest_neighbors import TpuKnnFactory
from .parsers import ParseUtf8
from .splitters import null_splitter

__all__ = ["DocumentStore", "SlidesDocumentStore"]


from ...stdlib.indexing.embedding_adapter import EmbeddingIndexAdapter


class DocumentStore:
    """Ingest documents (bytes + metadata) -> parse -> post-process -> split
    -> index; answer retrieval/statistics/inputs queries."""

    class RetrieveQuerySchema(Schema):
        query: str
        k: int = column_definition(default_value=3)
        metadata_filter: Optional[str] = column_definition(default_value=None)
        filepath_globpattern: Optional[str] = column_definition(default_value=None)

    class StatisticsQuerySchema(Schema):
        pass

    class InputsQuerySchema(Schema):
        metadata_filter: Optional[str] = column_definition(default_value=None)
        filepath_globpattern: Optional[str] = column_definition(default_value=None)

    def __init__(
        self,
        docs: Union[Table, Sequence[Table]],
        retriever_factory=None,
        parser: Optional[UDF] = None,
        splitter: Optional[UDF] = None,
        doc_post_processors: Optional[Sequence[Callable[[str, dict], Tuple[str, dict]]]] = None,
        embedder: Optional[UDF] = None,
        dimensions: Optional[int] = None,
    ):
        if isinstance(docs, Table):
            docs_list = [docs]
        else:
            docs_list = list(docs)
        self.docs = docs_list[0] if len(docs_list) == 1 else docs_list[0].concat_reindex(*docs_list[1:])
        self.parser = parser or ParseUtf8()
        self.splitter = splitter
        self.doc_post_processors = list(doc_post_processors or [])
        if retriever_factory is None:
            from .embedders import TpuEmbedder

            embedder = embedder or TpuEmbedder()
            retriever_factory = TpuKnnFactory(
                dimension=embedder.get_embedding_dimension(), embedder=embedder
            )
        self.retriever_factory = retriever_factory
        self.embedder = embedder or getattr(retriever_factory, "embedder", None)
        self.dimensions = dimensions or getattr(retriever_factory, "dimension", None)
        self.build_pipeline()

    # ------------------------------------------------------------------
    def build_pipeline(self) -> None:
        """(reference: document_store.py:286)"""
        docs = self.docs
        # normalise input columns: data + _metadata
        cols = docs.column_names
        data_col = "data" if "data" in cols else cols[0]
        has_meta = "_metadata" in cols

        parser = self.parser
        post = list(self.doc_post_processors)
        splitter = self.splitter

        def full_parse(data, meta):
            base_meta = dict(meta) if isinstance(meta, dict) else {}
            chunks = parser.func(data)
            out = []
            for text, cmeta in chunks:
                merged = {**base_meta, **(cmeta or {})}
                for proc in post:
                    text, merged = proc(text, merged)
                if splitter is not None:
                    for stext, smeta in splitter.func(text):
                        out.append((stext, {**merged, **(smeta or {})}))
                else:
                    out.append((text, merged))
            return tuple(out)

        meta_expr = (
            ColumnReference(docs, "_metadata")
            if has_meta
            else ApplyExpression(lambda d: {}, dt.JSON, args=(ColumnReference(docs, data_col),))
        )
        parsed = docs.select(
            _pw_chunks=ApplyExpression(
                full_parse,
                dt.ANY,
                args=(ColumnReference(docs, data_col), meta_expr),
            )
        ).flatten(this._pw_chunks)
        chunks = parsed.select(
            text=ApplyExpression(lambda c: c[0], dt.STR, args=(this._pw_chunks,)),
            metadata=ApplyExpression(lambda c: c[1], dt.JSON, args=(this._pw_chunks,)),
        )
        self.parsed_docs = chunks

        factory_embedder = getattr(self.retriever_factory, "embedder", None)
        embedder = factory_embedder or self.embedder
        factory = self.retriever_factory
        if embedder is not None and factory_embedder is None:
            # factories carrying their own embedder already wrap themselves
            # (stdlib/indexing/nearest_neighbors.py build_inner_index)
            base_factory = factory

            class _WrappedFactory:
                def build_inner_index(self, dimension=None):
                    dim = dimension or getattr(base_factory, "dimension", None)
                    if dim is None:
                        dim = embedder.get_embedding_dimension()
                    inner = base_factory.build_inner_index(dim)
                    return EmbeddingIndexAdapter(inner, embedder)

            factory = _WrappedFactory()
        if embedder is not None:
            dim = getattr(self.retriever_factory, "dimension", None) or (
                embedder.get_embedding_dimension()
            )
        else:
            dim = self.dimensions
        self.index = DataIndex(
            chunks,
            InnerIndex(
                data_column=chunks.text,
                metadata_column=chunks.metadata,
                factory=factory,
                dimension=dim,
            ),
        )

    # ------------------------------------------------------------------
    @staticmethod
    def merge_filters(metadata_filter, globpattern) -> Optional[str]:
        """Combine a metadata filter with a path glob (reference:
        document_store.py filter merging)."""
        parts = []
        if metadata_filter:
            parts.append(f"({metadata_filter})")
        if globpattern:
            parts.append(f"globmatch('{globpattern}', path)")
        return " && ".join(parts) if parts else None

    def retrieve_query(self, queries: Table) -> Table:
        """(reference: document_store.py:426) — returns a ``result`` column
        with a list of {text, metadata, dist} dicts per query."""
        merged = queries.select(
            query=this.query,
            k=this.k,
            _pw_filter=ApplyExpression(
                DocumentStore.merge_filters,
                dt.ANY,
                args=(this.metadata_filter, this.filepath_globpattern),
            ),
        )
        result = self.index.query_as_of_now(
            merged.query,
            number_of_matches=merged.k,
            metadata_filter=merged._pw_filter,
        )
        chunks = self.parsed_docs
        docs_out = result.select(
            _pw_texts=chunks.text,
            _pw_metas=chunks.metadata,
            _pw_scores=result.score,
        )

        def pack(texts, metas, scores):
            out = []
            for t, m, s in zip(texts or (), metas or (), scores or ()):
                out.append({"text": t, "metadata": m, "dist": -float(s)})
            return out

        return docs_out.select(
            result=ApplyExpression(
                pack, dt.JSON, args=(this._pw_texts, this._pw_metas, this._pw_scores)
            )
        )

    def statistics_query(self, info_queries: Table) -> Table:
        """(reference: document_store.py statistics endpoint)"""
        chunks_store = self.parsed_docs._engine_table.store
        meta_idx = self.parsed_docs._engine_table.column_names.index(
            self.parsed_docs._column_mapping["metadata"]
        )

        def stats(*_args):
            count = 0
            last_modified = None
            last_indexed = None
            for _key, row in chunks_store.items():
                count += 1
                md = row[meta_idx] or {}
                if isinstance(md, dict):
                    m = md.get("modified_at")
                    if m is not None:
                        last_modified = max(last_modified or 0, m)
                    s = md.get("seen_at")
                    if s is not None:
                        last_indexed = max(last_indexed or 0, s)
            return {
                "file_count": count,
                "last_modified": last_modified,
                "last_indexed": last_indexed,
            }

        return info_queries.select(result=ApplyExpression(stats, dt.JSON, args=()))

    def inputs_query(self, input_queries: Table) -> Table:
        """(reference: document_store.py inputs endpoint)"""
        from ...stdlib.indexing.filters import compile_filter

        chunks_store = self.parsed_docs._engine_table.store
        meta_idx = self.parsed_docs._engine_table.column_names.index(
            self.parsed_docs._column_mapping["metadata"]
        )

        def inputs(metadata_filter, globpattern):
            combined = DocumentStore.merge_filters(metadata_filter, globpattern)
            accept = compile_filter(combined) if combined else None
            seen = {}
            for _key, row in chunks_store.items():
                md = row[meta_idx] or {}
                if not isinstance(md, dict):
                    continue
                if accept is not None and not accept(md):
                    continue
                path = md.get("path", "<memory>")
                seen[path] = {
                    "path": path,
                    "modified_at": md.get("modified_at"),
                    "seen_at": md.get("seen_at"),
                }
            return list(seen.values())

        return input_queries.select(
            result=ApplyExpression(
                inputs, dt.JSON, args=(this.metadata_filter, this.filepath_globpattern)
            )
        )

    @property
    def index_table(self) -> Table:
        return self.parsed_docs


class SlidesDocumentStore(DocumentStore):
    """(reference: document_store.py SlidesDocumentStore variant)"""
