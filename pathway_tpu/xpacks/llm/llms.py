"""Chat model UDFs (reference: xpacks/llm/llms.py:84-544 — OpenAIChat,
LiteLLMChat, HFPipelineChat, CohereChat; capacity/retry/cache via
udfs.async_options)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

from ...internals.udfs import UDF

__all__ = [
    "BaseChat",
    "OpenAIChat",
    "LiteLLMChat",
    "CohereChat",
    "HFPipelineChat",
    "TpuChat",
    "prompt_chat_single_qa",
]

Message = Dict[str, str]


def _messages_to_prompt(messages: Union[str, Sequence[Message]]) -> str:
    if isinstance(messages, str):
        return messages
    parts = []
    for m in messages:
        role = m.get("role", "user")
        parts.append(f"{role}: {m.get('content', '')}")
    return "\n".join(parts)


def prompt_chat_single_qa(question: str) -> List[Message]:
    """(reference: llms.py prompt_chat_single_qa helper)"""
    return [{"role": "user", "content": str(question)}]


class BaseChat(UDF):
    """Chat UDFs accept a message list (or plain string) per row and return
    the model answer."""

    model: Optional[str] = None

    def _accepts_call_arg(self, name: str) -> bool:
        return True


class TpuChat(BaseChat):
    """Local generation on the flax causal LM (batched decode under one jit)
    — the TPU-native slot for the reference's HFPipelineChat.

    ``continuous=True`` (or ``PATHWAY_CHAT_CONTINUOUS=1``) routes every
    prompt through the shared :class:`~pathway_tpu.serve.ContinuousDecoder`
    slot pool instead of call-granular decode: concurrent chat rows —
    and anything else submitted to the same engine, e.g. the cascade's
    listwise LLM rerank prompts — share one token-level step loop, with
    per-prompt EOS leave freeing slots mid-flight.  Tokens are identical
    either way (the engine is solo-``generate``-token-identical per
    request)."""

    def __init__(
        self,
        model: str = "pathway-mini-lm",
        max_new_tokens: int = 48,
        temperature: float = 0.0,
        checkpoint_path: Optional[str] = None,
        generator=None,
        continuous: Optional[bool] = None,
        decoder=None,
        **kwargs,
    ):
        import os

        from ...models.generator import TextGenerator

        self.model = model
        self._generator = generator or TextGenerator(
            model=model, checkpoint_path=checkpoint_path
        )
        gen = self._generator
        if continuous is None:
            from ... import config

            continuous = config.get("chat.continuous")
        self._decoder = decoder
        if decoder is None and continuous:
            from ...serve import ContinuousDecoder

            self._decoder = ContinuousDecoder(gen)
        engine = self._decoder

        def chat(messages) -> str:
            prompts = [_messages_to_prompt(m) for m in messages]
            import numpy as np

            if engine is not None:
                # submit-then-gather: every row joins the shared slot
                # pool, so concurrent micro-batches coalesce at token
                # granularity instead of serializing whole decodes
                tickets = [
                    engine.submit(
                        p,
                        max_new_tokens=max_new_tokens,
                        temperature=temperature,
                    )
                    for p in prompts
                ]
                results = [t() for t in tickets]
                for r in results:
                    if getattr(r, "degraded", ()) and not str(r):
                        # an empty degraded decode (generator down at
                        # prefill) must surface as a chat FAILURE so the
                        # QA layer's extractive_answer rung takes over;
                        # partial flagged results still serve their text
                        raise RuntimeError(
                            "continuous decode degraded: "
                            + ",".join(r.degraded)
                        )
                outs = [str(r) for r in results]
            else:
                outs = gen.generate(
                    prompts,
                    max_new_tokens=max_new_tokens,
                    temperature=temperature,
                )
            return np.array(outs, dtype=object)

        super().__init__(chat, batched=True, **kwargs)


class HFPipelineChat(BaseChat):
    """Local transformers pipeline (reference: llms.py:441).  Works when the
    model files exist locally; batched over the micro-batch."""

    def __init__(
        self,
        model: Optional[str] = None,
        call_kwargs: dict | None = None,
        device: str = "cpu",
        **pipeline_kwargs,
    ):
        self.model = model
        call_kwargs = call_kwargs or {}
        import transformers

        pipe = transformers.pipeline(
            "text-generation", model=model, device=device, **pipeline_kwargs
        )

        def chat(messages) -> Any:
            import numpy as np

            prompts = [_messages_to_prompt(m) for m in messages]
            outs = pipe(prompts, **call_kwargs)
            texts = []
            for out in outs:
                if isinstance(out, list):
                    out = out[0]
                texts.append(out.get("generated_text", ""))
            return np.array(texts, dtype=object)

        super().__init__(chat, batched=True)

    def crop_to_max_context_size(self, text: str) -> str:
        return text


class _ApiChat(BaseChat):
    def __init__(
        self,
        model: Optional[str] = None,
        capacity: Optional[int] = None,
        retry_strategy=None,
        cache_strategy=None,
        temperature: float = 0.0,
        max_tokens: Optional[int] = None,
        **call_kwargs,
    ):
        self.model = model
        self.temperature = temperature
        self.max_tokens = max_tokens
        self.call_kwargs = call_kwargs
        super().__init__(
            self._make_chat_fn(),
            executor="async",
            capacity=capacity,
            retry_strategy=retry_strategy,
            cache_strategy=cache_strategy,
        )

    def _make_chat_fn(self):
        raise NotImplementedError


class OpenAIChat(_ApiChat):
    """(reference: llms.py:84)"""

    def __init__(self, model: str = "gpt-4o-mini", **kwargs):
        super().__init__(model=model, **kwargs)

    def _make_chat_fn(self):
        async def chat(messages, **kw):
            try:
                import openai
            except ImportError as e:
                raise ImportError("OpenAIChat requires the `openai` package") from e
            client = openai.AsyncOpenAI()
            if isinstance(messages, str):
                messages = prompt_chat_single_qa(messages)
            response = await client.chat.completions.create(
                model=kw.pop("model", self.model),
                messages=list(messages),
                temperature=self.temperature,
                **{**self.call_kwargs, **kw},
            )
            return response.choices[0].message.content

        return chat


class LiteLLMChat(_ApiChat):
    """(reference: llms.py:313)"""

    def _make_chat_fn(self):
        async def chat(messages, **kw):
            try:
                import litellm
            except ImportError as e:
                raise ImportError("LiteLLMChat requires the `litellm` package") from e
            if isinstance(messages, str):
                messages = prompt_chat_single_qa(messages)
            response = await litellm.acompletion(
                model=kw.pop("model", self.model),
                messages=list(messages),
                **{**self.call_kwargs, **kw},
            )
            return response.choices[0].message.content

        return chat


class CohereChat(_ApiChat):
    """(reference: llms.py:544)"""

    def _make_chat_fn(self):
        async def chat(messages, **kw):
            try:
                import cohere
            except ImportError as e:
                raise ImportError("CohereChat requires the `cohere` package") from e
            client = cohere.AsyncClient()
            prompt = _messages_to_prompt(messages)
            response = await client.chat(
                message=prompt, model=kw.pop("model", self.model) or "command-r"
            )
            return response.text

        return chat
