"""VectorStoreServer — self-contained docs->parse->split->embed->KNN server
(reference: xpacks/llm/vector_store.py:38-747 — VectorStoreServer with
/v1/retrieve, /v1/statistics, /v1/inputs endpoints, VectorStoreClient,
Langchain/LlamaIndex adapters)."""

from __future__ import annotations

import threading
from typing import Callable, List, Optional, Sequence, Union

from ...internals.table import Table
from ...stdlib.indexing.nearest_neighbors import TpuKnnFactory
from .document_store import DocumentStore
from .servers import DocumentStoreServer

__all__ = ["VectorStoreServer", "VectorStoreClient"]


class VectorStoreServer:
    def __init__(
        self,
        *docs: Table,
        embedder=None,
        parser=None,
        splitter=None,
        doc_post_processors=None,
        index_factory=None,
    ):
        if embedder is None and index_factory is None:
            from .embedders import TpuEmbedder

            embedder = TpuEmbedder()
        if index_factory is None:
            index_factory = TpuKnnFactory(
                dimension=embedder.get_embedding_dimension(), embedder=embedder
            )
        self.document_store = DocumentStore(
            list(docs),
            retriever_factory=index_factory,
            parser=parser,
            splitter=splitter,
            doc_post_processors=doc_post_processors,
            embedder=embedder,
        )
        self._server: Optional[DocumentStoreServer] = None

    @classmethod
    def from_langchain_components(
        cls, *docs, embedder=None, splitter=None, **kwargs
    ) -> "VectorStoreServer":
        """(reference: vector_store.py:418) — langchain embeddings/splitters."""
        parser = None
        sp = None
        if splitter is not None:
            from ...internals.udfs import UDF

            sp = UDF(lambda text: [(chunk, {}) for chunk in splitter.split_text(text)])
        emb = None
        if embedder is not None:
            import numpy as np

            from .embedders import BaseEmbedder

            class _LCEmbedder(BaseEmbedder):
                def __init__(self):
                    def embed(texts):
                        vectors = embedder.embed_documents([str(t) for t in texts])
                        return np.asarray(vectors, dtype=np.float32)

                    super().__init__(embed, batched=True)

            emb = _LCEmbedder()
        return cls(*docs, embedder=emb, parser=parser, splitter=sp, **kwargs)

    @classmethod
    def from_llamaindex_components(cls, *docs, transformations=None, **kwargs):
        """(reference: vector_store.py:456)"""
        raise NotImplementedError(
            "llamaindex adapter: wrap your embed_model as a batched UDF and "
            "pass it as `embedder`"
        )

    def run_server(
        self,
        host: str = "0.0.0.0",
        port: int = 8000,
        threaded: bool = False,
        with_cache: bool = True,
        cache_backend=None,
        **kwargs,
    ):
        """(reference: vector_store.py:629)"""
        self._server = DocumentStoreServer(host, port, self.document_store)
        return self._server.run(threaded=threaded, with_cache=with_cache, **kwargs)


class VectorStoreClient:
    """(reference: vector_store.py client class)"""

    def __init__(
        self,
        host: Optional[str] = None,
        port: Optional[int] = None,
        url: Optional[str] = None,
        timeout: int = 60,
    ):
        self.url = url or f"http://{host or '127.0.0.1'}:{port or 8000}"
        self.timeout = timeout

    def query(
        self,
        query: str,
        k: int = 3,
        metadata_filter: Optional[str] = None,
        filepath_globpattern: Optional[str] = None,
    ) -> List[dict]:
        import requests

        resp = requests.post(
            self.url + "/v1/retrieve",
            json={
                "query": query,
                "k": k,
                "metadata_filter": metadata_filter,
                "filepath_globpattern": filepath_globpattern,
            },
            timeout=self.timeout,
        )
        resp.raise_for_status()
        return resp.json()

    __call__ = query

    def get_vectorstore_statistics(self) -> dict:
        import requests

        resp = requests.post(self.url + "/v1/statistics", json={}, timeout=self.timeout)
        resp.raise_for_status()
        return resp.json()

    def get_input_files(self, metadata_filter=None, filepath_globpattern=None):
        import requests

        resp = requests.post(
            self.url + "/v1/inputs",
            json={
                "metadata_filter": metadata_filter,
                "filepath_globpattern": filepath_globpattern,
            },
            timeout=self.timeout,
        )
        resp.raise_for_status()
        return resp.json()
