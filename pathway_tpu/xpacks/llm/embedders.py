"""Embedder UDFs (reference: xpacks/llm/embedders.py:85-330 — OpenAIEmbedder,
LiteLLMEmbedder, SentenceTransformerEmbedder, GeminiEmbedder; dimension
probed by embedding ".", vector_store.py:86).

TPU-first change: local embedders are *batched by construction* — one jitted
flax forward per engine micro-batch (the reference encodes one string at a
time, embedders.py:315-327)."""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from ...internals import udfs
from ...internals.udfs import UDF

__all__ = [
    "BaseEmbedder",
    "SentenceTransformerEmbedder",
    "TpuEmbedder",
    "OpenAIEmbedder",
    "LiteLLMEmbedder",
    "GeminiEmbedder",
    "ClipTextEmbedder",
    "ClipImageEmbedder",
]


class BaseEmbedder(UDF):
    def get_embedding_dimension(self, **kwargs) -> int:
        """Probe output dimension by embedding "." (reference vector_store.py:86)."""
        result = self.func(np.array(["."], dtype=object), **kwargs)
        return int(np.asarray(result).shape[-1])


class TpuEmbedder(BaseEmbedder):
    """Batched on-device embedder over the flax SentenceEncoder."""

    def __init__(
        self,
        model: str = "pathway-mini",
        dimension: int = 384,
        n_layers: int = 6,
        max_length: int = 128,
        checkpoint_path: Optional[str] = None,
        mesh=None,
        call_kwargs: dict | None = None,
        packed: bool = True,
        **kwargs,
    ):
        from ...models.encoder import SentenceEncoder

        self._encoder = SentenceEncoder(
            model=model,
            dimension=dimension,
            n_layers=n_layers,
            max_length=max_length,
            checkpoint_path=checkpoint_path,
            mesh=mesh,
        )
        encoder = self._encoder

        if packed and mesh is None:
            # sequence packing: short docs share rows under block-diagonal
            # attention (models/encoder.py) — same embeddings, much better
            # MXU utilization on variable-length micro-batches.  Packing
            # reshapes rows, so the mesh-sharded path keeps plain batches.
            def embed(texts) -> np.ndarray:
                out = encoder.encode_packed_to_device(list(texts))
                return np.asarray(out, dtype=np.float32)  # pathway: allow(value-flow): the embedder xpack's contract IS a host ndarray — a deliberate synchronous fetch on the ingest/UDF path, never inside a serve stage

        else:

            def embed(texts) -> np.ndarray:
                return encoder.encode(list(texts))

        super().__init__(embed, batched=True, **kwargs)

    def get_embedding_dimension(self, **kwargs) -> int:
        return self._encoder.get_embedding_dimension()


class SentenceTransformerEmbedder(BaseEmbedder):
    """Local sentence embedder (reference: embedders.py:270).

    If ``model`` is a local sentence_transformers checkpoint directory it is
    used (batched ``model.encode`` on the whole micro-batch — already an
    upgrade over the reference's per-row call); otherwise falls back to the
    TPU-native flax encoder with the given output dimension."""

    def __init__(
        self,
        model: str = "pathway-mini",
        call_kwargs: dict | None = None,
        device: str = "tpu",
        dimension: int = 384,
        **init_kwargs,
    ):
        import os

        self.model_name = model
        call_kwargs = call_kwargs or {}
        if os.path.isdir(model):
            from sentence_transformers import SentenceTransformer

            st_model = SentenceTransformer(model, **init_kwargs)
            self._dimension = int(st_model.get_sentence_embedding_dimension())

            def embed(texts) -> np.ndarray:
                return np.asarray(  # pathway: allow(value-flow): SentenceTransformer is a HOST-side model — its .encode matches the device-producer spelling but returns numpy rows; no device crossing exists here
                    st_model.encode(list(texts), **call_kwargs), dtype=np.float32
                )

        else:
            from ...models.encoder import SentenceEncoder

            encoder = SentenceEncoder(model=model, dimension=dimension)
            self._dimension = encoder.get_embedding_dimension()

            def embed(texts) -> np.ndarray:
                return encoder.encode(list(texts))

        super().__init__(embed, batched=True)

    def get_embedding_dimension(self, **kwargs) -> int:
        return self._dimension


class _ApiEmbedder(BaseEmbedder):
    """Async API embedders (capacity/retry/cache via udfs.async_options)."""

    _import_error = "this embedder's client library is not installed"

    def __init__(
        self,
        capacity: Optional[int] = None,
        retry_strategy=None,
        cache_strategy=None,
        model: Optional[str] = None,
        **call_kwargs,
    ):
        self.model = model
        self.call_kwargs = call_kwargs
        embed = self._make_embed_fn()
        super().__init__(
            embed,
            executor="async",
            capacity=capacity,
            retry_strategy=retry_strategy,
            cache_strategy=cache_strategy,
        )

    def _make_embed_fn(self) -> Callable:
        raise NotImplementedError

    def get_embedding_dimension(self, **kwargs) -> int:
        import asyncio

        return int(
            np.asarray(asyncio.run(self.func(".", **kwargs))).shape[-1]
        )


class OpenAIEmbedder(_ApiEmbedder):
    """(reference: embedders.py:85 — async OpenAI embeddings API)"""

    def __init__(self, model: str = "text-embedding-3-small", **kwargs):
        super().__init__(model=model, **kwargs)

    def _make_embed_fn(self):
        model = self.model if hasattr(self, "model") else None
        call_kwargs = getattr(self, "call_kwargs", {})

        async def embed(text: str, **kw):
            try:
                import openai
            except ImportError as e:
                raise ImportError(
                    "OpenAIEmbedder requires the `openai` package"
                ) from e
            client = openai.AsyncOpenAI()
            response = await client.embeddings.create(
                input=[text or "."], model=self.model, **{**call_kwargs, **kw}
            )
            return np.array(response.data[0].embedding, dtype=np.float32)

        return embed


class LiteLLMEmbedder(_ApiEmbedder):
    """(reference: embedders.py:180)"""

    def __init__(self, model: str = "text-embedding-3-small", **kwargs):
        super().__init__(model=model, **kwargs)

    def _make_embed_fn(self):
        call_kwargs = getattr(self, "call_kwargs", {})

        async def embed(text: str, **kw):
            try:
                import litellm
            except ImportError as e:
                raise ImportError(
                    "LiteLLMEmbedder requires the `litellm` package"
                ) from e
            response = await litellm.aembedding(
                input=[text or "."], model=self.model, **{**call_kwargs, **kw}
            )
            return np.array(response.data[0]["embedding"], dtype=np.float32)

        return embed


class GeminiEmbedder(_ApiEmbedder):
    """(reference: embedders.py:330)"""

    def __init__(self, model: str = "models/embedding-001", **kwargs):
        super().__init__(model=model, **kwargs)

    def _make_embed_fn(self):
        async def embed(text: str, **kw):
            try:
                import google.generativeai as genai
            except ImportError as e:
                raise ImportError(
                    "GeminiEmbedder requires `google-generativeai`"
                ) from e
            result = genai.embed_content(model=self.model, content=text or ".")
            return np.array(result["embedding"], dtype=np.float32)

        return embed


class ClipTextEmbedder(BaseEmbedder):
    """Text side of the multimodal CLIP embedder (BASELINE config 3)."""

    def __init__(self, clip_model=None, **kwargs):
        from ...models.clip import ClipModel

        self._model = clip_model or ClipModel()
        model = self._model

        def embed(texts) -> np.ndarray:
            return model.encode_text(list(texts))

        super().__init__(embed, batched=True, **kwargs)

    def get_embedding_dimension(self, **kwargs) -> int:
        return self._model.get_embedding_dimension()


class ClipImageEmbedder(BaseEmbedder):
    """Image side: embeds ndarray image columns."""

    def __init__(self, clip_model=None, **kwargs):
        from ...models.clip import ClipModel

        self._model = clip_model or ClipModel()
        model = self._model

        def embed(images) -> np.ndarray:
            return model.encode_image(list(images))

        super().__init__(embed, batched=True, **kwargs)

    def get_embedding_dimension(self, **kwargs) -> int:
        return self._model.get_embedding_dimension()
