"""Prompt templates (reference: xpacks/llm/prompts.py:447)."""

from __future__ import annotations

from typing import List, Sequence

__all__ = [
    "prompt_qa",
    "prompt_qa_geometric_rag",
    "prompt_summarize",
    "prompt_short_qa",
]


def prompt_qa(
    query: str,
    docs: Sequence[str],
    information_not_found_response: str = "No information found.",
) -> str:
    context = "\n\n".join(str(d) for d in docs)
    return (
        "Use the below context documents to answer the question. If the "
        f"answer is not in the documents, reply exactly: "
        f"{information_not_found_response}\n\n"
        f"Context:\n{context}\n\nQuestion: {query}\nAnswer:"
    )


def prompt_qa_geometric_rag(
    query: str,
    docs: Sequence[str],
    information_not_found_response: str = "No information found.",
) -> str:
    """(reference: the adaptive-RAG prompt used by
    answer_with_geometric_rag_strategy, question_answering.py:97)"""
    context = "\n\n".join(f"Source {i + 1}: {d}" for i, d in enumerate(docs))
    return (
        "Answer the question based ONLY on the sources below. Keep the "
        "answer short. If the sources do not contain the answer, reply "
        f"exactly: {information_not_found_response}\n\n"
        f"{context}\n\nQuestion: {query}\nAnswer:"
    )


def prompt_summarize(texts: Sequence[str]) -> str:
    joined = "\n\n".join(str(t) for t in texts)
    return f"Summarize the following texts concisely:\n\n{joined}\n\nSummary:"


def prompt_short_qa(query: str, docs: Sequence[str]) -> str:
    context = " ".join(str(d) for d in docs)
    return f"Context: {context}\nQ: {query}\nA (one sentence):"
