"""pw.xpacks.llm — the LLM/RAG extension pack
(reference inventory: python/pathway/xpacks/llm/ — SURVEY.md §2.10)."""

from . import (
    embedders,
    llms,
    parsers,
    prompts,
    rerankers,
    servers,
    splitters,
)
from .document_store import DocumentStore
from .vector_store import VectorStoreClient, VectorStoreServer

__all__ = [
    "embedders",
    "llms",
    "parsers",
    "prompts",
    "rerankers",
    "servers",
    "splitters",
    "DocumentStore",
    "VectorStoreServer",
    "VectorStoreClient",
]
