"""Rerankers (reference: xpacks/llm/rerankers.py:15-345 — rerank_topk_filter,
LLMReranker, CrossEncoderReranker, EncoderReranker, FlashRankReranker)."""

from __future__ import annotations

import re
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from ...internals import dtype as dt
from ...internals.expression import ApplyExpression
from ...internals.udfs import UDF

__all__ = [
    "rerank_topk_filter",
    "LLMReranker",
    "CrossEncoderReranker",
    "EncoderReranker",
    "FlashRankReranker",
]


def rerank_topk_filter(docs, scores, k: int = 5):
    """Keep the k best docs by score (reference: rerankers.py:15) — expression
    over (docs tuple, scores tuple) columns."""

    def topk(doc_list, score_list):
        if doc_list is None:
            return ()
        pairs = sorted(
            zip(doc_list, score_list), key=lambda p: -float(p[1])
        )[:k]
        return tuple(d for d, _ in pairs), tuple(float(s) for _, s in pairs)

    return ApplyExpression(topk, dt.ANY, args=(docs, scores))


class CrossEncoderReranker(UDF):
    """Pair scoring with the on-device cross-encoder (reference:
    rerankers.py:186 uses sentence_transformers CrossEncoder per row; here the
    whole micro-batch of (query, doc) pairs is one jitted forward).

    The in-framework model scores with SEQUENCE PACKING by default
    (models/cross_encoder.py): short (query, doc) pairs share rows under
    block-diagonal segment attention instead of each padding to
    ``max_length``, so a dataflow micro-batch of short pairs costs a
    fraction of the MXU work.  For the fused two-dispatch serving path see
    ``ops.RetrieveRerankPipeline``, which chains retrieval and this model's
    packed rescoring with one round trip per stage."""

    def __init__(
        self,
        model_name: str = "pathway-mini-cross",
        checkpoint_path: Optional[str] = None,
        cross_encoder=None,
        packed: Optional[bool] = None,
        **kwargs,
    ):
        import os

        if cross_encoder is not None:
            self._model = cross_encoder
        elif os.path.isdir(model_name):
            from sentence_transformers import CrossEncoder

            st = CrossEncoder(model_name)
            self._model = st
        else:
            from ...models.cross_encoder import CrossEncoderModel

            self._model = CrossEncoderModel(
                model=model_name, checkpoint_path=checkpoint_path
            )

        model = self._model
        # capability check ONCE at construction (a per-batch
        # except-TypeError probe would mask genuine TypeErrors from inside
        # the packed scoring path and silently rescore the batch)
        import inspect

        try:
            takes_packed = "packed" in inspect.signature(model.predict).parameters
        except (TypeError, ValueError):  # builtins / C-impl predict
            takes_packed = False
        # consumers that unwrap ._model and call predict themselves (e.g.
        # BaseRAGQuestionAnswerer(reranker=...)) must honor an explicit
        # packed= choice; None when the model's predict doesn't take it
        self._predict_packed = packed if takes_packed else None

        def score(docs, queries) -> np.ndarray:
            pairs = [(str(q), str(d)) for q, d in zip(queries, docs)]
            if takes_packed:
                scores = model.predict(pairs, packed=packed)
            else:  # sentence_transformers CrossEncoder
                scores = model.predict(pairs)
            return np.asarray(scores, dtype=np.float64)

        super().__init__(score, batched=True, **kwargs)


class EncoderReranker(UDF):
    """Embedding dot-product reranker (reference: rerankers.py:251)."""

    def __init__(self, embedder, **kwargs):
        self._embedder = embedder

        def score(docs, queries) -> np.ndarray:
            texts = [str(d) for d in docs] + [str(q) for q in queries]
            embs = embedder.func(np.array(texts, dtype=object))
            embs = np.asarray([np.asarray(e) for e in embs])
            n = len(docs)
            de, qe = embs[:n], embs[n:]
            de = de / np.maximum(np.linalg.norm(de, axis=1, keepdims=True), 1e-9)
            qe = qe / np.maximum(np.linalg.norm(qe, axis=1, keepdims=True), 1e-9)
            return np.sum(de * qe, axis=1).astype(np.float64)

        super().__init__(score, batched=True, **kwargs)


class LLMReranker(UDF):
    """LLM scores each (doc, query) 1-5 (reference: rerankers.py:58)."""

    def __init__(self, llm, *, retry_strategy=None, cache_strategy=None, **kwargs):
        self.llm = llm
        chat_fn = llm.func

        def score(doc: str, query: str) -> float:
            prompt = (
                "Given a query and a document snippet, rate on an integer "
                "scale of 1 to 5 how relevant the document is to the query. "
                "Answer with ONLY the number.\n"
                f"Query: {query}\nDocument: {doc}\nScore:"
            )
            import asyncio
            import inspect

            if inspect.iscoroutinefunction(chat_fn):
                answer = asyncio.run(chat_fn([{"role": "user", "content": prompt}]))
            else:
                result = chat_fn(np.array([[{"role": "user", "content": prompt}]], dtype=object))
                answer = result[0] if hasattr(result, "__getitem__") else result
            m = re.search(r"[1-5]", str(answer))
            return float(m.group(0)) if m else 1.0

        super().__init__(
            score, retry_strategy=retry_strategy, cache_strategy=cache_strategy, **kwargs
        )


class FlashRankReranker(UDF):
    """(reference: rerankers.py:319 — flashrank library; gated)"""

    def __init__(self, model: str = "ms-marco-TinyBERT-L-2-v2", **kwargs):
        try:
            from flashrank import Ranker, RerankRequest
        except ImportError as e:
            raise ImportError(
                "FlashRankReranker requires the `flashrank` package; use "
                "CrossEncoderReranker for the on-device equivalent"
            ) from e
        ranker = Ranker(model_name=model)

        def score(doc: str, query: str) -> float:
            from flashrank import RerankRequest

            req = RerankRequest(query=str(query), passages=[{"text": str(doc)}])
            return float(ranker.rerank(req)[0]["score"])

        super().__init__(score, **kwargs)
