"""Document parsers (reference: xpacks/llm/parsers.py:53-928 — ParseUtf8,
ParseUnstructured, OpenParse, ImageParser, SlideParser, PypdfParser).
Parsers map raw bytes -> list[(text, metadata)]."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ...internals.udfs import UDF

__all__ = [
    "ParseUtf8",
    "Utf8Parser",
    "ParseUnstructured",
    "UnstructuredParser",
    "PypdfParser",
    "ImageParser",
    "SlideParser",
    "ParseMarkdown",
]

Chunk = Tuple[str, Dict]


def _to_text(contents: Any) -> str:
    if isinstance(contents, bytes):
        return contents.decode("utf-8", errors="replace")
    return str(contents)


class ParseUtf8(UDF):
    """(reference: parsers.py:53)"""

    def __init__(self, **kwargs):
        super().__init__(lambda contents: [(_to_text(contents), {})], **kwargs)


Utf8Parser = ParseUtf8


class ParseMarkdown(UDF):
    """Split a markdown document on headings into (section, metadata) chunks."""

    def __init__(self, **kwargs):
        def parse(contents: Any) -> List[Chunk]:
            text = _to_text(contents)
            chunks: List[Chunk] = []
            current: List[str] = []
            heading = ""
            for line in text.splitlines():
                if line.startswith("#"):
                    if current:
                        chunks.append(("\n".join(current).strip(), {"heading": heading}))
                    heading = line.lstrip("# ").strip()
                    current = [line]
                else:
                    current.append(line)
            if current:
                chunks.append(("\n".join(current).strip(), {"heading": heading}))
            return [c for c in chunks if c[0]]

        super().__init__(parse, **kwargs)


class ParseUnstructured(UDF):
    """(reference: parsers.py:79 — unstructured-io; gated on the library)"""

    def __init__(self, mode: str = "single", **kwargs):
        try:
            from unstructured.partition.auto import partition
        except ImportError as e:
            raise ImportError(
                "ParseUnstructured requires the `unstructured` package; use "
                "ParseUtf8 / ParseMarkdown / PypdfParser instead"
            ) from e

        def parse(contents: Any) -> List[Chunk]:
            import io

            elements = partition(file=io.BytesIO(contents))
            if mode == "single":
                return [("\n\n".join(str(e) for e in elements), {})]
            return [(str(e), {"category": e.category}) for e in elements]

        super().__init__(parse, **kwargs)


UnstructuredParser = ParseUnstructured


class PypdfParser(UDF):
    """(reference: parsers.py:746 — pypdf text extraction; gated)"""

    def __init__(self, apply_text_cleanup: bool = True, **kwargs):
        try:
            import pypdf
        except ImportError as e:
            raise ImportError("PypdfParser requires the `pypdf` package") from e

        def parse(contents: bytes) -> List[Chunk]:
            import io

            reader = pypdf.PdfReader(io.BytesIO(contents))
            out = []
            for i, page in enumerate(reader.pages):
                text = page.extract_text() or ""
                if apply_text_cleanup:
                    text = " ".join(text.split())
                if text:
                    out.append((text, {"page": i}))
            return out

        super().__init__(parse, **kwargs)


class ImageParser(UDF):
    """(reference: parsers.py:396 — vision-LLM image description; here decodes
    the image into an ndarray chunk for the CLIP image embedder path)."""

    def __init__(self, downsize_to: int = 64, **kwargs):
        def parse(contents: bytes) -> List[Chunk]:
            import io

            import numpy as np

            try:
                from PIL import Image
            except ImportError as e:
                raise ImportError("ImageParser requires `Pillow`") from e
            img = Image.open(io.BytesIO(contents)).convert("RGB")
            img = img.resize((downsize_to, downsize_to))
            arr = np.asarray(img, dtype=np.float32) / 255.0
            return [("", {"image": arr})]

        super().__init__(parse, **kwargs)


class SlideParser(UDF):
    """(reference: parsers.py:569 — slide decks via vision LLM; gated)"""

    def __init__(self, **kwargs):
        raise ImportError(
            "SlideParser requires vision-LLM tooling unavailable offline; "
            "use ParseUtf8/PypdfParser"
        )
