"""Document parsers (reference: xpacks/llm/parsers.py:53-928 — ParseUtf8,
ParseUnstructured, OpenParse, ImageParser, SlideParser, PypdfParser).
Parsers map raw bytes -> list[(text, metadata)]."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ...internals.udfs import UDF

__all__ = [
    "ParseUtf8",
    "Utf8Parser",
    "ParseUnstructured",
    "UnstructuredParser",
    "PdfParser",
    "PypdfParser",
    "ImageParser",
    "SlideParser",
    "ParseMarkdown",
]

Chunk = Tuple[str, Dict]


def _to_text(contents: Any) -> str:
    if isinstance(contents, bytes):
        return contents.decode("utf-8", errors="replace")
    return str(contents)


class ParseUtf8(UDF):
    """(reference: parsers.py:53)"""

    def __init__(self, **kwargs):
        super().__init__(lambda contents: [(_to_text(contents), {})], **kwargs)


Utf8Parser = ParseUtf8


class ParseMarkdown(UDF):
    """Split a markdown document on headings into (section, metadata) chunks."""

    def __init__(self, **kwargs):
        def parse(contents: Any) -> List[Chunk]:
            text = _to_text(contents)
            chunks: List[Chunk] = []
            current: List[str] = []
            heading = ""
            for line in text.splitlines():
                if line.startswith("#"):
                    if current:
                        chunks.append(("\n".join(current).strip(), {"heading": heading}))
                    heading = line.lstrip("# ").strip()
                    current = [line]
                else:
                    current.append(line)
            if current:
                chunks.append(("\n".join(current).strip(), {"heading": heading}))
            return [c for c in chunks if c[0]]

        super().__init__(parse, **kwargs)


class ParseUnstructured(UDF):
    """(reference: parsers.py:79 — unstructured-io; gated on the library)"""

    def __init__(self, mode: str = "single", **kwargs):
        try:
            from unstructured.partition.auto import partition
        except ImportError as e:
            raise ImportError(
                "ParseUnstructured requires the `unstructured` package; use "
                "ParseUtf8 / ParseMarkdown / PypdfParser instead"
            ) from e

        def parse(contents: Any) -> List[Chunk]:
            import io

            elements = partition(file=io.BytesIO(contents))
            if mode == "single":
                return [("\n\n".join(str(e) for e in elements), {})]
            return [(str(e), {"category": e.category}) for e in elements]

        super().__init__(parse, **kwargs)


UnstructuredParser = ParseUnstructured


def _pdf_literal_string(raw: bytes) -> str:
    """Decode a PDF literal string body (backslash escapes, octal)."""
    out = bytearray()
    i = 0
    while i < len(raw):
        c = raw[i]
        if c == 0x5C and i + 1 < len(raw):  # backslash
            n = raw[i + 1]
            mapped = {
                0x6E: 0x0A, 0x72: 0x0D, 0x74: 0x09, 0x62: 0x08, 0x66: 0x0C,
                0x28: 0x28, 0x29: 0x29, 0x5C: 0x5C,
            }.get(n)
            if mapped is not None:
                out.append(mapped)
                i += 2
                continue
            if 0x30 <= n <= 0x37:  # octal escape
                j = i + 1
                digits = b""
                while j < len(raw) and len(digits) < 3 and 0x30 <= raw[j] <= 0x37:
                    digits += bytes([raw[j]])
                    j += 1
                out.append(int(digits, 8) & 0xFF)
                i = j
                continue
            i += 1  # line continuation / unknown escape: drop the backslash
            continue
        out.append(c)
        i += 1
    return out.decode("latin-1")


def _pdf_extract_text(contents: bytes) -> List[str]:
    """Minimal pure-python PDF text extraction: inflate every Flate stream
    and collect the Tj/TJ/'-operator strings of its BT..ET text blocks.
    Handles the simple-font PDFs that text exporters produce; CID/Type0
    composite fonts need a real PDF library."""
    import re
    import zlib

    texts: List[str] = []
    for m in re.finditer(rb"(?<!end)stream\r?\n", contents):
        start = m.end()
        end = contents.find(b"endstream", start)
        if end < 0:
            continue
        data = contents[start:end].rstrip(b"\r\n")
        try:
            data = zlib.decompress(data)
        except zlib.error:
            pass  # uncompressed stream (or an image) — try as-is
        if b"BT" not in data:
            continue
        parts: List[str] = []
        for block in re.findall(rb"BT(.*?)ET", data, re.S):
            # literal strings followed by a show operator; TJ arrays mix
            # strings and kerning numbers
            for sm in re.finditer(
                rb"\((?:[^()\\]|\\.)*\)|<[0-9A-Fa-f\s]+>", block
            ):
                token = sm.group(0)
                tail = block[sm.end(): sm.end() + 24]
                if not re.match(
                    rb"\s*(?:Tj|'|\")|[^\[]*?\]\s*TJ", tail
                ):
                    continue
                if token.startswith(b"("):
                    parts.append(_pdf_literal_string(token[1:-1]))
                else:
                    hexed = re.sub(rb"\s", b"", token[1:-1])
                    try:
                        parts.append(bytes.fromhex(hexed.decode()).decode(
                            "latin-1"
                        ))
                    except ValueError:
                        pass
            parts.append("\n")
        text = "".join(parts).strip()
        if text:
            texts.append(text)
    return texts


class PdfParser(UDF):
    """Pure-python PDF text extraction — no native PDF library in the image
    (reference capability: parsers.py:746 PypdfParser).  Covers simple-font
    Flate PDFs; composite-font documents should go through
    ParseUnstructured/PypdfParser where those libraries are installed."""

    def __init__(self, apply_text_cleanup: bool = True, **kwargs):
        def parse(contents: bytes) -> List[Chunk]:
            out: List[Chunk] = []
            for i, text in enumerate(_pdf_extract_text(bytes(contents))):
                if apply_text_cleanup:
                    text = " ".join(text.split())
                if text:
                    out.append((text, {"page": i}))
            return out

        super().__init__(parse, **kwargs)


class PypdfParser(UDF):
    """(reference: parsers.py:746 — pypdf text extraction; falls back to the
    pure-python PdfParser when pypdf is not installed)"""

    def __init__(self, apply_text_cleanup: bool = True, **kwargs):
        try:
            import pypdf
        except ImportError:
            pypdf = None

        def parse(contents: bytes) -> List[Chunk]:
            import io

            if pypdf is None:
                out: List[Chunk] = []
                for i, text in enumerate(_pdf_extract_text(bytes(contents))):
                    if apply_text_cleanup:
                        text = " ".join(text.split())
                    if text:
                        out.append((text, {"page": i}))
                return out
            reader = pypdf.PdfReader(io.BytesIO(contents))
            out = []
            for i, page in enumerate(reader.pages):
                text = page.extract_text() or ""
                if apply_text_cleanup:
                    text = " ".join(text.split())
                if text:
                    out.append((text, {"page": i}))
            return out

        super().__init__(parse, **kwargs)


class ImageParser(UDF):
    """(reference: parsers.py:396 — vision-LLM image description).  TPU-first
    redesign: instead of a remote vision LLM, the optional ``labels`` list
    zero-shot classifies the image with the local CLIP model and emits the
    top labels as the chunk text (searchable); the decoded ndarray always
    lands in metadata for the CLIP image-embedding index path."""

    def __init__(
        self,
        downsize_to: int = 64,
        labels: Optional[List[str]] = None,
        clip_model=None,
        top_k_labels: int = 3,
        **kwargs,
    ):
        clip = clip_model
        if labels and clip is None:
            from ...models.clip import ClipModel

            clip = ClipModel(image_size=downsize_to)
        label_vecs = None

        def parse(contents: bytes) -> List[Chunk]:
            import io

            import numpy as np

            try:
                from PIL import Image
            except ImportError as e:
                raise ImportError("ImageParser requires `Pillow`") from e
            img = Image.open(io.BytesIO(contents)).convert("RGB")
            img = img.resize((downsize_to, downsize_to))
            arr = np.asarray(img, dtype=np.float32) / 255.0
            text = ""
            meta: Dict[str, Any] = {"image": arr}
            if labels:
                nonlocal label_vecs
                if label_vecs is None:
                    label_vecs = clip.encode_text(list(labels))
                img_vec = clip.encode_image([arr])[0]
                scores = label_vecs @ img_vec
                order = scores.argsort()[::-1][:top_k_labels]
                picked = [labels[i] for i in order]
                text = ", ".join(picked)
                meta["labels"] = picked
            return [(text, meta)]

        super().__init__(parse, **kwargs)


def _pdf_slide_scan(contents: bytes):
    """Walk a PDF's streams in document order, yielding per-slide text and
    embedded JPEG images: ("text", slide_idx, str) and
    ("image", slide_idx, jpeg_bytes).  Slide index advances at each
    text-bearing content stream (one content stream per exported slide is
    how deck exporters write PDFs)."""
    import re
    import zlib

    slide = -1
    for m in re.finditer(rb"(?<!end)stream\r?\n", contents):
        start = m.end()
        end = contents.find(b"endstream", start)
        if end < 0:
            continue
        data = contents[start:end].rstrip(b"\r\n")
        # embedded JPEG (DCTDecode) XObjects pass through undeflated
        if data[:3] == b"\xff\xd8\xff":
            yield ("image", max(slide, 0), data)
            continue
        try:
            inflated = zlib.decompress(data)
        except zlib.error:
            inflated = data
        if inflated[:3] == b"\xff\xd8\xff":
            yield ("image", max(slide, 0), inflated)
            continue
        if b"BT" not in inflated:
            continue
        slide += 1
        texts = _pdf_extract_text(
            b"stream\n" + data + b"\nendstream"
        )
        yield ("text", slide, " ".join(" ".join(t.split()) for t in texts))


class SlideParser(UDF):
    """Slide decks (PDF exports) parsed fully offline — the TPU-first
    redesign of the reference's vision-LLM SlideParser (parsers.py:569,
    which rasterizes slides and asks a remote vision model to describe
    them): per-slide text chunks come from the pure-python PDF extractor,
    and embedded slide images are zero-shot labeled with the local CLIP
    model (like ImageParser) so image-only slides stay searchable."""

    def __init__(
        self,
        labels: Optional[List[str]] = None,
        clip_model=None,
        top_k_labels: int = 3,
        downsize_to: int = 64,
        **kwargs,
    ):
        clip = clip_model
        if labels and clip is None:
            from ...models.clip import ClipModel

            clip = ClipModel(image_size=downsize_to)
        label_vecs = None

        def parse(contents: bytes) -> List[Chunk]:
            import io as _io

            slide_text: Dict[int, List[str]] = {}
            slide_labels: Dict[int, List[str]] = {}
            for kind, slide, payload in _pdf_slide_scan(bytes(contents)):
                if kind == "text":
                    if payload:
                        slide_text.setdefault(slide, []).append(payload)
                    continue
                if not labels:
                    continue
                try:
                    from PIL import Image

                    import numpy as np

                    img = Image.open(_io.BytesIO(payload)).convert("RGB")
                except Exception:  # noqa: BLE001 - undecodable image
                    continue
                img = img.resize((downsize_to, downsize_to))
                arr = np.asarray(img, dtype=np.float32) / 255.0
                nonlocal label_vecs
                if label_vecs is None:
                    label_vecs = clip.encode_text(list(labels))
                img_vec = clip.encode_image([arr])[0]
                order = (label_vecs @ img_vec).argsort()[::-1][:top_k_labels]
                slide_labels.setdefault(slide, []).extend(
                    labels[i] for i in order
                )
            out: List[Chunk] = []
            for slide in sorted(set(slide_text) | set(slide_labels)):
                text = " ".join(slide_text.get(slide, []))
                picked = slide_labels.get(slide, [])
                if picked:
                    text = (text + " " if text else "") + ", ".join(picked)
                meta: Dict[str, Any] = {"slide": slide}
                if picked:
                    meta["labels"] = picked
                if text:
                    out.append((text, meta))
            return out

        super().__init__(parse, **kwargs)
