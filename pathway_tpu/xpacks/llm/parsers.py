"""Document parsers (reference: xpacks/llm/parsers.py:53-928 — ParseUtf8,
ParseUnstructured, OpenParse, ImageParser, SlideParser, PypdfParser).
Parsers map raw bytes -> list[(text, metadata)]."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ...internals.udfs import UDF

__all__ = [
    "ParseUtf8",
    "Utf8Parser",
    "ParseUnstructured",
    "UnstructuredParser",
    "PdfParser",
    "PypdfParser",
    "ImageParser",
    "SlideParser",
    "OpenParse",
    "ParseMarkdown",
]

Chunk = Tuple[str, Dict]

DEFAULT_VISION_PROMPT = (
    "Describe the contents of this image in detail. If it contains a "
    "table, transcribe the table as markdown."
)


_IMAGE_MAGIC = (
    (b"\x89PNG", "image/png"),
    (b"\xff\xd8\xff", "image/jpeg"),
    (b"GIF8", "image/gif"),
    (b"RIFF", "image/webp"),
    (b"BM", "image/bmp"),
)


def _image_mime(data: bytes) -> str:
    for magic, mime in _IMAGE_MAGIC:
        if data[: len(magic)] == magic:
            return mime
    return "application/octet-stream"


def _call_vision_chat(llm, image_bytes: bytes, prompt: str) -> str:
    """Ask a vision-capable chat model about one image (reference
    parsers.py:235-396 routes tables/images through vision prompts).  The
    message shape is the OpenAI multi-part content form every API chat
    accepts: image_url (base64 data URI, media type sniffed from the
    payload) + text; dispatch delegates to the shared chat invoker."""
    import base64

    from .question_answering import _call_chat

    data = bytes(image_bytes)
    b64 = base64.b64encode(data).decode()
    mime = _image_mime(data)
    messages = [
        {
            "role": "user",
            "content": [
                {
                    "type": "image_url",
                    "image_url": {"url": f"data:{mime};base64,{b64}"},
                },
                {"type": "text", "text": prompt},
            ],
        }
    ]
    return _call_chat(llm, messages)


def _clip_labeler(labels: Optional[List[str]], clip_model, downsize_to: int):
    """Shared zero-shot labeling closure for the offline image tiers:
    returns ``label(img_source) -> (picked_labels, decoded_array | None)``
    with the text-embedding cache inside.  ``img_source`` is raw bytes (or
    an already-decoded float array); undecodable inputs yield ([], None)."""
    clip = clip_model
    if labels and clip is None:
        from ...models.clip import ClipModel

        clip = ClipModel(image_size=downsize_to)
    state: Dict[str, Any] = {"vecs": None}

    def label(img_source, top_k: int):
        import io as _io

        import numpy as np

        if isinstance(img_source, (bytes, bytearray, memoryview)):
            try:
                from PIL import Image

                img = Image.open(_io.BytesIO(img_source)).convert("RGB")
            except Exception:  # noqa: BLE001 - undecodable image
                return [], None
            img = img.resize((downsize_to, downsize_to))
            arr = np.asarray(img, dtype=np.float32) / 255.0
        else:
            arr = img_source
        if not labels:
            return [], arr
        if state["vecs"] is None:
            state["vecs"] = clip.encode_text(list(labels))
        img_vec = clip.encode_image([arr])[0]
        order = (state["vecs"] @ img_vec).argsort()[::-1][:top_k]
        return [labels[i] for i in order], arr

    return label


def _to_text(contents: Any) -> str:
    if isinstance(contents, bytes):
        return contents.decode("utf-8", errors="replace")
    return str(contents)


class ParseUtf8(UDF):
    """(reference: parsers.py:53)"""

    def __init__(self, **kwargs):
        super().__init__(lambda contents: [(_to_text(contents), {})], **kwargs)


Utf8Parser = ParseUtf8


class ParseMarkdown(UDF):
    """Split a markdown document on headings into (section, metadata) chunks."""

    def __init__(self, **kwargs):
        def parse(contents: Any) -> List[Chunk]:
            text = _to_text(contents)
            chunks: List[Chunk] = []
            current: List[str] = []
            heading = ""
            for line in text.splitlines():
                if line.startswith("#"):
                    if current:
                        chunks.append(("\n".join(current).strip(), {"heading": heading}))
                    heading = line.lstrip("# ").strip()
                    current = [line]
                else:
                    current.append(line)
            if current:
                chunks.append(("\n".join(current).strip(), {"heading": heading}))
            return [c for c in chunks if c[0]]

        super().__init__(parse, **kwargs)


class ParseUnstructured(UDF):
    """(reference: parsers.py:79 — unstructured-io; gated on the library)"""

    def __init__(self, mode: str = "single", **kwargs):
        try:
            from unstructured.partition.auto import partition
        except ImportError as e:
            raise ImportError(
                "ParseUnstructured requires the `unstructured` package; use "
                "ParseUtf8 / ParseMarkdown / PypdfParser instead"
            ) from e

        def parse(contents: Any) -> List[Chunk]:
            import io

            elements = partition(file=io.BytesIO(contents))
            if mode == "single":
                return [("\n\n".join(str(e) for e in elements), {})]
            return [(str(e), {"category": e.category}) for e in elements]

        super().__init__(parse, **kwargs)


UnstructuredParser = ParseUnstructured


def _pdf_literal_string(raw: bytes) -> str:
    """Decode a PDF literal string body (backslash escapes, octal)."""
    out = bytearray()
    i = 0
    while i < len(raw):
        c = raw[i]
        if c == 0x5C and i + 1 < len(raw):  # backslash
            n = raw[i + 1]
            mapped = {
                0x6E: 0x0A, 0x72: 0x0D, 0x74: 0x09, 0x62: 0x08, 0x66: 0x0C,
                0x28: 0x28, 0x29: 0x29, 0x5C: 0x5C,
            }.get(n)
            if mapped is not None:
                out.append(mapped)
                i += 2
                continue
            if 0x30 <= n <= 0x37:  # octal escape
                j = i + 1
                digits = b""
                while j < len(raw) and len(digits) < 3 and 0x30 <= raw[j] <= 0x37:
                    digits += bytes([raw[j]])
                    j += 1
                out.append(int(digits, 8) & 0xFF)
                i = j
                continue
            i += 1  # line continuation / unknown escape: drop the backslash
            continue
        out.append(c)
        i += 1
    return out.decode("latin-1")


def _pdf_extract_text(contents: bytes) -> List[str]:
    """Minimal pure-python PDF text extraction: inflate every Flate stream
    and collect the Tj/TJ/'-operator strings of its BT..ET text blocks.
    Handles the simple-font PDFs that text exporters produce; CID/Type0
    composite fonts need a real PDF library."""
    import re
    import zlib

    texts: List[str] = []
    for m in re.finditer(rb"(?<!end)stream\r?\n", contents):
        start = m.end()
        end = contents.find(b"endstream", start)
        if end < 0:
            continue
        data = contents[start:end].rstrip(b"\r\n")
        try:
            data = zlib.decompress(data)
        except zlib.error:
            pass  # uncompressed stream (or an image) — try as-is
        if b"BT" not in data:
            continue
        parts: List[str] = []
        for block in re.findall(rb"BT(.*?)ET", data, re.S):
            # literal strings followed by a show operator; TJ arrays mix
            # strings and kerning numbers
            for sm in re.finditer(
                rb"\((?:[^()\\]|\\.)*\)|<[0-9A-Fa-f\s]+>", block
            ):
                token = sm.group(0)
                tail = block[sm.end(): sm.end() + 24]
                if not re.match(
                    rb"\s*(?:Tj|'|\")|[^\[]*?\]\s*TJ", tail
                ):
                    continue
                if token.startswith(b"("):
                    parts.append(_pdf_literal_string(token[1:-1]))
                else:
                    hexed = re.sub(rb"\s", b"", token[1:-1])
                    try:
                        parts.append(bytes.fromhex(hexed.decode()).decode(
                            "latin-1"
                        ))
                    except ValueError:
                        pass
            parts.append("\n")
        text = "".join(parts).strip()
        if text:
            texts.append(text)
    return texts


class PdfParser(UDF):
    """Pure-python PDF text extraction — no native PDF library in the image
    (reference capability: parsers.py:746 PypdfParser).  Covers simple-font
    Flate PDFs; composite-font documents should go through
    ParseUnstructured/PypdfParser where those libraries are installed."""

    def __init__(self, apply_text_cleanup: bool = True, **kwargs):
        def parse(contents: bytes) -> List[Chunk]:
            out: List[Chunk] = []
            for i, text in enumerate(_pdf_extract_text(bytes(contents))):
                if apply_text_cleanup:
                    text = " ".join(text.split())
                if text:
                    out.append((text, {"page": i}))
            return out

        super().__init__(parse, **kwargs)


class PypdfParser(UDF):
    """(reference: parsers.py:746 — pypdf text extraction; falls back to the
    pure-python PdfParser when pypdf is not installed)"""

    def __init__(self, apply_text_cleanup: bool = True, **kwargs):
        try:
            import pypdf
        except ImportError:
            pypdf = None

        def parse(contents: bytes) -> List[Chunk]:
            import io

            if pypdf is None:
                out: List[Chunk] = []
                for i, text in enumerate(_pdf_extract_text(bytes(contents))):
                    if apply_text_cleanup:
                        text = " ".join(text.split())
                    if text:
                        out.append((text, {"page": i}))
                return out
            reader = pypdf.PdfReader(io.BytesIO(contents))
            out = []
            for i, page in enumerate(reader.pages):
                text = page.extract_text() or ""
                if apply_text_cleanup:
                    text = " ".join(text.split())
                if text:
                    out.append((text, {"page": i}))
            return out

        super().__init__(parse, **kwargs)


class ImageParser(UDF):
    """(reference: parsers.py:396 — vision-LLM image description).  Two
    tiers: when a vision-capable chat ``llm`` is configured, the image is
    described via a vision prompt like the reference does; otherwise the
    offline tier zero-shot classifies it with the LOCAL CLIP model using
    the optional ``labels`` list.  The decoded ndarray always lands in
    metadata for the CLIP image-embedding index path."""

    def __init__(
        self,
        downsize_to: int = 64,
        labels: Optional[List[str]] = None,
        clip_model=None,
        top_k_labels: int = 3,
        llm=None,
        llm_prompt: str = DEFAULT_VISION_PROMPT,
        **kwargs,
    ):
        labeler = _clip_labeler(labels if llm is None else None, clip_model, downsize_to)

        def parse(contents: bytes) -> List[Chunk]:
            import io

            import numpy as np

            try:
                from PIL import Image
            except ImportError as e:
                raise ImportError("ImageParser requires `Pillow`") from e
            img = Image.open(io.BytesIO(contents)).convert("RGB")
            img = img.resize((downsize_to, downsize_to))
            arr = np.asarray(img, dtype=np.float32) / 255.0
            text = ""
            meta: Dict[str, Any] = {"image": arr}
            if llm is not None:
                # vision tier: the ORIGINAL bytes go to the model (the
                # downsized array is only for the CLIP embedding path)
                text = _call_vision_chat(llm, contents, llm_prompt)
            elif labels:
                picked, _ = labeler(arr, top_k_labels)
                text = ", ".join(picked)
                meta["labels"] = picked
            return [(text, meta)]

        super().__init__(parse, **kwargs)


def _pdf_slide_scan(contents: bytes):
    """Walk a PDF's streams in document order, yielding per-slide text and
    embedded JPEG images: ("text", slide_idx, str) and
    ("image", slide_idx, jpeg_bytes).  Slide index advances at each
    text-bearing content stream (one content stream per exported slide is
    how deck exporters write PDFs)."""
    import re
    import zlib

    slide = -1
    for m in re.finditer(rb"(?<!end)stream\r?\n", contents):
        start = m.end()
        end = contents.find(b"endstream", start)
        if end < 0:
            continue
        data = contents[start:end].rstrip(b"\r\n")
        # embedded JPEG (DCTDecode) XObjects pass through undeflated
        if data[:3] == b"\xff\xd8\xff":
            yield ("image", max(slide, 0), data)
            continue
        try:
            inflated = zlib.decompress(data)
        except zlib.error:
            inflated = data
        if inflated[:3] == b"\xff\xd8\xff":
            yield ("image", max(slide, 0), inflated)
            continue
        if b"BT" not in inflated:
            continue
        slide += 1
        texts = _pdf_extract_text(
            b"stream\n" + data + b"\nendstream"
        )
        yield ("text", slide, " ".join(" ".join(t.split()) for t in texts))


class SlideParser(UDF):
    """Slide decks (PDF exports).  Per-slide text chunks come from the
    pure-python PDF extractor; embedded slide images go through the vision
    chat ``llm`` when one is configured (the reference's tier,
    parsers.py:569 — rasterize and ask a vision model), and are otherwise
    zero-shot labeled with the LOCAL CLIP model so image-only slides stay
    searchable fully offline."""

    def __init__(
        self,
        labels: Optional[List[str]] = None,
        clip_model=None,
        top_k_labels: int = 3,
        downsize_to: int = 64,
        llm=None,
        llm_prompt: str = DEFAULT_VISION_PROMPT,
        **kwargs,
    ):
        labeler = _clip_labeler(labels if llm is None else None, clip_model, downsize_to)

        def parse(contents: bytes) -> List[Chunk]:
            slide_text: Dict[int, List[str]] = {}
            slide_labels: Dict[int, List[str]] = {}
            for kind, slide, payload in _pdf_slide_scan(bytes(contents)):
                if kind == "text":
                    if payload:
                        slide_text.setdefault(slide, []).append(payload)
                    continue
                if llm is not None:
                    desc = _call_vision_chat(llm, payload, llm_prompt)
                    if desc:
                        slide_labels.setdefault(slide, []).append(desc)
                    continue
                if not labels:
                    continue
                picked, _arr = labeler(payload, top_k_labels)
                if picked:
                    slide_labels.setdefault(slide, []).extend(picked)
            out: List[Chunk] = []
            for slide in sorted(set(slide_text) | set(slide_labels)):
                text = " ".join(slide_text.get(slide, []))
                picked = slide_labels.get(slide, [])
                if picked:
                    text = (text + " " if text else "") + ", ".join(picked)
                meta: Dict[str, Any] = {"slide": slide}
                if picked:
                    meta["labels"] = picked
                if text:
                    out.append((text, meta))
            return out

        super().__init__(parse, **kwargs)


class OpenParse(UDF):
    """Structured PDF parsing with a vision-LLM tier (reference:
    parsers.py:235 — OpenParse extracts text nodes plus tables/images via
    vision prompts when ``parse_images``/table args are enabled).

    Tiers here: text nodes always come from the pure-python PDF extractor;
    embedded images become their own chunks — described by the vision chat
    ``llm`` when configured (``parse_images=True``), zero-shot labeled by
    the LOCAL CLIP model when only ``labels`` is given, and skipped
    otherwise.  Each chunk carries its page/slide index and node kind in
    metadata."""

    def __init__(
        self,
        llm=None,
        parse_images: bool = False,
        image_prompt: str = DEFAULT_VISION_PROMPT,
        labels: Optional[List[str]] = None,
        clip_model=None,
        top_k_labels: int = 3,
        downsize_to: int = 64,
        **kwargs,
    ):
        if parse_images and llm is None and not labels:
            raise ValueError(
                "OpenParse(parse_images=True) needs a vision `llm` or "
                "CLIP `labels` to turn images into text"
            )
        labeler = _clip_labeler(labels if llm is None else None, clip_model, downsize_to)

        def parse(contents: bytes) -> List[Chunk]:
            out: List[Chunk] = []
            for kind, page, payload in _pdf_slide_scan(bytes(contents)):
                if kind == "text":
                    if payload:
                        out.append((payload, {"page": page, "kind": "text"}))
                    continue
                if not parse_images:
                    continue
                if llm is not None:
                    desc = _call_vision_chat(llm, payload, image_prompt)
                    if desc:
                        out.append(
                            (desc, {"page": page, "kind": "image"})
                        )
                    continue
                picked, _arr = labeler(payload, top_k_labels)
                if picked:
                    out.append(
                        (", ".join(picked), {"page": page, "kind": "image", "labels": picked})
                    )
            return out

        super().__init__(parse, **kwargs)
