"""Text splitters (reference: xpacks/llm/splitters.py:13-121 —
TokenCountSplitter over tiktoken, null_splitter).  Token counting uses the
offline hashing tokenizer (tiktoken requires downloads)."""

from __future__ import annotations

from typing import Dict, List, Tuple

from ...internals.udfs import UDF
from ...models.tokenizer import HashTokenizer

__all__ = ["TokenCountSplitter", "NullSplitter", "null_splitter"]

Chunk = Tuple[str, Dict]


def null_splitter(txt: str) -> List[Chunk]:
    """(reference: splitters.py:13)"""
    return [(txt, {})]


class NullSplitter(UDF):
    def __init__(self, **kwargs):
        super().__init__(lambda txt: [(txt, {})], **kwargs)


class TokenCountSplitter(UDF):
    """Split into chunks of min..max tokens, preferring sentence/punctuation
    boundaries (reference: splitters.py:34)."""

    def __init__(
        self,
        min_tokens: int = 50,
        max_tokens: int = 500,
        encoding_name: str = "cl100k_base",
        **kwargs,
    ):
        self.min_tokens = min_tokens
        self.max_tokens = max_tokens
        tokenizer = HashTokenizer()
        _PUNCT = ".?!\n"

        def split(txt: str) -> List[Chunk]:
            words = str(txt).split()
            if not words:
                return []
            chunks: List[Chunk] = []
            current: List[str] = []
            for word in words:
                current.append(word)
                if len(current) >= max_tokens:
                    chunks.append((" ".join(current), {}))
                    current = []
                elif len(current) >= min_tokens and word and word[-1] in _PUNCT:
                    chunks.append((" ".join(current), {}))
                    current = []
            if current:
                if chunks and len(current) < min_tokens:
                    last_text, meta = chunks[-1]
                    chunks[-1] = (last_text + " " + " ".join(current), meta)
                else:
                    chunks.append((" ".join(current), {}))
            return chunks

        super().__init__(split, **kwargs)
