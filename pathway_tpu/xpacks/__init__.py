from . import llm

__all__ = ["llm"]
