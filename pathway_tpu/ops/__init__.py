"""pw.ops — jitted device compute primitives (the TPU analog of the
reference's native hot paths: ndarray expressions in src/mat_mul.rs, external
index scoring in src/external_integration/)."""

from .knn import DeviceKnnIndex
from .recompile_guard import (
    RecompileBudgetExceeded,
    RecompileTripwire,
    RecompileWarning,
    guarded_jit,
)
from .retrieve_rerank import (
    CrossEncoderStage,
    LateInteractionStage,
    RerankStage,
    RetrieveRerankPipeline,
)
from .serving import FusedEncodeSearch
from .topk import merge_topk, sharded_topk

__all__ = [
    "CrossEncoderStage",
    "DeviceKnnIndex",
    "FusedEncodeSearch",
    "LateInteractionStage",
    "RecompileBudgetExceeded",
    "RecompileTripwire",
    "RecompileWarning",
    "RerankStage",
    "RetrieveRerankPipeline",
    "guarded_jit",
    "sharded_topk",
    "merge_topk",
]
