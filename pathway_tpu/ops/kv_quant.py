"""int8 KV-pool quantization — per-(layer, head, channel) stored scales.

The forward index (index/forward.py, PAPER.md §2.5) already ships the
int8+stored-scales idiom for token states: absmax-derived scales, values
``round(x / scale)`` clipped into [-127, 127], dequantized inside the
consuming kernel.  This module applies the same idiom to the continuous
decoder's slot K/V pool ``[slots, L, T, H, hd]`` (serve/decode.py):
halving bytes-per-cached-token doubles slots×context at fixed HBM.

Two properties drive the design:

- **scales are STATIC per (layer, head, channel)** — derived from the
  generator's own projection weights, not calibrated per token.  K/V
  entries are LayerNorm outputs pushed through the key/value Dense
  layers, so ``|k_c| <= sqrt(d) * ||gamma ⊙ W[:, c]||_2 +
  |beta · W[:, c]| + |b_c|`` (Cauchy–Schwarz over the unit-variance LN
  output) is a rigorous per-channel bound: no runtime clipping of
  in-bound values, no per-token scale storage (which would eat the 2×
  ratio the int8 pool exists for), and the same scale for every write
  makes quantization IDEMPOTENT — ``quantize(dequantize(q)) == q`` —
  so warm prefix-cache joins re-quantize to bit-identical pool bytes.
- **every read goes through the same dequant** — prefill and decode
  both attend ``dequantize(int8)`` (models/transformer.py quant twins),
  so warm and cold joins see identical attention inputs and int8
  decodes are deterministic; the bf16-vs-int8 token drift is bounded by
  tests/test_decode.py against a pinned golden.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax.numpy as jnp

__all__ = ["dequantize_kv", "kv_pool_scales", "quantize_kv"]


def kv_pool_scales(params, config) -> Tuple[Any, Any]:
    """Per-(layer, head, channel) K/V scales ``[L, H, hd]`` (f32) for a
    generator param tree (``block_i`` → LayerNorm_0 + SelfAttention_0
    key/value Dense).  ``scale = bound / 127`` with the channel bound
    above — host/init-time math, one tiny array per pool."""
    L = config.n_layers
    H = config.n_heads
    hd = config.d_model // H
    d = config.d_model
    k_rows = []
    v_rows = []
    sqrt_d = float(d) ** 0.5
    for i in range(L):
        blk = params[f"block_{i}"]
        gamma = jnp.asarray(blk["LayerNorm_0"]["scale"], jnp.float32)
        beta = jnp.asarray(blk["LayerNorm_0"]["bias"], jnp.float32)
        for name, rows in (("key", k_rows), ("value", v_rows)):
            dense = blk["SelfAttention_0"][name]
            W = jnp.asarray(dense["kernel"], jnp.float32)  # [d, d]
            b = jnp.asarray(dense["bias"], jnp.float32)    # [d]
            bound = (
                sqrt_d * jnp.linalg.norm(gamma[:, None] * W, axis=0)
                + jnp.abs(beta @ W)
                + jnp.abs(b)
            )
            rows.append(jnp.maximum(bound / 127.0, 1e-8).reshape(H, hd))
    return jnp.stack(k_rows), jnp.stack(v_rows)


def quantize_kv(x, scales):
    """``[..., T, H, hd]`` K/V values → int8 against ``[..., H, hd]``
    scales (broadcast over the T axis).  Traced fragment — used inside
    the compiled prefill/step/verify fns and at pool init alike."""
    s = jnp.expand_dims(scales, -3)
    q = jnp.round(x.astype(jnp.float32) / s)
    return jnp.clip(q, -127, 127).astype(jnp.int8)


def dequantize_kv(q, scales, dtype=jnp.float32):
    """int8 K/V back to ``dtype`` — the read-side half, fused into the
    attention kernels by XLA (the int8 buffer is the only HBM-resident
    copy; the dequantized values live in registers/VMEM)."""
    s = jnp.expand_dims(scales, -3)
    return (q.astype(jnp.float32) * s).astype(dtype)
