"""Dispatch/fetch accounting hook for the serving hot path.

The latency claims on a tunneled TPU are round-trip counts, not FLOPs
("a retrieve+rerank serve call issues exactly two device dispatches and two
host fetches in steady state").  Timing can't prove that on CPU CI, so the
serving paths report every compiled-function launch and every device→host
result copy here; tests and bench install a counter around a steady-state
call and assert on ground truth instead of wall clock.

Two consumers share each report:

- the **flight recorder** (``pathway_tpu/observe``) — ALWAYS on: every
  dispatch/fetch increments the ``pathway_serve_dispatches_total`` /
  ``pathway_serve_fetches_total`` counters on the scrape endpoint, so the
  budget is continuously visible in production, not only under a test;
- an **installed ``DispatchCounter``** — the test/bench assertion hook,
  still a no-op dict read when none is installed.

Per-shard-group accounting (the sharded serve path): a scatter-dispatch
fan-out launches one kernel per index shard plus a merge, but the batch
still pays ONE wire round trip — the per-shard launches overlap on their
own devices and only the merged output is fetched.  Reporting sites pass
``shards=N`` for such a group; the counter books it as ONE **logical**
dispatch (what the 2+2 budget is stated in) while ``physical_dispatches``
accumulates the real launch count (``N``), and the recorder exports the
physical count on ``pathway_serve_shard_dispatches_total`` so fan-out
width stays visible in production.  ``mode="physical"`` flips the
headline ``dispatches``/``fetches`` attributes to the physical counts
for tests that want to pin the fan-out width itself.

Thread-safety: each ``DispatchCounter`` carries its OWN lock (the old
module-global lock serialized unrelated counters and the ``_active`` read
happened outside it), and ``events`` is bounded — a long soak under an
installed counter keeps the first ``max_events`` events and counts the
rest in ``events_dropped`` instead of growing without bound.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from .. import observe
from ..observe import trace as _trace

__all__ = ["DispatchCounter", "install", "uninstall", "record_dispatch", "record_fetch"]

_install_lock = threading.Lock()
_active: Optional["DispatchCounter"] = None

# pre-resolved recorder counters per tag (tags are a small fixed set of
# serve-path literals; the cache makes the always-on path two dict reads
# + one locked increment)
_obs_counters: Dict[Tuple[str, str], observe.Counter] = {}


def _obs_counter(kind: str, tag: str) -> observe.Counter:
    key = (kind, tag)
    c = _obs_counters.get(key)
    if c is None:
        c = _obs_counters[key] = observe.counter(
            f"pathway_serve_{kind}es_total", tag=tag
        )
    return c


def _obs_shard_counter(kind: str, tag: str) -> observe.Counter:
    key = (f"shard_{kind}", tag)
    c = _obs_counters.get(key)
    if c is None:
        c = _obs_counters[key] = observe.counter(
            f"pathway_serve_shard_{kind}es_total", tag=tag
        )
    return c


class DispatchCounter:
    """Counts device dispatches and host fetches on the serving paths.

    ``mode="logical"`` (default): a shard-group fan-out reported with
    ``shards=N`` counts as ONE dispatch/fetch — the number the 2+2
    per-batch budget is asserted against.  ``mode="physical"``: the
    headline counts are the real per-device launch counts.  Both modes
    always keep both views (``dispatches``/``fetches`` honor the mode;
    ``physical_dispatches``/``physical_fetches`` are always physical).
    """

    def __init__(self, max_events: int = 4096, mode: str = "logical") -> None:
        if mode not in ("logical", "physical"):
            raise ValueError(f"unknown accounting mode {mode!r}")
        self.max_events = int(max_events)
        self.mode = mode
        self.dispatches = 0
        self.fetches = 0
        self.physical_dispatches = 0
        self.physical_fetches = 0
        self.events: List[Tuple[str, str]] = []  # ("dispatch"|"fetch", tag)
        self.events_dropped = 0
        self._lock = threading.Lock()

    def _record(self, kind: str, tag: str, shards: int) -> None:
        physical = max(1, int(shards))
        logical = 1
        with self._lock:
            if kind == "dispatch":
                self.physical_dispatches += physical
                self.dispatches += (
                    physical if self.mode == "physical" else logical
                )
            else:
                self.physical_fetches += physical
                self.fetches += physical if self.mode == "physical" else logical
            if len(self.events) < self.max_events:
                self.events.append((kind, tag))
            else:
                self.events_dropped += 1

    def reset(self) -> None:
        with self._lock:
            self.dispatches = 0
            self.fetches = 0
            self.physical_dispatches = 0
            self.physical_fetches = 0
            self.events = []
            self.events_dropped = 0

    def snapshot(self) -> Tuple[int, int]:
        with self._lock:
            return self.dispatches, self.fetches

    def __enter__(self) -> "DispatchCounter":
        install(self)
        return self

    def __exit__(self, *exc) -> None:
        uninstall()


def install(counter: Optional[DispatchCounter] = None) -> DispatchCounter:
    global _active
    with _install_lock:
        _active = counter or DispatchCounter()
        return _active


def uninstall() -> None:
    global _active
    with _install_lock:
        _active = None


def record_dispatch(tag: str, shards: int = 1) -> None:
    """Report one LOGICAL dispatch.  ``shards > 1`` marks a shard-group
    fan-out: ``shards`` physical kernel launches that together cost the
    batch one round trip (scatter + per-shard search + merge).  The
    active trace (observe/trace.py), when one exists, gets the count
    stamped too — a kept trace carries its own 2+2 budget evidence."""
    _obs_counter("dispatch", tag).inc()
    if shards > 1:
        _obs_shard_counter("dispatch", tag).inc(shards)
    t = _trace.current()
    if t is not None:
        t.note_dispatch(tag, shards)
    c = _active
    if c is not None:
        c._record("dispatch", tag, shards)


def record_fetch(tag: str, shards: int = 1) -> None:
    _obs_counter("fetch", tag).inc()
    if shards > 1:
        _obs_shard_counter("fetch", tag).inc(shards)
    t = _trace.current()
    if t is not None:
        t.note_fetch(tag, shards)
    c = _active
    if c is not None:
        c._record("fetch", tag, shards)
