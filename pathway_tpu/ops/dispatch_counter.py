"""Dispatch/fetch accounting hook for the serving hot path.

The latency claims on a tunneled TPU are round-trip counts, not FLOPs
("a retrieve+rerank serve call issues exactly two device dispatches and two
host fetches in steady state").  Timing can't prove that on CPU CI, so the
serving paths report every compiled-function launch and every device→host
result copy here; tests and bench install a counter around a steady-state
call and assert on ground truth instead of wall clock.

No-op (one dict lookup) unless a counter is installed — never on by
default in production serving.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Tuple

__all__ = ["DispatchCounter", "install", "uninstall", "record_dispatch", "record_fetch"]

_lock = threading.Lock()
_active: Optional["DispatchCounter"] = None


class DispatchCounter:
    """Counts device dispatches and host fetches on the serving paths."""

    def __init__(self) -> None:
        self.dispatches = 0
        self.fetches = 0
        self.events: List[Tuple[str, str]] = []  # ("dispatch"|"fetch", tag)

    def reset(self) -> None:
        self.dispatches = 0
        self.fetches = 0
        self.events = []

    def snapshot(self) -> Tuple[int, int]:
        return self.dispatches, self.fetches

    def __enter__(self) -> "DispatchCounter":
        install(self)
        return self

    def __exit__(self, *exc) -> None:
        uninstall()


def install(counter: Optional[DispatchCounter] = None) -> DispatchCounter:
    global _active
    with _lock:
        _active = counter or DispatchCounter()
        return _active


def uninstall() -> None:
    global _active
    with _lock:
        _active = None


def record_dispatch(tag: str) -> None:
    c = _active
    if c is not None:
        with _lock:
            c.dispatches += 1
            c.events.append(("dispatch", tag))


def record_fetch(tag: str) -> None:
    c = _active
    if c is not None:
        with _lock:
            c.fetches += 1
            c.events.append(("fetch", tag))
