"""Device-resident incremental KNN index — the framework's retrieval hot path.

TPU-first redesign of the reference's brute-force index
(src/external_integration/brute_force_knn_integration.rs:22-182: growable
Array2<f64> row store with 2x grow / 4x shrink and dot-product scoring):

- the embedding matrix lives in HBM as ``[capacity, d]``, row-sharded over
  the mesh "data" axis (multi-chip) or on the single device;
- add/remove are slot-allocator updates (free-list + capacity doubling) done
  as batched scatters — no host round-trip of the matrix;
- queries are padded to bucket sizes so XLA compiles a handful of shapes,
  scored as one [B,d]x[d,N] matmul (MXU) + ``lax.top_k``; multi-chip search
  does per-shard top-k then an ICI all-gather of k candidates per shard
  (ops/topk.py) — never the full score row.
"""

from __future__ import annotations

import threading
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..internals.keys import KEY_DTYPE
from ..parallel.mesh import global_zeros, host_to_global, is_multiprocess
from .topk import local_score_topk, sharded_topk

__all__ = ["DeviceKnnIndex", "normalize_metric"]


def normalize_metric(metric) -> str:
    """Accepts "cos"/"l2sq"/"dot", the reference metric-kind enums, or any
    casing; anything unrecognised raises instead of silently mis-scoring."""
    value = getattr(metric, "value", metric)
    value = str(value).lower().replace("cosine", "cos")
    if value in ("ip", "inner_product"):
        value = "dot"
    if value not in ("cos", "l2sq", "dot"):
        raise ValueError(f"unknown KNN metric {metric!r}")
    return value

_QUERY_BUCKETS = (1, 4, 16, 64, 256, 1024)


def _bucket(n: int) -> int:
    for b in _QUERY_BUCKETS:
        if n <= b:
            return b
    return ((n + 1023) // 1024) * 1024


@jax.jit
def _scatter_rows(matrix: jnp.ndarray, slots: jnp.ndarray, rows: jnp.ndarray):
    return matrix.at[slots].set(rows.astype(matrix.dtype))


@partial(jax.jit, static_argnums=2)
def _scatter_flags(valid: jnp.ndarray, slots: jnp.ndarray, flag: bool):
    return valid.at[slots].set(flag)


@jax.jit
def _scatter_vals(arr: jnp.ndarray, slots: jnp.ndarray, vals: jnp.ndarray):
    return arr.at[slots].set(vals)


class DeviceKnnIndex:
    """Incrementally maintained dense KNN index on TPU.

    metric: "cos" (vectors L2-normalised at insert; score = cosine sim) or
    "l2sq" (score = -squared distance) or "dot".
    """

    def __init__(
        self,
        dimension: int,
        metric: str = "cos",
        initial_capacity: int = 1024,
        mesh: Optional[Mesh] = None,
        dtype=jnp.float32,
    ):
        self.dimension = dimension
        self.metric = normalize_metric(metric)
        self.dtype = dtype
        self.mesh = mesh
        self._lock = threading.RLock()
        self.n_shards = mesh.shape["data"] if mesh is not None else 1
        # multi-host mesh: host-side device_put can't target non-addressable
        # devices — all transfers go through host_to_global / jitted creation
        # (SPMD replicas supply identical host data; see parallel/distributed)
        self._multiproc = mesh is not None and is_multiprocess(mesh)
        cap = max(initial_capacity, self.n_shards * 8)
        cap = self._round_capacity(cap)
        self.capacity = cap
        self._matrix = self._device_zeros((cap, dimension))
        self._valid = self._device_zeros((cap,), dtype=jnp.bool_)
        # device-resident slot->key map as two int32 planes (jax runs with
        # 32-bit ints; keys are uint64).  The fused serving path gathers the
        # top slots' keys ON DEVICE so query completion needs no host-side
        # metadata snapshot — an O(len(index)) set/copy per call was the
        # dominant cost of the old host mapping at 1M rows (~30 ms/batch).
        self._keys_hi = self._device_zeros((cap,), dtype=jnp.int32)
        self._keys_lo = self._device_zeros((cap,), dtype=jnp.int32)
        self.key_to_slot: Dict[int, int] = {}
        self.slot_to_key = np.zeros(cap, dtype=KEY_DTYPE)
        self._free: List[int] = list(range(cap - 1, -1, -1))
        self._search_fns: Dict[Tuple[int, int, int], object] = {}
        # result-visibility generation (same contract as
        # IvfKnnIndex.generation): bumped on every mutation that can
        # change what a serve returns — the coalescing scheduler keys
        # its in-window dedup on (text, generation)
        self.generation = 0
        # HBM ledger (observe/hbm.py): the dense matrix + validity/key
        # planes, sampled at scrape time only (weakly held)
        from ..observe import hbm

        hbm.track("knn", self)

    def hbm_bytes(self) -> Dict[str, int]:
        """Device-resident bytes: the allocated-capacity matrix and the
        slot metadata planes (``.nbytes`` is metadata, never a sync)."""
        planes = sum(
            int(getattr(buf, "nbytes", 0))
            for buf in (self._valid, self._keys_hi, self._keys_lo)
        )
        return {
            "matrix": int(getattr(self._matrix, "nbytes", 0)),
            "planes": planes,
        }

    # -- storage helpers ---------------------------------------------------
    def _round_capacity(self, cap: int) -> int:
        """Capacity multiple of shards*8 so row-sharding divides evenly and
        tiles align with the (8,128) f32 layout."""
        unit = self.n_shards * 8
        return ((cap + unit - 1) // unit) * unit

    def _sharding(self, row_sharded: bool = True):
        if self.mesh is None:
            return None
        return NamedSharding(
            self.mesh, P("data", None) if row_sharded else P("data")
        )

    def _device_zeros(self, shape, dtype=None):
        dtype = dtype or self.dtype
        if self.mesh is None:
            return jnp.zeros(shape, dtype=dtype)
        spec = P("data", None) if len(shape) == 2 else P("data")
        return global_zeros(shape, dtype, self.mesh, spec)

    def _to_mesh(self, value, spec=P()):
        """Host (or local-device) data → array usable in jit on this index's
        mesh; replicated by default.  No-op for data already on the mesh."""
        if self.mesh is None:
            return value if isinstance(value, jax.Array) else jnp.asarray(value)
        if (
            isinstance(value, jax.Array)
            and getattr(value.sharding, "mesh", None) == self.mesh
        ):
            return value
        if not self._multiproc and isinstance(value, jax.Array):
            return value  # single-process: jit can reshard local arrays
        if isinstance(value, jax.Array) and not value.is_fully_addressable:
            raise ValueError(
                "device array lives on a different multi-process mesh than "
                "this index — re-shard it onto the index mesh first"
            )
        return host_to_global(np.asarray(value), self.mesh, spec)

    def __len__(self) -> int:
        return len(self.key_to_slot)

    # -- growth ------------------------------------------------------------
    def _grow(self, needed: int) -> None:
        new_cap = self._round_capacity(max(self.capacity * 2, self.capacity + needed))
        old_cap = self.capacity
        dim = self.dimension
        dtype = self.dtype
        if self.mesh is None:
            # device-side copy keeps data in HBM
            new_matrix = jax.lax.dynamic_update_slice(
                jnp.zeros((new_cap, dim), dtype), self._matrix, (0, 0)
            )
            new_valid = jax.lax.dynamic_update_slice(
                jnp.zeros((new_cap,), jnp.bool_), self._valid, (0,)
            )
            new_hi = jax.lax.dynamic_update_slice(
                jnp.zeros((new_cap,), jnp.int32), self._keys_hi, (0,)
            )
            new_lo = jax.lax.dynamic_update_slice(
                jnp.zeros((new_cap,), jnp.int32), self._keys_lo, (0,)
            )
        else:
            # jitted grow with explicit out_shardings: stays sharded, works on
            # multi-process meshes where host-side device_put cannot re-pin
            new_matrix = jax.jit(
                lambda m: jax.lax.dynamic_update_slice(
                    jnp.zeros((new_cap, dim), dtype), m, (0, 0)
                ),
                out_shardings=self._sharding(True),
            )(self._matrix)
            grow_flat = jax.jit(
                lambda v: jax.lax.dynamic_update_slice(
                    jnp.zeros((new_cap,), v.dtype), v, (0,)
                ),
                out_shardings=self._sharding(False),
            )
            new_valid = grow_flat(self._valid)
            new_hi = grow_flat(self._keys_hi)
            new_lo = grow_flat(self._keys_lo)
        self._matrix = new_matrix
        self._valid = new_valid
        self._keys_hi = new_hi
        self._keys_lo = new_lo
        self.slot_to_key = np.concatenate(
            [self.slot_to_key, np.zeros(new_cap - old_cap, dtype=KEY_DTYPE)]
        )
        self._free.extend(range(new_cap - 1, old_cap - 1, -1))
        self.capacity = new_cap
        self._search_fns.clear()  # capacity is baked into compiled shapes

    # -- mutation ----------------------------------------------------------
    def add(self, keys: Sequence[int], vectors: np.ndarray) -> None:
        if len(keys) == 0:
            return
        # coerce BEFORE the lock: callers hand the encoder's device rows
        # straight here — the device→host sync must not run under the
        # index lock (value-flow analyzer finding)
        vectors = np.asarray(vectors, dtype=np.float32).reshape(
            len(keys), self.dimension
        )
        with self._lock:
            # upsert: remove keys that already exist
            existing = [k for k in keys if int(k) in self.key_to_slot]
            if existing:
                self.remove(existing)
            if len(self._free) < len(keys):
                self._grow(len(keys) - len(self._free))
            slots = np.array(
                [self._free.pop() for _ in keys], dtype=np.int32
            )
            if self.metric == "cos":
                norms = np.linalg.norm(vectors, axis=1)
                safe = np.where(norms == 0, 1.0, norms)
                vectors = vectors / safe[:, None]
            for key, slot in zip(keys, slots):
                self.key_to_slot[int(key)] = int(slot)
                self.slot_to_key[slot] = int(key)
            self._scatter(slots, vectors, True, keys=keys)
            self.generation += 1

    def add_from_device(self, keys: Sequence[int], vectors) -> None:
        """Ingest vectors that already live on device (e.g. encoder output) —
        no host round trip at all: normalisation happens on device and
        nothing is fetched back, so a pipelined caller never blocks (l2sq
        ranking recomputes row norms inside the scoring kernel)."""
        with self._lock:
            if len(keys) == 0:
                return
            vectors = vectors.reshape(len(keys), self.dimension)
            existing = [k for k in keys if int(k) in self.key_to_slot]
            if existing:
                self.remove(existing)
            if len(self._free) < len(keys):
                self._grow(len(keys) - len(self._free))
            slots = np.array([self._free.pop() for _ in keys], dtype=np.int32)
            # route through the mesh first, then normalise on device
            vectors = self._to_mesh(vectors)
            norm_fn = getattr(self, "_norm_fn_cache", None)
            if norm_fn is None:
                cos = self.metric == "cos"
                dtype = self.dtype

                def _norms_and_rows(v):
                    norms = jnp.linalg.norm(v.astype(jnp.float32), axis=1)
                    if cos:
                        safe = jnp.where(norms == 0, 1.0, norms)
                        v = (v.astype(jnp.float32) / safe[:, None]).astype(dtype)
                    return norms, v

                out_sh = (
                    None
                    if self.mesh is None
                    else NamedSharding(self.mesh, P())
                )
                norm_fn = (
                    jax.jit(_norms_and_rows)
                    if out_sh is None
                    else jax.jit(_norms_and_rows, out_shardings=(out_sh, out_sh))
                )
                self._norm_fn_cache = norm_fn
            _norms_dev, vectors = norm_fn(vectors)
            for key, slot in zip(keys, slots):
                self.key_to_slot[int(key)] = int(slot)
                self.slot_to_key[slot] = int(key)
            self._scatter(slots, vectors, True, keys=keys)
            self.generation += 1

    def remove(self, keys: Sequence[int]) -> None:
        with self._lock:
            slots = []
            for key in keys:
                slot = self.key_to_slot.pop(int(key), None)
                if slot is not None:
                    slots.append(slot)
                    self._free.append(slot)
            if not slots:
                return
            slots = np.array(slots, dtype=np.int32)
            self._scatter(slots, np.zeros((len(slots), self.dimension), np.float32), False)
            self.generation += 1

    def _scatter(
        self, slots: np.ndarray, vectors, valid: bool, keys=None
    ) -> None:
        """Batched scatter, padded to a bucket to bound recompiles (pad rows
        repeat the first row — idempotent writes).  ``vectors`` may be a host
        numpy array or a device array (add_from_device path).  ``keys`` (add
        path) also updates the device slot->key planes; removals skip them —
        the cleared valid flag masks stale keys."""
        n = len(slots)
        b = _bucket(n)
        on_device = isinstance(vectors, jax.Array)
        if keys is not None:
            keys64 = np.fromiter(
                (int(k) for k in keys), dtype=np.uint64, count=n
            )
            hi = (keys64 >> np.uint64(32)).astype(np.uint32).view(np.int32)
            lo = (keys64 & np.uint64(0xFFFFFFFF)).astype(np.uint32).view(np.int32)
            if b > n:
                hi = np.concatenate([hi, np.full(b - n, hi[0], np.int32)])
                lo = np.concatenate([lo, np.full(b - n, lo[0], np.int32)])
        if b > n:
            slots = np.concatenate([slots, np.full(b - n, slots[0], np.int32)])
            xp = jnp if on_device else np
            vectors = xp.concatenate([vectors, xp.repeat(vectors[:1], b - n, 0)])
        if not on_device:
            vectors = np.asarray(vectors, dtype=self.dtype)
        slots_dev = self._to_mesh(np.asarray(slots))
        vectors_dev = self._to_mesh(vectors)
        if self.mesh is None:
            self._matrix = _scatter_rows(self._matrix, slots_dev, vectors_dev)
            self._valid = _scatter_flags(self._valid, slots_dev, valid)
            if keys is not None:
                self._keys_hi = _scatter_vals(self._keys_hi, slots_dev, self._to_mesh(hi))
                self._keys_lo = _scatter_vals(self._keys_lo, slots_dev, self._to_mesh(lo))
        else:
            row_fn, flag_fn = self._scatter_jits()
            self._matrix = row_fn(self._matrix, slots_dev, vectors_dev)
            self._valid = flag_fn(self._valid, slots_dev, valid)
            if keys is not None:
                val_fn = self._scatter_val_jit()
                self._keys_hi = val_fn(self._keys_hi, slots_dev, self._to_mesh(hi))
                self._keys_lo = val_fn(self._keys_lo, slots_dev, self._to_mesh(lo))

    def _scatter_jits(self):
        """Scatter fns with explicit sharded out_shardings (keeps the matrix
        pinned to the mesh without a host-side device_put re-pin — required
        on multi-process meshes, cheaper on single-process ones)."""
        fns = getattr(self, "_scatter_fn_cache", None)
        if fns is None:
            fns = (
                jax.jit(
                    lambda m, s, r: m.at[s].set(r.astype(m.dtype)),
                    out_shardings=self._sharding(True),
                ),
                jax.jit(
                    lambda v, s, f: v.at[s].set(f),
                    static_argnums=2,
                    out_shardings=self._sharding(False),
                ),
            )
            self._scatter_fn_cache = fns
        return fns

    def _scatter_val_jit(self):
        fn = getattr(self, "_scatter_val_cache", None)
        if fn is None:
            fn = jax.jit(
                lambda a, s, v: a.at[s].set(v),
                out_shardings=self._sharding(False),
            )
            self._scatter_val_cache = fn
        return fn

    # -- search ------------------------------------------------------------
    def search(
        self,
        queries: np.ndarray,
        k: int,
        candidate_keys: Optional[Sequence[Sequence[int]]] = None,
    ) -> List[List[Tuple[int, float]]]:
        """Top-k per query; returns [(key, score), ...] per query row.

        ``candidate_keys``: optional per-query allow-list (metadata filtering
        path) — scoring stays on device, the allow-mask is built host-side."""
        # off-lock coercion: a device-array query batch syncs here, not
        # while holding the index lock (value-flow analyzer finding)
        queries = np.asarray(queries, dtype=np.float32).reshape(-1, self.dimension)
        with self._lock:
            nq = queries.shape[0]
            if nq == 0 or not self.key_to_slot:
                return [[] for _ in range(nq)]
            if self.metric == "cos":
                norms = np.linalg.norm(queries, axis=1)
                queries = queries / np.where(norms == 0, 1.0, norms)[:, None]
            k_eff = min(k, len(self.key_to_slot))
            b = _bucket(nq)
            if b > nq:
                queries = np.concatenate(
                    [queries, np.zeros((b - nq, self.dimension), np.float32)]
                )
            q = self._to_mesh(queries.astype(self.dtype, copy=False))
            scores, idx = self._run_search(q, k_eff)
            # overlap the two d2h copies (each sync fetch costs a full RTT on
            # tunneled TPUs — see ops/serving.py)
            for a in (scores, idx):
                if hasattr(a, "copy_to_host_async"):
                    a.copy_to_host_async()
            scores = np.asarray(scores)[:nq]
            idx = np.asarray(idx)[:nq]
            out: List[List[Tuple[int, float]]] = []
            for qi in range(nq):
                allow = None
                if candidate_keys is not None and candidate_keys[qi] is not None:
                    allow = {int(c) for c in candidate_keys[qi]}
                row: List[Tuple[int, float]] = []
                for j in range(k_eff):
                    s = float(scores[qi, j])
                    if not np.isfinite(s):
                        continue
                    key = int(self.slot_to_key[int(idx[qi, j])])
                    if key not in self.key_to_slot:
                        continue
                    if allow is not None and key not in allow:
                        continue
                    row.append((key, s))
                out.append(row[:k])
            return out

    def search_oversampled(
        self,
        queries: np.ndarray,
        k: int,
        accept,  # callable(key) -> bool
        oversample: int = 4,
        max_rounds: int = 3,
    ) -> List[List[Tuple[int, float]]]:
        """Filtered search by over-sampling: fetch oversample*k, drop rejected,
        widen until satisfied or the index is exhausted."""
        return oversampled_filtered_search(
            self, queries, k, accept, oversample=oversample, max_rounds=max_rounds
        )

    def _run_search(self, q: jnp.ndarray, k: int):
        key = (q.shape[0], k, self.capacity)
        fn = self._search_fns.get(key)
        if fn is None:
            if self.mesh is not None:
                mesh = self.mesh
                metric = self.metric

                def fn(qq, m, v):
                    return sharded_topk(mesh, qq, m, v, k, metric=metric)

                fn = jax.jit(fn)
            else:
                metric = self.metric

                def fn(qq, m, v):
                    if metric == "l2sq":
                        # -||q - x||^2 = 2 q.x - ||x||^2 - ||q||^2; rank by 2qx - x2
                        scores = 2 * jnp.dot(
                            qq, m.T, preferred_element_type=jnp.float32
                        ) - jnp.sum(m * m, axis=1)[None, :]
                        scores = jnp.where(v[None, :], scores, -jnp.inf)
                        return jax.lax.top_k(scores, k)
                    return local_score_topk(qq, m, v, k)

                fn = jax.jit(fn)
            self._search_fns[key] = fn
        return fn(q, self._matrix, self._valid)

    # l2sq exact distances post-hoc (scores returned are ranking scores)
    def scores_to_distances(self, scores: np.ndarray, query_norms: np.ndarray):
        if self.metric == "cos":
            return 1.0 - scores
        if self.metric == "l2sq":
            return -(scores - query_norms[:, None] ** 2)
        return -scores


def oversampled_filtered_search(
    index,
    queries: np.ndarray,
    k: int,
    accept,  # callable(key) -> bool
    oversample: int = 4,
    max_rounds: int = 3,
) -> List[List[Tuple[int, float]]]:
    """Shared filtered-search-by-oversampling loop over any index with
    ``search(queries, k)`` / ``__len__`` / ``dimension`` (DeviceKnnIndex and
    IvfKnnIndex): fetch oversample*k, drop rejected, widen until satisfied
    or the index is exhausted."""
    nq = np.asarray(queries).reshape(-1, index.dimension).shape[0]
    results: List[List[Tuple[int, float]]] = [[] for _ in range(nq)]
    kk = k * oversample
    for _ in range(max_rounds):
        rows = index.search(queries, kk)
        done = True
        for qi, row in enumerate(rows):
            accepted = [(key, s) for key, s in row if accept(key)]
            results[qi] = accepted[:k]
            if len(accepted) < k and len(row) >= len(index):
                pass  # exhausted
            elif len(accepted) < k and len(row) == kk:
                done = False
        if done or kk >= max(len(index), 1):
            break
        kk *= 4
    return results
