"""Runtime recompile tripwire — the dynamic half of the recompile-hazard
lint (pathway_tpu/analysis/recompile_hazard.py).

The static rule catches jitted calls fed unbucketed shapes lexically; a
hazard that slips past it (shapes threaded through data, a bucketing
helper that stops covering a new code path) still shows up at runtime as
one jitted callable accumulating compiled signatures without bound.  Every
compiled-fn cache in the serving stack registers its signatures here; a
callable crossing its budget warns once in production and FAILS under
tests (pytest or ``PATHWAY_RECOMPILE_STRICT=1``), so a recompile leak is
a red test instead of a silent latency cliff.

``RecompileTripwire`` is the counting primitive (used directly by the
per-shape ``_fns`` caches); ``guarded_jit`` wraps a bare function for
code without a cache dict.  The default budget is generous — the bucketed
paths compile a few dozen shapes at most (batch buckets × /16 length
buckets) — and tunable via ``PATHWAY_RECOMPILE_LIMIT``.
"""

from __future__ import annotations

import threading
import warnings
from typing import Any, Callable, Dict, Optional, Set, Tuple

from .. import config

__all__ = [
    "RecompileBudgetExceeded",
    "RecompileTripwire",
    "RecompileWarning",
    "guarded_jit",
    "signature_of",
    "strict_mode",
]


class RecompileWarning(UserWarning):
    """A jitted callable crossed its compiled-signature budget."""


class RecompileBudgetExceeded(RuntimeError):
    """Strict-mode flavor of :class:`RecompileWarning`."""


def _default_limit() -> int:
    return config.get("ops.recompile_limit")


def strict_mode() -> bool:
    """Fail (raise) instead of warn: explicitly via
    ``PATHWAY_RECOMPILE_STRICT=1`` / off via ``=0``; defaults to on under
    pytest so a recompile leak is a red test, never a silent slowdown."""
    return config.get("ops.recompile_strict")


class RecompileTripwire:
    """Counts distinct compile signatures for ONE logical jitted callable
    (an instance's compiled-fn cache, or one ``guarded_jit`` wrapper).

    ``observe(key)`` is called with the compile key each time a new
    compiled variant is (about to be) created; past ``limit`` distinct
    keys it warns — or raises in strict mode — with the full signature
    census so the unbucketed dimension is visible in the message."""

    def __init__(self, name: str, limit: Optional[int] = None):
        self.name = name
        self.limit = limit if limit is not None else _default_limit()
        self._sigs: Set[Any] = set()
        self._lock = threading.Lock()
        self.tripped = False
        # flight-recorder export: the per-callable compile-signature
        # census shows up as pathway_recompile_* gauges on /metrics
        # (weakly registered — a dropped tripwire leaves the scrape);
        # the id label uniquifies same-named callables across instances
        from .. import observe

        self._observe_id = observe.next_id()
        observe.register_provider(self)

    def observe_metrics(self):
        """Scrape-time gauge samples (flight-recorder provider)."""
        labels = {"callable": self.name, "id": str(self._observe_id)}
        yield ("gauge", "pathway_recompile_signatures", labels, len(self._sigs))
        yield ("gauge", "pathway_recompile_limit", labels, self.limit)
        yield ("gauge", "pathway_recompile_tripped", labels, int(self.tripped))

    @property
    def signatures(self) -> int:
        return len(self._sigs)

    def observe(self, signature: Any) -> bool:
        """Record one compile signature; returns True if it was new.
        Warns/raises when the count first exceeds ``limit`` (and again at
        every further doubling, so a still-leaking path stays loud without
        spamming every call)."""
        with self._lock:
            if signature in self._sigs:
                return False
            self._sigs.add(signature)
            n = len(self._sigs)
        if n > self.limit and (
            n == self.limit + 1 or (n & (n - 1)) == 0
        ):
            self.tripped = True
            msg = (
                f"jitted callable {self.name!r} accumulated {n} compiled "
                f"signatures (budget {self.limit}) — an input dimension "
                "is not bucketed, so every new size pays an XLA compile "
                f"on the hot path; last signature: {signature!r}. Bucket "
                "the varying dimension (_bucket/seg_bucket/"
                "row_length_bucket) or raise PATHWAY_RECOMPILE_LIMIT if "
                "the shape set is genuinely this large."
            )
            if strict_mode():
                raise RecompileBudgetExceeded(msg)
            warnings.warn(msg, RecompileWarning, stacklevel=3)
        return True


def signature_of(*args: Any, **kwargs: Any) -> Tuple:
    """Abstract compile signature of a call: (shape, dtype) for
    array-likes, pytrees walked structurally, everything else by type —
    mirroring what jax keys its compile cache on (weak types aside)."""

    def leaf(x: Any) -> Any:
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is not None and dtype is not None:
            return (tuple(shape), str(dtype))
        if isinstance(x, (list, tuple)):
            return tuple(leaf(v) for v in x)
        if isinstance(x, dict):
            return tuple(sorted((k, leaf(v)) for k, v in x.items()))
        if isinstance(x, (bool, int, float, str, bytes, type(None))):
            # static-ish scalars: value participates (python scalars
            # re-trace under jit only via weak-type promotion, but a
            # varying static arg IS a recompile)
            return (type(x).__name__, x)
        return type(x).__name__
    sig = tuple(leaf(a) for a in args)
    if kwargs:
        sig += (tuple(sorted((k, leaf(v)) for k, v in kwargs.items())),)
    return sig


def guarded_jit(
    fn: Optional[Callable] = None,
    *,
    name: Optional[str] = None,
    limit: Optional[int] = None,
    **jit_kwargs: Any,
) -> Callable:
    """``jax.jit`` with the tripwire attached: each call's abstract
    signature is observed before dispatch, so shape churn trips even when
    jax silently absorbs it into its own cache.  Usable bare
    (``@guarded_jit``) or configured (``@guarded_jit(limit=8)``); the
    wrapper exposes ``.tripwire`` for tests."""
    if fn is None:
        return lambda f: guarded_jit(f, name=name, limit=limit, **jit_kwargs)
    import functools

    import jax

    jitted = jax.jit(fn, **jit_kwargs)
    tripwire = RecompileTripwire(
        name or getattr(fn, "__qualname__", repr(fn)), limit=limit
    )

    @functools.wraps(fn)
    def wrapper(*args: Any, **kwargs: Any):
        tripwire.observe(signature_of(*args, **kwargs))
        return jitted(*args, **kwargs)

    wrapper.tripwire = tripwire
    wrapper.jitted = jitted
    return wrapper
