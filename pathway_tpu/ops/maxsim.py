"""Fused gather + dequantize + MaxSim + top-k — the late-interaction
rerank kernel over the device-resident forward index.

Stage-2 cross-encoding re-runs a transformer over every (query, doc)
pair at serve time, so rerank FLOPs scale with document length times the
over-fetch even though the documents never change between requests.
Late interaction ("Efficient Neural Ranking using Forward Indexes and
Lightweight Encoders", arxiv 2311.01263; KaLM-Reranker-V1's
compressed-document reranking, arxiv 2606.22807) moves the doc-side
encode to INGEST: per-document token embeddings are pooled to a fixed
row budget, int8-quantized, and stored HBM-resident
(pathway_tpu/index/forward.py); a serve only pays

    gather candidate rows by slot  ->  dequantize  ->
    MaxSim against the query token states  ->  per-query top-k

all inside ONE jitted dispatch with one packed int32 output — the same
shape discipline as the stage-1 fused kernel (ops/serving.py) and the
packed cross-encoder (ops/retrieve_rerank.py).  The query token states
arrive DEVICE-RESIDENT from the stage-1 dispatch (``FusedEncodeSearch``
exports them alongside the pooled embedding), so the whole happy-path
serve stays at 2 dispatches + 2 fetches.

FLOPs per pair: ``Lq x T' x d`` MACs (T' pooled doc rows), versus a full
transformer forward over the concatenated pair for the cross-encoder —
two to three orders of magnitude less device work at matched over-fetch
(the ``late_interaction`` bench phase prices both).

Shapes are compile dimensions and every one of them is bucketed by the
caller (query batch/length from stage 1, candidate width fixed per
stage, doc-row budget fixed per index, capacity grown in doubling
steps), so the kernel holds a handful of compile signatures in steady
state — the forward index's recompile tripwire counts them.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "build_maxsim_kernel",
    "build_maxsim_table_kernel",
    "build_table_merge_kernel",
    "maxsim_scores_host",
]


def _maxsim_table(qtok, qmask, tok, scales, nvalid, slots, B, Kc, T, quantized):
    """Traced core shared by the fused single-index kernel and the
    sharded per-shard kernel: gather + dequantize + MaxSim -> the full
    ``[B, Kc]`` candidate score table (``-inf`` for absent slots)."""
    flat = jnp.maximum(slots, 0).reshape(B * Kc)
    docs = jnp.take(tok, flat, axis=0).astype(jnp.float32)  # [B*Kc, T, d]
    if quantized:
        s = jnp.take(scales, flat, axis=0)  # [B*Kc, d]
        docs = docs * s[:, None, :]
    nv = jnp.take(nvalid, flat)  # [B*Kc]
    d = docs.shape[-1]
    docs = docs.reshape(B, Kc, T, d)
    # sim[b, k, l, t] = qtok[b, l] . docs[b, k, t] — one einsum, MXU
    sim = jnp.einsum(
        "bld,bktd->bklt", qtok, docs, preferred_element_type=jnp.float32
    )
    tvalid = (jnp.arange(T)[None, :] < nv[:, None]).reshape(B, Kc, 1, T)
    sim = jnp.where(tvalid, sim, -jnp.inf)
    best = jnp.max(sim, axis=3)  # [B, Kc, Lq] per-query-token best row
    # pad query tokens contribute 0; real tokens of a candidate with
    # no valid rows stay -inf, so the whole sum is -inf and the
    # candidate drops out of the top-k below
    best = jnp.where(qmask[:, None, :] > 0, best, 0.0)
    scores = jnp.sum(best, axis=2)  # [B, Kc]
    return jnp.where(slots >= 0, scores, -jnp.inf)


def build_maxsim_table_kernel(B: int, Lq: int, Kc: int, T: int, quantized: bool):
    """Per-shard flavor for the SHARDED forward index: same inputs as
    ``build_maxsim_kernel`` but the output is the raw ``[B, Kc]``
    float32 score table (``-inf`` where this shard holds no row for the
    candidate).  Document routing assigns every candidate to exactly one
    owning shard, so the cross-shard merge is an elementwise max over
    the per-shard tables — each cell has at most one finite
    contributor, and the merged table is bit-identical to what one
    unsharded index holding every row would have produced."""

    @jax.jit
    def fused(qtok, qmask, tok, scales, nvalid, slots):
        return _maxsim_table(
            qtok, qmask, tok, scales, nvalid, slots, B, Kc, T, quantized
        )

    return fused


def build_table_merge_kernel(S: int, B: int, Kc: int, k_out: int):
    """Merge ``S`` per-shard score tables: elementwise max (ownership is
    disjoint, so max = the owning shard's score) then one per-query
    top-k, emitting the same packed ``[B, 2*k_out]`` int32 layout as
    ``build_maxsim_kernel`` — the sharded and single-index rerank paths
    are drop-in interchangeable for the completion code."""

    @jax.jit
    def fused(*tables):
        table = tables[0]
        for t in tables[1:]:
            table = jnp.maximum(table, t)
        s, perm = jax.lax.top_k(table, k_out)
        s_bits = jax.lax.bitcast_convert_type(s, jnp.int32)
        return jnp.concatenate([s_bits, perm.astype(jnp.int32)], axis=1)

    return fused


def build_maxsim_kernel(
    B: int, Lq: int, Kc: int, T: int, k_out: int, quantized: bool
):
    """One dispatch: ``(qtok [B, Lq, d], qmask [B, Lq], tok [N, T, d],
    scales [N, d], nvalid [N], slots [B, Kc]) -> [B, 2*k_out] int32``
    (``k_out`` score bit-patterns, then the winning candidate indices —
    per-query permutations of the stage-1 candidate order, exactly the
    packed layout the cross-encoder stage-2 kernel uses).

    ``slots`` holds forward-index row-bucket slots, ``-1`` for a
    candidate that is not resident (scores ``-inf`` and can never
    outrank a real one; the host appends such candidates back from the
    previous stage's ordering).  Pad doc rows (``t >= nvalid[slot]``)
    are masked ``-inf`` before the per-query-token max; pad query tokens
    (``qmask == 0``) contribute nothing to the MaxSim sum.  Scores ride
    int32 lanes bit-exactly for the same NaN-canonicalization reason as
    every other packed serve output (ops/serving.py)."""

    @jax.jit
    def fused(qtok, qmask, tok, scales, nvalid, slots):
        scores = _maxsim_table(
            qtok, qmask, tok, scales, nvalid, slots, B, Kc, T, quantized
        )
        s, perm = jax.lax.top_k(scores, k_out)
        s_bits = jax.lax.bitcast_convert_type(s, jnp.int32)
        return jnp.concatenate([s_bits, perm.astype(jnp.int32)], axis=1)

    return fused


def maxsim_scores_host(
    qtok: np.ndarray,
    qmask: np.ndarray,
    docs: np.ndarray,
    nvalid: np.ndarray,
) -> np.ndarray:
    """NumPy reference for the kernel's scoring math (tests + the
    forward index's quantization-error audit): ``qtok [Lq, d]``,
    ``qmask [Lq]``, ``docs [K, T, d]``, ``nvalid [K]`` -> ``[K]``
    MaxSim scores.  A candidate with zero valid rows scores ``-inf``."""
    Lq = qtok.shape[0]
    K, T, _ = docs.shape
    out = np.full(K, -np.inf, np.float32)
    for ki in range(K):
        nv = int(nvalid[ki])
        if nv <= 0:
            continue
        sim = qtok @ docs[ki, :nv].T  # [Lq, nv]
        best = sim.max(axis=1)
        out[ki] = float(best[np.asarray(qmask[:Lq]) > 0].sum())
    return out
