"""Runtime donation tripwire — the dynamic half of the value-flow
analyzer's use-after-donate rule (pathway_tpu/analysis/value_flow.py).

The static rule catches use-after-donate lexically; a violation that
slips past it (a ref threaded through data structures, a snapshot taken
on another thread) surfaces at runtime only as jax's opaque "Array has
been deleted" — with no pointer to WHICH donation consumed the buffer,
and on backends where donation is silently unusable, as a p99 cliff
instead of an error.  ``PATHWAY_DONATION_GUARD=1`` arms this module:

- every donating compiled callable built through :func:`donating_jit`
  (the IVF absorb scatter, the forward-index commit scatter) POISONS
  its donated argument references after the call — the reference ids
  land in a site-attributed registry, and in strict mode the buffers
  are explicitly ``.delete()``-d so a later touch raises even on
  backends that ignored the donation;
- a poisoned reference passed back INTO any guarded call is a detected
  use-after-donate: **strict mode** (pytest, or
  ``PATHWAY_DONATION_GUARD_STRICT=1``) raises :class:`DonationViolation`
  naming both the donating and the re-using site; **production mode**
  logs once and counts ``pathway_donation_violations_total{site}`` —
  and runs the guarded call through a donation-FREE twin of the
  kernel, so the serve keeps producing correct results while the
  counter pins down the offender (the diagnostic trades donation's
  in-place-update win for safety while armed);
- ``check(value)`` is the explicit probe for tests and fetch helpers.

Guard off (the default): :func:`donating_jit` calls dispatch straight
through the donating executable — one flag read of overhead, donation
semantics untouched.
"""

from __future__ import annotations

import threading
import weakref
from typing import Any, Callable, Dict, Optional, Tuple

from .. import config

__all__ = [
    "DonationViolation",
    "check",
    "donating_jit",
    "enabled",
    "poison",
    "stats",
    "strict_mode",
    "wrap",
]


class DonationViolation(RuntimeError):
    """A donated (consumed) buffer reference was used again."""


def enabled() -> bool:
    return config.get("ops.donation_guard")


def strict_mode() -> bool:
    """Raise on a detected violation instead of log+count: explicitly
    via ``PATHWAY_DONATION_GUARD_STRICT=1`` / off via ``=0``; defaults
    to on under pytest so a use-after-donate is a red test, never a
    silent garbage read."""
    return config.get("ops.donation_guard_strict")


# id(buffer) -> (site, finalizer): site-attributed poison registry.  A
# finalizer removes the id on GC so a recycled id can never inherit a
# dead buffer's poison.
_poisoned: Dict[int, Tuple[str, Any]] = {}
_lock = threading.Lock()
_poisoned_total: Dict[str, int] = {}
_violations_total: Dict[str, int] = {}
_sites: Dict[str, None] = {}  # insertion-ordered site registry


class _Provider:
    """Flight-recorder provider: both families render for every known
    site (zeros stay visible — a silent counter is indistinguishable
    from a dead one)."""

    def observe_metrics(self):
        with _lock:
            sites = list(_sites)
            poisoned = dict(_poisoned_total)
            violations = dict(_violations_total)
        for site in sites:
            labels = {"site": site}
            yield (
                "counter", "pathway_donation_poisoned_total", labels,
                poisoned.get(site, 0),
            )
            yield (
                "counter", "pathway_donation_violations_total", labels,
                violations.get(site, 0),
            )


_provider = _Provider()


def _register_site(site: str) -> None:
    with _lock:
        first = not _sites
        _sites.setdefault(site, None)
    if first:
        # weakly registered, but the module global keeps it alive for
        # the process lifetime
        from .. import observe

        observe.register_provider(_provider)


def _forget(buf_id: int) -> None:
    with _lock:
        _poisoned.pop(buf_id, None)


def poison(site: str, *buffers: Any) -> None:
    """Mark donated buffer references consumed.  Strict mode also
    ``.delete()``-s them so ANY later touch raises, even on backends
    where the donation itself was unusable (retro-fitting TPU
    semantics onto CPU test runs)."""
    if not enabled():
        return
    _register_site(site)
    strict = strict_mode()
    n = 0
    for buf in buffers:
        if buf is None or not hasattr(buf, "is_deleted"):
            continue
        try:
            fin = weakref.finalize(buf, _forget, id(buf))
        except TypeError:  # not weakref-able: track without cleanup
            fin = None
        with _lock:
            _poisoned[id(buf)] = (site, fin)
        n += 1
        if strict:
            try:
                if not buf.is_deleted():
                    buf.delete()
            except Exception:
                pass  # a committed/aliased buffer: jax already owns it
    if n:
        with _lock:
            _poisoned_total[site] = _poisoned_total.get(site, 0) + n


def check(value: Any) -> Optional[str]:
    """The explicit probe: the donating site that consumed ``value``,
    or None when the reference is live."""
    with _lock:
        entry = _poisoned.get(id(value))
    return entry[0] if entry is not None else None


def _violation(origin: str, use_site: str) -> None:
    with _lock:
        _violations_total[use_site] = _violations_total.get(use_site, 0) + 1
    msg = (
        f"use-after-donate: a buffer donated to {origin!r} was passed "
        f"back into {use_site!r} — the donation consumed it in place; "
        "snapshot before the donating call or rebind from its results"
    )
    if strict_mode():
        raise DonationViolation(msg)
    from ..robust import log_once

    log_once(f"donation_guard:{origin}->{use_site}", "[donation_guard] %s", msg)


def _check_args(site: str, args: Tuple[Any, ...]) -> None:
    for arg in args:
        with _lock:
            entry = _poisoned.get(id(arg))
        if entry is not None:
            _violation(entry[0], site)


class _DonatingJit:
    """One donating compiled callable under the guard.  Guard off: the
    donating executable, straight through.  Guard on: incoming args are
    checked against the poison registry, the donated inputs are
    poisoned after the call, and production mode dispatches a
    donation-free twin so a detected violation stays log-only."""

    def __init__(self, fn: Callable, site: str,
                 donate_argnums: Tuple[int, ...], jit_kwargs: dict):
        import jax

        self.site = site
        self.donate_argnums = tuple(donate_argnums)
        self._fn = fn
        self._donating = jax.jit(
            fn, donate_argnums=self.donate_argnums, **jit_kwargs
        )
        self._safe: Optional[Callable] = None  # compiled on first use
        self._jit_kwargs = jit_kwargs
        self.__name__ = getattr(fn, "__name__", site)
        self.__doc__ = getattr(fn, "__doc__", None)

    def __call__(self, *args: Any, **kwargs: Any):
        if not enabled():
            return self._donating(*args, **kwargs)
        _register_site(self.site)
        _check_args(self.site, args)
        if strict_mode():
            out = self._donating(*args, **kwargs)
        else:
            # production diagnostic mode: skip the real donation so a
            # use-after-donate stays a counted log line, not a crash
            if self._safe is None:
                import jax

                self._safe = jax.jit(self._fn, **self._jit_kwargs)
            out = self._safe(*args, **kwargs)
        poison(
            self.site,
            *(args[i] for i in self.donate_argnums if i < len(args)),
        )
        return out


def donating_jit(
    fn: Optional[Callable] = None,
    *,
    site: str,
    donate_argnums: Tuple[int, ...],
    **jit_kwargs: Any,
) -> Callable:
    """``jax.jit(fn, donate_argnums=...)`` with the donation tripwire
    attached — the guard-aware constructor every donating kernel in the
    tree uses (the static analyzer registers this spelling alongside
    ``jax.jit``, so the wrapper launders nothing out of the rules)."""
    if fn is None:
        return lambda f: donating_jit(
            f, site=site, donate_argnums=donate_argnums, **jit_kwargs
        )
    return _DonatingJit(fn, site, tuple(donate_argnums), jit_kwargs)


def wrap(
    site: str, fn: Callable, donate_argnums: Tuple[int, ...]
) -> Callable:
    """Guard an ALREADY-compiled donating callable: args are checked
    and poisoned around every call.  Unlike :func:`donating_jit` this
    cannot substitute a donation-free twin, so production mode only
    counts — the underlying call still sees the real donation."""

    def guarded(*args: Any, **kwargs: Any):
        if not enabled():
            return fn(*args, **kwargs)
        _register_site(site)
        _check_args(site, args)
        out = fn(*args, **kwargs)
        poison(site, *(args[i] for i in donate_argnums if i < len(args)))
        return out

    guarded.__name__ = f"donation_guard[{site}]"
    guarded.site = site
    return guarded


def stats() -> dict:
    """Bench/test snapshot of the guard's counters."""
    with _lock:
        return {
            "sites": list(_sites),
            "tracked": len(_poisoned),
            "poisoned": dict(_poisoned_total),
            "violations": dict(_violations_total),
        }


def _reset_for_tests() -> None:
    with _lock:
        _poisoned.clear()
        _poisoned_total.clear()
        _violations_total.clear()
        _sites.clear()
