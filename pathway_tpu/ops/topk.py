"""Sharded top-k retrieval kernels.

The retrieval recipe for a row-sharded score matrix (SURVEY.md §2.6 TPU
notes): compute per-shard scores [B, N/s] on each device, take a *local*
``lax.top_k``, all-gather only the (k, index) pairs over ICI, and merge —
moving s·B·k elements over the interconnect instead of B·N.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["sharded_topk", "merge_topk", "local_score_topk"]


def local_score_topk(
    queries: jnp.ndarray,  # [B, d]
    matrix: jnp.ndarray,  # [N, d] (local shard rows)
    valid: jnp.ndarray,  # [N] bool
    k: int,
    metric: str = "dot",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Dense scores + local top-k.  MXU-shaped: one [B,d]x[d,N] matmul.

    metric "dot"/"cos" ranks by inner product (cos assumes normalised rows);
    "l2sq" ranks by 2*q.x - ||x||^2 (equivalent to -||q-x||^2 ordering)."""
    scores = jnp.dot(
        queries, matrix.T, preferred_element_type=jnp.float32
    )  # [B, N]
    if metric == "l2sq":
        scores = 2 * scores - jnp.sum(
            matrix.astype(jnp.float32) * matrix.astype(jnp.float32), axis=1
        )[None, :]
    scores = jnp.where(valid[None, :], scores, -jnp.inf)
    k_eff = min(k, matrix.shape[0])
    top_scores, top_idx = jax.lax.top_k(scores, k_eff)  # [B, k]
    if k_eff < k:
        pad = k - k_eff
        top_scores = jnp.pad(top_scores, ((0, 0), (0, pad)), constant_values=-jnp.inf)
        top_idx = jnp.pad(top_idx, ((0, 0), (0, pad)), constant_values=0)
    return top_scores, top_idx


def merge_topk(
    all_scores: jnp.ndarray,  # [S, B, k] per-shard candidates
    all_idx: jnp.ndarray,  # [S, B, k] local row indices
    shard_offsets: jnp.ndarray,  # [S] global row offset of each shard
    k: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Merge per-shard candidate lists into global top-k (global row ids)."""
    S, B, kk = all_scores.shape
    global_idx = all_idx + shard_offsets[:, None, None]
    flat_scores = jnp.transpose(all_scores, (1, 0, 2)).reshape(B, S * kk)
    flat_idx = jnp.transpose(global_idx, (1, 0, 2)).reshape(B, S * kk)
    top_scores, positions = jax.lax.top_k(flat_scores, k)
    top_global = jnp.take_along_axis(flat_idx, positions, axis=1)
    return top_scores, top_global


def sharded_topk(
    mesh: Mesh,
    queries: jnp.ndarray,  # [B, d] replicated
    matrix: jnp.ndarray,  # [N, d] sharded on rows over "data"
    valid: jnp.ndarray,  # [N] sharded over "data"
    k: int,
    metric: str = "dot",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """shard_map: per-device score+topk, all-gather candidates, merge.

    Returns replicated ([B, k] scores, [B, k] global row indices)."""
    n_shards = mesh.shape["data"]
    rows_per_shard = matrix.shape[0] // n_shards

    def per_shard(q, m, v):
        local_scores, local_idx = local_score_topk(q, m, v, k, metric=metric)
        # [1, B, k] on each shard -> all_gather over "data" -> [S, B, k]
        gathered_scores = jax.lax.all_gather(local_scores, "data")  # [S, B, k]
        gathered_idx = jax.lax.all_gather(local_idx, "data")
        my_index = jax.lax.axis_index("data")
        offsets = jnp.arange(n_shards) * rows_per_shard
        return merge_topk(gathered_scores, gathered_idx, offsets, k)

    fn = jax.shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(), P("data", None), P("data")),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return fn(queries, matrix, valid)
