"""Sharded top-k retrieval kernels.

The retrieval recipe for a row-sharded score matrix (SURVEY.md §2.6 TPU
notes): compute per-shard scores [B, N/s] on each device, take a *local*
``lax.top_k``, all-gather only the (k, index) pairs over ICI, and merge —
moving s·B·k elements over the interconnect instead of B·N.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "sharded_topk",
    "merge_topk",
    "local_score_topk",
    "tree_merge_topk",
    "tree_merge_topk_host",
]


def _shard_map(f, mesh, in_specs, out_specs):
    """``shard_map`` across jax versions: ``jax.shard_map`` (new) falls
    back to ``jax.experimental.shard_map.shard_map`` (0.4.x), where the
    replication check rejects the all-gather+merge pattern and is
    disabled the same way ``check_vma=False`` disables it upstream."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        try:
            return sm(
                f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=False,
            )
        except TypeError:
            pass
    from jax.experimental.shard_map import shard_map as sm

    return sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def local_score_topk(
    queries: jnp.ndarray,  # [B, d]
    matrix: jnp.ndarray,  # [N, d] (local shard rows)
    valid: jnp.ndarray,  # [N] bool
    k: int,
    metric: str = "dot",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Dense scores + local top-k.  MXU-shaped: one [B,d]x[d,N] matmul.

    metric "dot"/"cos" ranks by inner product (cos assumes normalised rows);
    "l2sq" ranks by 2*q.x - ||x||^2 (equivalent to -||q-x||^2 ordering)."""
    scores = jnp.dot(
        queries, matrix.T, preferred_element_type=jnp.float32
    )  # [B, N]
    if metric == "l2sq":
        scores = 2 * scores - jnp.sum(
            matrix.astype(jnp.float32) * matrix.astype(jnp.float32), axis=1
        )[None, :]
    scores = jnp.where(valid[None, :], scores, -jnp.inf)
    k_eff = min(k, matrix.shape[0])
    top_scores, top_idx = jax.lax.top_k(scores, k_eff)  # [B, k]
    if k_eff < k:
        pad = k - k_eff
        top_scores = jnp.pad(top_scores, ((0, 0), (0, pad)), constant_values=-jnp.inf)
        top_idx = jnp.pad(top_idx, ((0, 0), (0, pad)), constant_values=0)
    return top_scores, top_idx


def merge_topk(
    all_scores: jnp.ndarray,  # [S, B, k] per-shard candidates
    all_idx: jnp.ndarray,  # [S, B, k] local row indices
    shard_offsets: jnp.ndarray,  # [S] global row offset of each shard
    k: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Merge per-shard candidate lists into global top-k (global row ids)."""
    S, B, kk = all_scores.shape
    global_idx = all_idx + shard_offsets[:, None, None]
    flat_scores = jnp.transpose(all_scores, (1, 0, 2)).reshape(B, S * kk)
    flat_idx = jnp.transpose(global_idx, (1, 0, 2)).reshape(B, S * kk)
    top_scores, positions = jax.lax.top_k(flat_scores, k)
    top_global = jnp.take_along_axis(flat_idx, positions, axis=1)
    return top_scores, top_global


def tree_merge_topk(
    scores: jnp.ndarray,  # [S, B, K] per-shard candidate scores (desc)
    shard_ids: jnp.ndarray,  # [S, B, K] int32 origin shard of each candidate
    ids: jnp.ndarray,  # [S, B, K] int32 shard-local candidate ids
    k: int,
):
    """Hierarchical top-k over the shard axis: pairwise tree reduce —
    each level merges two shards' sorted candidate lists with one
    ``lax.top_k`` over their 2K-wide concat, halving the shard count
    until one list remains (⌈log2 S⌉ levels instead of one S·K-wide
    selection; at large S the level-wise merges keep every operand at
    the 2K width the top-k unit is fastest at).  Traced helper — callers
    close over it inside their own jitted merge kernel.

    Returns ``(scores [B, k], shard_ids [B, k], ids [B, k])`` sorted by
    score descending.  Only finite scores are meaningful; callers mask
    absent candidates to ``-inf`` (their shard/id survive the merge but
    the host filters non-finite rows)."""
    level = [
        (scores[s], shard_ids[s], ids[s]) for s in range(scores.shape[0])
    ]
    while len(level) > 1:
        nxt = []
        for i in range(0, len(level) - 1, 2):
            sa, ha, ia = level[i]
            sb, hb, ib = level[i + 1]
            cs = jnp.concatenate([sa, sb], axis=1)
            ch = jnp.concatenate([ha, hb], axis=1)
            ci = jnp.concatenate([ia, ib], axis=1)
            kk = min(k, cs.shape[1])
            ms, pos = jax.lax.top_k(cs, kk)
            nxt.append(
                (
                    ms,
                    jnp.take_along_axis(ch, pos, axis=1),
                    jnp.take_along_axis(ci, pos, axis=1),
                )
            )
        if len(level) % 2:
            nxt.append(level[-1])
        level = nxt
    s, h, i = level[0]
    if s.shape[1] > k:
        s, pos = jax.lax.top_k(s, k)
        h = jnp.take_along_axis(h, pos, axis=1)
        i = jnp.take_along_axis(i, pos, axis=1)
    return s, h, i


def tree_merge_topk_host(scores, shard_ids, ids, k):
    """NumPy reference for ``tree_merge_topk`` (tests + the host-merge
    probe the bench uses to price the on-device merge): same candidate
    set and score ordering, host argsort instead of the device tree."""
    import numpy as np

    S, B, K = scores.shape
    flat_s = np.transpose(scores, (1, 0, 2)).reshape(B, S * K)
    flat_h = np.transpose(shard_ids, (1, 0, 2)).reshape(B, S * K)
    flat_i = np.transpose(ids, (1, 0, 2)).reshape(B, S * K)
    order = np.argsort(-flat_s, axis=1, kind="stable")[:, :k]
    take = lambda a: np.take_along_axis(a, order, axis=1)  # noqa: E731
    return take(flat_s), take(flat_h), take(flat_i)


def sharded_topk(
    mesh: Mesh,
    queries: jnp.ndarray,  # [B, d] replicated
    matrix: jnp.ndarray,  # [N, d] sharded on rows over "data"
    valid: jnp.ndarray,  # [N] sharded over "data"
    k: int,
    metric: str = "dot",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """shard_map: per-device score+topk, all-gather candidates, merge.

    Returns replicated ([B, k] scores, [B, k] global row indices)."""
    n_shards = mesh.shape["data"]
    rows_per_shard = matrix.shape[0] // n_shards

    def per_shard(q, m, v):
        local_scores, local_idx = local_score_topk(q, m, v, k, metric=metric)
        # [1, B, k] on each shard -> all_gather over "data" -> [S, B, k]
        gathered_scores = jax.lax.all_gather(local_scores, "data")  # [S, B, k]
        gathered_idx = jax.lax.all_gather(local_idx, "data")
        my_index = jax.lax.axis_index("data")
        offsets = jnp.arange(n_shards) * rows_per_shard
        return merge_topk(gathered_scores, gathered_idx, offsets, k)

    fn = _shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(), P("data", None), P("data")),
        out_specs=(P(), P()),
    )
    return fn(queries, matrix, valid)
