"""Fused retrieve→rerank serving pipeline: TWO device round trips total.

Stage 1 is the existing ``FusedEncodeSearch`` dispatch (encode + score +
top-k in one launch); stage 2 re-scores the stage-1 candidates with the
on-device cross-encoder.  Every multi-stage ranking architecture pays this
chain per query (PAPERS.md: "An Exploration of Approaches to Integrating
Neural Reranking Models in Multi-Stage Ranking Architectures"; "Accelerating
Retrieval-Augmented Generation" names retrieve+rerank as the dominant
serving cost), and on a tunneled TPU each extra dispatch or fetch is a full
~70 ms RTT — so the stage-2 design goal is the same as stage 1's: ONE
dispatch, ONE packed fetch.

Stage 2 compiles (packed cross-encoder forward over length-bucketed,
sequence-packed (query, doc) rows) → (scatter pair scores to a [Q, Kc]
table) → (``lax.top_k`` per query) into a single jitted function whose
output is one packed int32 array: ``k`` score bit-patterns plus the ``k``
winning candidate indices (the per-query permutation of stage-1 ranks).
Short pairs share rows under block-diagonal segment attention
(models/transformer.py) instead of each padding to ``max_length`` — a
20-token pair no longer burns a 256-token row of MXU work.

``submit``/``complete`` follow the stage-1 async pattern, so consecutive
serve calls pipeline: stage 2 of call N runs on device while stage 1 of
call N+1 is already queued behind it.
"""

from __future__ import annotations

# pathway: serve-path  (hidden-sync lint applies: no implicit host round trips)

import threading
import time
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .. import observe
from .dispatch_counter import record_dispatch, record_fetch
from .recompile_guard import RecompileTripwire
from .serving import FusedEncodeSearch

__all__ = ["RetrieveRerankPipeline"]

# flight-recorder stage histograms: stage2_pack is host-side pair
# assembly + packing up to the rescore dispatch; stage2_rtt is the
# rescore dispatch→fetch; postprocess (shared series with stage 1's
# completion in ops/serving.py) is host result assembly.
_H_S2PACK = observe.histogram("pathway_serve_stage_seconds", stage="stage2_pack")
_H_S2RTT = observe.histogram("pathway_serve_stage_seconds", stage="stage2_rtt")
_H_POST = observe.histogram("pathway_serve_stage_seconds", stage="postprocess")


class _PendingServe:
    """In-flight retrieve→rerank serve handle: ``advance()`` completes
    stage 1 and dispatches stage 2 without blocking on the final fetch;
    calling the handle finishes the serve.  A per-handle lock makes both
    idempotent — a handle shared across threads (or completed twice)
    dispatches stage 2 and fetches its result exactly once."""

    __slots__ = (
        "_pipeline", "_stage1", "_queries", "_k",
        "_stage2", "_result", "_done", "_hlock",
    )

    def __init__(self, pipeline, stage1, queries, k) -> None:
        self._pipeline = pipeline
        self._stage1 = stage1
        self._queries = queries
        self._k = k
        self._stage2: Any = None
        self._result: Any = None
        self._done = False
        self._hlock = threading.Lock()

    def advance(self) -> None:
        with self._hlock:
            self._advance_locked()

    def _advance_locked(self) -> None:
        if self._stage2 is None:
            hits = self._stage1()  # host fetch #1 (stage-1 packed output)
            cand_keys = [[key for key, _ in row] for row in hits]
            with self._pipeline._lock:
                self._stage2 = self._pipeline._submit_stage2(
                    self._queries, cand_keys, self._k
                )

    def __call__(self) -> List[List[Tuple[int, float]]]:
        with self._hlock:
            if not self._done:
                self._advance_locked()
                self._result = self._stage2()
                self._done = True
            return self._result


class RetrieveRerankPipeline:
    """Chain ``FusedEncodeSearch`` (stage 1) with on-device cross-encoder
    rescoring (stage 2) at two round trips per serve call.

    ``doc_text`` maps a stage-1 winner key to its document text — a dict or
    a ``key -> str`` callable (the document store's chunk text column).
    ``candidates`` is the stage-1 shortlist width fed to the cross-encoder
    (fixed, so stage-2 compiles once per batch bucket); the final result is
    the rerank-ordered top ``k``.

    Recompiles per (row bucket, row length bucket, segment bucket, query
    bucket) — a handful of shapes in steady state.  HF-imported
    cross-encoders (no segment inputs) fall back to an unpacked host-side
    stage 2, same results, more transfers."""

    def __init__(
        self,
        retriever: FusedEncodeSearch,
        cross_encoder,
        doc_text: Union[Mapping[int, str], Callable[[int], str]],
        k: int = 10,
        candidates: Optional[int] = None,
    ):
        self.retriever = retriever
        self.cross_encoder = cross_encoder
        self.doc_text = doc_text
        self.k = k
        self.candidates = candidates or max(4 * k, 16)
        self._lock = threading.Lock()
        self._fns: Dict[Tuple, Any] = {}
        # recompile tripwire (ops/recompile_guard.py): stage-2 shapes are
        # bucketed (row/length/segment/query); a leak trips under tests
        self._tripwire = RecompileTripwire("RetrieveRerankPipeline.stage2")
        self.stats = {"serves": 0, "stage2_pairs": 0, "stage2_rows": 0}

    # -- host helpers -------------------------------------------------------
    def _text_of(self, key: int) -> str:
        src = self.doc_text
        try:
            if callable(src):
                return str(src(key) or "")
            return str(src.get(key, "") or "")
        except LookupError:  # a missing doc must not sink a serve; anything
            return ""  # else is a real bug in doc_text and must surface

    # -- stage 2 kernel -----------------------------------------------------
    def _compiled_stage2(self, R: int, L: int, S: int, Q: int, k_out: int):
        """One dispatch: packed cross-encoder forward -> scatter the pair
        scores into the [Q, Kc] candidate table -> per-query top-k -> ONE
        packed int32 output [Q, 2*k_out] (score bit-patterns, then the
        winning stage-1 candidate indices).  Scores ride int lanes for the
        same reason as serving.py: TPU float lanes canonicalize NaN
        payloads; int lanes survive bit-exact."""
        Kc = self.candidates
        key = (R, L, S, Q, k_out)
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        self._tripwire.observe(key)
        module = self.cross_encoder.module

        @jax.jit
        def fused(params, ids, segments, positions, pair_slot):
            scores = module.apply(
                {"params": params},
                ids,
                segments > 0,
                segments=segments,
                positions=positions,
                n_segments=S,
            )  # [R, S] per-segment pair scores
            flat = scores.reshape(R * S).astype(jnp.float32)
            # pair_slot[r*S+s] = q*Kc + j for real pairs, Q*Kc (out of
            # range -> dropped) for pad segments; absent candidates keep
            # -inf and can never outrank real ones
            table = jnp.full((Q * Kc,), -jnp.inf, jnp.float32)
            table = table.at[pair_slot].set(flat, mode="drop")
            s, perm = jax.lax.top_k(table.reshape(Q, Kc), k_out)
            s_bits = jax.lax.bitcast_convert_type(s, jnp.int32)
            return jnp.concatenate([s_bits, perm.astype(jnp.int32)], axis=1)

        self._fns[key] = fused
        return fused

    def _submit_stage2(
        self,
        queries: Sequence[str],
        cand_keys: List[List[int]],
        k: int,
    ):
        """Pack the (query, candidate) pairs and dispatch the stage-2
        kernel; returns a completion -> [[(key, rerank_score)]]."""
        from ..models.encoder import _bucket

        t_pack = time.perf_counter_ns()
        ce = self.cross_encoder
        Kc = self.candidates
        k_out = min(k, Kc)
        nq = len(queries)
        pairs: List[Tuple[str, str]] = []
        slot_ids: List[int] = []
        for qi, row in enumerate(cand_keys):
            for j, key in enumerate(row[:Kc]):
                pairs.append((queries[qi], self._text_of(key)))
                slot_ids.append(qi * Kc + j)
        if not pairs:
            return lambda: [[] for _ in range(nq)]
        if getattr(ce, "_hf", False):
            return self._submit_stage2_host(queries, cand_keys, pairs, k_out)
        from ..models.packing import pad_packed_rows, seg_bucket

        Qb = _bucket(nq)
        with ce._lock:
            ids, segments, positions, doc_slots, n_seg = ce._pack_pairs(pairs)
        rows_real = ids.shape[0]
        Rb = _bucket(rows_real)
        L = ids.shape[1]
        ids, segments, positions = pad_packed_rows(ids, segments, positions, Rb)
        Sb = seg_bucket(n_seg)
        pair_slot = np.full(Rb * Sb, Qb * Kc, np.int32)  # default: dropped
        for i, (r, s) in enumerate(doc_slots):
            pair_slot[r * Sb + s] = slot_ids[i]
        fn = self._compiled_stage2(Rb, L, Sb, Qb, k_out)
        out = fn(
            ce.params,
            jnp.asarray(ids),
            jnp.asarray(segments),
            jnp.asarray(positions),
            jnp.asarray(pair_slot),
        )
        record_dispatch("rerank_stage2")
        if hasattr(out, "copy_to_host_async"):
            out.copy_to_host_async()
        self.stats["stage2_pairs"] += len(pairs)
        self.stats["stage2_rows"] += Rb
        t_dispatch = time.perf_counter_ns()
        _H_S2PACK.observe_ns(t_dispatch - t_pack)
        # packing occupancy, both granularities: packed ROWS actually
        # carrying tokens vs the bucketed row count, and real PAIR
        # segments vs the padded [Rb, Sb] segment grid
        observe.record_occupancy("stage2", rows_real, Rb)
        observe.record_occupancy("stage2_pairs", len(pairs), Rb * Sb)

        def complete() -> List[List[Tuple[int, float]]]:
            arr = np.asarray(out)[:nq]
            record_fetch("rerank_stage2")
            t_fetch = time.perf_counter_ns()
            _H_S2RTT.observe_ns(t_fetch - t_dispatch)
            scores = np.ascontiguousarray(arr[:, :k_out]).view(np.float32)
            perm = arr[:, k_out:]
            results: List[List[Tuple[int, float]]] = []
            for qi in range(nq):
                row: List[Tuple[int, float]] = []
                cands = cand_keys[qi]
                for j in range(k_out):
                    s = float(scores[qi, j])
                    ci = int(perm[qi, j])
                    if not np.isfinite(s) or ci >= len(cands):
                        continue
                    row.append((cands[ci], s))
                results.append(row[:k])
            t_done = time.perf_counter_ns()
            _H_POST.observe_ns(t_done - t_fetch)
            observe.record_event(
                "serve", "rerank_stage2", t_done - t_pack,
                queries=nq, pairs=len(pairs), rows=Rb,
            )
            observe.emit_span(
                "pathway.serve.rerank_stage2",
                queries=nq, pairs=len(pairs),
                pack_ms=(t_dispatch - t_pack) * 1e-6,
                rtt_ms=(t_fetch - t_dispatch) * 1e-6,
                postprocess_ms=(t_done - t_fetch) * 1e-6,
            )
            return results

        return complete

    def _submit_stage2_host(self, queries, cand_keys, pairs, k_out):
        """HF fallback: unpacked async scoring + host-side per-query sort
        (HF modules take no segment inputs; still one dispatch + one fetch,
        just a max-length-padded batch)."""
        from ..models.encoder import _bucket

        t_pack = time.perf_counter_ns()
        score_done = self.cross_encoder.submit(pairs, packed=False)
        record_dispatch("rerank_stage2_host")
        self.stats["stage2_pairs"] += len(pairs)
        rows = _bucket(len(pairs))  # one row per pair
        self.stats["stage2_rows"] += rows
        t_dispatch = time.perf_counter_ns()
        _H_S2PACK.observe_ns(t_dispatch - t_pack)
        observe.record_occupancy("stage2", len(pairs), rows)

        def complete() -> List[List[Tuple[int, float]]]:
            flat = score_done()
            record_fetch("rerank_stage2_host")
            t_fetch = time.perf_counter_ns()
            _H_S2RTT.observe_ns(t_fetch - t_dispatch)
            results: List[List[Tuple[int, float]]] = []
            pos = 0
            for qi in range(len(queries)):
                n_c = min(len(cand_keys[qi]), self.candidates)
                scored = list(
                    zip(cand_keys[qi][:n_c], flat[pos : pos + n_c].tolist())
                )
                pos += n_c
                scored.sort(key=lambda kv: -kv[1])
                results.append(scored[:k_out])
            t_done = time.perf_counter_ns()
            _H_POST.observe_ns(t_done - t_fetch)
            observe.record_event(
                "serve", "rerank_stage2_host", t_done - t_pack,
                queries=len(queries), pairs=len(pairs),
            )
            return results

        return complete

    # -- serve --------------------------------------------------------------
    def submit(self, queries: Sequence[str], k: Optional[int] = None):
        """Dispatch stage 1 WITHOUT waiting; returns a handle that is also
        the completion callable.  ``handle.advance()`` completes stage 1
        and dispatches stage 2 without blocking on the final fetch, so a
        caller driving several in-flight serves keeps the device queue
        full (stage 2 of call N overlaps stage 1 of call N+1);
        ``handle()`` finishes the serve.  ``k`` is capped at the
        ``candidates`` pool width (standard top-k semantics: a serve cannot
        return more documents than stage 1 retrieved)."""
        k = k or self.k
        queries = list(queries)
        if not queries:
            done = _PendingServe(self, lambda: [], [], k)
            done._stage2 = lambda: []
            return done
        stage1 = self.retriever.submit(queries, self.candidates)
        with self._lock:
            self.stats["serves"] += 1
        return _PendingServe(self, stage1, queries, k)

    def __call__(
        self, queries: Sequence[str], k: Optional[int] = None
    ) -> List[List[Tuple[int, float]]]:
        return self.submit(queries, k)()
