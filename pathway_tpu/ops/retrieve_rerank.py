"""Fused retrieve→rerank serving pipeline: TWO device round trips total.

Stage 1 is the existing ``FusedEncodeSearch`` dispatch (encode + score +
top-k in one launch); stage 2 re-scores the stage-1 candidates with the
on-device cross-encoder.  Every multi-stage ranking architecture pays this
chain per query (PAPERS.md: "An Exploration of Approaches to Integrating
Neural Reranking Models in Multi-Stage Ranking Architectures"; "Accelerating
Retrieval-Augmented Generation" names retrieve+rerank as the dominant
serving cost), and on a tunneled TPU each extra dispatch or fetch is a full
~70 ms RTT — so the stage-2 design goal is the same as stage 1's: ONE
dispatch, ONE packed fetch.

Stage 2 compiles (packed cross-encoder forward over length-bucketed,
sequence-packed (query, doc) rows) → (scatter pair scores to a [Q, Kc]
table) → (``lax.top_k`` per query) into a single jitted function whose
output is one packed int32 array: ``k`` score bit-patterns plus the ``k``
winning candidate indices (the per-query permutation of stage-1 ranks).
Short pairs share rows under block-diagonal segment attention
(models/transformer.py) instead of each padding to ``max_length`` — a
20-token pair no longer burns a 256-token row of MXU work.

``submit``/``complete`` follow the stage-1 async pattern, so consecutive
serve calls pipeline: stage 2 of call N runs on device while stage 1 of
call N+1 is already queued behind it.

Rerank stages are PLUGGABLE (the refactor behind ROADMAP item 3's
configurable cascade): the pipeline runs a list of ``RerankStage``
objects, each carrying its score fn (``submit``), over-fetch factor,
deadline sub-budget, and degradation-ladder rung.  Two stages ship:

- ``CrossEncoderStage`` — the packed cross-encoder rescore above
  (rung ``rerank_skipped``);
- ``LateInteractionStage`` — MaxSim over a device-resident forward
  index (``pathway_tpu/index``): candidates' precomputed compressed
  token rows are gathered, dequantized, scored against the stage-1
  query token states and top-k'd in ONE fused dispatch (rung
  ``late_interaction_skipped``).  The query token states ride the
  stage-1 handle device-resident, so the happy-path serve stays at
  2 dispatches + 2 fetches — and the rerank device FLOPs drop by the
  document length (the cross-encoder re-encoded every pair; MaxSim is
  one ``Lq x T' x d`` score per pair).

A stage that fails (dispatch, fetch, deadline, circuit open, forward
index unavailable) flags its rung and the serve continues with the best
ranking so far — stage-by-stage degradation instead of all-or-nothing.
The default MaxSim->cross-encoder cascade runs the cross-encoder as an
optional high-precision pass over only the top few.
"""

from __future__ import annotations

# pathway: serve-path  (hidden-sync lint applies: no implicit host round trips)

import math
import threading
import time
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .. import observe
from ..observe import profile, trace
from ..robust import (
    CircuitBreaker,
    CircuitOpen,
    Deadline,
    DeadlineExceeded,
    LATE_INTERACTION_SKIPPED,
    RERANK_SKIPPED,
    RETRIEVAL_FAILED,
    RetryPolicy,
    ServeResult,
    breaker as robust_breaker,
    inject,
    log_once,
    record_degraded,
    retry_call,
    stage1_fraction,
)
from .dispatch_counter import record_dispatch, record_fetch
from .recompile_guard import RecompileTripwire
from .serving import FusedEncodeSearch

__all__ = [
    "CrossEncoderStage",
    "LateInteractionStage",
    "RerankStage",
    "RetrieveRerankPipeline",
]

# the packed stage-2 dispatch launches under the pipeline lock (the
# compile cache + stats it snapshots live there), so its retry backoff
# must stay in the low milliseconds — a long sleep would stall every
# concurrent serve's stage-2 submission
_STAGE2_RETRY = RetryPolicy(attempts=3, base_delay_s=0.002, max_delay_s=0.02)
# the HF host path wraps CrossEncoderModel.submit, whose OWN dispatch
# already retries under the "cross_encoder.dispatch" site: one outer
# attempt keeps the breaker gate + fault site without multiplying the
# inner attempt budget (3x3 dispatches and triple-counted breaker
# failures otherwise)
_OUTER_RETRY = RetryPolicy(attempts=1)

# flight-recorder stage histograms: stage2_pack is host-side pair
# assembly + packing up to the rescore dispatch; stage2_rtt is the
# rescore dispatch→fetch; postprocess (shared series with stage 1's
# completion in ops/serving.py) is host result assembly.
_H_S2PACK = observe.histogram("pathway_serve_stage_seconds", stage="stage2_pack")
_H_S2RTT = observe.histogram("pathway_serve_stage_seconds", stage="stage2_rtt")
_H_POST = observe.histogram("pathway_serve_stage_seconds", stage="postprocess")


# -- pluggable rerank stages -------------------------------------------------
class RerankStage:
    """One rung of the ranking cascade.  A stage declares

    - ``name`` — its dispatch/diagnostic label;
    - ``rung`` — the degradation-ladder flag recorded when the stage is
      skipped (failure, deadline, circuit open);
    - ``over_fetch`` — candidate-pool factor: the stage rescores the
      previous stage's top ``width(k)`` rows (an explicit ``candidates``
      count overrides the factor);
    - ``budget_fraction`` — optional share of the REMAINING deadline
      this stage may spend (``None`` = whatever remains);

    and implements ``submit(pipeline, queries, cand_rows, keep,
    deadline, query_tokens, query_mask) -> completion`` where the
    completion returns ``(rows, meta)``: per-query ``[(key, score)]``
    rankings (descending, at most ``keep`` long) plus response metadata
    to merge.  A stage failure — at submit OR completion — must raise;
    the pipeline converts it into the stage's rung and serves the best
    ranking so far (degrade, never die)."""

    name = "rerank"
    rung = RERANK_SKIPPED
    over_fetch: float = 4.0
    budget_fraction: Optional[float] = None
    needs_query_tokens = False

    def __init__(
        self,
        candidates: Optional[int] = None,
        over_fetch: Optional[float] = None,
        budget_fraction: Optional[float] = None,
    ):
        self.candidates = candidates
        if over_fetch is not None:
            self.over_fetch = float(over_fetch)
        if budget_fraction is not None:
            self.budget_fraction = float(budget_fraction)

    def width(self, k: int) -> int:
        """Input candidate-pool width for final top-``k`` serving."""
        if self.candidates is not None:
            return max(int(self.candidates), 1)
        return max(int(math.ceil(self.over_fetch * k)), k, 1)

    def sub_deadline(self, deadline: Optional[Deadline]) -> Optional[Deadline]:
        if deadline is not None and self.budget_fraction is not None:
            return deadline.sub_budget(self.budget_fraction)
        return deadline

    def submit(
        self, pipeline, queries, cand_rows, keep, deadline,
        query_tokens=None, query_mask=None, pool_width=None,
    ):
        """``cand_rows`` arrive truncated to this stage's resolved pool
        width, which the chain also passes explicitly as ``pool_width``
        so the stage can pin device shapes to it (rows may be shorter
        when the corpus is small)."""
        raise NotImplementedError

    def note_failure(self, pipeline, exc: BaseException) -> None:
        """Hook for failure bookkeeping beyond the ladder (e.g. feeding
        a model's circuit breaker).  Policy outcomes (deadline, circuit
        open) are not model failures and never reach here."""


class CrossEncoderStage(RerankStage):
    """The packed cross-encoder rescore — now also the optional
    high-precision tail of a MaxSim cascade.  Scoring runs through the
    pipeline's ``_submit_stage2`` (one packed dispatch, one fetch) sized
    to THIS stage's pool width (a cascade tail over the top 10 must not
    pay the stage-1 over-fetch's [Q, 32] score table); failures feed the
    shared per-model circuit breaker."""

    name = "cross_encoder"
    rung = RERANK_SKIPPED

    def submit(
        self, pipeline, queries, cand_rows, keep, deadline,
        query_tokens=None, query_mask=None, pool_width=None,
    ):
        cand_keys = [[key for key, _ in row] for row in cand_rows]
        return pipeline._submit_stage2(
            queries, cand_keys, keep, deadline=deadline, pool=pool_width
        )

    def note_failure(self, pipeline, exc: BaseException) -> None:
        pipeline._breaker.record_failure()


class LateInteractionStage(RerankStage):
    """MaxSim late interaction over a device-resident ``ForwardIndex``
    (pathway_tpu/index): gather candidate rows by doc id, dequantize,
    score against the stage-1 query token states, top-k — ONE fused
    dispatch, no document re-encoding, no extra query encode (the token
    states ride the stage-1 handle device-resident).

    Candidates missing from the forward index (not yet absorbed, or
    evicted) are backfilled AFTER the MaxSim-ranked rows in their
    previous-stage order and reported in ``meta["forward_missing"]``; a
    gather with nothing resident (or no token states, or a spent
    deadline) raises and serves the previous stage's scores flagged
    ``late_interaction_skipped``."""

    name = "late_interaction"
    rung = LATE_INTERACTION_SKIPPED
    needs_query_tokens = True

    def __init__(
        self,
        forward_index,
        candidates: Optional[int] = None,
        over_fetch: Optional[float] = None,
        budget_fraction: Optional[float] = None,
    ):
        super().__init__(
            candidates=candidates, over_fetch=over_fetch,
            budget_fraction=budget_fraction,
        )
        self.forward = forward_index

    def submit(
        self, pipeline, queries, cand_rows, keep, deadline,
        query_tokens=None, query_mask=None, pool_width=None,
    ):
        done, missing = self.forward.gather_submit(
            query_tokens,
            query_mask,
            [[key for key, _ in row] for row in cand_rows],
            keep,
            deadline=deadline,
            # pin the gather grid to the stage's resolved pool width so a
            # growing corpus (wider stage-1 rows) never changes shape
            width=pool_width,
        )

        def complete():
            scores, perm = done()
            results: List[List[Tuple[int, float]]] = []
            missing_keys: List[int] = []
            for qi, row in enumerate(cand_rows):
                ranked: List[Tuple[int, float]] = []
                for j in range(perm.shape[1]):
                    s = float(scores[qi, j])
                    ci = int(perm[qi, j])
                    if not np.isfinite(s) or ci >= len(row):
                        continue
                    ranked.append((row[ci][0], s))
                # candidates the forward index has no rows for could not
                # be rescored: they backfill AFTER the MaxSim-ranked rows
                # in previous-stage order with previous-stage scores (an
                # honest partial rerank beats dropping them), and every
                # one is reported in the response metadata
                for j in missing[qi]:
                    if j < len(row):
                        missing_keys.append(row[j][0])
                        if len(ranked) < keep:
                            ranked.append(row[j])
                results.append(ranked[:keep])
            meta = (
                {"forward_missing": tuple(missing_keys)}
                if missing_keys
                else None
            )
            return results, meta

        return complete


class _PendingServe:
    """In-flight retrieve→rerank serve handle: ``advance()`` completes
    stage 1 and dispatches stage 2 without blocking on the final fetch;
    calling the handle finishes the serve.  A per-handle lock makes both
    idempotent — a handle shared across threads (or completed twice)
    dispatches stage 2 and fetches its result exactly once.

    The handle is also where the degradation ladder lands (robust/):
    stage-1 results that are already on host are NEVER discarded for a
    stage-2 problem.  Reranker down / circuit open / deadline spent ⇒
    the stage-1 ranking is served flagged ``rerank_skipped``; stage 1
    itself failing (after its retry budget) ⇒ an empty result flagged
    ``retrieval_failed``.  No failure mode raises out of the handle."""

    __slots__ = (
        "_pipeline", "_stage1", "_queries", "_k",
        "_stage2", "_result", "_done", "_hlock",
        "_deadline", "_stage1_rows", "_n_requests",
    )

    def __init__(
        self, pipeline, stage1, queries, k, deadline=None, n_requests=1
    ) -> None:
        self._pipeline = pipeline
        self._stage1 = stage1
        self._queries = queries
        self._k = k
        self._stage2: Any = None
        self._result: Any = None
        self._done = False
        self._hlock = threading.Lock()
        self._deadline: Optional[Deadline] = deadline
        self._stage1_rows: Any = None
        # how many coalesced caller REQUESTS ride this serve (the serve
        # scheduler packs several into one batch): degradation flags are
        # batch-scoped but the ladder counters must count affected
        # requests, not batches — a 16-rider batch failing stage 1 is 16
        # degraded serves on pathway_serve_degraded_total
        self._n_requests = max(1, int(n_requests))

    def advance(self) -> None:
        with self._hlock:
            self._advance_locked()

    def _advance_locked(self) -> None:
        if self._stage2 is not None:
            return
        deadline = self._deadline
        try:
            hits = self._stage1()  # host fetch #1 (stage-1 packed output)
        except Exception as exc:  # ladder bottom: retrieval itself is down
            if not isinstance(exc, DeadlineExceeded):
                log_once(
                    f"stage1:{type(exc).__name__}",
                    "stage-1 retrieval failed (%r); serving empty degraded "
                    "results — first occurrence, further ones counted on "
                    "pathway_serve_degraded_total",
                    exc,
                )
            # per-request accounting: every coalesced rider of this batch
            # is an affected request (the scheduler demuxes the flagged
            # empty rows to each of them); later batches start clean
            record_degraded(RETRIEVAL_FAILED, self._n_requests)
            empty = ServeResult(
                [[] for _ in self._queries], degraded=(RETRIEVAL_FAILED,)
            )
            self._stage2 = lambda: empty
            return
        self._stage1_rows = hits
        try:
            if deadline is not None:
                # deadline-tight rung: no budget left for the rescore
                # round trip — serve the stage-1 ranking immediately
                deadline.check("stage2_submit")
            # NO pipeline lock here: stage-2 pack is pure host prep and
            # must overlap other batches' device time (the compiled-fn
            # cache + stats take the lock internally, briefly).  The
            # stage chain handles per-stage failures internally (each
            # stage's rung, cascade falls through); only the spent
            # deadline above lands in the except below.
            self._stage2 = self._pipeline._submit_chain(
                self._queries, hits, self._k,
                deadline=deadline,
                query_tokens=getattr(self._stage1, "query_tokens", None),
                query_mask=getattr(self._stage1, "query_mask", None),
                n_requests=self._n_requests,
            )
        except Exception as exc:
            # CircuitOpen / DeadlineExceeded are policy outcomes (the
            # breaker bookkeeping happened inside retry_call); anything
            # else was a dispatch failure that exhausted its retries
            if not isinstance(exc, DeadlineExceeded):
                log_once(
                    f"stage2:{type(exc).__name__}",
                    "stage-2 rerank dispatch failed (%r); serving stage-1 "
                    "scores flagged %s",
                    exc,
                    self._pipeline.stages[0].rung,
                )
            self._stage2 = self._stage1_fallback_fn()

    def _stage1_fallback_fn(self):
        """A completion serving the stage-1 ranking truncated to ``k``,
        flagged with the FIRST rerank stage's rung (stage-1's own flags
        carried over).  Later stages never ran, so only the first rung
        is recorded — the serve degraded at that point of the cascade."""
        hits = self._stage1_rows
        if hits is None:
            hits = [[] for _ in self._queries]
        k = self._k
        rung = self._pipeline.stages[0].rung
        result = ServeResult(
            [list(row[:k]) for row in hits],
            degraded=tuple(getattr(hits, "degraded", ())) + (rung,),
        )
        record_degraded(rung, self._n_requests)
        return lambda: result

    def __call__(self) -> List[List[Tuple[int, float]]]:
        with self._hlock:
            if not self._done:
                self._advance_locked()
                try:
                    self._result = self._stage2()
                except DeadlineExceeded:
                    # stage 2 missed the deadline mid-fetch: the stage-1
                    # results already on host are the serve
                    self._result = self._stage1_fallback_fn()()
                except Exception as exc:
                    # last-resort safety net (the stage chain handles its
                    # own failures): serve the stage-1 ranking flagged
                    # with the first stage's rung
                    log_once(
                        f"stage2_fetch:{type(exc).__name__}",
                        "stage-2 rerank completion failed (%r); serving "
                        "stage-1 scores flagged %s",
                        exc,
                        self._pipeline.stages[0].rung,
                    )
                    self._result = self._stage1_fallback_fn()()
                self._done = True
            return self._result


class RetrieveRerankPipeline:
    """Chain ``FusedEncodeSearch`` (stage 1) with on-device cross-encoder
    rescoring (stage 2) at two round trips per serve call.

    ``doc_text`` maps a stage-1 winner key to its document text — a dict or
    a ``key -> str`` callable (the document store's chunk text column).
    ``candidates`` is the stage-1 shortlist width fed to the cross-encoder
    (fixed, so stage-2 compiles once per batch bucket); the final result is
    the rerank-ordered top ``k``.

    Recompiles per (row bucket, row length bucket, segment bucket, query
    bucket) — a handful of shapes in steady state.  HF-imported
    cross-encoders (no segment inputs) fall back to an unpacked host-side
    stage 2, same results, more transfers."""

    def __init__(
        self,
        retriever: FusedEncodeSearch,
        cross_encoder=None,
        doc_text: Union[Mapping[int, str], Callable[[int], str], None] = None,
        k: int = 10,
        candidates: Optional[int] = None,
        deadline_ms: Optional[float] = None,
        rerank_breaker: Optional[CircuitBreaker] = None,
        forward_index=None,
        cascade: Optional[int] = None,
        stages: Optional[Sequence[RerankStage]] = None,
    ):
        self.retriever = retriever
        self.cross_encoder = cross_encoder
        self.doc_text = doc_text
        self.k = k
        # per-serve wall-clock budget: explicit arg beats the
        # PATHWAY_SERVE_DEADLINE_MS env default; <= 0 disables
        self.deadline_ms = deadline_ms
        # per-model circuit breaker shared across pipelines scoring
        # through the same cross-encoder: persistent rerank failures
        # open it and every serve fast-paths to the rerank_skipped rung
        # until the half-open probe succeeds (robust/retry.py)
        self._breaker = rerank_breaker or robust_breaker("cross_encoder")
        # -- the ranking cascade (pluggable stages) -------------------------
        # explicit ``stages`` wins; else a ``forward_index`` builds the
        # MaxSim stage, with the cross-encoder as an optional
        # high-precision pass over the top ``cascade`` rows; else the
        # classic single cross-encoder stage
        width = candidates or max(4 * k, 16)
        if stages is not None:
            self.stages: List[RerankStage] = list(stages)
        elif forward_index is not None:
            self.stages = [LateInteractionStage(forward_index, candidates=width)]
            if cascade:
                self.stages.append(
                    CrossEncoderStage(candidates=max(int(cascade), k))
                )
        else:
            self.stages = [CrossEncoderStage(candidates=width)]
        if not self.stages:
            raise ValueError("RetrieveRerankPipeline needs at least one stage")
        if any(isinstance(s, CrossEncoderStage) for s in self.stages) and (
            cross_encoder is None or doc_text is None
        ):
            raise ValueError(
                "a CrossEncoderStage needs cross_encoder= and doc_text="
            )
        # stage-1 over-fetch = the first rerank stage's candidate pool
        self.candidates = self.stages[0].width(k)
        # the MaxSim stage scores against the stage-1 query token states:
        # flip the retriever's device-resident export on (no extra query
        # encode; the fused stage-1 kernel returns them alongside).  A
        # retriever that CANNOT export (HF-imported trunk, non-mean
        # pooling) must fail HERE — otherwise every serve would silently
        # degrade late_interaction_skipped forever
        if any(s.needs_query_tokens for s in self.stages):
            retriever.export_query_tokens = True
            # POSITIVE capability proof: a retriever that cannot show a
            # truthy ``_exporting()`` (HF trunk, non-mean pooling, or a
            # duck-typed retriever with no export support at all) would
            # serve every request late_interaction_skipped forever —
            # that is a construction error, not a runtime degradation
            exporting = getattr(retriever, "_exporting", None)
            if exporting is None or not exporting():
                raise ValueError(
                    "a late-interaction stage needs query token states, "
                    "but this retriever cannot export them (requires "
                    "FusedEncodeSearch over the in-framework "
                    "TransformerEncoder trunk with pool='mean'; "
                    "HF-imported encoders pool internally)"
                )
        self._lock = threading.Lock()
        self._fns: Dict[Tuple, Any] = {}
        # recompile tripwire (ops/recompile_guard.py): stage-2 shapes are
        # bucketed (row/length/segment/query); a leak trips under tests
        self._tripwire = RecompileTripwire("RetrieveRerankPipeline.stage2")
        self.stats = {"serves": 0, "stage2_pairs": 0, "stage2_rows": 0}

    def _default_deadline(self) -> Optional[Deadline]:
        if self.deadline_ms is not None:
            return (
                Deadline.after_ms(self.deadline_ms)
                if self.deadline_ms > 0
                else None
            )
        return Deadline.from_env()

    def index_generation(self) -> int:
        """Result-visibility generation of the stage-1 index, for the
        coalescing scheduler's generation-keyed in-window dedup (an
        absorb/retrain landing mid-window must not let a later rider
        share a slot dispatched against the pre-mutation index).

        The serve-cache plumb-through rides the same counter: stage 1
        stamps its DISPATCH-time generation into
        ``meta["index_generation"]`` (ops/serving.py), ``_submit_chain``
        merges stage-1 meta into the final ``ServeResult``, and the
        scheduler's tier-0 capture refuses any row whose dispatch
        observed a newer generation than its admission key
        (serve/scheduler.py ``_demux``)."""
        gen_fn = getattr(self.retriever, "index_generation", None)
        if callable(gen_fn):
            return int(gen_fn())
        return int(
            getattr(getattr(self.retriever, "index", None), "generation", 0)
        )

    # -- the stage chain ----------------------------------------------------
    def _submit_chain(
        self,
        queries: Sequence[str],
        hits,
        k: int,
        deadline: Optional[Deadline] = None,
        query_tokens=None,
        query_mask=None,
        n_requests: int = 1,
    ):
        """Dispatch the FIRST rerank stage now (so stage 2 of this serve
        overlaps stage 1 of the next — the pipelining contract) and
        return a completion that walks the remaining cascade.  Each
        stage rescores the best ranking so far, truncated to its own
        candidate width; a stage that fails — submit, fetch, deadline,
        circuit open — flags its rung, counts the affected requests, and
        the chain continues from the previous ranking (stage-by-stage
        degradation, never an exception out of the serve).

        The final ``ServeResult`` carries the union of stage-1 flags,
        every skipped stage's rung (each exactly once) and the merged
        stage metadata; ``ServeResult`` itself mirrors the flags into
        ``meta["degraded_reasons"]``."""
        stages = self.stages
        flags: List[str] = list(getattr(hits, "degraded", ()))
        meta: Dict[str, Any] = dict(getattr(hits, "meta", {}) or {})
        meta.pop("degraded_reasons", None)  # regenerated from final flags
        rows: List[List[Tuple[int, float]]] = [list(r) for r in hits]
        # keep_i: how many rows stage i must emit — the next stage's
        # candidate pool, or the final k for the last stage
        keeps = [
            stages[i + 1].width(k) if i + 1 < len(stages) else k
            for i in range(len(stages))
        ]
        # per-stage trace bookkeeping (observe/trace.py): submit time and
        # sub-budget, stamped onto each cascade-stage span so a kept
        # trace shows WHERE down the ladder a serve degraded and how
        # much budget the stage had when it ran
        t_stage: List[int] = [0] * len(stages)
        stage_budget_ms: List[Optional[float]] = [None] * len(stages)

        def stage_span(i: int, status: str, t_end: int, **attrs) -> None:
            _t = trace.current()
            if _t is None:
                return
            t0 = t_stage[i] or t_end
            _t.add_span(
                "stage." + stages[i].name, t0, t_end, status=status,
                budget_ms=stage_budget_ms[i], keep=keeps[i], **attrs,
            )

        def skip(stage: RerankStage, exc: BaseException) -> None:
            if not isinstance(exc, (DeadlineExceeded, CircuitOpen)):
                stage.note_failure(self, exc)
                log_once(
                    f"stage:{stage.name}:{type(exc).__name__}",
                    "rerank stage %s failed (%r); serving the previous "
                    "ranking flagged %s",
                    stage.name,
                    exc,
                    stage.rung,
                )
            stage_span(
                stages.index(stage), stage.rung, time.perf_counter_ns(),
                error=type(exc).__name__,
            )
            if stage.rung not in flags:
                flags.append(stage.rung)
                record_degraded(stage.rung, n_requests)

        def try_submit(i: int, cur_rows):
            stage = stages[i]
            if not any(cur_rows):
                return None  # nothing to rerank (empty retrieval): no rung
            if deadline is not None:
                deadline.check(f"{stage.name}_submit")
            width = stage.width(k)
            t_stage[i] = time.perf_counter_ns()
            sub = stage.sub_deadline(deadline)
            if sub is not None and trace.current() is not None:
                stage_budget_ms[i] = round(sub.remaining_s() * 1e3, 3)
            return stage.submit(
                self,
                queries,
                [r[:width] for r in cur_rows],
                keeps[i],
                sub,
                query_tokens=query_tokens,
                query_mask=query_mask,
                pool_width=width,
            )

        # stage 0 dispatches NOW (pipelining); its submit failure is
        # handled HERE like any other stage's, so the cascade falls
        # through — a cold forward index (gather unavailable) must not
        # rob a healthy cross-encoder tail of its rescore
        pending = None
        try:
            pending = try_submit(0, rows)
        except Exception as exc:
            skip(stages[0], exc)

        def complete() -> ServeResult:
            nonlocal rows
            i = 0
            cur = pending
            while i < len(stages):
                if cur is not None:
                    try:
                        res = cur()
                        if isinstance(res, tuple):
                            new_rows, stage_meta = res
                        else:  # a ServeResult-style completion
                            new_rows = list(res)
                            stage_meta = getattr(res, "meta", None)
                            for f in getattr(res, "degraded", ()):
                                if f not in flags:
                                    flags.append(f)
                        rows = [list(r) for r in new_rows]
                        if stage_meta:
                            stage_meta = dict(stage_meta)
                            stage_meta.pop("degraded_reasons", None)
                            meta.update(stage_meta)
                        stage_span(i, "ok", time.perf_counter_ns())
                    except Exception as exc:
                        skip(stages[i], exc)
                i += 1
                if i < len(stages):
                    cur = None
                    try:
                        cur = try_submit(i, rows)
                    except Exception as exc:
                        skip(stages[i], exc)
            return ServeResult(
                [list(r[:k]) for r in rows],
                degraded=flags,
                meta=meta or None,
            )

        return complete

    # -- host helpers -------------------------------------------------------
    def _text_of(self, key: int, missing: Optional[List[int]] = None) -> str:
        """Document text for a stage-1 winner.  A key evicted between
        retrieval and rerank (LookupError, or absent from the mapping)
        must not sink the serve: it scores against empty text and is
        reported in the response metadata (``meta["missing_docs"]``).
        Any OTHER exception is a real bug in ``doc_text`` and surfaces."""
        src = self.doc_text
        try:
            if callable(src):
                text = src(key)
            else:
                if key not in src:
                    raise LookupError(key)
                text = src[key]
        except LookupError:
            if missing is not None:
                missing.append(key)
            return ""
        return str(text or "")

    # -- stage 2 kernel -----------------------------------------------------
    def _compiled_stage2(
        self, R: int, L: int, S: int, Q: int, k_out: int,
        Kc: Optional[int] = None,
    ):
        """One dispatch: packed cross-encoder forward -> scatter the pair
        scores into the [Q, Kc] candidate table -> per-query top-k -> ONE
        packed int32 output [Q, 2*k_out] (score bit-patterns, then the
        winning stage-1 candidate indices).  Scores ride int lanes for the
        same reason as serving.py: TPU float lanes canonicalize NaN
        payloads; int lanes survive bit-exact.

        Takes the pipeline lock internally (cache dict + tripwire only):
        callers pack and dispatch OFF the lock so concurrent batches'
        host prep overlaps.  ``Kc`` is the calling stage's candidate-pool
        width (the [Q, Kc] score-table dimension) — a cascade's
        cross-encoder tail over the top few must not pay the stage-1
        over-fetch's table and top-k."""
        Kc = Kc or self.candidates
        key = (R, L, S, Q, k_out, Kc)
        with self._lock:
            fn = self._fns.get(key)
            if fn is not None:
                return fn
            self._tripwire.observe(key)
            module = self.cross_encoder.module

            @jax.jit
            def fused(params, ids, segments, positions, pair_slot):
                scores = module.apply(
                    {"params": params},
                    ids,
                    segments > 0,
                    segments=segments,
                    positions=positions,
                    n_segments=S,
                )  # [R, S] per-segment pair scores
                flat = scores.reshape(R * S).astype(jnp.float32)
                # pair_slot[r*S+s] = q*Kc + j for real pairs, Q*Kc (out of
                # range -> dropped) for pad segments; absent candidates keep
                # -inf and can never outrank real ones
                table = jnp.full((Q * Kc,), -jnp.inf, jnp.float32)
                table = table.at[pair_slot].set(flat, mode="drop")
                s, perm = jax.lax.top_k(table.reshape(Q, Kc), k_out)
                s_bits = jax.lax.bitcast_convert_type(s, jnp.int32)
                return jnp.concatenate([s_bits, perm.astype(jnp.int32)], axis=1)

            # device-time attribution (observe/profile.py)
            fused = profile.wrap("rerank.stage2", fused)
            self._fns[key] = fused
            return fused

    def _submit_stage2(
        self,
        queries: Sequence[str],
        cand_keys: List[List[int]],
        k: int,
        deadline: Optional[Deadline] = None,
        stage1_flags: Sequence[str] = (),
        pool: Optional[int] = None,
    ):
        """Pack the (query, candidate) pairs and dispatch the stage-2
        kernel; returns a completion -> ``ServeResult`` of
        [[(key, rerank_score)]] carrying the stage-1 degradation flags
        and any ``missing_docs`` metadata.  ``pool`` is the calling
        stage's candidate width (defaults to the pipeline's stage-1
        over-fetch — the classic single-stage configuration)."""
        from ..models.encoder import _bucket

        t_pack = time.perf_counter_ns()
        ce = self.cross_encoder
        Kc = pool or self.candidates
        k_out = min(k, Kc)
        nq = len(queries)
        pairs: List[Tuple[str, str]] = []
        slot_ids: List[int] = []
        missing: List[int] = []
        for qi, row in enumerate(cand_keys):
            for j, key in enumerate(row[:Kc]):
                pairs.append((queries[qi], self._text_of(key, missing)))
                slot_ids.append(qi * Kc + j)
        meta = {"missing_docs": tuple(missing)} if missing else None
        if not pairs:
            return lambda: ServeResult(
                [[] for _ in range(nq)], degraded=stage1_flags, meta=meta
            )
        if getattr(ce, "_hf", False):
            return self._submit_stage2_host(
                queries, cand_keys, pairs, k_out,
                deadline=deadline, stage1_flags=stage1_flags, meta=meta,
                pool=Kc,
            )
        from ..models.packing import pad_packed_rows, seg_bucket

        Qb = _bucket(nq)
        # pack OFF every lock: tokenization + row packing are pure host
        # work on stateless helpers, and under the coalescing scheduler
        # batch N+1's pack must overlap batch N's device time
        ids, segments, positions, doc_slots, n_seg = ce._pack_pairs(pairs)
        rows_real = ids.shape[0]
        Rb = _bucket(rows_real)
        L = ids.shape[1]
        ids, segments, positions = pad_packed_rows(ids, segments, positions, Rb)
        Sb = seg_bucket(n_seg)
        pair_slot = np.full(Rb * Sb, Qb * Kc, np.int32)  # default: dropped
        for i, (r, s) in enumerate(doc_slots):
            pair_slot[r * Sb + s] = slot_ids[i]
        fn = self._compiled_stage2(Rb, L, Sb, Qb, k_out, Kc=Kc)
        # retry transient dispatch failures; the per-model breaker both
        # gates the attempts (CircuitOpen fast-fails to the ladder) and
        # learns from their outcomes ("rerank.dispatch" is the chaos site)
        out = retry_call(
            "rerank.dispatch",
            fn,
            ce.params,
            jnp.asarray(ids),
            jnp.asarray(segments),
            jnp.asarray(positions),
            jnp.asarray(pair_slot),
            deadline=deadline,
            policy=_STAGE2_RETRY,
            breaker=self._breaker,
        )
        record_dispatch("rerank_stage2")
        if hasattr(out, "copy_to_host_async"):
            out.copy_to_host_async()
        with self._lock:
            self.stats["stage2_pairs"] += len(pairs)
            self.stats["stage2_rows"] += Rb
        t_dispatch = time.perf_counter_ns()
        _H_S2PACK.observe_ns(t_dispatch - t_pack)
        # packing occupancy, both granularities: packed ROWS actually
        # carrying tokens vs the bucketed row count, and real PAIR
        # segments vs the padded [Rb, Sb] segment grid
        observe.record_occupancy("stage2", rows_real, Rb)
        observe.record_occupancy("stage2_pairs", len(pairs), Rb * Sb)
        _t = trace.current()
        if _t is not None:
            _t.add_span(
                "stage2.pack_dispatch", t_pack, t_dispatch,
                exemplar=_H_S2PACK, pairs=len(pairs), rows=Rb,
            )

        def complete() -> List[List[Tuple[int, float]]]:
            inject.fire("cross_encoder.fetch", deadline=deadline)
            if deadline is not None:
                # budget spent before blocking on the stage-2 copy: the
                # stage-1 results already on host ARE the serve — the
                # caller (_PendingServe) converts this into the
                # rerank_skipped rung instead of waiting longer
                deadline.check("cross_encoder.fetch")
            arr = np.asarray(out)[:nq]
            record_fetch("rerank_stage2")
            t_fetch = time.perf_counter_ns()
            _H_S2RTT.observe_ns(t_fetch - t_dispatch)
            _ct = trace.current()
            if _ct is not None:
                _ct.add_span(
                    "stage2.rtt", t_dispatch, t_fetch, exemplar=_H_S2RTT
                )
            scores = np.ascontiguousarray(arr[:, :k_out]).view(np.float32)
            perm = arr[:, k_out:]
            results: List[List[Tuple[int, float]]] = []
            for qi in range(nq):
                row: List[Tuple[int, float]] = []
                cands = cand_keys[qi]
                for j in range(k_out):
                    s = float(scores[qi, j])
                    ci = int(perm[qi, j])
                    if not np.isfinite(s) or ci >= len(cands):
                        continue
                    row.append((cands[ci], s))
                results.append(row[:k])
            t_done = time.perf_counter_ns()
            _H_POST.observe_ns(t_done - t_fetch)
            observe.record_event(
                "serve", "rerank_stage2", t_done - t_pack,
                queries=nq, pairs=len(pairs), rows=Rb,
            )
            observe.emit_span(
                "pathway.serve.rerank_stage2",
                queries=nq, pairs=len(pairs),
                pack_ms=(t_dispatch - t_pack) * 1e-6,
                rtt_ms=(t_fetch - t_dispatch) * 1e-6,
                postprocess_ms=(t_done - t_fetch) * 1e-6,
            )
            return ServeResult(results, degraded=stage1_flags, meta=meta)

        return complete

    def _submit_stage2_host(
        self,
        queries,
        cand_keys,
        pairs,
        k_out,
        deadline: Optional[Deadline] = None,
        stage1_flags: Sequence[str] = (),
        meta=None,
        pool: Optional[int] = None,
    ):
        """HF fallback: unpacked async scoring + host-side per-query sort
        (HF modules take no segment inputs; still one dispatch + one fetch,
        just a max-length-padded batch)."""
        from ..models.encoder import _bucket

        t_pack = time.perf_counter_ns()
        # the lambda forwards the deadline to the MODEL's submit (so its
        # inner "cross_encoder.dispatch" retries and its completion-time
        # check are budget-bounded) — retry_call's own deadline= kwarg is
        # consumed by the wrapper and would otherwise never reach it
        score_done = retry_call(
            "rerank.dispatch",
            lambda: self.cross_encoder.submit(
                pairs, packed=False, deadline=deadline
            ),
            deadline=deadline,
            policy=_OUTER_RETRY,
            breaker=self._breaker,
        )
        record_dispatch("rerank_stage2_host")
        rows = _bucket(len(pairs))  # one row per pair
        with self._lock:
            self.stats["stage2_pairs"] += len(pairs)
            self.stats["stage2_rows"] += rows
        t_dispatch = time.perf_counter_ns()
        _H_S2PACK.observe_ns(t_dispatch - t_pack)
        observe.record_occupancy("stage2", len(pairs), rows)

        def complete() -> List[List[Tuple[int, float]]]:
            inject.fire("cross_encoder.fetch", deadline=deadline)
            if deadline is not None:
                deadline.check("cross_encoder.fetch")
            flat = score_done()
            record_fetch("rerank_stage2_host")
            t_fetch = time.perf_counter_ns()
            _H_S2RTT.observe_ns(t_fetch - t_dispatch)
            _ct = trace.current()
            if _ct is not None:
                _ct.add_span(
                    "stage2.rtt", t_dispatch, t_fetch,
                    exemplar=_H_S2RTT, host=True,
                )
            results: List[List[Tuple[int, float]]] = []
            pos = 0
            width = pool or self.candidates
            for qi in range(len(queries)):
                n_c = min(len(cand_keys[qi]), width)
                scored = list(
                    zip(cand_keys[qi][:n_c], flat[pos : pos + n_c].tolist())
                )
                pos += n_c
                scored.sort(key=lambda kv: -kv[1])
                results.append(scored[:k_out])
            t_done = time.perf_counter_ns()
            _H_POST.observe_ns(t_done - t_fetch)
            observe.record_event(
                "serve", "rerank_stage2_host", t_done - t_pack,
                queries=len(queries), pairs=len(pairs),
            )
            return ServeResult(results, degraded=stage1_flags, meta=meta)

        return complete

    # -- serve --------------------------------------------------------------
    def submit(
        self,
        queries: Sequence[str],
        k: Optional[int] = None,
        deadline: Optional[Deadline] = None,
        n_requests: int = 1,
    ):
        """Dispatch stage 1 WITHOUT waiting; returns a handle that is also
        the completion callable.  ``handle.advance()`` completes stage 1
        and dispatches stage 2 without blocking on the final fetch, so a
        caller driving several in-flight serves keeps the device queue
        full (stage 2 of call N overlaps stage 1 of call N+1);
        ``handle()`` finishes the serve.  ``k`` is capped at the
        ``candidates`` pool width (standard top-k semantics: a serve cannot
        return more documents than stage 1 retrieved).

        ``deadline`` (default: ``deadline_ms`` ctor arg, then the
        ``PATHWAY_SERVE_DEADLINE_MS`` env knob) is the serve's wall-clock
        budget: stage 1 gets a ``stage1_fraction()`` sub-budget, stage 2
        whatever remains, and a spent budget degrades the serve down the
        ladder (rerank_skipped / retrieval_failed) instead of raising.

        ``n_requests`` is the coalesced-rider count when a serve
        scheduler packed several caller requests into this one batch:
        degradation COUNTERS then count affected requests, not batches
        (the flags on the shared ``ServeResult`` are demuxed to each
        rider by the scheduler)."""
        k = k or self.k
        queries = list(queries)
        if deadline is None:
            deadline = self._default_deadline()
        if not queries:
            done = _PendingServe(self, lambda: ServeResult(), [], k)
            done._stage2 = lambda: ServeResult()
            return done
        stage1_deadline = (
            deadline.sub_budget(stage1_fraction()) if deadline else None
        )
        try:
            # only pass the kwarg when there IS a deadline, so duck-typed
            # retrievers with the pre-deadline submit(texts, k) signature
            # keep working in the no-deadline configuration
            if stage1_deadline is not None:
                stage1 = self.retriever.submit(
                    queries, self.candidates, deadline=stage1_deadline
                )
            else:
                stage1 = self.retriever.submit(queries, self.candidates)
        except TypeError:
            # a signature mismatch is a programming error, not a
            # retrieval outage — it must surface loudly at submit time,
            # never masquerade as permanent retrieval_failed serves
            raise
        except Exception as exc:
            # stage-1 dispatch failed past its retry budget: the handle
            # re-raises at advance() time so the ladder lands in ONE
            # place (_PendingServe), whether dispatch or fetch failed
            def stage1(_exc: Exception = exc):
                raise _exc

        with self._lock:
            self.stats["serves"] += 1
        return _PendingServe(
            self, stage1, queries, k, deadline=deadline, n_requests=n_requests
        )

    def __call__(
        self,
        queries: Sequence[str],
        k: Optional[int] = None,
        deadline: Optional[Deadline] = None,
    ) -> List[List[Tuple[int, float]]]:
        return self.submit(queries, k, deadline=deadline)()
