"""Ring attention — sequence-parallel exact attention for long contexts.

The reference has no model-execution long-context machinery (SURVEY §5.7);
this is new TPU-first surface: shard the sequence over a mesh axis, keep
each device's Q block resident, and rotate K/V blocks around the ring with
``ppermute`` while accumulating softmax online (flash-attention style
running max / normalizer), so attention over length L costs O(L/n) memory
per device and the K/V transfers ride ICI neighbor links.  Equivalent in
exact arithmetic to full softmax attention — verified against the dense
computation in tests on a virtual 8-device mesh.

Layouts (per device, via shard_map):
  q, k, v: [B, L_local, H, Dh]   sharded on the sequence axis
  kv_mask: [B, L_local]          key validity (padding)
  positions: [B, L_local]        global token positions (for causal)
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["ring_attention", "ring_attention_sharded"]


def _block_attn(q, k, v, kv_allowed, q_pos, k_pos, causal, scale):
    """Scores of the local Q block against one K/V block + online-softmax
    pieces.  Returns (block_max, exp_scores @ v, exp_scores row-sums)."""
    s = jnp.einsum(
        "blhd,bmhd->bhlm", q, k, preferred_element_type=jnp.float32
    ) * scale
    allowed = kv_allowed[:, None, None, :]  # [B,1,1,M]
    if causal:
        allowed = jnp.logical_and(
            allowed, (k_pos[:, None, None, :] <= q_pos[:, None, :, None])
        )
    s = jnp.where(allowed, s, -jnp.inf)
    m = jnp.max(s, axis=-1)  # [B,H,L]
    # keep -inf rows finite: exp(-inf - finite) handled via where
    safe_m = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - safe_m[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    o = jnp.einsum("bhlm,bmhd->blhd", p, v.astype(jnp.float32))
    l = jnp.sum(p, axis=-1)  # [B,H,L]
    return m, o, l


def _axis_size(axis_name: str) -> int:
    """Static mapped-axis size across jax versions: ``jax.lax.axis_size``
    (new) falls back to the classic ``psum(1, axis)`` constant-fold on
    0.4.x — both yield a Python int at trace time, which the ring needs
    for its static permutation list and scan length."""
    size_fn = getattr(jax.lax, "axis_size", None)
    if size_fn is not None:
        return int(size_fn(axis_name))
    return int(jax.lax.psum(1, axis_name))


def ring_attention(
    q, k, v, kv_mask, positions, axis_name: str, causal: bool = False
):
    """Per-device body (call inside shard_map over ``axis_name``)."""
    n = _axis_size(axis_name)
    scale = 1.0 / np.sqrt(q.shape[-1])
    q32 = q.astype(jnp.float32)
    q_pos = positions

    def merge(m, o, l, bm, bo, bl):
        new_m = jnp.maximum(m, bm)
        safe = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        corr_old = jnp.where(jnp.isfinite(m), jnp.exp(m - safe), 0.0)
        corr_new = jnp.where(jnp.isfinite(bm), jnp.exp(bm - safe), 0.0)
        o = o * corr_old[..., None].transpose(0, 2, 1, 3) + bo * corr_new[
            ..., None
        ].transpose(0, 2, 1, 3)
        l = l * corr_old + bl * corr_new
        return new_m, o, l

    # local block first, then rotate-then-compute for the remaining n-1
    # blocks — n blocks need only n-1 rotations, so no wasted ICI round
    allowed0 = kv_mask.astype(bool)
    m, o, l = _block_attn(
        q32, k.astype(jnp.float32), v, allowed0, q_pos, positions, causal, scale
    )

    def step(carry, _):
        k_blk, v_blk, blk_mask, blk_pos, m, o, l = carry
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        blk_mask = jax.lax.ppermute(blk_mask, axis_name, perm)
        blk_pos = jax.lax.ppermute(blk_pos, axis_name, perm)
        bm, bo, bl = _block_attn(
            q32, k_blk.astype(jnp.float32), v_blk, blk_mask, q_pos, blk_pos,
            causal, scale,
        )
        m, o, l = merge(m, o, l, bm, bo, bl)
        return (k_blk, v_blk, blk_mask, blk_pos, m, o, l), None

    if n > 1:
        (k_f, v_f, m_f, p_f, m, o, l), _ = jax.lax.scan(
            step,
            (k, v, allowed0, positions, m, o, l),
            None,
            length=n - 1,
        )
    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]  # [B,L,H,1]
    return (o / denom).astype(q.dtype)


def ring_attention_sharded(
    mesh: Mesh,
    q,
    k,
    v,
    kv_mask,
    positions,
    axis: str = "sp",
    causal: bool = False,
):
    """shard_map wrapper: q/k/v sharded on the sequence dim over ``axis``."""
    from .topk import _shard_map

    spec_qkv = P(None, axis, None, None)
    spec_mask = P(None, axis)
    fn = _shard_map(
        partial(ring_attention, axis_name=axis, causal=causal),
        mesh=mesh,
        in_specs=(spec_qkv, spec_qkv, spec_qkv, spec_mask, spec_mask),
        out_specs=spec_qkv,
    )
    return fn(q, k, v, kv_mask, positions)
