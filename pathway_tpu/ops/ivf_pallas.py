"""Pallas TPU kernel for the IVF shortlist rescore.

XLA expresses the rescore as a per-row gather (`matrix[cand]` with cand =
probed members) — HBM-random access that measured ~220 ms per 64-query
batch at 1M x 384, 40x slower than the exact full-matrix sweep, because
gathers cannot stream.  The TPU-native fix is LAYOUT + DMA: the index is
stored cluster-sorted as padded slabs ``[C, M, d]`` (rows of one cluster
contiguous), and this kernel walks grid (p, B) with the probed cluster ids
scalar-prefetched, so each program's slab arrives as ONE contiguous
[M, d] DMA (the ``BlockSpec`` index_map reads the prefetched probe table —
the standard Mosaic pattern for data-dependent block fetches) and is scored
on the MXU.  HBM traffic becomes sequential slab streams instead of
row-granular chaos.

Mosaic tiling (last two block dims % (8, 128)) shapes the layout choices:
queries ride in groups of 8 rows (each program selects its own row), M and
d are padded to 128 multiples at build, the additive bias (0 live /
-inf pad+removed) rides in 8-row blocks selected by ``probe % 8``, and the
output lands as [p, B/8, 8, M] blocks revisited by the 8 consecutive
b-fastest programs, then transposed back outside.

``interpret=True`` runs the same kernel on CPU for tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["ivf_rescore"]


def _rescore_kernel(probe_ref, q_ref, slab_ref, bias_ref, out_ref):
    j = pl.program_id(0)
    b = pl.program_id(1)
    row = jax.lax.rem(b, 8)
    q = q_ref[pl.ds(row, 1), :]  # [1, d]
    slab = slab_ref[0]  # [M, d]
    # matmul form ([M, d] x [d, 1]) — Mosaic's mat-vec reduction lowering
    # rejects non-constant accumulators, the MXU matmul path does not
    s = jnp.dot(
        slab.astype(jnp.float32),
        q.astype(jnp.float32).T,
        preferred_element_type=jnp.float32,
    )  # [M, 1]
    ci = probe_ref[b, j]
    bias = bias_ref[pl.ds(jax.lax.rem(ci, 8), 1), :]  # [1, M]
    out_ref[0, 0, pl.ds(row, 1), :] = s.T + bias


def rescore_shortlist(probe, q, slabs, bias, *, use_pallas: bool):
    """Backend-dispatching rescore shared by IvfKnnIndex.search and the
    fused serving path: handles the kernel's B % 8 == 0 requirement and
    falls back to an XLA slab gather off-TPU.  Traceable (call inside jit).

    probe [B, p] int32, q [B, d_pad] f32 -> [B, p, M] f32.
    """
    B, p = probe.shape
    if not use_pallas:
        rows = slabs[probe]  # [B, p, M, d_pad] gather (non-TPU path)
        return (
            jnp.einsum(
                "bpmd,bd->bpm",
                rows.astype(jnp.float32),
                q.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )
            + bias[probe]
        )
    B8 = ((B + 7) // 8) * 8
    if B8 != B:
        q = jnp.concatenate([q, jnp.zeros((B8 - B, q.shape[1]), q.dtype)])
        probe = jnp.concatenate(
            [probe, jnp.zeros((B8 - B, p), probe.dtype)]
        )
        return ivf_rescore(probe, q, slabs, bias)[:B]
    return ivf_rescore(probe, q, slabs, bias)


@functools.partial(jax.jit, static_argnames=("interpret",))
def ivf_rescore(probe, q, slabs, bias, *, interpret: bool = False):
    """scores[b, j, :] = q[b] . slabs[probe[b, j]].T + bias[probe[b, j]].

    probe [B, p] int32 (B % 8 == 0), q [B, d] f32 (d % 128 == 0),
    slabs [C, M, d] (M % 128 == 0), bias [C, M] f32 (C % 8 == 0)
    -> [B, p, M] f32 (padded/removed rows carry -inf from the bias).
    """
    B, p = probe.shape
    C, M, d = slabs.shape
    out = pl.pallas_call(
        _rescore_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(p, B),  # b fastest: the 8-row output block is revisited
            # by consecutive programs, written back once
            in_specs=[
                pl.BlockSpec((8, d), lambda j, b, probe: (b // 8, 0)),
                pl.BlockSpec(
                    (1, M, d), lambda j, b, probe: (probe[b, j], 0, 0)
                ),
                pl.BlockSpec(
                    (8, M), lambda j, b, probe: (probe[b, j] // 8, 0)
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, 8, M), lambda j, b, probe: (j, b // 8, 0, 0)
            ),
        ),
        out_shape=jax.ShapeDtypeStruct((p, B // 8, 8, M), jnp.float32),
        interpret=interpret,
    )(probe, q, slabs, bias)
    # [p, B/8, 8, M] -> [B, p, M]
    return out.transpose(1, 2, 0, 3).reshape(B, p, M)
