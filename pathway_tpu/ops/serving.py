"""Fused serving path: text -> embedding -> top-k in ONE device dispatch.

The live-retrieval hot loop (SURVEY §3.3) is latency-bound by host↔device
round trips, not FLOPs — on a tunneled/remote TPU each dispatch or fetch
costs a full RTT, and compute for a 64-query batch over a 1M-doc index is
~8 ms while one RTT can be ~70 ms.  Chaining ``encoder.encode`` (fetch) and
``index.search`` (dispatch + 2 fetches) pays 3-4 RTTs; this path compiles
tokenize-output -> transformer forward -> normalize -> [B,d]x[d,N] score ->
``lax.top_k`` into a single jitted function with ONE packed output and an
async host copy — exactly one round trip per serve call.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .knn import _bucket

__all__ = ["FusedEncodeSearch"]


class FusedEncodeSearch:
    """Callable serving path over a ``SentenceEncoder`` + ``DeviceKnnIndex``.

    Recompiles per (batch bucket, sequence length, k, index capacity) —
    a handful of shapes in steady state; index *content* changes (add/
    remove) never recompile."""

    def __init__(self, encoder, index, k: int = 10):
        self.encoder = encoder
        self.index = index
        self.k = k
        self._lock = threading.Lock()
        self._fns: Dict[Tuple[int, int, int, int], Any] = {}

    def _compiled(self, B: int, L: int, k: int, capacity: int):
        key = (B, L, k, capacity)
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        module = self.encoder.module
        metric = self.index.metric
        normalize = metric == "cos"

        @jax.jit
        def fused(params, ids, mask, matrix, valid, keys_hi, keys_lo):
            z = module.apply({"params": params}, ids, mask)
            z = z.astype(jnp.float32)
            if normalize:
                z = z / jnp.maximum(
                    jnp.linalg.norm(z, axis=-1, keepdims=True), 1e-9
                )
            scores = jnp.dot(
                z.astype(matrix.dtype),
                matrix.T,
                preferred_element_type=jnp.float32,
            )
            if metric == "l2sq":
                scores = 2 * scores - jnp.sum(
                    matrix.astype(jnp.float32) ** 2, axis=1
                )[None, :]
            scores = jnp.where(valid[None, :], scores, -jnp.inf)
            s, i = jax.lax.top_k(scores, k)
            # gather the winners' KEYS on device (int32 hi/lo planes kept by
            # the index): completion then needs no host-side slot->key
            # snapshot at all — the old per-call set()/copy() of the 1M-row
            # host mapping was ~30 ms, dwarfing the actual compute
            hi = jnp.take(keys_hi, i, axis=0)
            lo = jnp.take(keys_lo, i, axis=0)
            # pack into ONE INT32 output so the host fetch is a single
            # transfer.  The scores are bitcast into int lanes — not the
            # keys into float lanes — because TPU canonicalizes NaN payloads
            # in float values (0x7fc00000), which would silently corrupt any
            # key whose 32-bit half happens to be a NaN bit pattern (~0.8%
            # of uniform xxh3 keys); integer lanes always survive bit-exact.
            s_bits = jax.lax.bitcast_convert_type(s, jnp.int32)
            return jnp.concatenate([s_bits, hi, lo], axis=1)

        self._fns[key] = fused
        return fused

    def submit(self, texts: Sequence[str], k: Optional[int] = None):
        """Dispatch one serve batch WITHOUT waiting for the result; returns a
        zero-arg callable that completes it (blocking on the async host
        copy).  Concurrent serving pipelines dispatches so the device queue
        stays full — per-batch wall time approaches pure device time instead
        of one host RTT per call."""
        k = k or self.k
        index = self.index
        with index._lock, self._lock:
            n_items = len(index.key_to_slot)
            if not texts:
                return lambda: []
            if n_items == 0:
                empty: List[List[Tuple[int, float]]] = [[] for _ in texts]
                return lambda: empty
            k_eff = min(k, n_items)
            ids, mask = self.encoder.tokenizer.encode_batch(texts)
            ids = np.asarray(ids)
            mask = np.asarray(mask)
            n_real = ids.shape[0]
            # pad the batch to a bucket so B in the compile key takes a
            # handful of values (matches encoder.encode's padding; round-1
            # advice: distinct len(texts) must not each recompile the fused fn)
            b = _bucket(n_real)
            if b > n_real:
                ids = np.concatenate(
                    [ids, np.zeros((b - n_real, ids.shape[1]), ids.dtype)]
                )
                mask = np.concatenate(
                    [mask, np.zeros((b - n_real, mask.shape[1]), mask.dtype)]
                )
            B, L = ids.shape
            fn = self._compiled(B, L, k_eff, index.capacity)
            out = fn(
                self.encoder.params,
                ids,
                mask,
                index._matrix,
                index._valid,
                index._keys_hi,
                index._keys_lo,
            )
            if hasattr(out, "copy_to_host_async"):
                out.copy_to_host_async()
            # nothing host-side to snapshot: the dispatch captured a
            # consistent device view under the index lock (matrix/valid/keys
            # are replaced functionally, never mutated in place), and the
            # winners' keys come back IN the packed output.  A slot whose row
            # was removed at dispatch time scores -inf and is dropped below.

        def complete() -> List[List[Tuple[int, float]]]:
            arr = np.asarray(out)[:n_real]
            scores = np.ascontiguousarray(arr[:, :k_eff]).view(np.float32)
            ints = np.ascontiguousarray(arr[:, k_eff:]).view(np.uint32)
            hi = ints[:, :k_eff].astype(np.uint64)
            lo = ints[:, k_eff:].astype(np.uint64)
            keys = (hi << np.uint64(32)) | lo
            results: List[List[Tuple[int, float]]] = []
            for qi in range(len(texts)):
                row: List[Tuple[int, float]] = []
                for j in range(k_eff):
                    s = float(scores[qi, j])
                    if not np.isfinite(s):
                        continue
                    row.append((int(keys[qi, j]), s))
                results.append(row[:k])
            return results

        return complete

    def __call__(
        self, texts: Sequence[str], k: Optional[int] = None
    ) -> List[List[Tuple[int, float]]]:
        return self.submit(texts, k)()
