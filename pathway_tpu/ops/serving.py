"""Fused serving path: text -> embedding -> top-k in ONE device dispatch.

The live-retrieval hot loop (SURVEY §3.3) is latency-bound by host↔device
round trips, not FLOPs — on a tunneled/remote TPU each dispatch or fetch
costs a full RTT, and compute for a 64-query batch over a 1M-doc index is
~8 ms while one RTT can be ~70 ms.  Chaining ``encoder.encode`` (fetch) and
``index.search`` (dispatch + 2 fetches) pays 3-4 RTTs; this path compiles
tokenize-output -> transformer forward -> normalize -> [B,d]x[d,N] score ->
``lax.top_k`` into a single jitted function with ONE packed output and an
async host copy — exactly one round trip per serve call.
"""

from __future__ import annotations

# pathway: serve-path  (hidden-sync lint applies: no implicit host round trips)

import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import observe
from ..observe import profile, trace
from ..models.transformer import TransformerEncoder
from ..robust import (
    CircuitOpen,
    Deadline,
    RetryPolicy,
    SHARD_SKIPPED,
    ServeResult,
    TAIL_SKIPPED,
    inject,
    log_once,
    record_degraded,
    retry_call,
)
from .dispatch_counter import record_dispatch, record_fetch
from .knn import _bucket
from .recompile_guard import RecompileTripwire

__all__ = ["FusedEncodeSearch"]

# retry schedule for the IVF dispatch, which launches while HOLDING the
# index + serve locks (the donated absorb buffers force launch-before-
# unlock): its backoff sleeps stall every concurrent add()/serve, so the
# whole budget must stay in the low milliseconds.  The off-lock exact
# path keeps the env-tunable default policy.
_LOCKED_DISPATCH_RETRY = RetryPolicy(
    attempts=3, base_delay_s=0.002, max_delay_s=0.02
)

# flight-recorder stage histograms (pathway_tpu/observe): resolved once
# at import so the per-serve cost is one observe_ns per stage boundary.
# tokenize_pack covers host prep (lock wait + tokenize + pad + compiled-fn
# lookup) up to the dispatch; stage1_rtt is dispatch→fetch-complete of the
# fused kernel; postprocess is the host-side result assembly.
#
# Tracing (observe/trace.py) reuses the SAME clock reads: every span on
# this path is recorded with the timestamps already taken for these
# histograms (explicit t0/t1 — no span context manager is ever held
# across the serve locks), and the histogram objects ride along as
# exemplar targets so a kept trace stamps its id onto the exact bucket
# its stage durations landed in.
_H_TOKENIZE = observe.histogram("pathway_serve_stage_seconds", stage="tokenize_pack")
_H_STAGE1 = observe.histogram("pathway_serve_stage_seconds", stage="stage1_rtt")
_H_POST = observe.histogram("pathway_serve_stage_seconds", stage="postprocess")


class FusedEncodeSearch:
    """Callable serving path over a ``SentenceEncoder`` plus either a
    ``DeviceKnnIndex`` (exact) or an ``IvfKnnIndex`` (approximate): encode,
    score — full matmul or centroid-probe + shortlist rescore — and top-k
    compile into ONE dispatch either way.

    Recompiles per (batch bucket, sequence length, k, index shape) —
    a handful of shapes in steady state; index *content* changes (add/
    remove) never recompile."""

    def __init__(self, encoder, index, k: int = 10,
                 export_query_tokens: bool = False,
                 embed_cache: Any = "env"):
        self.encoder = encoder
        self.index = index
        self.k = k
        self._lock = threading.Lock()
        self._fns: Dict[Tuple, Any] = {}
        # tier-1 query-embedding cache (pathway_tpu/cache): keyed on
        # token ids, so a known query skips the stage-1 trunk forward
        # even after an index mutation invalidated its result-cache
        # entry.  ``"env"`` resolves the PATHWAY_CACHE_EMBED knob
        # (opt-in); pass an EmbeddingCache or None explicitly otherwise.
        if embed_cache == "env":
            from ..cache import embedding_cache_from_env

            embed_cache = embedding_cache_from_env()
        self.embed_cache = embed_cache
        # recompile tripwire (ops/recompile_guard.py): the fused kernel
        # must stay at a handful of compile shapes in steady state
        self._tripwire = RecompileTripwire("FusedEncodeSearch")
        # IVF indexes lack device key planes; winners map slot->key on host
        self._ivf = hasattr(index, "_centroids")
        # sharded index (ops/ivf.ShardedIvfIndex): scatter-dispatch fan-out
        # + on-device hierarchical merge instead of one fused kernel
        self._sharded = hasattr(index, "shards") and hasattr(index, "group")
        # bench/test probe: True makes the sharded completion fetch the
        # per-shard candidate lists and tree-merge them ON HOST instead
        # of dispatching the device merge — the A/B that prices the
        # merge's share of serve latency (and the NumPy reference the
        # merge-kernel parity test checks against)
        self.shard_host_merge = False
        # per-shard dispatch-latency histograms, resolved lazily per
        # shard id (pathway_serve_shard_stage_seconds{stage=...,shard=...})
        self._shard_hists: Dict[Tuple[str, int], Any] = {}
        # query TOKEN-STATE export for a downstream late-interaction
        # rerank stage (pathway_tpu/index): the fused kernel additionally
        # returns the per-token hidden states, DEVICE-RESIDENT (never
        # fetched here) — the MaxSim stage consumes them in its own single
        # dispatch, so the query is encoded exactly once per serve.  The
        # retrieve→rerank pipeline flips this on when it is built with a
        # forward index; HF-imported trunks (internal pooling) ignore it.
        self.export_query_tokens = bool(export_query_tokens)

    def _exporting(self) -> bool:
        module = self.encoder.module
        return (
            self.export_query_tokens
            and isinstance(module, TransformerEncoder)
            and module.config.pool == "mean"
        )

    def index_generation(self) -> int:
        """Result-visibility generation of the underlying index — the
        coalescing scheduler folds it into its in-window dedup key so a
        mutation landing mid-window (absorb, retrain install, add)
        can't hand a later rider results from a pre-mutation slot."""
        return int(getattr(self.index, "generation", 0))

    def _query_forward(self, export: bool):
        """The query-encode fragment of the fused kernels: returns a
        traced ``(params, ids, mask) -> (z [B, d] f32, qtok | None)``
        helper.  With ``export`` the trunk runs through a pool-free twin
        (same params) so the SAME single dispatch yields both the pooled
        embedding (bit-identical math to the module's own mean pool) and
        the L2-normalized per-token states for a MaxSim stage."""
        module = self.encoder.module
        if not export:
            def forward(params, ids, mask):
                z = module.apply({"params": params}, ids, mask)
                return z.astype(jnp.float32), None

            return forward
        from ..models.transformer import (
            normalized_token_states,
            token_state_trunk,
        )

        trunk = token_state_trunk(module.config)

        def forward(params, ids, mask):
            hidden = trunk.apply({"params": params}, ids, mask)
            # replicate the module's masked mean pool (same ops, same
            # order, same dtypes — TransformerEncoder.__call__)
            m = mask[:, :, None].astype(hidden.dtype)
            summed = jnp.sum(hidden * m, axis=1)
            counts = jnp.maximum(jnp.sum(m, axis=1), 1.0)
            z = (summed / counts).astype(jnp.float32)
            # the SAME canonical post-processing the doc-side ingest
            # export uses — one vector space for MaxSim by construction
            qtok = normalized_token_states(hidden, mask)
            return z, qtok

        return forward

    def _compiled(self, B: int, L: int, k: int, capacity: int,
                  from_z: bool = False):
        """Exact-index stage-1 kernel.  ``from_z=False`` is the classic
        fused encode+search (params, ids, mask, ...); ``from_z=True`` is
        the SEARCH-ONLY twin taking a precomputed (metric-normalized)
        ``[B, d]`` embedding — the embedding-cache path composes cached
        and fresh rows on device and skips the trunk forward here."""
        export = self._exporting() and not from_z
        key = (B, L, k, capacity, export, from_z)
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        self._tripwire.observe(key)
        metric = self.index.metric
        normalize = metric == "cos"
        forward = self._query_forward(export)

        def search(z, qtok, matrix, valid, keys_hi, keys_lo):
            scores = jnp.dot(
                z.astype(matrix.dtype),
                matrix.T,
                preferred_element_type=jnp.float32,
            )
            if metric == "l2sq":
                scores = 2 * scores - jnp.sum(
                    matrix.astype(jnp.float32) ** 2, axis=1
                )[None, :]
            scores = jnp.where(valid[None, :], scores, -jnp.inf)
            s, i = jax.lax.top_k(scores, k)
            # gather the winners' KEYS on device (int32 hi/lo planes kept by
            # the index): completion then needs no host-side slot->key
            # snapshot at all — the old per-call set()/copy() of the 1M-row
            # host mapping was ~30 ms, dwarfing the actual compute
            hi = jnp.take(keys_hi, i, axis=0)
            lo = jnp.take(keys_lo, i, axis=0)
            # pack into ONE INT32 output so the host fetch is a single
            # transfer.  The scores are bitcast into int lanes — not the
            # keys into float lanes — because TPU canonicalizes NaN payloads
            # in float values (0x7fc00000), which would silently corrupt any
            # key whose 32-bit half happens to be a NaN bit pattern (~0.8%
            # of uniform xxh3 keys); integer lanes always survive bit-exact.
            s_bits = jax.lax.bitcast_convert_type(s, jnp.int32)
            packed = jnp.concatenate([s_bits, hi, lo], axis=1)
            if qtok is not None:
                return packed, qtok
            return packed

        if from_z:

            @jax.jit
            def fused(z, matrix, valid, keys_hi, keys_lo):
                # z arrives already metric-normalized (_encode_fn /
                # cached rows captured from it)
                return search(z, None, matrix, valid, keys_hi, keys_lo)

        else:

            @jax.jit
            def fused(params, ids, mask, matrix, valid, keys_hi, keys_lo):
                z, qtok = forward(params, ids, mask)
                if normalize:
                    z = z / jnp.maximum(
                        jnp.linalg.norm(z, axis=-1, keepdims=True), 1e-9
                    )
                return search(z, qtok, matrix, valid, keys_hi, keys_lo)

        # device-time attribution (observe/profile.py): the compiled fn
        # is stored wrapped, so every steady-state call is sampled
        fused = profile.wrap(
            "serve.exact_search" if from_z else "serve.fused_exact", fused
        )
        self._fns[key] = fused
        return fused

    def _compiled_ivf(self, B: int, L: int, k: int, t_pad: int,
                      from_z: bool = False):
        """Returns (fused_fn, k_main, k_tail) — the kernel's output is
        [B, 2*k_main + 2*k_tail] int32 columns: k_main score bit-patterns,
        k_main slots, then k_tail tail-score bit-patterns, k_tail tail row
        indices.  ``t_pad`` is the bucketed exact-tail size (0 = no tail):
        fresh rows not yet absorbed into the slabs are brute-force scored
        INSIDE the same dispatch, so serving never triggers a rebuild.
        ``from_z=True`` is the search-only twin over a precomputed
        metric-normalized ``[B, d]`` embedding (the embedding-cache
        path) — probe + rescore + tail scan unchanged, no trunk forward."""
        index = self.index
        normalize = index.metric == "cos"
        M = index._M_pad
        C = index._centroids.shape[0]
        d = index.dimension
        p = index.n_probe or index._default_probe()
        p = min(p, C)
        k_main = min(k, p * M)
        k_tail = min(k, t_pad) if t_pad else 0
        export = self._exporting() and not from_z
        shape_key = (
            "ivf", B, L, k, p, t_pad,
            index._slabs.shape[0],
            C,
            M,
            export,
            from_z,
        )
        fn = self._fns.get(shape_key)
        if fn is not None:
            return fn, k_main, k_tail
        self._tripwire.observe(shape_key)
        use_pallas = jax.default_backend() == "tpu"
        forward = self._query_forward(export)

        def search(z, qtok, slabs, bias, centroids, tail_mat, tail_valid):
            cscores = jnp.dot(
                z.astype(centroids.dtype), centroids.T,
                preferred_element_type=jnp.float32,
            )
            _, probe = jax.lax.top_k(cscores, p)
            probe = probe.astype(jnp.int32)
            d_pad = slabs.shape[2]
            zq = z
            if d_pad > d:
                zq = jnp.concatenate(
                    [z, jnp.zeros((B, d_pad - d), z.dtype)], axis=1
                )
            from .ivf_pallas import rescore_shortlist

            scores3 = rescore_shortlist(
                probe, zq, slabs, bias, use_pallas=use_pallas
            )
            scores = scores3.reshape(B, p * M)
            s, i = jax.lax.top_k(scores, k_main)
            jj = i // M
            mm = i % M
            slots = jnp.take_along_axis(probe, jj, axis=1) * M + mm
            slots = jnp.where(jnp.isfinite(s), slots, -1)
            s_bits = jax.lax.bitcast_convert_type(s, jnp.int32)
            parts = [s_bits, slots]
            if t_pad:
                ts = jnp.dot(
                    z.astype(tail_mat.dtype), tail_mat.T,
                    preferred_element_type=jnp.float32,
                )
                ts = jnp.where(tail_valid[None, :], ts, -jnp.inf)
                t_s, t_i = jax.lax.top_k(ts, k_tail)
                parts += [
                    jax.lax.bitcast_convert_type(t_s, jnp.int32),
                    t_i.astype(jnp.int32),
                ]
            packed = jnp.concatenate(parts, axis=1)
            if qtok is not None:
                return packed, qtok
            return packed

        if from_z:

            @jax.jit
            def fused(z, slabs, bias, centroids, tail_mat, tail_valid):
                return search(
                    z, None, slabs, bias, centroids, tail_mat, tail_valid
                )

        else:

            @jax.jit
            def fused(
                params, ids, mask, slabs, bias, centroids, tail_mat, tail_valid
            ):
                z, qtok = forward(params, ids, mask)
                if normalize:
                    z = z / jnp.maximum(
                        jnp.linalg.norm(z, axis=-1, keepdims=True), 1e-9
                    )
                return search(
                    z, qtok, slabs, bias, centroids, tail_mat, tail_valid
                )

        fused = profile.wrap(
            "serve.ivf_search" if from_z else "serve.fused_ivf", fused
        )
        self._fns[shape_key] = fused
        return fused, k_main, k_tail

    # -- sharded scatter-dispatch serve path --------------------------------
    def _encode_fn(self, B: int, L: int):
        """Compiled query-encode kernel for the sharded path: ``(params,
        ids, mask) -> z [B, d] f32`` (metric-normalized), plus the
        device-resident per-token states when a late-interaction stage
        asked for the export.  The embedding is computed ONCE and then
        scattered to every shard — the per-shard search kernels take it
        as input instead of re-running the trunk S times."""
        export = self._exporting()
        key = ("encode", B, L, export, self.index.metric)
        with self._lock:
            fn = self._fns.get(key)
            if fn is not None:
                return fn
            self._tripwire.observe(key)
            normalize = self.index.metric == "cos"
            forward = self._query_forward(export)

            @jax.jit
            def fn(params, ids, mask):
                z, qtok = forward(params, ids, mask)
                if normalize:
                    z = z / jnp.maximum(
                        jnp.linalg.norm(z, axis=-1, keepdims=True), 1e-9
                    )
                if qtok is not None:
                    return z, qtok
                return z

            fn = profile.wrap("serve.encode", fn)
            self._fns[key] = fn
            return fn

    def _cached_embeddings(self, ids, mask, n_real: int, deadline=None):
        """Tier-1 cache wrapper (pathway_tpu/cache): resolve the batch's
        query embeddings — cached device rows where the token ids are
        known, ONE bucketed ``_encode_fn`` launch for the misses — and
        compose them into the shared ``[B, d]`` device batch the
        search-only kernels consume.  Returns ``(z, encoded)`` where
        ``encoded`` says whether an encode launch happened (the caller
        reports it inside the stage-1 logical dispatch group via
        ``record_dispatch(tag, shards=...)`` — the analyzer's
        cache-wrapper convention: a dispatch guarded by a cache lookup
        is accounted by the serve path that owns the group).  All cache
        traffic stays off the serve/index locks; fresh rows are captured
        as async device slices (no fetch, no upload).

        ``models/encoder.py _cached_encode_rows`` is this wrapper's twin
        for the plain encode contract ([n, d], its own retry site, no
        deadline plumbing) — deliberately parallel rather than shared,
        so each dispatch stays lexically visible to the analyzer; a
        cache-path fix here almost certainly applies there too."""
        cache = self.embed_cache
        B, L = ids.shape
        # value-space signature: rows here are the fused trunk's
        # metric-normalized f32 embeddings — a tier shared with the
        # plain encoder must never serve its rows into this space
        rows, misses, row_keys = cache.lookup_rows(
            ids, mask, n_real, deadline=deadline,
            space=f"serve:{self.index.metric}",
        )
        fresh: Dict[int, Any] = {}
        if misses:
            n_miss = len(misses)
            Bm = _bucket(n_miss)
            ids_m = ids[misses]
            mask_m = mask[misses]
            if Bm > n_miss:
                ids_m = np.concatenate(
                    [ids_m, np.zeros((Bm - n_miss, L), ids.dtype)]
                )
                mask_m = np.concatenate(
                    [mask_m, np.zeros((Bm - n_miss, L), mask.dtype)]
                )
            enc = self._encode_fn(Bm, L)
            z_m = retry_call(
                "serve.dispatch", enc, self.encoder.params,
                jnp.asarray(ids_m), jnp.asarray(mask_m), deadline=deadline,
            )
            for j, i in enumerate(misses):
                row = z_m[j]
                fresh[i] = row
                cache.put_row(row_keys[i], row, deadline=deadline)
        d = self.index.dimension
        parts = [
            rows[i] if rows[i] is not None else fresh[i]
            for i in range(n_real)
        ]
        parts += [jnp.zeros((d,), jnp.float32)] * (B - n_real)
        return jnp.stack(parts), bool(misses)

    def _shard_search_fn(self, child, B: int, K: int, t_pad: int):
        """Compiled per-shard search kernel: ``(z [B, d] f32, slabs,
        bias, centroids, tail_mat, tail_valid) -> [B, 2K] int32`` — the
        shard's best ``K`` candidates as score bit-patterns plus packed
        candidate ids (slab slot, or ``n_slotspace + tail_row`` for
        exact-tail winners; ``-1`` invalid).  Resident probe/rescore and
        the exact-tail scan are merged into the one per-shard top-K
        INSIDE the kernel, so the cross-shard merge reduces one sorted
        list per shard.  Returns ``(fn, n_slotspace)``.

        Cache key is pure shapes — shards with identical layout shapes
        (the steady state of balanced routing) share one compiled fn."""
        M = child._M_pad
        C = child._centroids.shape[0]
        C_pad = child._slabs.shape[0]
        d = child.dimension
        d_pad = child._d_pad
        p = child.n_probe or child._default_probe()
        p = min(p, C)
        k_main = min(K, p * M)
        k_tail = min(K, t_pad) if t_pad else 0
        n_slotspace = C_pad * M
        key = ("shard", B, K, p, t_pad, C_pad, C, M, d_pad)
        with self._lock:
            fn = self._fns.get(key)
            if fn is not None:
                return fn, n_slotspace
            self._tripwire.observe(key)
            use_pallas = jax.default_backend() == "tpu"

            @jax.jit
            def fn(z, slabs, bias, centroids, tail_mat, tail_valid):
                cscores = jnp.dot(
                    z.astype(centroids.dtype), centroids.T,
                    preferred_element_type=jnp.float32,
                )
                _, probe = jax.lax.top_k(cscores, p)
                probe = probe.astype(jnp.int32)
                zq = z
                if d_pad > d:
                    zq = jnp.concatenate(
                        [z, jnp.zeros((B, d_pad - d), z.dtype)], axis=1
                    )
                from .ivf_pallas import rescore_shortlist

                scores3 = rescore_shortlist(
                    probe, zq, slabs, bias, use_pallas=use_pallas
                )
                scores = scores3.reshape(B, p * M)
                s, i = jax.lax.top_k(scores, k_main)
                jj = i // M
                mm = i % M
                slots = jnp.take_along_axis(probe, jj, axis=1) * M + mm
                cand_s = [s]
                cand_i = [jnp.where(jnp.isfinite(s), slots, -1)]
                if t_pad:
                    ts = jnp.dot(
                        z.astype(tail_mat.dtype), tail_mat.T,
                        preferred_element_type=jnp.float32,
                    )
                    ts = jnp.where(tail_valid[None, :], ts, -jnp.inf)
                    t_s, t_i = jax.lax.top_k(ts, k_tail)
                    cand_s.append(t_s)
                    cand_i.append(
                        jnp.where(
                            jnp.isfinite(t_s),
                            n_slotspace + t_i.astype(jnp.int32),
                            -1,
                        )
                    )
                cs = jnp.concatenate(cand_s, axis=1)
                ci = jnp.concatenate(cand_i, axis=1)
                if cs.shape[1] < K:
                    pad = K - cs.shape[1]
                    cs = jnp.pad(cs, ((0, 0), (0, pad)), constant_values=-jnp.inf)
                    ci = jnp.pad(ci, ((0, 0), (0, pad)), constant_values=-1)
                s_out, pos = jax.lax.top_k(cs, K)
                i_out = jnp.take_along_axis(ci, pos, axis=1)
                s_bits = jax.lax.bitcast_convert_type(s_out, jnp.int32)
                return jnp.concatenate([s_bits, i_out], axis=1)

            fn = profile.wrap("serve.shard_search", fn)
            self._fns[key] = fn
            return fn, n_slotspace

    def _merge_fn(self, S: int, B: int, K: int):
        """Compiled hierarchical merge kernel: ``S`` per-shard packed
        candidate lists ``[B, 2K]`` -> global top-K ``[B, 3K]`` int32
        (score bit-patterns, live-shard ordinals, shard-local candidate
        ids) via a pairwise tree reduce over the shard axis
        (ops/topk.tree_merge_topk) — ⌈log2 S⌉ 2K-wide top-k levels
        instead of one S·K selection."""
        from .topk import tree_merge_topk

        key = ("merge", S, B, K)
        with self._lock:
            fn = self._fns.get(key)
            if fn is not None:
                return fn
            self._tripwire.observe(key)

            @jax.jit
            def fn(*packed):
                scores = jnp.stack(
                    [
                        jax.lax.bitcast_convert_type(p[:, :K], jnp.float32)
                        for p in packed
                    ]
                )
                ids = jnp.stack([p[:, K:] for p in packed])
                shard_ids = jnp.stack(
                    [jnp.full((B, K), s, jnp.int32) for s in range(S)]
                )
                s, h, i = tree_merge_topk(scores, shard_ids, ids, K)
                s_bits = jax.lax.bitcast_convert_type(s, jnp.int32)
                return jnp.concatenate([s_bits, h, i], axis=1)

            fn = profile.wrap("serve.shard_merge", fn)
            self._fns[key] = fn
            return fn

    def _shard_hist(self, stage: str, shard: int):
        key = (stage, shard)
        h = self._shard_hists.get(key)
        if h is None:
            h = self._shard_hists[key] = observe.histogram(
                "pathway_serve_shard_stage_seconds",
                stage=stage,
                shard=str(shard),
            )
        return h

    def _submit_sharded(
        self,
        texts: Sequence[str],
        ids: np.ndarray,
        mask: np.ndarray,
        n_real: int,
        k: int,
        t_start: int,
        deadline: Optional[Deadline] = None,
    ):
        """Scatter-dispatch serve over a ``ShardedIvfIndex``: encode the
        coalesced batch ONCE, fan the device-resident embedding out to
        every shard's resident search kernel (one ``device_put`` + one
        launch per shard, all asynchronous), and tree-merge the
        per-shard candidate lists on device — ONE logical dispatch, one
        packed fetch, so the happy-path serve stays at 2 logical
        dispatches + 2 fetches per batch (the dispatch counter's
        per-shard-group accounting carries the physical fan-out width).

        Per-shard failure domains: a shard whose dispatch fails (or
        whose breaker is open) is SKIPPED — the merge runs over the live
        shards, the response is flagged ``shard_skipped``, and only that
        shard's partition loses recall.  The whole serve fails only when
        every nonempty shard is down."""
        index = self.index
        group = index.group
        shards = index.shards
        # dispatch-time GROUP generation snapshot (sums the shard gens),
        # stamped into the result for the tier-0 capture guard
        gen0 = self.index_generation()
        if len(index) == 0:
            empty = ServeResult(
                [[] for _ in texts], meta={"index_generation": gen0}
            )
            handle = lambda: empty  # noqa: E731
            handle.query_tokens = None
            handle.query_mask = mask
            handle.n_queries = n_real
            return handle
        k_eff = min(k, len(index))
        B, L = ids.shape
        enc = self._encode_fn(B, L)
        # the encode launch opens the stage-1 logical dispatch group;
        # its failure (past retries) is a stage-1 outage — the caller's
        # ladder turns it into retrieval_failed
        if self._exporting():
            z, qtok = retry_call(
                "serve.dispatch", enc, self.encoder.params, ids, mask,
                deadline=deadline,
            )
        else:
            z = retry_call(
                "serve.dispatch", enc, self.encoder.params, ids, mask,
                deadline=deadline,
            )
            qtok = None
        _t = trace.current()
        if _t is not None:
            _t.add_span(
                "stage1.encode", t_start, time.perf_counter_ns(),
                queries=n_real, batch=B,
            )
        physical = 1  # the encode launch
        outs: List[Any] = []
        snaps: List[Any] = []
        skipped: List[int] = []
        for s, child in enumerate(shards):
            t_shard = time.perf_counter_ns()
            try:
                if len(child) == 0:
                    outs.append(None)
                    snaps.append(None)
                    continue
                breaker = group.breaker(s)
                if not breaker.allow():
                    raise CircuitOpen(breaker.name)
                # per-shard chaos site OUTSIDE the retry loop: arming
                # shard.dispatch.<s> kills exactly this shard
                # deterministically (the generic shard.dispatch site
                # fires inside retry_call and models transient faults)
                inject.fire(f"shard.dispatch.{s}", deadline=deadline)
                with jax.default_device(group.device(s)), child._lock:  # pathway: allow(lock-order): rank exception index(3)<scheduler(5) — the fused-serve pair order is index-before-pipeline at EVERY site (absorb DONATES slab buffers, forcing launch-before-unlock under the shard's index lock; the compiled-getter guard self._lock nests briefly inside), so the pair is globally ordered and deadlock-free
                    if child._slabs is None:
                        child.build()  # first build only
                    else:
                        child.maybe_retrain_async()
                    tail, tail_dev, tail_valid_dev, t_pad = (
                        child._tail_snapshot_device()
                    )
                    fn, n_slotspace = self._shard_search_fn(
                        child, B, k_eff, t_pad
                    )
                    # scatter leg: the shared embedding hops to the
                    # shard's device (async d2d), then the shard kernel
                    # launches — under the child lock, because a
                    # concurrent absorb commit DONATES the slab buffers
                    # (same launch-before-unlock rule as _submit_ivf)
                    z_s = jax.device_put(z, group.device(s))  # pathway: allow(lock-discipline, value-flow): device→device scatter of an UNFETCHED [B, d] embedding — an async ICI hop enqueued like a dispatch, not a host link round trip; the value is loop-invariant but the TARGET device varies per shard (mirrored in residency.DECLARED_TRANSFERS), and it must precede the launch that consumes it under this lock
                    out = retry_call(  # pathway: allow(lock-discipline): dispatch-only — donated absorb buffers force launch-before-unlock; the merged fetch happens off-lock in the completion
                        "shard.dispatch",
                        fn,
                        z_s,
                        child._slabs,
                        child._bias,
                        child._centroids
                        if isinstance(child._centroids, jax.Array)
                        else jnp.asarray(child._centroids),
                        tail_dev,
                        tail_valid_dev,
                        deadline=deadline,
                        policy=_LOCKED_DISPATCH_RETRY,
                        breaker=breaker,
                    )
                    keys_by_slot = child._keys_by_slot  # dispatch-time snap
            except Exception as exc:
                # a dead shard costs recall on its partition, never the
                # request: skip it, flag the serve, keep the rest going
                group.record_skip(s)
                if not skipped:
                    record_degraded(SHARD_SKIPPED)
                skipped.append(s)
                log_once(
                    f"shard.dispatch:{type(exc).__name__}",
                    "stage-1 dispatch to shard %d failed (%r); serving "
                    "without its partition (shard_skipped)",
                    s,
                    exc,
                )
                _t = trace.current()
                if _t is not None:
                    _t.add_span(
                        "shard.dispatch", t_shard, time.perf_counter_ns(),
                        status="skipped", shard=s,
                        error=type(exc).__name__,
                    )
                outs.append(None)
                snaps.append(None)
                continue
            physical += 1
            outs.append(out)
            snaps.append((keys_by_slot, tail, n_slotspace, child))
            t_shard_done = time.perf_counter_ns()
            self._shard_hist("dispatch", s).observe_ns(t_shard_done - t_shard)
            _t = trace.current()
            if _t is not None:
                _t.add_span(
                    "shard.dispatch", t_shard, t_shard_done, shard=s
                )
        live = [s for s in range(len(shards)) if outs[s] is not None]
        if not live:
            if skipped:
                raise RuntimeError(
                    f"every nonempty shard failed stage-1 dispatch "
                    f"(skipped={skipped})"
                )
            empty = ServeResult(
                [[] for _ in texts], meta={"index_generation": gen0}
            )
            handle = lambda: empty  # noqa: E731
            handle.query_tokens = qtok
            handle.query_mask = mask
            handle.n_queries = n_real
            return handle
        tail_skipped = any(snaps[s][3].tail_degraded for s in live)
        host_merge = bool(self.shard_host_merge)
        merge_dev = getattr(z, "device", None) or group.device(0)
        out_m = None
        t_merge = time.perf_counter_ns()
        if not host_merge:
            # gather leg: per-shard packed candidate lists hop back to
            # the merge device (async d2d), then ONE tree-reduce merge
            # kernel produces the packed global top-K — the only output
            # the host ever fetches
            moved = [jax.device_put(outs[s], merge_dev) for s in live]
            mfn = self._merge_fn(len(live), B, k_eff)
            out_m = retry_call(
                "shard.merge", mfn, *moved,
                deadline=deadline, policy=_LOCKED_DISPATCH_RETRY,
            )
            physical += 1
            if hasattr(out_m, "copy_to_host_async"):
                out_m.copy_to_host_async()
        record_dispatch("serve_sharded", shards=physical)
        t_dispatch = time.perf_counter_ns()
        self._shard_hist("merge_dispatch", -1).observe_ns(
            t_dispatch - t_merge
        )
        _H_TOKENIZE.observe_ns(t_dispatch - t_start)
        observe.record_occupancy("stage1", n_real, B)
        _t = trace.current()
        if _t is not None:
            _t.add_span(
                "shard.merge", t_merge, t_dispatch,
                shards=len(live), host_merge=bool(host_merge),
                skipped=len(skipped),
            )

        def complete() -> List[List[Tuple[int, float]]]:
            inject.fire("serve.fetch", deadline=deadline)
            if host_merge:
                # probe mode (bench A/B + merge parity reference): fetch
                # every shard's list and tree-merge on host
                from .topk import tree_merge_topk_host

                per_shard = [np.asarray(outs[s])[:n_real] for s in live]
                record_fetch("serve_sharded_host", shards=len(live))
                scores = np.stack(
                    [
                        np.ascontiguousarray(a[:, :k_eff]).view(np.float32)
                        for a in per_shard
                    ]
                )
                cids = np.stack([a[:, k_eff:] for a in per_shard])
                ords = np.stack(
                    [np.full((n_real, k_eff), i, np.int32) for i in range(len(live))]
                )
                m_s, m_h, m_i = tree_merge_topk_host(
                    scores, ords, cids, k_eff
                )
            else:
                arr = np.asarray(out_m)[:n_real]
                record_fetch("serve_sharded")
                m_s = np.ascontiguousarray(arr[:, :k_eff]).view(np.float32)
                m_h = arr[:, k_eff : 2 * k_eff]
                m_i = arr[:, 2 * k_eff :]
            t_fetch = time.perf_counter_ns()
            _H_STAGE1.observe_ns(t_fetch - t_dispatch)
            _ct = trace.current()
            if _ct is not None:
                _ct.add_span(
                    "stage1.fetch", t_dispatch, t_fetch,
                    exemplar=_H_STAGE1, kind="sharded",
                )
            results: List[List[Tuple[int, float]]] = []
            for qi in range(len(texts)):
                row: List[Tuple[int, float]] = []
                for j in range(m_s.shape[1]):
                    sc = float(m_s[qi, j])
                    if not np.isfinite(sc):
                        continue
                    ordinal = int(m_h[qi, j])
                    cid = int(m_i[qi, j])
                    if ordinal < 0 or cid < 0:
                        continue
                    keys_by_slot, tail_keys, n_slotspace, _child = snaps[
                        live[ordinal]
                    ]
                    if cid < n_slotspace:
                        row.append((int(keys_by_slot[cid]), sc))
                    elif cid - n_slotspace < len(tail_keys):
                        row.append((tail_keys[cid - n_slotspace], sc))
                # merged list arrives score-sorted; dedupe upsert twins
                # (a key resident in both the slab and the tail)
                seen = set()
                dedup = []
                for key, sc in row:
                    if key not in seen:
                        seen.add(key)
                        dedup.append((key, sc))
                results.append(dedup[:k])
            t_post = time.perf_counter_ns()
            _H_POST.observe_ns(t_post - t_fetch)
            if _ct is not None:
                _ct.add_span("stage1.postprocess", t_fetch, t_post)
            flags: List[str] = []
            if tail_skipped:
                flags.append(TAIL_SKIPPED)
            if skipped:
                flags.append(SHARD_SKIPPED)
            meta: Dict[str, Any] = {"index_generation": gen0}
            if skipped:
                meta["shards_skipped"] = tuple(skipped)
            return ServeResult(results, degraded=flags, meta=meta)

        complete.query_tokens = qtok
        complete.query_mask = mask
        complete.n_queries = n_real
        return complete

    def _submit_ivf(
        self,
        texts: Sequence[str],
        ids: np.ndarray,
        mask: np.ndarray,
        n_real: int,
        k: int,
        t_start: int,
        deadline: Optional[Deadline] = None,
        z=None,
        stage1_launches: int = 1,
    ):
        """IVF flavor of submit (holds both locks; ``ids``/``mask`` were
        tokenized and bucket-padded OFF them by the caller): centroid
        probe + shortlist rescore + exact-tail scan + top-k in ONE
        dispatch.  NEVER rebuilds (VERDICT r4 #2): fresh rows ride the
        exact tail until add() absorbs them / the background retrain
        lands; staleness just kicks the async retrain.  Winners come back
        as built-index SLOTS (+ tail indices) and map to keys on host
        (O(B*k)) — the key mapping is snapshotted AT DISPATCH
        (keys_by_slot reference + tail key list), so completion reflects
        dispatch-time state even if a rebuild or removal lands in between
        (ADVICE r4 low #3).

        ``z`` (embedding-cache path) is a precomputed metric-normalized
        ``[B, d]`` device embedding: the search-only kernel twin skips
        the trunk forward, and ``stage1_launches`` carries the launch
        group's physical width (2 when the cache wrapper encoded misses,
        1 all-hit) into the dispatch counter's group accounting."""
        index = self.index
        if len(index) == 0:
            empty = ServeResult(
                [[] for _ in texts],
                meta={"index_generation": self.index_generation()},
            )
            return lambda: empty
        if index._slabs is None:
            index.build()  # first build only: nothing to serve from yet
        else:
            index.maybe_retrain_async()
        k_eff = min(k, len(index))
        # exact tail: rows not yet absorbed into the slabs.  The device
        # upload is CACHED on the index and invalidated only when the tail
        # mutates (add/absorb/remove/install) — re-uploading the padded
        # ~3 MB tail matrix on every dispatch was a per-call host->device
        # transfer on the one-RTT latency path (ADVICE r5 #1)
        tail, tail_dev, tail_valid_dev, t_pad = index._tail_snapshot_device()
        # degradation ladder: a failed tail upload (after its retry
        # budget) serves resident-only results, flagged on the response;
        # the degraded counter was bumped by the snapshot itself
        tail_skipped = bool(getattr(index, "tail_degraded", False))
        fn, k_main, k_tail = self._compiled_ivf(
            ids.shape[0], ids.shape[1], k_eff, t_pad, from_z=z is not None
        )
        if z is not None:
            args = [z]
        else:
            args = [self.encoder.params, ids, mask]
        args += [
            index._slabs,
            index._bias,
            index._centroids
            if isinstance(index._centroids, jax.Array)
            else jnp.asarray(index._centroids),
            tail_dev,
            tail_valid_dev,
        ]
        # dispatch-time generation snapshot, stamped into the result so
        # the tier-0 capture can refuse a row whose dispatch observed a
        # newer index state than its admission key
        gen0 = self.index_generation()
        # transient dispatch failures retry with backoff under the site's
        # budget ("ivf.dispatch" is also the chaos-suite fault site); the
        # deadline bounds both the attempts and the backoff sleeps
        if self._exporting() and z is None:
            out, qtok = retry_call(
                "ivf.dispatch", fn, *args,
                deadline=deadline, policy=_LOCKED_DISPATCH_RETRY,
            )
        else:
            out = retry_call(
                "ivf.dispatch", fn, *args,
                deadline=deadline, policy=_LOCKED_DISPATCH_RETRY,
            )
            qtok = None
        record_dispatch("serve_ivf", shards=stage1_launches)
        if hasattr(out, "copy_to_host_async"):
            out.copy_to_host_async()
        # instrumentation: timestamps only between dispatch and fetch —
        # the observe calls are integer updates, never a host sync
        t_dispatch = time.perf_counter_ns()
        _H_TOKENIZE.observe_ns(t_dispatch - t_start)
        observe.record_occupancy("stage1", n_real, ids.shape[0])
        _t = trace.current()
        if _t is not None:
            _t.add_span(
                "stage1.dispatch", t_start, t_dispatch,
                exemplar=_H_TOKENIZE, kind="ivf",
                queries=n_real, batch=ids.shape[0], tail=t_pad,
            )
        keys_by_slot = index._keys_by_slot  # rebuilds REPLACE the array

        def complete() -> List[List[Tuple[int, float]]]:
            inject.fire("serve.fetch", deadline=deadline)
            arr = np.asarray(out)[:n_real]
            record_fetch("serve_ivf")
            t_fetch = time.perf_counter_ns()
            _H_STAGE1.observe_ns(t_fetch - t_dispatch)
            _ct = trace.current()
            if _ct is not None:
                _ct.add_span(
                    "stage1.fetch", t_dispatch, t_fetch,
                    exemplar=_H_STAGE1, kind="ivf",
                )
            scores = np.ascontiguousarray(arr[:, :k_main]).view(np.float32)
            slots = arr[:, k_main : 2 * k_main]
            if k_tail:
                t_scores = np.ascontiguousarray(
                    arr[:, 2 * k_main : 2 * k_main + k_tail]
                ).view(np.float32)
                t_idx = arr[:, 2 * k_main + k_tail :]
            results: List[List[Tuple[int, float]]] = []
            for qi in range(len(texts)):
                row: List[Tuple[int, float]] = []
                for j in range(slots.shape[1]):
                    s = float(scores[qi, j])
                    slot = int(slots[qi, j])
                    if not np.isfinite(s) or slot < 0:
                        continue
                    # no live-dict filter: removed rows were already biased
                    # to -inf in the DISPATCHED arrays (dispatch-time
                    # semantics); keys_by_slot is the dispatch-time snapshot
                    row.append((int(keys_by_slot[slot]), s))
                if k_tail:
                    for j in range(t_idx.shape[1]):
                        s = float(t_scores[qi, j])
                        ti = int(t_idx[qi, j])
                        if np.isfinite(s) and ti < len(tail):
                            row.append((tail[ti], s))
                row.sort(key=lambda kv: -kv[1])
                seen = set()
                dedup = []
                for key, s in row:
                    if key not in seen:
                        seen.add(key)
                        dedup.append((key, s))
                results.append(dedup[:k])
            t_post = time.perf_counter_ns()
            _H_POST.observe_ns(t_post - t_fetch)
            if _ct is not None:
                _ct.add_span("stage1.postprocess", t_fetch, t_post)
            return ServeResult(
                results,
                degraded=(TAIL_SKIPPED,) if tail_skipped else (),
                meta={"index_generation": gen0},
            )

        # DEVICE-RESIDENT query token states for a late-interaction rerank
        # stage: rides the handle, never fetched on this path (the MaxSim
        # stage consumes it inside its own single dispatch)
        complete.query_tokens = qtok
        complete.query_mask = mask
        complete.n_queries = n_real
        return complete

    def submit(
        self,
        texts: Sequence[str],
        k: Optional[int] = None,
        deadline: Optional[Deadline] = None,
    ):
        """Dispatch one serve batch WITHOUT waiting for the result; returns a
        zero-arg callable that completes it (blocking on the async host
        copy).  Concurrent serving pipelines dispatches so the device queue
        stays full — per-batch wall time approaches pure device time instead
        of one host RTT per call.  ``deadline`` bounds the dispatch (and its
        retry budget); exceeding it raises ``DeadlineExceeded`` to the
        caller — the retrieve→rerank pipeline converts that into a flagged
        degraded response instead of surfacing it to the user."""
        k = k or self.k
        index = self.index
        t_start = time.perf_counter_ns()
        if not texts:
            return lambda: ServeResult()
        # host prep FULLY OFF the serve lock: tokenize + bucket-pad here,
        # so batch N+1's tokenization overlaps batch N's device time and
        # concurrent submitters never serialize their host prep behind
        # one thread's lock hold (tokenizers are stateless; the bucket
        # padding matches encoder.encode's, so B in the compile key still
        # takes a handful of values — round-1 advice)
        ids, mask = self.encoder.tokenizer.encode_batch(texts)
        ids = np.asarray(ids)
        mask = np.asarray(mask)
        n_real = ids.shape[0]
        b = _bucket(n_real)
        if b > n_real:
            ids = np.concatenate(
                [ids, np.zeros((b - n_real, ids.shape[1]), ids.dtype)]
            )
            mask = np.concatenate(
                [mask, np.zeros((b - n_real, mask.shape[1]), mask.dtype)]
            )
        if self._sharded:
            # no global lock: per-shard child locks cover the donated
            # buffers, and the compile caches lock internally
            return self._submit_sharded(
                texts, ids, mask, n_real, k, t_start, deadline
            )
        # tier-1 embedding cache (pathway_tpu/cache): resolve the batch's
        # embeddings BEFORE any serve lock — cached device rows compose
        # with one bucketed encode launch for the misses, and the search
        # kernels below run their from_z twins.  Gated off while a
        # late-interaction stage needs the per-token export (pooled rows
        # cannot stand in for token states).
        z = None
        stage1_launches = 1
        if self.embed_cache is not None and not self._exporting():
            z, encoded = self._cached_embeddings(ids, mask, n_real, deadline)
            stage1_launches = 2 if encoded else 1
        if self._ivf:
            with index._lock, self._lock:  # pathway: allow(lock-order): rank exception index(3)<scheduler(5) — index-before-pipeline is the fused-serve pair order at EVERY site (IVF absorb DONATES slab buffers, so the stage-1 launch must precede unlocking the index; self._lock nests inside to guard the compiled-fn cache), globally ordered with the shard fan-out's child._lock→self._lock
                return self._submit_ivf(
                    texts, ids, mask, n_real, k, t_start, deadline,
                    z=z, stage1_launches=stage1_launches,
                )
        return self._submit_exact(
            texts, ids, mask, n_real, k, t_start, deadline,
            z=z, stage1_launches=stage1_launches,
        )

    def _submit_exact(
        self,
        texts: Sequence[str],
        ids: np.ndarray,
        mask: np.ndarray,
        n_real: int,
        k: int,
        t_start: int,
        deadline: Optional[Deadline] = None,
        z=None,
        stage1_launches: int = 1,
    ):
        """Exact-index flavor of submit (``ids``/``mask`` tokenized and
        bucket-padded off-lock by the caller; ``z``/``stage1_launches``
        as in ``_submit_ivf``)."""
        index = self.index
        with index._lock, self._lock:  # pathway: allow(lock-order): rank exception index(3)<scheduler(5) — same index-before-pipeline pair order as the IVF branch (one global order for the pair keeps it deadlock-free; the exact index swaps buffers functionally but shares the submit shape)
            n_items = len(index.key_to_slot)
            if n_items == 0:
                empty = ServeResult(
                    [[] for _ in texts],
                    meta={"index_generation": self.index_generation()},
                )
                return lambda: empty
            k_eff = min(k, n_items)
            B, L = ids.shape
            fn = self._compiled(
                B, L, k_eff, index.capacity, from_z=z is not None
            )
            # capture the device view under the lock; LAUNCH off it.  The
            # exact index replaces matrix/valid/keys functionally (never
            # in place, never donated), so refs snapshotted here stay
            # valid and consistent after the lock drops — unlike the IVF
            # path, whose absorb DONATES slab buffers and must launch
            # before unlocking.  Nothing else host-side to snapshot: the
            # winners' keys come back IN the packed output, and a slot
            # removed at snapshot time scores -inf and is dropped below.
            planes = (
                index._matrix,
                index._valid,
                index._keys_hi,
                index._keys_lo,
            )
            args = (
                (z,) + planes
                if z is not None
                else (self.encoder.params, ids, mask) + planes
            )
            gen0 = self.index_generation()  # dispatch-time snapshot
        # transient dispatch failures retry with backoff ("serve.dispatch"
        # doubles as the chaos-suite fault site); deadline bounds attempts
        if self._exporting() and z is None:
            out, qtok = retry_call(
                "serve.dispatch", fn, *args, deadline=deadline
            )
        else:
            out = retry_call("serve.dispatch", fn, *args, deadline=deadline)
            qtok = None
        record_dispatch("serve_exact", shards=stage1_launches)
        if hasattr(out, "copy_to_host_async"):
            out.copy_to_host_async()
        t_dispatch = time.perf_counter_ns()
        _H_TOKENIZE.observe_ns(t_dispatch - t_start)
        observe.record_occupancy("stage1", n_real, B)
        _t = trace.current()
        if _t is not None:
            _t.add_span(
                "stage1.dispatch", t_start, t_dispatch,
                exemplar=_H_TOKENIZE, kind="exact",
                queries=n_real, batch=B,
            )

        def complete() -> List[List[Tuple[int, float]]]:
            inject.fire("serve.fetch", deadline=deadline)
            arr = np.asarray(out)[:n_real]
            record_fetch("serve_exact")
            t_fetch = time.perf_counter_ns()
            _H_STAGE1.observe_ns(t_fetch - t_dispatch)
            _ct = trace.current()
            if _ct is not None:
                _ct.add_span(
                    "stage1.fetch", t_dispatch, t_fetch,
                    exemplar=_H_STAGE1, kind="exact",
                )
            scores = np.ascontiguousarray(arr[:, :k_eff]).view(np.float32)
            ints = np.ascontiguousarray(arr[:, k_eff:]).view(np.uint32)
            hi = ints[:, :k_eff].astype(np.uint64)
            lo = ints[:, k_eff:].astype(np.uint64)
            keys = (hi << np.uint64(32)) | lo
            results: List[List[Tuple[int, float]]] = []
            for qi in range(len(texts)):
                row: List[Tuple[int, float]] = []
                for j in range(k_eff):
                    s = float(scores[qi, j])
                    if not np.isfinite(s):
                        continue
                    row.append((int(keys[qi, j]), s))
                results.append(row[:k])
            t_post = time.perf_counter_ns()
            _H_POST.observe_ns(t_post - t_fetch)
            if _ct is not None:
                _ct.add_span("stage1.postprocess", t_fetch, t_post)
            return ServeResult(results, meta={"index_generation": gen0})

        # device-resident query token states for a late-interaction stage
        # (see _submit_ivf): attached, never fetched here
        complete.query_tokens = qtok
        complete.query_mask = mask
        complete.n_queries = n_real
        return complete

    def __call__(
        self,
        texts: Sequence[str],
        k: Optional[int] = None,
        deadline: Optional[Deadline] = None,
    ) -> List[List[Tuple[int, float]]]:
        return self.submit(texts, k, deadline=deadline)()
