"""IVF approximate KNN — the sublinear tier above DeviceKnnIndex.

The reference reserves approximate search for usearch HNSW
(src/external_integration/usearch_integration.rs:20-42, f16-quantized
graph walks).  Graph traversal is hostile to TPUs (pointer chasing, dynamic
shapes); the TPU-idiomatic redesign is IVF:

- **train**: k-means centroids fitted with matmul assignment steps (the
  assignment [S, C] score matrix is one MXU matmul per iteration);
- **build**: every row is assigned to its nearest centroid under a balance
  cap, and the index is laid out CLUSTER-SORTED as padded slabs
  ``[C_pad, M_pad, d_pad]`` with an additive bias plane (0 live, -inf
  pad/removed) — rows of one cluster are physically contiguous;
- **search**: one [B, d]x[d, C] matmul scores the centroids, ``lax.top_k``
  picks the ``n_probe`` clusters per query, and the probed slabs are
  *exactly* rescored.  On TPU the rescore is a Pallas kernel
  (ops/ivf_pallas.py) that scalar-prefetches the probe table and streams
  each probed slab as one contiguous DMA onto the MXU — measured 2.5 ms
  per 64-query batch at 1M x 384 vs ~220 ms for XLA's per-row gather
  (HBM-random access cannot stream) and 5.1 ms for the exact full sweep.
  Off-TPU the same math runs as an XLA slab gather.

Scoring FLOPs drop from B·N·d to B·(C + n_probe·M_pad)·d: clusters target
~240 rows (so the 128-multiple M_pad wastes little) and the probe fraction
from ``_default_probe`` tapers the shortlist to ~16k rows/query (≈1.8% of
1M); ≥0.95 recall@10 on real text embeddings (tests/test_ivf.py).  The
exact DeviceKnnIndex remains the default for latency below ~1M rows; the
IVF tier wins on FLOPs (multi-tenant packing, larger-than-sweep corpora).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import observe
from ..observe import hbm, profile, trace
from ..robust import (
    RetryPolicy,
    TAIL_SKIPPED,
    inject,
    log_once,
    record_degraded,
    retry_call,
)
from . import donation_guard
from .knn import _bucket, normalize_metric
from .recompile_guard import RecompileTripwire

__all__ = ["IvfKnnIndex", "ShardedIvfIndex"]

# backoff schedule for failed background maintenance passes (absorb /
# retrain): a transient device error must not leave the tail growing
# unboundedly, but a persistent one must not spin the maintenance
# thread either — bounded attempts, exponential backoff, seeded jitter
_MAINT_RETRY = RetryPolicy(attempts=3, base_delay_s=0.05, max_delay_s=1.0)
# the serve-path tail upload retries fast and briefly: it runs under
# the index lock, so its whole retry budget must stay in the low ms
_TAIL_RETRY = RetryPolicy(attempts=3, base_delay_s=0.002, max_delay_s=0.02)

# maintenance-duration histograms (flight recorder): absorb/retrain wall
# time, observed from the maintenance threads AFTER their lock sections
_H_ABSORB = observe.histogram("pathway_ivf_absorb_seconds")
_H_RETRAIN = observe.histogram("pathway_ivf_retrain_seconds")


def _kmeans(
    sample: np.ndarray, n_clusters: int, iters: int, seed: int
) -> np.ndarray:
    """k-means on device: assignment is a matmul+argmax per iteration;
    centroid update is a host segment-mean (C·d small)."""
    rng = np.random.default_rng(seed)
    n = sample.shape[0]
    n_clusters = min(n_clusters, n)
    centroids = sample[rng.choice(n, size=n_clusters, replace=False)].copy()
    sample_dev = jnp.asarray(sample)

    @jax.jit
    def assign(cents):
        scores = jnp.dot(
            sample_dev, cents.T, preferred_element_type=jnp.float32
        )
        return jnp.argmax(scores, axis=1)

    for _ in range(iters):
        # pathway: allow(recompile-hazard, value-flow): train-time — centroids keep one [C, d] shape for all iterations of a build; one compile per (C, d), and the synchronous fetch is the k-means loop's contract, off the serve path
        owner = np.asarray(assign(jnp.asarray(centroids)))
        sums = np.zeros_like(centroids)
        np.add.at(sums, owner, sample)
        counts = np.bincount(owner, minlength=n_clusters).astype(np.float32)
        empty = counts == 0
        counts[empty] = 1.0
        centroids = sums / counts[:, None]
        # re-seed empty clusters from random rows
        if empty.any():
            centroids[empty] = sample[
                rng.choice(n, size=int(empty.sum()), replace=False)
            ]
        norms = np.linalg.norm(centroids, axis=1, keepdims=True)
        centroids = centroids / np.where(norms == 0, 1.0, norms)
    return centroids.astype(np.float32)


from functools import partial


def _balanced_assign(order: np.ndarray, C: int, cap: int):
    """Balanced nearest-centroid assignment under a per-cluster cap:
    rows competing for one cluster are ranked by sort position and the
    first (cap - fill) win; losers retry at their next preference.
    ``order`` is [N, n_pref] centroid preferences.  Returns
    (assignment [N], counts [C])."""
    n, n_pref = order.shape
    counts = np.zeros(C, np.int64)
    assignment = np.full(n, -1, np.int64)
    unassigned = np.arange(n)
    for r in range(n_pref):
        if unassigned.size == 0:
            break
        cand = order[unassigned, r]
        sort_ix = np.argsort(cand, kind="stable")
        cand_sorted = cand[sort_ix]
        # within-cluster arrival rank of each competing row
        starts = np.searchsorted(cand_sorted, cand_sorted, side="left")
        within = np.arange(cand_sorted.size) - starts
        accept = within < (cap - counts[cand_sorted])
        winners = unassigned[sort_ix[accept]]
        assignment[winners] = cand_sorted[accept]
        np.add.at(counts, cand_sorted[accept], 1)
        unassigned = unassigned[sort_ix[~accept]]
    for i in unassigned:  # rare: all preferred clusters full
        c = int(np.argmin(counts))
        assignment[i] = c
        counts[c] += 1
    return assignment, counts


@partial(jax.jit, static_argnums=(2,))
def _tail_prefs(rows, centroids, n_pref):
    """Per-row top-``n_pref`` centroid preferences for absorb assignment."""
    s = jnp.dot(
        rows, centroids.T.astype(rows.dtype), preferred_element_type=jnp.float32
    )
    _, idx = jax.lax.top_k(s, n_pref)
    return idx


@partial(
    donation_guard.donating_jit,
    site="ivf.absorb_scatter",
    donate_argnums=(0, 1),
)
def _absorb_scatter(slabs, bias, slots, vecs):
    """Scatter absorbed rows into free slots; donated buffers so XLA can
    update the (possibly GB-scale) slabs in place instead of copying.
    Compiled through the donation tripwire (``PATHWAY_DONATION_GUARD=1``
    poisons the donated refs post-call — ops/donation_guard.py)."""
    C_pad, M_pad, d_pad = slabs.shape
    flat = slabs.reshape(C_pad * M_pad, d_pad).at[slots].set(vecs)
    b = bias.reshape(-1).at[slots].set(jnp.float32(0.0))
    return flat.reshape(C_pad, M_pad, d_pad), b.reshape(C_pad, M_pad)


class IvfKnnIndex:
    """Incrementally maintained approximate KNN (same host API as
    DeviceKnnIndex: add / remove / search / __len__).

    Streaming maintenance — NO stop-the-world rebuild on the serve path
    (VERDICT r4 #2; reference behavior to match: usearch streaming
    add/remove, src/external_integration/usearch_integration.rs:53-99):

    - **tail**: fresh rows are exact-scored alongside the probed shortlist
      (the as-of-now contract — results never miss recent writes);
    - **absorb**: once the tail passes ``absorb_threshold``, rows are
      assigned to their nearest centroid WITH spare slab capacity and
      scattered into free slots in one donated device update — a few ms,
      no retrain, runs in ``add()`` (ingest), never in search/submit;
    - **background retrain**: when the index has grown/churned past
      ``rebuild_fraction``, a daemon thread re-trains k-means and lays out
      fresh slabs from a snapshot, then atomically swaps them in under the
      lock; serving continues on the old slabs throughout.  Rows
      added/removed/upserted DURING the retrain are reconciled at install
      (masked or kept in the tail).
    """

    def __init__(
        self,
        dimension: int,
        metric: str = "cos",
        n_clusters: Optional[int] = None,
        n_probe: Optional[int] = None,
        dtype=jnp.float32,
        train_sample: int = 32768,
        kmeans_iters: int = 8,
        rebuild_fraction: float = 0.25,
        absorb_threshold: int = 4096,
        seed: int = 0,
    ):
        self.dimension = dimension
        self.metric = normalize_metric(metric)
        if self.metric == "l2sq":
            raise NotImplementedError(
                "IvfKnnIndex supports cos/dot; use DeviceKnnIndex for l2sq"
            )
        self.dtype = dtype
        self.n_clusters = n_clusters
        self.n_probe = n_probe
        self.train_sample = train_sample
        self.kmeans_iters = kmeans_iters
        self.rebuild_fraction = rebuild_fraction
        self.absorb_threshold = absorb_threshold
        self.seed = seed
        self._lock = threading.RLock()
        # host-of-record row store (rebuild source)
        self._rows: Dict[int, np.ndarray] = {}
        # device structures (built lazily): cluster-sorted padded slabs
        # [C_pad, M_pad, d_pad] + additive bias [C_pad, M_pad] (0 live,
        # -inf pad/removed); slot = c * M_pad + j
        self._slabs = None
        self._bias = None
        self._centroids = None  # [C, d]
        self._keys_by_slot = None  # uint64 [C_pad * M_pad]
        self._M_pad = 0
        self._d_pad = 0
        self._slot_of_key: Dict[int, int] = {}
        self._tail: Dict[int, None] = {}  # keys added since last build
        self._built_n = 0
        self._search_fns: Dict[tuple, Any] = {}
        # recompile tripwire (ops/recompile_guard.py): search shapes are
        # bucketed, so the signature census stays small; a leak trips
        self._tripwire = RecompileTripwire("IvfKnnIndex.search")
        # host mirror of slot occupancy (True = live row), for absorb's
        # free-slot allocation without a device fetch
        self._live_mask: Optional[np.ndarray] = None
        self._retraining = False
        self._absorbing = False
        # bumped whenever a freshly trained layout is installed — an
        # off-lock absorb whose snapshot predates the install must abort
        # (its slot plan refers to the replaced slabs)
        self._layout_gen = 0
        # PUBLIC result-visibility generation: bumped on every mutation
        # that can change what a serve returns (add/remove/absorb
        # commit/retrain install/bulk build).  The coalescing scheduler
        # keys its in-window dedup on (text, generation) so an absorb or
        # retrain landing mid-window can't hand a later rider results
        # from a slot dispatched against the pre-mutation index.
        self.generation = 0
        # device-resident exact-tail upload, cached between serves and
        # invalidated only when the tail mutates (ADVICE r5 #1): steady-
        # state serving with an unchanged tail pays no per-call transfer
        self._tail_cache: Optional[Tuple] = None
        # damping for absorb re-attempts: when an absorb could place
        # NOTHING (preferred clusters full), remember the tail size so
        # every subsequent add() doesn't pay a futile tail x C matmul;
        # re-arm once the tail grows another threshold, a slot frees, or
        # a retrain rebalances the layout
        self._absorb_stuck_at: Optional[int] = None
        # maintenance counters (observable by tests/bench: the serve path
        # must show sync_builds frozen while absorbs/retrains advance);
        # tail_cache_* counts device-upload reuse on the serve path
        self.stats = {
            "sync_builds": 0,
            "retrains": 0,
            "absorbs": 0,
            "tail_cache_hits": 0,
            "tail_cache_misses": 0,
            "absorb_failures": 0,
            "retrain_failures": 0,
        }
        # degradation-ladder flag: True while the LAST tail-snapshot
        # device upload failed past its retry budget (serving then runs
        # resident-only, flagged tail_skipped); cleared by any
        # successful snapshot.  Read by ops/serving.py under the lock.
        self.tail_degraded = False
        # flight-recorder export: index gauges sampled at scrape time
        # only (zero serve-path cost); id uniquifies multiple indexes
        self._observe_id = observe.next_id()
        observe.register_provider(self)
        # HBM ledger (observe/hbm.py): resident slabs/centroids + the
        # cached tail upload, sampled at scrape time only (weakly held)
        hbm.track("ivf", self)

    def hbm_bytes(self) -> Dict[str, int]:
        """Device-resident bytes by component: the built structure
        (slabs + bias + centroids) and the cached exact-tail upload.
        ``.nbytes`` is array metadata — reading it never syncs."""
        resident = 0
        for buf in (self._slabs, self._bias, self._centroids):
            if buf is not None:
                resident += int(getattr(buf, "nbytes", 0))
        tail = 0
        cache = self._tail_cache
        if cache is not None:
            _keys, dev_mat, dev_valid, _t_pad = cache
            tail = int(getattr(dev_mat, "nbytes", 0)) + int(
                getattr(dev_valid, "nbytes", 0)
            )
        return {"resident": resident, "tail": tail}

    def observe_metrics(self):
        """Scrape-time ``pathway_ivf_*`` samples (flight-recorder
        provider): structure gauges from live state, maintenance and
        tail-upload-cache counters from ``stats``.  Lock-free reads of
        GIL-consistent attributes — a scrape never touches the index
        lock."""
        labels = {"index": str(self._observe_id)}
        centroids = self._centroids
        nlist = int(centroids.shape[0]) if centroids is not None else 0
        yield ("gauge", "pathway_ivf_nlist", labels, nlist)
        yield ("gauge", "pathway_ivf_resident_vectors", labels, len(self))
        yield ("gauge", "pathway_ivf_tail_size", labels, len(self._tail))
        for kind in ("sync_builds", "retrains", "absorbs"):
            yield (
                "counter",
                "pathway_ivf_maintenance_total",
                {**labels, "kind": kind},
                self.stats.get(kind, 0),
            )
        # legacy alias: the absorb_errors series pre-dates the
        # maintenance_failures family; both read the ONE failure counter
        yield (
            "counter",
            "pathway_ivf_maintenance_total",
            {**labels, "kind": "absorb_errors"},
            self.stats.get("absorb_failures", 0),
        )
        for kind, key in (
            ("absorb", "absorb_failures"),
            ("retrain", "retrain_failures"),
        ):
            yield (
                "counter",
                "pathway_ivf_maintenance_failures_total",
                {**labels, "kind": kind},
                self.stats.get(key, 0),
            )
        for result, key in (("hit", "tail_cache_hits"), ("miss", "tail_cache_misses")):
            yield (
                "counter",
                "pathway_ivf_tail_cache_total",
                {**labels, "result": result},
                self.stats.get(key, 0),
            )

    def __len__(self) -> int:
        # built live keys + unbuilt tail — counts correctly both for the
        # host-of-record path (_rows holds everything) and for
        # build_from_matrix (corpus stays on device; _rows holds only tail)
        if self._slabs is None:
            return len(self._rows)
        return len(self._slot_of_key) + len(self._tail)

    # -- mutation (host-of-record; device rebuilt lazily) ------------------
    def add(self, keys: Sequence[int], vectors: np.ndarray) -> int:
        # coerce + normalize BEFORE the lock: callers hand the encoder's
        # device rows straight here, and the implicit device→host sync
        # must not stall every concurrent search/absorb on the index
        # lock (value-flow analyzer finding)
        vectors = np.asarray(vectors, np.float32).reshape(
            len(keys), self.dimension
        )
        if self.metric == "cos":
            norms = np.linalg.norm(vectors, axis=1, keepdims=True)
            vectors = vectors / np.where(norms == 0, 1.0, norms)
        with self._lock:
            # membership check covers BOTH stores: host rows and (after
            # build_from_matrix) device-only bulk keys known via their slot
            existing = [
                int(k)
                for k in keys
                if int(k) in self._rows or int(k) in self._slot_of_key
            ]
            self._forget_built(existing)
            for key, vec in zip(keys, vectors):
                key = int(key)
                self._rows[key] = vec
                self._tail[key] = None
            self._tail_cache = None
            self.generation += 1
            if (
                self._slabs is not None
                and not self._absorbing
                and len(self._tail) >= self.absorb_threshold
                and (
                    self._absorb_stuck_at is None
                    or len(self._tail)
                    >= self._absorb_stuck_at + self.absorb_threshold
                )
            ):
                # absorb runs OFF the index lock on a maintenance thread
                # (like retrain): the device prefs matmul + its host sync
                # used to block concurrent search()/submit() for the whole
                # absorb, a serve-latency spike at every absorb tick
                # (ADVICE r5 #5).  Only the final donated scatter +
                # bookkeeping re-acquire the lock.
                self._absorbing = True
                try:
                    threading.Thread(
                        target=self._absorb_bg, daemon=True, name="ivf-absorb"
                    ).start()
                except RuntimeError:
                    # thread exhaustion: re-arm so a later add() retries
                    # instead of disabling absorbs for the index lifetime
                    self._absorbing = False
            self.maybe_retrain_async()
            # the generation this commit produced: the live-ingest
            # runner stamps it on the batch trace — documents become
            # retrievable (and the scheduler's generation-keyed result
            # cache rolls over) exactly at this value
            return self.generation

    def remove(self, keys: Sequence[int]) -> None:
        with self._lock:
            dropped = []
            for k in keys:
                k = int(k)
                in_rows = self._rows.pop(k, None) is not None
                if in_rows or k in self._slot_of_key:
                    dropped.append(k)
            self._forget_built(dropped)
            if dropped:
                self.generation += 1

    def _forget_built(self, keys: Sequence[int]) -> None:
        """Invalidate built slots (upsert/remove path) in ONE device scatter;
        also drop the keys from the unbuilt tail."""
        slots = []
        for key in keys:
            slot = self._slot_of_key.pop(key, None)
            if slot is not None:
                slots.append(slot)
            if key in self._tail:
                del self._tail[key]
                self._tail_cache = None
        if slots and self._bias is not None:
            arr = np.asarray(slots, np.int64)
            self._bias = self._bias.at[
                arr // self._M_pad, arr % self._M_pad
            ].set(-np.inf)
            if self._live_mask is not None:
                self._live_mask[arr] = False  # freed: absorb may reuse
            self._absorb_stuck_at = None  # capacity changed: re-arm absorb

    # -- build -------------------------------------------------------------
    def _needs_rebuild(self) -> bool:
        if self._slabs is None:
            return True
        grown = len(self._rows) - self._built_n
        return grown > max(64, self.rebuild_fraction * max(self._built_n, 1))

    def build(self) -> None:
        """Synchronous full (re)train + install — the explicit BULK path
        (initial load, tests, bench setup).  The serve path never calls
        this; streaming maintenance goes through the background
        ``_absorb_bg`` and retrain threads instead."""
        with self._lock:
            if not self._rows:
                self._slabs = None
                self._tail = {}
                self._tail_cache = None
                self._layout_gen += 1
                self.generation += 1
                return
            snapshot = dict(self._rows)
            self.stats["sync_builds"] += 1
        built = self._train_layout(snapshot)
        with self._lock:
            self._install(built, snapshot)

    def maybe_retrain_async(self) -> None:
        """Kick a background retrain when the index has churned past
        ``rebuild_fraction`` since the last build.  Returns immediately;
        at most one retrain runs at a time.  Caller may hold the lock."""
        with self._lock:
            if (
                self._slabs is None
                or self._retraining
                or not self._needs_rebuild()
                # build_from_matrix keeps the corpus on device; the host
                # row store only holds the streamed tail, so a host-side
                # retrain would DROP the bulk — skip until a full
                # host-of-record exists (or build_from_matrix is re-run)
                or len(self._rows) < len(self)
            ):
                return
            self._retraining = True
        threading.Thread(
            target=self._retrain_bg, daemon=True, name="ivf-retrain"
        ).start()

    def _retrain_bg(self) -> None:
        """Background retrain with a failure policy: an exception no
        longer dies silently with the daemon thread — it is logged ONCE
        per failure type, counted on
        ``pathway_ivf_maintenance_failures_total{kind="retrain"}``, and
        the pass retries with backoff from a FRESH snapshot (a stale one
        could mask rows that changed during the failed attempt).  After
        the attempt budget the thread exits; serving continues on the
        old slabs and the next add()/search() re-kicks a retrain."""
        try:
            for attempt in range(_MAINT_RETRY.attempts):
                try:
                    inject.fire("ivf.retrain")
                    with self._lock:
                        snapshot = dict(self._rows)
                    if not snapshot:
                        return
                    # the expensive part (k-means + layout + upload) runs
                    # WITHOUT the lock: serving continues on the old
                    # slabs throughout
                    t0 = time.perf_counter_ns()
                    built = self._train_layout(snapshot)
                    with self._lock:
                        self._install(built, snapshot)
                        self.stats["retrains"] += 1
                    _H_RETRAIN.observe_ns(time.perf_counter_ns() - t0)
                    return
                except Exception as exc:
                    with self._lock:
                        self.stats["retrain_failures"] = (
                            self.stats.get("retrain_failures", 0) + 1
                        )
                    log_once(
                        f"ivf.retrain:{type(exc).__name__}",
                        "IVF background retrain failed (%r); retrying with "
                        "backoff — failures counted on "
                        "pathway_ivf_maintenance_failures_total",
                        exc,
                    )
                    if attempt + 1 >= _MAINT_RETRY.attempts:
                        return
                    time.sleep(_MAINT_RETRY.delay_s("ivf.retrain", attempt + 1))
        finally:
            self._retraining = False

    def _train_layout(self, rows: Dict[int, np.ndarray]) -> Dict[str, Any]:
        """Train k-means + balanced assignment + slab layout + device upload
        for a snapshot of rows.  Lock-free: touches only its arguments."""
        n = len(rows)
        keys = list(rows.keys())
        data = np.stack([rows[k] for k in keys])
        return self._layout_from_data(keys, data)

    def _layout_from_data(self, keys: List[int], data: np.ndarray) -> Dict[str, Any]:
        n = len(keys)
        # cluster count targets ~240 rows at the balance CAP; since
        # the cap is 2x the mean fill, slab occupancy is structurally
        # ~50% (bf16 slabs ≈ a dense f32 matrix in HBM — the padding
        # buys contiguous per-cluster DMA for the Pallas rescore).  The
        # probe fraction from _default_probe keeps the rescored
        # shortlist ≈ min(N/5, 16k) padded rows/query at any N
        C = self.n_clusters or int(
            np.clip(np.ceil(n / 120.0), 16, 65536)
        )
        rng = np.random.default_rng(self.seed)
        sample_n = min(n, max(self.train_sample, 8 * C))
        C = min(C, n, sample_n)
        sample = data[rng.choice(n, size=sample_n, replace=False)]
        centroids = _kmeans(sample, C, self.kmeans_iters, self.seed)

        # balanced assignment: nearest centroid with a 2N/C cap; overflow
        # rows fall to their next-best centroid (keeps M bounded so the
        # gather shapes stay small).  Vectorized per preference rank —
        # rows competing for one cluster are ranked by sort position and
        # the first (cap - fill) win; losers retry at the next rank.
        cap = max(1, int(np.ceil(2.0 * n / C)))
        n_pref = min(8, C)
        # per-row top centroids computed ON DEVICE, fetched as [N, 8]
        # indices — the full [N, C] score matrix is 8 GB at 1M x 2000
        # and must never cross the host link
        cents_dev = jnp.asarray(centroids.T)

        @jax.jit
        def _prefs(chunk_rows):
            s = jnp.dot(
                chunk_rows, cents_dev, preferred_element_type=jnp.float32
            )
            _, idx = jax.lax.top_k(s, n_pref)
            return idx

        parts = []
        step = 131072
        for start in range(0, n, step):
            chunk = data[start : start + step]
            if chunk.shape[0] < step and n > step:
                pad = np.zeros((step - chunk.shape[0], data.shape[1]), data.dtype)
                # pathway: allow(recompile-hazard, value-flow): build-time — chunks are padded to the fixed 131072-row step, so large builds compile once (the n<=step case once per corpus size), and the chunked synchronous fetch IS the layout build, off the serve path
                got = np.asarray(_prefs(jnp.asarray(np.concatenate([chunk, pad]))))
                parts.append(got[: chunk.shape[0]])
            else:
                # pathway: allow(recompile-hazard, value-flow): build-time — one compile per (n, d) layout build and a deliberate synchronous fetch, off the serve path (serving shapes go through _bucket)
                parts.append(np.asarray(_prefs(jnp.asarray(chunk))))
        order = np.concatenate(parts) if len(parts) > 1 else parts[0]
        assignment, counts = _balanced_assign(order, C, cap)
        # CLUSTER-SORTED SLAB LAYOUT: rows of one cluster are contiguous
        # and padded to [C_pad, M_pad, d_pad], so the rescore reads each
        # probed cluster as ONE sequential DMA (ops/ivf_pallas.py) —
        # per-row gathers measured 40x slower than this layout on TPU.
        # Padding follows Mosaic tiling: M_pad % 128 (also the output
        # block's lane dim), d_pad % 128, C_pad % 8 (bias block rows).
        M = int(counts.max())
        M_pad = max(128, ((M + 127) // 128) * 128)
        d = data.shape[1]
        d_pad = ((d + 127) // 128) * 128
        C_pad = ((C + 7) // 8) * 8
        keys_arr = np.asarray(keys, dtype=np.uint64)
        order_by_cluster = np.argsort(assignment, kind="stable")
        sorted_cluster = assignment[order_by_cluster]
        starts = np.searchsorted(sorted_cluster, sorted_cluster, "left")
        j_within = np.arange(n) - starts
        slots = sorted_cluster * M_pad + j_within
        slabs = np.zeros((C_pad * M_pad, d_pad), np.float32)
        slabs[slots, :d] = data[order_by_cluster]
        bias = np.full(C_pad * M_pad, -np.inf, np.float32)
        bias[slots] = 0.0
        keys_by_slot = np.zeros(C_pad * M_pad, dtype=np.uint64)
        sorted_keys = keys_arr[order_by_cluster]
        keys_by_slot[slots] = sorted_keys
        slot_of_key = dict(zip(sorted_keys.tolist(), slots.tolist()))
        live_mask = np.zeros(C_pad * M_pad, dtype=bool)
        live_mask[slots] = True
        return {
            "keys_by_slot": keys_by_slot,
            "slot_of_key": slot_of_key,
            "live_mask": live_mask,
            # uploads happen here, OFF the lock (install just swaps refs);
            # centroids live ON DEVICE: a host-resident copy would re-upload
            # C x d floats on every dispatch (12.8 MB ~= 213 ms through the
            # tunnel at 1M-doc scale — measured as the entire serve latency)
            "slabs": jnp.asarray(
                slabs.reshape(C_pad, M_pad, d_pad), self.dtype
            ),
            "bias": jnp.asarray(bias.reshape(C_pad, M_pad)),
            "centroids": jnp.asarray(centroids),
            "M_pad": M_pad,
            "d_pad": d_pad,
            "n": n,
        }

    def _install(self, built: Dict[str, Any], snapshot: Dict[int, np.ndarray]) -> None:
        """Swap freshly built structures in (caller holds the lock),
        reconciling rows that changed while the build ran off-lock:
        removed/upserted keys are masked out of the new slabs; keys the
        snapshot never saw stay in the exact tail."""
        slot_of_key = built["slot_of_key"]
        # a built key is stale iff it was removed, or UPSERTED since the
        # snapshot (add() binds a fresh array per key, so object identity
        # of the stored vector is an exact change detector)
        stale = [
            k
            for k in slot_of_key
            if self._rows.get(k) is not snapshot[k]
        ]
        if stale:
            slots = np.asarray(
                [slot_of_key.pop(k) for k in stale], np.int64
            )
            M_pad = built["M_pad"]
            built["bias"] = built["bias"].at[
                slots // M_pad, slots % M_pad
            ].set(-np.inf)
            built["live_mask"][slots] = False
        self._keys_by_slot = built["keys_by_slot"]
        self._slot_of_key = slot_of_key
        self._live_mask = built["live_mask"]
        self._slabs = built["slabs"]
        self._bias = built["bias"]
        self._centroids = built["centroids"]
        self._M_pad = built["M_pad"]
        self._d_pad = built["d_pad"]
        self._tail = {
            k: None for k in self._rows if k not in slot_of_key
        }
        self._built_n = built["n"]
        self._absorb_stuck_at = None  # fresh layout: re-arm absorb
        self._tail_cache = None
        self._layout_gen += 1  # in-flight off-lock absorb plans must abort
        self.generation += 1
        self._search_fns.clear()

    def _absorb_bg(self) -> None:
        """Background absorb (maintenance thread, like retrain): snapshot
        under the lock, run the expensive plan (centroid-preference matmul
        + host fetch + free-slot placement) WITHOUT the lock — serving
        continues throughout — then re-acquire the lock only for the
        donated scatter + bookkeeping.

        Failure policy (ISSUE 4): an exception used to kill this daemon
        thread with only an excepthook traceback, leaving the tail to
        grow unboundedly until the next threshold crossing.  Now each
        failure is logged ONCE per type, counted on
        ``pathway_ivf_maintenance_failures_total{kind="absorb"}``, and
        the pass retries with backoff from a FRESH snapshot (the failed
        attempt may have raced a layout swap).  After the attempt budget
        the flag clears and the next add() re-arms an absorb."""
        try:
            for attempt in range(_MAINT_RETRY.attempts):
                try:
                    t0 = time.perf_counter_ns()
                    with self._lock:
                        snap = self._absorb_snapshot()
                    if snap is None:
                        return
                    plan = self._plan_absorb(snap)
                    with self._lock:
                        self._commit_absorb(snap, plan)
                    _H_ABSORB.observe_ns(time.perf_counter_ns() - t0)
                    return
                except Exception as exc:
                    with self._lock:
                        self.stats["absorb_failures"] = (
                            self.stats.get("absorb_failures", 0) + 1
                        )
                    log_once(
                        f"ivf.absorb:{type(exc).__name__}",
                        "IVF background absorb failed (%r); retrying with "
                        "backoff — failures counted on "
                        "pathway_ivf_maintenance_failures_total",
                        exc,
                    )
                    if attempt + 1 >= _MAINT_RETRY.attempts:
                        return
                    time.sleep(_MAINT_RETRY.delay_s("ivf.absorb", attempt + 1))
        finally:
            self._absorbing = False

    def _absorb_snapshot(self) -> Optional[Dict[str, Any]]:
        """Consistent view of the tail + slab occupancy for absorb planning
        (caller holds the lock)."""
        tail_keys = [k for k in self._tail if k in self._rows]
        if not tail_keys or self._slabs is None:
            return None
        vec_refs = [self._rows[k] for k in tail_keys]
        return {
            "tail_keys": tail_keys,
            # object identity of the stored vectors doubles as an exact
            # staleness detector at commit (add() binds a fresh array per
            # key, the same trick _install uses)
            "vec_refs": vec_refs,
            "data": np.stack(vec_refs),
            "live": self._live_mask.copy(),
            "centroids": self._centroids,
            "M_pad": self._M_pad,
            "C_pad": self._bias.shape[0],
            "d_pad": self._d_pad,
            "gen": self._layout_gen,
        }

    def _plan_absorb(self, snap: Dict[str, Any]) -> Dict[str, Any]:
        """Assign tail rows to FREE slab slots at their nearest centroid
        with spare capacity.  Lock-free: touches only the snapshot.  The
        device preference matmul + its host sync live here — the whole
        point of planning off the lock."""
        inject.fire("ivf.absorb")  # chaos site: the off-lock planning pass
        data = snap["data"]
        t = data.shape[0]
        M_pad = snap["M_pad"]
        C_pad = snap["C_pad"]
        C = snap["centroids"].shape[0]
        n_pref = min(4, C)
        tb = _bucket(t)  # bucketed batch: a handful of compile shapes
        data_p = (
            np.concatenate([data, np.zeros((tb - t, data.shape[1]), np.float32)])
            if tb > t
            else data
        )
        prefs = np.asarray(  # pathway: allow(value-flow): absorb PLAN phase — a deliberate synchronous preference fetch on the off-lock background planner, never on the serve path
            _tail_prefs(jnp.asarray(data_p), snap["centroids"], n_pref)
        )[:t]
        live = snap["live"]
        free_count = M_pad - np.add.reduceat(
            live.astype(np.int64), np.arange(0, C_pad * M_pad, M_pad)
        )
        target = np.full(t, -1, np.int64)
        fill = np.zeros(C_pad, np.int64)
        for r in range(n_pref):
            todo = target < 0
            if not todo.any():
                break
            cand = prefs[todo, r]
            room = free_count[cand] - fill[cand] > 0
            # rank competing rows within each cluster (same trick as build)
            idxs = np.flatnonzero(todo)[room]
            cand = cand[room]
            order = np.argsort(cand, kind="stable")
            cs = cand[order]
            starts = np.searchsorted(cs, cs, "left")
            within = np.arange(cs.size) - starts
            ok = within < (free_count[cs] - fill[cs])
            target[idxs[order[ok]]] = cs[ok]
            np.add.at(fill, cs[ok], 1)
        placed = np.flatnonzero(target >= 0)
        if placed.size == 0:
            return {"placed": placed, "slots": np.empty(0, np.int64)}
        # concrete free slot per placed row
        slots = np.empty(placed.size, np.int64)
        pos = 0
        for c in np.unique(target[placed]):
            rows_c = placed[target[placed] == c]
            free_js = np.flatnonzero(~live[c * M_pad : (c + 1) * M_pad])
            js = free_js[: rows_c.size]
            slots[pos : pos + rows_c.size] = c * M_pad + js
            pos += rows_c.size
        # keep (row -> slot) pairing aligned with the per-cluster slot fill
        order_rows = np.argsort(target[placed], kind="stable")
        placed = placed[order_rows]
        return {"placed": placed, "slots": slots}

    def _commit_absorb(self, snap: Dict[str, Any], plan: Dict[str, Any]) -> None:
        """Install an absorb plan (caller holds the lock): donated device
        scatter + bookkeeping only.  Rows that mutated while the plan ran
        off-lock (removed/upserted) are dropped; a layout swap (background
        retrain landed) aborts the whole plan — the retrain already
        reconciled the tail against its fresh slabs."""
        if snap["gen"] != self._layout_gen or self._slabs is None:
            return
        placed = plan["placed"]
        if placed.size == 0:
            # only suppress future absorbs if occupancy is unchanged since
            # the snapshot: a concurrent remove() freed capacity and
            # re-armed (_forget_built sets _absorb_stuck_at = None) while
            # the plan ran off-lock — a stale zero-placement plan must not
            # clobber that
            if np.array_equal(self._live_mask, snap["live"]):
                self._absorb_stuck_at = len(self._tail)
            return
        tail_keys = snap["tail_keys"]
        vec_refs = snap["vec_refs"]
        # staleness filter: key still in the tail with the SAME vector
        keep = np.asarray(
            [
                tail_keys[int(i)] in self._tail
                and self._rows.get(tail_keys[int(i)]) is vec_refs[int(i)]
                for i in placed
            ],
            bool,
        )
        placed = placed[keep]
        slots = plan["slots"][keep]
        if placed.size == 0:
            return
        self._absorb_stuck_at = None
        d = self.dimension
        vecs = np.zeros((placed.size, snap["d_pad"]), np.float32)
        vecs[:, :d] = snap["data"][placed]
        b = _bucket(placed.size)
        if b > placed.size:
            slots_p = np.concatenate(
                [slots, np.repeat(slots[-1], b - placed.size)]
            )
            vecs_p = np.concatenate(
                [vecs, np.repeat(vecs[-1:], b - placed.size, axis=0)]
            )
        else:
            slots_p, vecs_p = slots, vecs
        self._slabs, self._bias = _absorb_scatter(
            self._slabs,
            self._bias,
            jnp.asarray(slots_p, jnp.int32),
            jnp.asarray(vecs_p, self.dtype),
        )
        self._live_mask[slots] = True
        # copy-on-write: an in-flight serve dispatch snapshotted the OLD
        # keys_by_slot reference; mutating it in place could attribute a
        # reused slot's dispatch-time score to the newly absorbed key
        keys_by_slot = self._keys_by_slot.copy()
        for i, row_i in enumerate(placed):
            key = tail_keys[int(row_i)]
            slot = int(slots[i])
            keys_by_slot[slot] = key
            self._slot_of_key[key] = slot
            del self._tail[key]
        self._keys_by_slot = keys_by_slot
        self._tail_cache = None
        self.generation += 1
        self.stats["absorbs"] += 1

    def _tail_snapshot(self) -> Tuple[List[int], np.ndarray, np.ndarray, int]:
        """Materialize the exact tail for scoring (caller holds the lock):
        ``(tail_keys, tail_mat [t_pad, d], tail_valid [t_pad], t_pad)``.
        ``t_pad`` is the bucketed row count (0 = empty tail); pad rows are
        zero vectors masked invalid so they can never outrank real rows.
        Shared by host ``search`` and the fused serving path.

        A nonempty tail pads to at least ``absorb_threshold`` rows: the
        steady-state tail oscillates below the threshold, so this keeps
        the serving kernel at ONE compile shape instead of recompiling at
        every /256 tail bucket a stream passes through."""
        tail = [key for key in self._tail if key in self._rows]
        t_pad = (
            _bucket(max(len(tail), min(self.absorb_threshold, 8192)))
            if tail
            else 0
        )
        tail_mat = (
            np.stack([self._rows[key] for key in tail])
            if tail
            else np.zeros((0, self.dimension), np.float32)
        )
        if t_pad > len(tail):
            tail_mat = np.concatenate(
                [
                    tail_mat,
                    np.zeros((t_pad - len(tail), self.dimension), np.float32),
                ]
            )
        tail_valid = np.zeros(max(t_pad, 1), bool)
        tail_valid[: len(tail)] = True
        return tail, tail_mat, tail_valid, t_pad

    def _tail_snapshot_device(self) -> Tuple[List[int], Any, Any, int]:
        """Device-resident flavor of ``_tail_snapshot`` for the fused
        serving path (caller holds the lock): ``(tail_keys, tail_mat_dev,
        tail_valid_dev, t_pad)``.  The upload is CACHED on the index and
        invalidated only when the tail mutates (add / absorb / remove /
        layout install), so steady-state serving with an unchanged tail
        pays no per-dispatch host->device transfer — the padded tail is
        ~3 MB bf16 at d=384, previously re-sent on every serve call
        (ADVICE r5 #1)."""
        cache = self._tail_cache
        if cache is None:
            self.stats["tail_cache_misses"] += 1
            t_up0 = time.perf_counter_ns()
            tail, tail_mat, tail_valid, t_pad = self._tail_snapshot()

            def _upload():
                if t_pad:
                    return (
                        jnp.asarray(tail_mat[:t_pad], self.dtype),
                        jnp.asarray(tail_valid[:t_pad]),
                    )
                # placeholder shapes for the tail-less kernel signature
                return (
                    jnp.asarray(
                        np.zeros((1, self.dimension), np.float32), self.dtype
                    ),
                    jnp.asarray(np.zeros(1, bool)),
                )

            try:
                # transient upload failures retry briefly (the caller
                # holds the index lock, so the budget is milliseconds);
                # "ivf.tail_upload" is the chaos-suite fault site
                dev_mat, dev_valid = retry_call(
                    "ivf.tail_upload", _upload, policy=_TAIL_RETRY
                )
            except Exception as exc:
                # degradation ladder: tail unavailable ⇒ serve resident-
                # only results, flagged + counted.  NOT cached, so the
                # next serve retries the upload and recovery is automatic.
                log_once(
                    f"ivf.tail_upload:{type(exc).__name__}",
                    "IVF exact-tail device upload failed (%r); serving "
                    "resident-only (tail_skipped) until it recovers",
                    exc,
                )
                record_degraded(TAIL_SKIPPED)
                self.tail_degraded = True
                _t = trace.current()
                if _t is not None:
                    _t.add_span(
                        "ivf.tail_upload", t_up0, time.perf_counter_ns(),
                        status=TAIL_SKIPPED, error=type(exc).__name__,
                    )
                return (
                    [],
                    jnp.asarray(
                        np.zeros((1, self.dimension), np.float32), self.dtype
                    ),
                    jnp.asarray(np.zeros(1, bool)),
                    0,
                )
            self.tail_degraded = False
            _t = trace.current()
            if _t is not None:
                # a serve that paid the (cache-miss) tail re-upload shows
                # it as its own span — the classic "why was THIS one
                # slow" answer after an absorb invalidated the cache
                _t.add_span(
                    "ivf.tail_upload", t_up0, time.perf_counter_ns(),
                    rows=t_pad,
                )
            cache = (tail, dev_mat, dev_valid, t_pad)
            self._tail_cache = cache
        else:
            self.stats["tail_cache_hits"] += 1
            self.tail_degraded = False
        return cache

    def build_from_matrix(self, keys: Sequence[int], matrix_dev) -> None:
        """Bulk build directly from a DEVICE-RESIDENT row matrix [n, d]
        (e.g. the exact DeviceKnnIndex's HBM store) — the corpus never
        crosses the host link (VERDICT r4 #7).  Host transfers are only:
        the k-means training sample (one gather+fetch), the [n, n_pref]
        assignment preferences, and the layout index uploads; the slab
        scatter itself is a device gather.

        The host row store afterwards holds only streamed tail rows, so
        the background retrain is disabled until a full host-of-record
        exists (absorb + exact-tail streaming maintenance still work)."""
        n = int(matrix_dev.shape[0])
        keys = [int(k) for k in keys]
        assert len(keys) == n
        d = self.dimension
        C = self.n_clusters or int(np.clip(np.ceil(n / 120.0), 16, 65536))
        rng = np.random.default_rng(self.seed)
        sample_n = min(n, max(self.train_sample, 8 * C))
        C = min(C, n, sample_n)
        sample_idx = np.sort(rng.choice(n, size=sample_n, replace=False))
        sample = np.asarray(
            jnp.take(matrix_dev, jnp.asarray(sample_idx), axis=0),
            np.float32,
        )
        if self.metric == "cos":
            norms = np.linalg.norm(sample, axis=1, keepdims=True)
            sample = sample / np.where(norms == 0, 1.0, norms)
        centroids = _kmeans(sample, C, self.kmeans_iters, self.seed)

        cap = max(1, int(np.ceil(2.0 * n / C)))
        n_pref = min(8, C)
        cents_dev = jnp.asarray(centroids.T)

        @jax.jit
        def _prefs(chunk_rows):
            rows = chunk_rows.astype(jnp.float32)
            if self.metric == "cos":
                rows = rows / jnp.maximum(
                    jnp.linalg.norm(rows, axis=-1, keepdims=True), 1e-9
                )
            s = jnp.dot(rows, cents_dev, preferred_element_type=jnp.float32)
            _, idx = jax.lax.top_k(s, n_pref)
            return idx

        parts = []
        step = 131072
        for start in range(0, n, step):
            m = min(step, n - start)
            chunk = jax.lax.dynamic_slice_in_dim(matrix_dev, start, m, 0) \
                if m == step else matrix_dev[start : start + m]
            parts.append(np.asarray(_prefs(chunk)))  # pathway: allow(value-flow): bulk build — deliberate chunked synchronous fetch of cluster preferences, never on the serve path
        order = np.concatenate(parts) if len(parts) > 1 else parts[0]
        assignment, counts = _balanced_assign(order, C, cap)

        M = int(counts.max())
        M_pad = max(128, ((M + 127) // 128) * 128)
        d_pad = ((d + 127) // 128) * 128
        C_pad = ((C + 7) // 8) * 8
        keys_arr = np.asarray(keys, dtype=np.uint64)
        order_by_cluster = np.argsort(assignment, kind="stable")
        sorted_cluster = assignment[order_by_cluster]
        starts = np.searchsorted(sorted_cluster, sorted_cluster, "left")
        j_within = np.arange(n) - starts
        slots = sorted_cluster * M_pad + j_within

        # slab layout as ONE device gather+scatter — no host copy of rows
        @jax.jit
        def _layout(matrix, order_ix, slot_ix):
            rows = jnp.take(matrix, order_ix, axis=0).astype(jnp.float32)
            if self.metric == "cos":
                rows = rows / jnp.maximum(
                    jnp.linalg.norm(rows, axis=-1, keepdims=True), 1e-9
                )
            if d_pad > d:
                rows = jnp.concatenate(
                    [rows, jnp.zeros((rows.shape[0], d_pad - d), rows.dtype)],
                    axis=1,
                )
            flat = jnp.zeros((C_pad * M_pad, d_pad), self.dtype)
            return flat.at[slot_ix].set(rows.astype(self.dtype)).reshape(
                C_pad, M_pad, d_pad
            )

        # pathway: allow(recompile-hazard): bulk build — one compile per (n, layout) build_from_matrix call; never on the serve path
        slabs = _layout(
            matrix_dev,
            jnp.asarray(order_by_cluster, jnp.int32),
            jnp.asarray(slots, jnp.int32),
        )
        bias = np.full(C_pad * M_pad, -np.inf, np.float32)
        bias[slots] = 0.0
        keys_by_slot = np.zeros(C_pad * M_pad, dtype=np.uint64)
        sorted_keys = keys_arr[order_by_cluster]
        keys_by_slot[slots] = sorted_keys
        live_mask = np.zeros(C_pad * M_pad, dtype=bool)
        live_mask[slots] = True
        with self._lock:
            self._keys_by_slot = keys_by_slot
            self._slot_of_key = dict(
                zip(sorted_keys.tolist(), slots.tolist())
            )
            self._live_mask = live_mask
            self._slabs = slabs
            self._bias = jnp.asarray(bias.reshape(C_pad, M_pad))
            self._centroids = jnp.asarray(centroids)
            self._M_pad = M_pad
            self._d_pad = d_pad
            self._tail = {k: None for k in self._rows if k not in self._slot_of_key}
            self._built_n = n
            self._absorb_stuck_at = None
            self._tail_cache = None
            self._layout_gen += 1
            self.generation += 1
            self._search_fns.clear()
            self.stats["sync_builds"] += 1

    # -- durable warm state (serve/warmstate.py) -----------------------------
    def warm_state(self) -> Dict[str, Any]:
        """Snapshot everything a replica needs to serve bit-identically
        to this index: the host-of-record rows, the built device
        structures (resident slabs + bias + centroids), the slot
        bookkeeping, the exact tail, and the PUBLIC generation (cache /
        dedup keys on a restored replica must agree with the writer's).

        Refs are captured under the lock; device→host coercion runs OFF
        the lock (all device updates here are functional, so snapshotted
        refs stay valid — the same discipline as the off-lock absorb)."""
        with self._lock:
            rows = dict(self._rows)
            slabs, bias, cents = self._slabs, self._bias, self._centroids
            keys_by_slot = self._keys_by_slot
            live_mask = self._live_mask
            state: Dict[str, Any] = {
                "kind": "ivf",
                "dimension": int(self.dimension),
                "metric": self.metric,
                "M_pad": int(self._M_pad),
                "d_pad": int(self._d_pad),
                "slot_of_key": dict(self._slot_of_key),
                "tail": list(self._tail),
                "built_n": int(self._built_n),
                "generation": int(self.generation),
            }
        state["rows"] = rows
        state["slabs"] = None if slabs is None else np.asarray(slabs)
        state["bias"] = None if bias is None else np.asarray(bias)
        state["centroids"] = None if cents is None else np.asarray(cents)
        state["keys_by_slot"] = (
            None if keys_by_slot is None else np.array(keys_by_slot)
        )
        state["live_mask"] = None if live_mask is None else np.array(live_mask)
        return state

    def load_warm_state(self, state: Dict[str, Any]) -> None:
        """Install a ``warm_state()`` snapshot (replica bring-up): the
        restored index serves bit-identically to the writer at the
        snapshot's generation.  Uploads happen OFF the lock; the locked
        install is a pure pointer swap (the same launch-discipline as
        ``_install``).  Raises ``ValueError`` on a geometry mismatch —
        the warm-state manager turns that into a counted cold-start."""
        if state.get("kind") != "ivf":
            raise ValueError(f"not an IVF warm state: {state.get('kind')!r}")
        if int(state["dimension"]) != int(self.dimension):
            raise ValueError(
                f"dimension mismatch: snapshot {state['dimension']} "
                f"vs index {self.dimension}"
            )
        if state["metric"] != self.metric:
            raise ValueError(
                f"metric mismatch: snapshot {state['metric']!r} "
                f"vs index {self.metric!r}"
            )
        slabs = (
            None if state["slabs"] is None
            else jnp.asarray(state["slabs"], self.dtype)
        )
        bias = (
            None if state["bias"] is None
            else jnp.asarray(state["bias"], jnp.float32)
        )
        cents = (
            None if state["centroids"] is None
            else jnp.asarray(state["centroids"], jnp.float32)
        )
        rows = {
            int(k): np.asarray(v, np.float32) for k, v in state["rows"].items()
        }
        with self._lock:
            self._rows = rows
            self._slabs = slabs
            self._bias = bias
            self._centroids = cents
            self._keys_by_slot = state["keys_by_slot"]
            self._live_mask = state["live_mask"]
            self._M_pad = int(state["M_pad"])
            self._d_pad = int(state["d_pad"])
            self._slot_of_key = {
                int(k): int(s) for k, s in state["slot_of_key"].items()
            }
            self._tail = {int(k): None for k in state["tail"]}
            self._built_n = int(state["built_n"])
            self._absorb_stuck_at = None
            self._tail_cache = None
            self._layout_gen += 1  # in-flight off-lock plans must abort
            self.generation = int(state["generation"])
            self._search_fns.clear()

    def _default_probe(self) -> int:
        """Probe count bounding the rescore shortlist: up to 20% of
        clusters for small corpora (coarse clusters need generous probing
        for recall; exact search owns that regime anyway), tapering so
        n_probe*M_pad (the rescored rows per query) stays ~16k at large N."""
        C = self._centroids.shape[0]
        n = max(self._built_n, 1)
        # generous at small N (coarse clusters need more probes for recall;
        # exact search owns that regime anyway), tapering to ~16k rescored
        # rows per query at large N
        frac = min(0.2, 8192.0 / n)
        return max(1, min(C, int(np.ceil(C * frac))))

    # -- search ------------------------------------------------------------
    def search(  # pathway: allow(value-flow): reference host search — the synchronous host-results contract (serving uses submit/complete, which books its crossings); the fetch + float/int post-process below runs OFF the lock by design
        self, queries: np.ndarray, k: int, n_probe: Optional[int] = None
    ) -> List[List[Tuple[int, float]]]:
        # off-lock coercion: a device-array query batch syncs here, not
        # while holding the index lock
        queries = np.asarray(queries, np.float32).reshape(-1, self.dimension)
        with self._lock:
            nq = queries.shape[0]
            if nq == 0 or len(self) == 0:
                return [[] for _ in range(nq)]
            if self._slabs is None:
                # first build only: there is nothing to serve from yet.
                # After that the serve path NEVER rebuilds — staleness is
                # handled by absorb (in add) + background retrain.
                self.build()
            else:
                self.maybe_retrain_async()
            if self.metric == "cos":
                norms = np.linalg.norm(queries, axis=1, keepdims=True)
                queries = queries / np.where(norms == 0, 1.0, norms)
            C = self._centroids.shape[0]
            p = n_probe or self.n_probe or self._default_probe()
            p = min(p, C)
            b = _bucket(nq)
            if b > nq:
                queries = np.concatenate(
                    [queries, np.zeros((b - nq, self.dimension), np.float32)]
                )
            # exact tail of unbuilt recent rows, brute-force scored
            # alongside (device upload cached until the tail mutates)
            tail, tail_dev, tail_valid_dev, t_pad = self._tail_snapshot_device()
            fn = self._search_fn(b, k, p, t_pad)
            q_pad = queries
            if self._d_pad > self.dimension:
                q_pad = np.concatenate(
                    [
                        queries,
                        np.zeros(
                            (queries.shape[0], self._d_pad - self.dimension),
                            np.float32,
                        ),
                    ],
                    axis=1,
                )
            # dispatch must stay under the lock: a concurrent absorb commit
            # DONATES the slab/bias buffers (_absorb_scatter), so a launch
            # against refs snapshotted before the lock dropped could name
            # freed device memory.  The enqueue itself is async (no host
            # block); only the launch ordering needs the lock.
            scores, slots, t_scores, t_idx = fn(  # pathway: allow(lock-discipline): dispatch-only — donated absorb buffers force launch-before-unlock; fetch happens off-lock below
                jnp.asarray(q_pad, jnp.float32),
                self._slabs,
                self._bias,
                self._centroids if isinstance(self._centroids, jnp.ndarray)
                else jnp.asarray(self._centroids),
                tail_dev,
                tail_valid_dev,
            )
            # dispatch-time snapshot for off-lock completion: rebuilds and
            # absorbs REPLACE keys_by_slot (copy-on-write), so this ref is
            # the dispatch-time slot->key view.  No live-dict filter below:
            # rows removed BEFORE dispatch are already -inf-biased in the
            # dispatched arrays (bias is replaced functionally), and a
            # removal landing after dispatch must not shrink this result —
            # dispatch-time semantics, same as the fused serving path
            keys_by_slot = self._keys_by_slot
        # device round trip + python post-processing OFF the lock — holding
        # it across the fetch blocked every concurrent add()/absorb commit
        # and search for the full device latency (the round-5 bug class;
        # found by `python -m pathway_tpu.analysis`)
        scores = np.asarray(scores)[:nq]
        slots = np.asarray(slots)[:nq]
        t_scores = np.asarray(t_scores)[:nq] if t_pad else None
        t_idx = np.asarray(t_idx)[:nq] if t_pad else None
        out: List[List[Tuple[int, float]]] = []
        for qi in range(nq):
            row: List[Tuple[int, float]] = []
            for j in range(slots.shape[1]):
                s = float(scores[qi, j])
                slot = int(slots[qi, j])
                if not np.isfinite(s) or slot < 0:
                    continue
                row.append((int(keys_by_slot[slot]), s))
            if t_pad:
                for j in range(t_idx.shape[1]):
                    s = float(t_scores[qi, j])
                    ti = int(t_idx[qi, j])
                    if np.isfinite(s) and ti < len(tail):
                        row.append((tail[ti], s))
            row.sort(key=lambda kv: -kv[1])
            # drop duplicate keys (upsert landed in both built+tail)
            seen = set()
            dedup = []
            for key, s in row:
                if key not in seen:
                    seen.add(key)
                    dedup.append((key, s))
            out.append(dedup[:k])
        return out

    def _search_fn(self, B: int, k: int, p: int, t_pad: int):
        key = (
            B, k, p, t_pad,
            self._slabs.shape[0],
            self._M_pad,
            self._centroids.shape[0],
        )
        fn = self._search_fns.get(key)
        if fn is None:
            self._tripwire.observe(key)
            M = self._M_pad
            d = self.dimension
            k_main = min(k, p * M)
            k_tail = min(k, t_pad) if t_pad else 0
            use_pallas = jax.default_backend() == "tpu"

            @jax.jit
            def fn(q, slabs, bias, centroids, tail_mat, tail_valid):
                qf = q.astype(jnp.float32)
                cscores = jnp.dot(
                    qf[:, :d], centroids.T, preferred_element_type=jnp.float32
                )  # [B, C]
                _, probe = jax.lax.top_k(cscores, p)  # [B, p]
                probe = probe.astype(jnp.int32)
                from .ivf_pallas import rescore_shortlist

                scores3 = rescore_shortlist(
                    probe, qf, slabs, bias, use_pallas=use_pallas
                )
                scores = scores3.reshape(B, p * M)
                s, i = jax.lax.top_k(scores, k_main)
                jj = i // M
                mm = i % M
                slots = jnp.take_along_axis(probe, jj, axis=1) * M + mm
                slots = jnp.where(jnp.isfinite(s), slots, -1)
                if t_pad:
                    ts = jnp.dot(
                        qf[:, :d], tail_mat.T.astype(jnp.float32),
                        preferred_element_type=jnp.float32,
                    )
                    # mask pad rows: a 0.0 pad score would outrank real rows
                    # with negative similarity
                    ts = jnp.where(tail_valid[None, :], ts, -jnp.inf)
                    t_s, t_i = jax.lax.top_k(ts, k_tail)
                else:
                    t_s = jnp.zeros((B, 0), jnp.float32)
                    t_i = jnp.zeros((B, 0), jnp.int32)
                return s, slots, t_s, t_i

            # device-time attribution (observe/profile.py)
            fn = profile.wrap("ivf.search", fn)
            self._search_fns[key] = fn
        return self._search_fns[key]

    def search_oversampled(
        self,
        queries: np.ndarray,
        k: int,
        accept,  # callable(key) -> bool
        oversample: int = 4,
        max_rounds: int = 3,
    ) -> List[List[Tuple[int, float]]]:
        """Filtered search by over-sampling (same contract as
        DeviceKnnIndex.search_oversampled; shared loop in ops/knn.py)."""
        from .knn import oversampled_filtered_search

        return oversampled_filtered_search(
            self, queries, k, accept, oversample=oversample, max_rounds=max_rounds
        )

    # diagnostics ----------------------------------------------------------
    def score_flops_fraction(self) -> float:
        """Fraction of brute-force scoring FLOPs a probed search performs
        (centroid matmul + shortlist rescore vs full matrix)."""
        if self._slabs is None or len(self) == 0:
            return 1.0
        C = self._centroids.shape[0]
        M = self._M_pad
        p = self.n_probe or self._default_probe()
        n = max(self._built_n, 1)
        return (C + min(p, C) * M + len(self._tail)) / n


class _ShardIvf(IvfKnnIndex):
    """One shard-resident IVF partition: an ``IvfKnnIndex`` whose device
    structures live on a pinned device.  The synchronous entry points are
    wrapped by ``ShardedIvfIndex`` under ``jax.default_device``; the
    background maintenance threads (absorb/retrain) re-enter the pin here
    because ``jax.default_device`` is thread-local and a thread started
    inside ``add()`` would otherwise plan and scatter on device 0,
    migrating the shard's slabs off its home chip one absorb at a time."""

    def __init__(self, *args, device=None, **kwargs):
        self._device = device
        super().__init__(*args, **kwargs)

    def _absorb_bg(self) -> None:
        if self._device is None:
            return super()._absorb_bg()
        with jax.default_device(self._device):
            return super()._absorb_bg()

    def _retrain_bg(self) -> None:
        if self._device is None:
            return super()._retrain_bg()
        with jax.default_device(self._device):
            return super()._retrain_bg()


class ShardedIvfIndex:
    """Document-sharded IVF over a serve device group: ``n_shards``
    shard-resident ``IvfKnnIndex`` partitions (centroids, postings slabs,
    and exact tail all living on the owning shard's device), routed by
    the group's single placement rule ``owner_of(key)``.

    Same host API as the single-device indexes (add / remove / search /
    __len__ / build), so it drops into ``FusedEncodeSearch`` — which
    detects the ``shards`` attribute and switches to the scatter-dispatch
    serve path (ops/serving.py): encode once, fan the embedded batch out
    to every shard's resident search kernel, and tree-merge the per-shard
    candidates on device, all inside ONE logical dispatch (asserted by
    the dispatch counter's per-shard-group accounting).

    Maintenance stays shard-local: ``add()`` routes each document to its
    owning shard, whose own off-lock-plan/locked-commit absorb and
    background retrain discipline is unchanged — an absorb on shard 3
    never takes any other shard's lock.  The PUBLIC ``generation`` sums
    the children's mutation generations plus a routing-level counter, and
    every child bump happens under that child's lock, so the value moves
    atomically with the result-visible state of the whole group.

    Failure domains are per shard: the group's circuit breakers +
    ``shard.dispatch`` chaos site let one dead shard degrade recall on
    its partition (rung ``shard_skipped``) while the request succeeds.
    """

    def __init__(
        self,
        dimension: int,
        metric: str = "cos",
        group=None,
        n_shards: Optional[int] = None,
        devices: Optional[Sequence] = None,
        **ivf_kwargs: Any,
    ):
        from ..parallel.shards import ShardGroup

        self.group = group or ShardGroup(n_shards=n_shards, devices=devices)
        self.dimension = dimension
        self.metric = normalize_metric(metric)
        self.dtype = ivf_kwargs.get("dtype", jnp.float32)
        self._lock = threading.Lock()
        self._gen_base = 0  # routing-level bumps (e.g. dropped ingest)
        self.shards: List[_ShardIvf] = [
            _ShardIvf(
                dimension,
                metric=metric,
                device=self.group.device(s),
                **ivf_kwargs,
            )
            for s in range(self.group.n_shards)
        ]
        # routing-level failure accounting (a shard.absorb fault drops
        # that shard's documents from THIS ingest round only)
        self.stats: Dict[str, int] = {"route_drops": 0, "route_drop_docs": 0}
        self._observe_id = observe.next_id()
        observe.register_provider(self)

    # -- introspection ------------------------------------------------------
    def __len__(self) -> int:
        return sum(len(c) for c in self.shards)

    @property
    def generation(self) -> int:
        """Result-visibility generation of the whole group (see
        ``IvfKnnIndex.generation``): child bumps happen under the owning
        shard's lock, so any absorb/retrain/add landing anywhere in the
        group moves this value."""
        return self._gen_base + sum(c.generation for c in self.shards)

    @property
    def tail_degraded(self) -> bool:
        return any(c.tail_degraded for c in self.shards)

    # -- mutation (routed to the owning shard) ------------------------------
    def add(self, keys: Sequence[int], vectors: np.ndarray) -> None:
        keys = [int(k) for k in keys]
        if not keys:
            return
        vectors = np.asarray(vectors, np.float32).reshape(
            len(keys), self.dimension
        )
        for s, rows in sorted(self.group.route(keys).items()):
            try:
                # chaos sites: the per-shard ingest leg.  A raise drops
                # THIS shard's documents from this round only — the other
                # shards commit theirs, and the group stays serveable
                # (degrade-not-die, the forward-index failure policy).
                inject.fire(f"shard.absorb.{s}")
                inject.fire("shard.absorb")
                with jax.default_device(self.group.device(s)):
                    self.shards[s].add(
                        [keys[i] for i in rows], vectors[rows]
                    )
            except Exception as exc:
                with self._lock:
                    self.stats["route_drops"] += 1
                    self.stats["route_drop_docs"] += len(rows)
                    self._gen_base += 1
                log_once(
                    f"shard.absorb:{type(exc).__name__}",
                    "sharded ingest to shard %d failed (%r); its documents "
                    "are dropped from this round only — counted on "
                    "pathway_serve_shard_ingest_drops_total",
                    s,
                    exc,
                )

    def remove(self, keys: Sequence[int]) -> None:
        keys = [int(k) for k in keys]
        for s, rows in sorted(self.group.route(keys).items()):
            with jax.default_device(self.group.device(s)):
                self.shards[s].remove([keys[i] for i in rows])

    def build(self) -> None:
        """Synchronous bulk (re)build of every shard — the explicit bulk
        path, like ``IvfKnnIndex.build``.  The serve path never calls
        this; per-shard streaming maintenance handles staleness."""
        for s, child in enumerate(self.shards):
            with jax.default_device(self.group.device(s)):
                child.build()

    # -- host search (parity/reference; the serve path uses the fused
    # scatter-dispatch in ops/serving.py) -----------------------------------
    def search(
        self, queries: np.ndarray, k: int, n_probe: Optional[int] = None
    ) -> List[List[Tuple[int, float]]]:
        queries = np.asarray(queries, np.float32).reshape(-1, self.dimension)
        nq = queries.shape[0]
        merged: List[List[Tuple[int, float]]] = [[] for _ in range(nq)]
        for s, child in enumerate(self.shards):
            if len(child) == 0:
                continue
            with jax.default_device(self.group.device(s)):
                rows = child.search(queries, k, n_probe=n_probe)
            for qi, row in enumerate(rows):
                merged[qi].extend(row)
        out: List[List[Tuple[int, float]]] = []
        for row in merged:
            row.sort(key=lambda kv: -kv[1])
            out.append(row[:k])
        return out

    def search_oversampled(
        self, queries, k, accept, oversample: int = 4, max_rounds: int = 3
    ):
        from .knn import oversampled_filtered_search

        return oversampled_filtered_search(
            self, queries, k, accept, oversample=oversample,
            max_rounds=max_rounds,
        )

    # -- flight-recorder provider ------------------------------------------
    def observe_metrics(self):
        """Per-shard residency on the ``pathway_serve_shard_*`` family
        (the group's skip/breaker series ride the ``ShardGroup``
        provider; the children's own ``pathway_ivf_*`` series keep their
        per-index labels)."""
        labels = {"index": str(self._observe_id)}
        yield (
            "counter",
            "pathway_serve_shard_ingest_drops_total",
            labels,
            self.stats["route_drops"],
        )
        for s, child in enumerate(self.shards):
            shard_labels = {**labels, "shard": str(s)}
            yield (
                "gauge",
                "pathway_serve_shard_resident_vectors",
                shard_labels,
                len(child),
            )
            yield (
                "gauge",
                "pathway_serve_shard_tail_size",
                shard_labels,
                len(child._tail),
            )
