"""pw.universes — universe promises
(reference: python/pathway/internals/universes.py)."""

from __future__ import annotations

from .internals.table import Table

__all__ = ["promise_are_equal", "promise_are_pairwise_disjoint", "promise_is_subset_of"]


def promise_are_equal(*tables: Table) -> None:
    for t in tables[1:]:
        tables[0]._universe.promise_equal(t._universe)


def promise_is_subset_of(subset: Table, superset: Table) -> None:
    subset._universe = superset._universe.subuniverse()


def promise_are_pairwise_disjoint(*tables: Table) -> None:
    # bookkeeping only; concat validates at runtime
    return None
