"""pw.universes — universe promises
(reference: python/pathway/internals/universes.py)."""

from __future__ import annotations

from .internals.table import Table

__all__ = ["promise_are_equal", "promise_are_pairwise_disjoint", "promise_is_subset_of"]


def promise_are_equal(*tables: Table) -> None:
    for t in tables[1:]:
        tables[0]._universe.promise_equal(t._universe)


def promise_is_subset_of(subset: Table, superset: Table) -> None:
    subset._universe.promise_subset_of(superset._universe)


def promise_are_pairwise_disjoint(*tables: Table) -> None:
    """Vouch the tables' key sets never intersect: ``concat`` built after
    this promise skips its runtime collision check (without a promise,
    collisions raise — reference: universes.py + the static universe
    solver)."""
    for i, a in enumerate(tables):
        for b in tables[i + 1 :]:
            a._universe.promise_disjoint(b._universe)
