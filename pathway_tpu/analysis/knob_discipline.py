"""knob-discipline: every ``PATHWAY_*`` knob flows through the registry.

Round 18 collapsed ~75 raw ``os.environ`` reads of 50+ ``PATHWAY_*``
names — three incompatible bool conventions, unvalidated ``int()``/
``float()`` parses that raised mid-serve, hot-path re-parses per call —
into ONE declarative registry (``pathway_tpu/config.py``).  This family
is the ratchet that keeps it collapsed:

- **raw-env-read**: any ``os.environ``/``os.getenv`` read of a
  ``PATHWAY_*`` name outside the registry module is a finding.  The
  message escalates when the read sits in a serve-path function (a
  per-request env parse) or lexically inside a lock body (env parsing
  extends the critical section).  Alias assignments
  (``env = os.environ``) and ``from os import environ`` are resolved.
- **undeclared-knob** (whole-program): a ``PATHWAY_*`` literal, or a
  ``config.get("<key>")``-style reference, that no declaration covers.
  Checked against the ANALYZED tree's registry module when one is in
  scope (the module calling the ``_knob`` declaration helper), falling
  back to the live imported registry for single-module runs — so a
  fixture referencing a made-up knob is a finding without needing the
  whole tree.
- **dead-knob** (whole-program): a declared knob never read back via
  ``config.get``/``get_site`` anywhere in the analyzed tree.  Dead
  declarations are doc rot with a type signature; they make the README
  knob table lie.  Skipped when the registry module is not among the
  analyzed files (single-fixture runs cannot see the readers).

Like the other whole-program families, per-module facts are extracted
in ``run`` and cross-module findings come from ``finalize`` — so the
incremental cache stores only summaries and re-derives undeclared/dead
verdicts fresh each run (a knob declared TODAY must clear yesterday's
cached "undeclared" verdict without invalidating other modules).

Intentional exceptions live in ``DECLARED_KNOB_WAIVERS`` — mirrored
both directions against in-tree ``allow(knob-discipline)`` pragmas by
the tier-1 suite, exactly like the residency transfer table.  The tree
currently needs ZERO waivers; keep it that way.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, ModuleContext, Rule
from .registry import dotted_name, is_lock_context

__all__ = ["DECLARED_KNOB_WAIVERS", "KnobDisciplineRule"]

# (display-path suffix, env or key name) -> reason.  Every entry must be
# matched by an in-tree ``pathway: allow(...)`` pragma naming this rule
# and vice versa (test_knob_waivers_mirror_matches_pragmas).
DECLARED_KNOB_WAIVERS: Dict[Tuple[str, str], str] = {}

_KNOB_NAME_RE = re.compile(r"PATHWAY_[A-Z0-9_]+")
# dotted registry keys look like "serve.coalesce_us"
_KEY_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z][a-z0-9_]*)+$")
_CONFIG_API = {"get", "get_site", "set", "clear_override"}


def waiver_for(display_path: str, name: str) -> Optional[str]:
    norm = display_path.replace("\\", "/")
    for (suffix, waived), reason in DECLARED_KNOB_WAIVERS.items():
        if name == waived and norm.endswith(suffix):
            return reason
    return None


def _is_environ_name(name: Optional[str], aliases: Set[str]) -> bool:
    return bool(name) and (
        name in ("os.environ", "environ") or name in aliases
    )


def _literal_env_arg(node: ast.AST) -> Optional[str]:
    """The PATHWAY_* env name an argument expression resolves to, if it
    statically starts with the prefix: plain literals, f-strings with a
    literal head, and ``"PATHWAY_X" + tail`` concatenations."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        text = node.value
    elif isinstance(node, ast.JoinedStr) and node.values:
        head = node.values[0]
        if not (
            isinstance(head, ast.Constant) and isinstance(head.value, str)
        ):
            return None
        text = head.value
    elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        return _literal_env_arg(node.left)
    else:
        return None
    m = _KNOB_NAME_RE.match(text)
    return m.group(0) if m else None


def _docstring_nodes(tree: ast.Module) -> Set[int]:
    """id()s of Constant nodes that are docstrings — knob names inside
    prose (e.g. historical design notes) are not references."""
    out: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(
            node,
            (ast.Module, ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef),
        ) and node.body:
            first = node.body[0]
            if isinstance(first, ast.Expr) and isinstance(
                first.value, ast.Constant
            ) and isinstance(first.value.value, str):
                out.add(id(first.value))
    return out


class KnobDisciplineRule(Rule):
    name = "knob-discipline"
    salt_sources = ("knob_discipline.py",)
    description = (
        "raw PATHWAY_* env read outside the config registry, or an "
        "undeclared/dead knob"
    )

    def __init__(self) -> None:
        self._summaries: Dict[str, dict] = {}

    # -- per-module ---------------------------------------------------------

    def run(self, ctx: ModuleContext) -> None:
        tree = ctx.tree
        decls = self._registry_decls(tree)
        is_registry = bool(decls)
        aliases = self._environ_aliases(tree)
        helpers = self._env_helper_names(tree, aliases)
        doc_nodes = _docstring_nodes(tree)
        lock_spans = [
            (node.body[0].lineno, node.end_lineno or node.lineno)
            for node in ast.walk(tree)
            if isinstance(node, ast.With) and is_lock_context(node)
            and node.body
        ]
        fn_spans = [
            (node.lineno, node.end_lineno or node.lineno)
            for node in ast.walk(tree)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]

        env_refs: List[List] = []
        key_refs: List[List] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) and isinstance(
                node.value, str
            ):
                if id(node) in doc_nodes:
                    continue
                for name in _KNOB_NAME_RE.findall(node.value):
                    env_refs.append([name, node.lineno, node.col_offset])
            if isinstance(node, ast.Call):
                key = self._config_key_ref(node)
                if key is not None:
                    key_refs.append(
                        [key, node.lineno, node.col_offset]
                    )
                if not is_registry:
                    self._check_raw_read(
                        ctx, node, aliases, helpers, lock_spans, fn_spans
                    )
            elif not is_registry and isinstance(node, ast.Subscript):
                if isinstance(node.ctx, ast.Load) and _is_environ_name(
                    dotted_name(node.value), aliases
                ):
                    name = _literal_env_arg(node.slice)
                    if name is not None:
                        self._report_raw(
                            ctx, node, name, lock_spans, fn_spans,
                            via=f"os.environ[{name!r}]",
                        )
            elif not is_registry and isinstance(node, ast.Compare):
                for op, right in zip(node.ops, node.comparators):
                    if isinstance(op, (ast.In, ast.NotIn)) and (
                        _is_environ_name(dotted_name(right), aliases)
                    ):
                        name = _literal_env_arg(node.left)
                        if name is not None:
                            self._report_raw(
                                ctx, node, name, lock_spans, fn_spans,
                                via=f"{name!r} in os.environ",
                            )

        self._summaries[ctx.display_path] = {
            "registry": is_registry,
            "decls": decls,
            "env_refs": env_refs,
            "key_refs": key_refs,
        }

    def _registry_decls(self, tree: ast.Module) -> List[List]:
        """[key, env, line] per ``_knob("key", "ENV", ...)`` call — the
        module making such calls IS the registry (and is the one module
        allowed to touch ``os.environ`` for PATHWAY names)."""
        decls: List[List] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            if (callee or "").rsplit(".", 1)[-1] != "_knob":
                continue
            if len(node.args) < 2:
                continue
            key, env = node.args[0], node.args[1]
            if isinstance(key, ast.Constant) and isinstance(
                env, ast.Constant
            ):
                decls.append([key.value, env.value, node.lineno])
        return decls

    def _environ_aliases(self, tree: ast.Module) -> Set[str]:
        aliases: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, (ast.Name, ast.Attribute)
            ):
                if dotted_name(node.value) in ("os.environ", "environ"):
                    for target in node.targets:
                        if isinstance(target, ast.Name):
                            aliases.add(target.id)
            elif isinstance(node, ast.ImportFrom) and node.module == "os":
                for a in node.names:
                    if a.name in ("environ", "getenv"):
                        aliases.add(a.asname or a.name)
        return aliases

    def _env_helper_names(self, tree: ast.Module, aliases) -> Set[str]:
        """Local functions that forward a parameter into an environ read
        (``def _env_int(name, default): ... os.environ.get(name)``) —
        calling one with a PATHWAY_* literal is still a raw read; the
        helper is just a trench coat."""
        helpers: Set[str] = set()
        for node in ast.walk(tree):
            if not isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            params = {
                a.arg
                for a in (
                    node.args.posonlyargs
                    + node.args.args
                    + node.args.kwonlyargs
                )
            }
            for sub in ast.walk(node):
                hit = False
                if isinstance(sub, ast.Call):
                    callee = dotted_name(sub.func) or ""
                    leaf = callee.rsplit(".", 1)[-1]
                    if (
                        leaf in ("get", "setdefault")
                        and _is_environ_name(
                            callee.rsplit(".", 1)[0], aliases
                        )
                    ) or callee in ("os.getenv", "getenv"):
                        hit = bool(sub.args) and isinstance(
                            sub.args[0], ast.Name
                        ) and sub.args[0].id in params
                elif isinstance(sub, ast.Subscript) and _is_environ_name(
                    dotted_name(sub.value), aliases
                ):
                    hit = isinstance(
                        sub.slice, ast.Name
                    ) and sub.slice.id in params
                if hit:
                    helpers.add(node.name)
                    break
        return helpers

    def _config_key_ref(self, node: ast.Call) -> Optional[str]:
        """The literal first argument of a ``config.<api>("a.b", ...)``
        call — a registry-key reference (the dead-knob liveness signal)."""
        if not isinstance(node.func, ast.Attribute):
            return None
        if node.func.attr not in _CONFIG_API:
            return None
        base = dotted_name(node.func.value) or ""
        if base.rsplit(".", 1)[-1] not in ("config", "_config", "pwconfig"):
            return None
        if not node.args:
            return None
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value if _KEY_RE.match(arg.value) else None
        return None

    def _check_raw_read(
        self, ctx, node: ast.Call, aliases, helpers, lock_spans, fn_spans
    ) -> None:
        callee = dotted_name(node.func)
        if not callee:
            return
        leaf = callee.rsplit(".", 1)[-1]
        is_read = (
            (leaf in ("get", "setdefault") and _is_environ_name(
                callee.rsplit(".", 1)[0], aliases
            ))
            or callee in ("os.getenv", "getenv")
            or (leaf == "getenv" and callee in aliases)
            or callee in helpers
        )
        if not is_read or not node.args:
            return
        name = _literal_env_arg(node.args[0])
        if name is None:
            return
        self._report_raw(
            ctx, node, name, lock_spans, fn_spans,
            via=f"{callee}({name!r})",
        )

    def _report_raw(
        self, ctx, node, name, lock_spans, fn_spans, via
    ) -> None:
        if waiver_for(ctx.display_path, name):
            return
        line = node.lineno
        if any(lo <= line <= hi for lo, hi in lock_spans):
            ctx.report(
                self.name, node,
                f"raw env read `{via}` inside a lock body — env parsing "
                "extends the critical section; read it once through "
                "config.get outside the lock",
            )
        elif ctx.serve_path and any(
            lo <= line <= hi for lo, hi in fn_spans
        ):
            ctx.report(
                self.name, node,
                f"raw env read `{via}` on a serve-path function — a "
                "per-request env parse; config.get is a cached typed "
                "lookup, use it",
            )
        else:
            ctx.report(
                self.name, node,
                f"raw env read `{via}` outside config.py — declare the "
                "knob once in the registry and read it via config.get",
            )

    # -- incremental-cache plumbing ----------------------------------------

    def dump_summary(self, display_path: str) -> Optional[dict]:
        return self._summaries.get(display_path)

    def load_summary(self, display_path: str, summary: dict) -> None:
        self._summaries[display_path] = summary

    # -- whole-program ------------------------------------------------------

    def finalize(self) -> List[Finding]:
        reg_modules = {
            path: s for path, s in self._summaries.items() if s["registry"]
        }
        if reg_modules:
            declared_keys = {
                d[0] for s in reg_modules.values() for d in s["decls"]
            }
            declared_envs = {
                d[1] for s in reg_modules.values() for d in s["decls"]
            }
            # prefix families (PATHWAY_RETRY_ATTEMPTS_<SITE>) are strings
            # in the declaration's keyword args, which the AST extraction
            # above does not carry — derive them from the live registry,
            # which is authoritative for the real tree
            prefixes = self._live_prefixes()
        else:
            live = self._live_registry()
            declared_keys = set(live)
            declared_envs = {k.env for k in live.values()}
            prefixes = self._live_prefixes()

        out: List[Finding] = []
        read_keys: Set[str] = set()
        for path in sorted(self._summaries):
            s = self._summaries[path]
            if s["registry"]:
                continue
            read_keys.update(ref[0] for ref in s["key_refs"])
            seen_here: Set[Tuple[str, int]] = set()
            for name, line, col in s["env_refs"]:
                if name in declared_envs:
                    continue
                if any(name.startswith(p) or name == p for p in prefixes):
                    continue
                if waiver_for(path, name):
                    continue
                if (name, line) in seen_here:
                    continue
                seen_here.add((name, line))
                out.append(
                    Finding(
                        path, line, col, self.name,
                        f"undeclared knob `{name}` — every PATHWAY_* env "
                        "must be declared exactly once in the config "
                        "registry (pathway_tpu/config.py)",
                    )
                )
            for key, line, col in s["key_refs"]:
                if key in declared_keys or waiver_for(path, key):
                    continue
                out.append(
                    Finding(
                        path, line, col, self.name,
                        f"config key `{key}` is not declared in the "
                        "registry — config.get on it raises "
                        "UnknownKnobError at runtime",
                    )
                )
        # dead knobs need the READER side of the whole tree in scope;
        # a lone-fixture run (no registry module analyzed) skips this
        for path, s in sorted(reg_modules.items()):
            for key, env, line in s["decls"]:
                if key in read_keys or waiver_for(path, key):
                    continue
                out.append(
                    Finding(
                        path, line, 0, self.name,
                        f"dead knob: `{key}` ({env}) is declared but "
                        "never read via config.get/get_site anywhere in "
                        "the analyzed tree — delete the declaration or "
                        "wire up the reader",
                    )
                )
        return out

    def _live_registry(self):
        from .. import config as pwconfig

        return pwconfig.registry()

    def _live_prefixes(self) -> Set[str]:
        try:
            live = self._live_registry()
        except Exception:  # standalone analysis checkouts
            return set()
        return {
            k.site_prefix for k in live.values() if k.site_prefix
        }
