"""Resolution helpers shared by the lint rules.

The rules need to answer, lexically, three questions the AST does not
answer directly:

1. **Is this call a device dispatch?**  A first pass over the module
   collects every function defined via ``jax.jit`` / ``pjit`` (decorator,
   ``partial(jax.jit, ...)`` decorator, or ``name = jax.jit(fn)``
   assignment).  The serving code additionally reaches jitted callables
   through per-shape cache getters (``self._forward_fn(...)``,
   ``self._compiled_ivf(...)``, ``self._search_fn(...)`` — the repo-wide
   convention), so a local variable assigned from such a getter is also a
   jitted callee.
2. **Is this variable a device array?**  Variables assigned (incl. tuple
   unpacking) from a jitted call hold unfetched device values; coercing
   one on the host (``np.asarray`` / ``float`` / ``int`` / ``.item()``)
   is a blocking transfer.
3. **Is this ``with`` statement a lock?**  Matched by name: any context
   expression whose terminal identifier contains ``lock``/``mutex``/
   ``cv``/``cond`` (``self._lock``, ``index._lock``,
   ``self._send_locks[peer]``, condition variables).
4. **Is this variable a serve completion handle?**  The serve stack's
   submit/complete contract hands back a handle whose CALL performs the
   host fetch (``handle = pipe.submit(...)`` then ``handle()`` /
   ``handle.result()`` / ``handle.advance()``).  The coalescing
   scheduler's future-handoff pattern (serve/scheduler.py) dispatches on
   the scheduler thread and fetches on the WAITER — completing a handle
   while holding a lock (e.g. the admission-queue lock) would stall
   every admitter for a device round trip, so the lock-discipline rule
   treats a handle completion under a lock as a violation.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

__all__ = [
    "booking_declares_fanout",
    "collect_donating_jits",
    "collect_jit_names",
    "dotted_name",
    "is_cache_access",
    "is_cache_wrapper",
    "is_device_producer_call",
    "is_dispatch_booking",
    "is_handle_fetch",
    "is_lock_context",
    "is_observability_callback",
    "is_stream_io",
    "scope_handle_vars",
    "scope_jit_and_device_vars",
    "walk_scope",
]

# cache getters that hand back per-shape jitted callables (the repo-wide
# naming convention for compiled-fn caches).  _token_fn/_pool_fn/
# _maxsim_fn/_audit_fn are the forward-index family (models/encoder.py
# token-state export + pathway_tpu/index/forward.py ingest and gather);
# _encode_fn/_shard_search_fn/_merge_fn/_table_fn/_scatter_fn are the
# sharded-serve family (ops/serving.py scatter-dispatch fan-out + tree
# merge, index/forward.py per-shard tables + max-merge, ops/knn.py
# sharded scatters); _slot_prefill_fn/_slot_step_fn/_slot_verify_fn/
# _slot_draft_fn are the continuous-decode slot pool (models/generator.py
# compiled join/step chunks plus the speculative draft→verify pair,
# driven by serve/decode.py — the slot-pool lock convention: allocating
# a slot under the pool lock is fine, CALLING one of these under it is a
# lock-discipline finding).  Tuple-returning getters (e.g.
# _shard_search_fn -> (fn, n_slotspace)) bind only their FIRST unpack
# target as the callee.
_CACHE_GETTER_RE = re.compile(
    r"^_(compiled\w*|forward_fn|packed_fn|search_fn"
    r"|token_fn|pool_fn|maxsim_fn|audit_fn"
    r"|encode_fn|shard_search_fn|merge_fn|table_fn|scatter_fn"
    r"|slot_prefill_fn|slot_step_fn|slot_verify_fn|slot_draft_fn)$"
)
_LOCK_NAME_RE = re.compile(r"lock|mutex|cv\b|cond", re.IGNORECASE)
# donation_guard.donating_jit is the guard-aware jit constructor
# (ops/donation_guard.py): it compiles the donating callable AND registers
# the runtime poison site, so the rules treat it exactly like jax.jit
_JIT_CTORS = {
    "jax.jit", "jit", "pjit", "jax.pjit",
    "donating_jit", "donation_guard.donating_jit",
}
# the robust retry wrapper (pathway_tpu/robust/retry.py): a call like
# ``retry_call("site", fn, *args)`` DISPATCHES ``fn`` when ``fn`` is a
# jitted callable — the rules must keep treating it as a device dispatch
# (for lock-discipline) and its result as a device value (for the
# hidden-sync fetch/budget checks), or wrapping a launch in a retry
# would silently launder it out of both rules
_RETRY_WRAPPERS = {"retry_call"}

# the profiler's instrumentation wrapper (observe/profile.py):
# ``fn = profile.wrap("site", jitted)`` returns a TRANSPARENT wrapper —
# calling it IS the dispatch, its result IS a device value.  The
# compiled-fn caches store their kernels through it, so an assignment
# from ``profile.wrap(...)`` whose function argument is jitted must bind
# the target as a jitted callable, or wrapping a kernel for attribution
# would silently launder it out of every rule (the retry_call lesson).
_PROFILE_WRAP_RE = re.compile(r"(^|\.)profile\.wrap$|^wrap$")

# observability CALLBACKS (profiler flush/stats, HBM ledger sample, SLO
# evaluation): pull-based by design — they walk registries, may fire
# the profile.sample / hbm.ledger / slo.evaluate chaos sites
# (delay/hang), and belong on scrape/bench threads, NEVER under a
# serve-path lock where the fault (or just the walk) stalls every
# admitter.  Matched as <receiver spelled like the observability
# modules>.<sampling method>.
_OBS_CALLBACK_METHOD_RE = re.compile(
    r"^(sample|evaluate|should_shed|profile_stats|ledger_stats|drain)$"
)
_OBS_RECEIVER_RE = re.compile(r"(^|_)(profile|hbm|ledger|slo)(_\w+)?$")

# the cache-wrapper convention (pathway_tpu/cache): a function named
# ``_cached_*`` / ``get_or_*`` wraps a device dispatch behind a cache
# lookup — ``_cached_embeddings`` (ops/serving.py), ``_cached_encode_rows``
# (models/encoder.py), ``get_or_compute`` (persistence/object_cache.py).
# Its dispatch fires only on a MISS and is accounted inside the CALLER's
# logical dispatch group (``record_dispatch(tag, shards=<launches>)``),
# so the hidden-sync budget check must not demand a record_dispatch in
# the wrapper scope itself — a cache lookup guarding a dispatch is not a
# hidden sync.  Everything else (sync-in-dispatch-scope, lock
# discipline) applies to wrapper scopes unchanged.
_CACHE_WRAPPER_RE = re.compile(r"^_?(cached_\w+|get_or_\w+)$")

# cache ACCESS, for the lock-discipline rule: a get/put-style method on
# a receiver whose terminal identifier is spelled like a cache
# (``self._result_cache.get(...)``, ``self.embed_cache.put_row(...)``).
# Cache lookups take the tier's own lock and fire the cache.get /
# cache.put chaos sites (which may delay or hang) — under a serve lock
# they would stall every admitter for the fault's duration.
_CACHE_METHOD_RE = re.compile(r"^(get|put|lookup|store|admit|match)")
_CACHE_RECEIVER_RE = re.compile(r"cache$", re.IGNORECASE)

# the fabric stream convention (serve/fabric.py over the exchange
# plane's FramedStream): ``<stream|link|peer|conn-spelled receiver>
# .send/.recv/.send_request(...)`` is BLOCKING network I/O — a frame
# send can stall for a full heartbeat timeout on a congested peer, a
# recv blocks until a frame (or the socket timeout) lands, and both
# fire the fabric.send/fabric.recv chaos sites (delay/hang).  Under a
# serve-path lock one slow host becomes a fleet-wide admission stall —
# the exact failure the fabric exists to contain.  The sanctioned shape
# is fabric.py's swap-under-lock / I/O-off-lock discipline: mutate the
# stream slot inside ``_conn_lock``, perform the send/recv/close after
# releasing it (``mark_down``, ``close``, ``send_request``).
_STREAM_IO_METHOD_RE = re.compile(r"^(send|recv|send_request)$")
_STREAM_RECEIVER_RE = re.compile(
    r"(^|_)(stream|link|peer|conn)s?$", re.IGNORECASE
)

# the scatter-gather fan-out booking convention (ops/dispatch_counter.py):
# a serve path that fans ONE logical dispatch out to N physical targets —
# the sharded index's per-shard device launches, the partitioned fabric's
# per-partition stream sends (serve/fabric.py ``fabric.scatter`` /
# ``fabric.gather``) — books it as ``record_dispatch(tag, shards=N)`` /
# ``record_fetch(tag, shards=N)``: 1 logical + N physical on the runtime
# counters, so the 2+2 per-batch budget stays a statement about LOGICAL
# round trips while the physical width remains visible
# (``pathway_serve_shard_dispatches_total``).  ``is_dispatch_booking``
# recognizes any record_dispatch/record_fetch call;
# ``booking_declares_fanout`` whether it carries the ``shards=`` width —
# the hidden-sync rule requires the width on scopes that visibly fan out
# (stream I/O inside a loop), or the budget would book an H-way scatter
# as one physical send.
_BOOKING_LEAVES = {"record_dispatch", "record_fetch"}


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains (subscripts transparent:
    ``self._send_locks[peer]`` -> ``self._send_locks``); None otherwise."""
    parts: List[str] = []
    while True:
        if isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Name):
            parts.append(node.id)
            return ".".join(reversed(parts))
        elif isinstance(node, ast.Call):
            node = node.func
        else:
            return None


def _is_jit_expr(node: ast.AST) -> bool:
    """``jax.jit(...)`` / ``pjit(...)`` / ``partial(jax.jit, ...)``."""
    if not isinstance(node, ast.Call):
        return dotted_name(node) in _JIT_CTORS
    name = dotted_name(node.func)
    if name in _JIT_CTORS:
        return True
    if name in ("partial", "functools.partial") and node.args:
        return dotted_name(node.args[0]) in _JIT_CTORS
    return False


def collect_jit_names(tree: ast.AST) -> Set[str]:
    """Names bound to jitted callables anywhere in the module (module
    level and nested: call sites resolve by bare name, which matches how
    the code actually reaches them)."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_jit_expr(dec) for dec in node.decorator_list):
                names.add(node.name)
        elif isinstance(node, ast.Assign):
            if _is_jit_expr(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        names.add(tgt.id)
    return names


def _donate_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """The ``donate_argnums=`` keyword of a jit-constructor call, as a
    tuple of positional indices, or None when absent/unparseable."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        value = kw.value
        if isinstance(value, ast.Constant) and isinstance(value.value, int):
            return (value.value,)
        if isinstance(value, (ast.Tuple, ast.List)):
            out = []
            for elt in value.elts:
                if not (
                    isinstance(elt, ast.Constant)
                    and isinstance(elt.value, int)
                ):
                    return None
                out.append(elt.value)
            return tuple(out)
        return None
    return None


def collect_donating_jits(tree: ast.AST) -> Dict[str, Tuple[int, ...]]:
    """Names bound to DONATING jitted callables in the module, mapped to
    their donated positional indices.  Covers every spelling the repo
    uses: ``@partial(jax.jit, donate_argnums=(0, 1))`` decorators (plain
    or through ``donation_guard.donating_jit``), direct
    ``@donating_jit(site=..., donate_argnums=...)`` decorator calls, and
    ``name = jax.jit(fn, donate_argnums=...)`` assignments.  The
    value-flow rule's use-after-donate check poisons the arguments at
    these positions after every call."""
    out: Dict[str, Tuple[int, ...]] = {}

    def from_expr(node: ast.AST) -> Optional[Tuple[int, ...]]:
        if not isinstance(node, ast.Call):
            return None
        name = dotted_name(node.func)
        if name in _JIT_CTORS or (
            name is not None and name.rsplit(".", 1)[-1] in _JIT_CTORS
        ):
            return _donate_positions(node)
        if name in ("partial", "functools.partial") and node.args:
            inner = dotted_name(node.args[0])
            if inner in _JIT_CTORS or (
                inner is not None
                and inner.rsplit(".", 1)[-1] in _JIT_CTORS
            ):
                return _donate_positions(node)
        return None

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                positions = from_expr(dec)
                if positions:
                    out[node.name] = positions
        elif isinstance(node, ast.Assign):
            positions = from_expr(node.value)
            if positions:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out[tgt.id] = positions
    return out


# device-PRODUCER method convention: ``<embedder|encoder|model>.encode(
# texts)`` returns device rows (SentenceEncoder.encode and friends) —
# the value-flow rule treats the result as a device value so an
# immediate host coercion (``np.asarray(embedder.encode(texts))``) is a
# visible device→host crossing even in modules with no jit of their own
# (the stdlib adapter class).  The receiver spelling carries the
# convention; ``str.encode`` receivers (payload/text vars) do not match.
# encode_to_device / encode_packed_to_device: the live-ingest runner
# (serve/ingest.py) reaches the encoder through the device-resident
# batch entries, so their results must carry device provenance too
_PRODUCER_METHODS = {
    "encode",
    "encode_token_states",
    "encode_to_device",
    "encode_packed_to_device",
}
_PRODUCER_RECEIVER_RE = re.compile(
    r"(^|_)(embedder|encoder|enc|model)s?$", re.IGNORECASE
)


def is_device_producer_call(call: ast.Call) -> bool:
    """``<encoder-spelled receiver>.encode(...)`` — a model call whose
    result lives on device by the repo's encoder convention."""
    func = call.func
    if not isinstance(func, ast.Attribute):
        return False
    if func.attr not in _PRODUCER_METHODS:
        return False
    receiver = dotted_name(func.value)
    if receiver is None:
        return False
    return bool(_PRODUCER_RECEIVER_RE.search(receiver.rsplit(".", 1)[-1]))


def is_lock_context(with_node: ast.With) -> bool:
    for item in with_node.items:
        name = dotted_name(item.context_expr)
        if name and _LOCK_NAME_RE.search(name.rsplit(".", 1)[-1]):
            return True
    return False


def walk_scope(node: ast.AST, *, into_functions: bool = False) -> Iterable[ast.AST]:
    """Walk ``node`` without descending into nested function/lambda/class
    bodies (unless ``into_functions``): statements inside a nested ``def``
    do not execute where they appear, so e.g. a completion closure defined
    under a lock does not RUN under that lock."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        if not into_functions and isinstance(
            child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
        ):
            continue
        yield child
        stack.extend(ast.iter_child_nodes(child))


def _target_names(target: ast.AST) -> List[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out: List[str] = []
        for elt in target.elts:
            out.extend(_target_names(elt))
        return out
    return []


def scope_jit_and_device_vars(
    scope: ast.AST,
    module_jit_names: Set[str],
    inherited_fns: Optional[Set[str]] = None,
    inherited_vars: Optional[Set[str]] = None,
) -> (Set[str], Set[str]):
    """For one function scope (or the module body): the set of local names
    holding JITTED CALLABLES (from the module registry, ``jax.jit``
    assignments, or cache-getter calls) and the set holding DEVICE VALUES
    (assigned from a call to one of those callables).  ``inherited_*``
    seed closures with the enclosing scope's sets."""
    jit_fns: Set[str] = set(module_jit_names) | set(inherited_fns or ())
    device_vars: Set[str] = set(inherited_vars or ())
    # two passes so a getter assignment above or below a use both resolve
    # (lexical order is irrelevant for name→kind classification here)
    for _ in range(2):
        for node in walk_scope(scope):
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            names: List[str] = []
            for tgt in node.targets:
                names.extend(_target_names(tgt))
            if not names:
                continue
            if _is_jit_expr(value):
                jit_fns.update(names)
            elif isinstance(value, ast.Call):
                callee = dotted_name(value.func)
                if callee is None:
                    continue
                leaf = callee.rsplit(".", 1)[-1]
                if _CACHE_GETTER_RE.match(leaf):
                    # tuple getters return (fn, extras...): only the first
                    # element is the callable
                    jit_fns.add(names[0])
                elif _is_profile_wrap(value, jit_fns):
                    # fn = profile.wrap("site", jitted) — the attribution
                    # wrapper IS the jitted callable for every rule
                    jit_fns.update(names)
                elif leaf in jit_fns or callee in jit_fns:
                    device_vars.update(names)
                elif _is_retry_wrapped_dispatch(value, jit_fns):
                    # x = retry_call("site", jitted_fn, ...) — the retry
                    # wrapper returns the jitted call's (device) result
                    device_vars.update(names)
    return jit_fns, device_vars


def _is_retry_wrapped_dispatch(call: ast.Call, jit_fns: Set[str]) -> bool:
    """``retry_call("site", fn, ...)`` with ``fn`` a jitted callable —
    the robust wrapper dispatches its function argument, so the rules
    treat the wrapper call itself as the dispatch."""
    callee = dotted_name(call.func)
    if callee is None or callee.rsplit(".", 1)[-1] not in _RETRY_WRAPPERS:
        return False
    for arg in call.args:
        name = dotted_name(arg)
        if name is None:
            continue
        if name in jit_fns or name.rsplit(".", 1)[-1] in jit_fns:
            return True
    return False


def _is_profile_wrap(call: ast.Call, jit_fns: Set[str]) -> bool:
    """``profile.wrap("site", fn, ...)`` (or a direct ``jax.jit(...)`` /
    cache-getter argument) — the profiler's transparent wrapper over a
    jitted callable."""
    callee = dotted_name(call.func)
    if callee is None or not _PROFILE_WRAP_RE.search(callee):
        return False
    for arg in call.args:
        if isinstance(arg, ast.Call) and _is_jit_expr(arg):
            return True
        name = dotted_name(arg)
        if name is None:
            continue
        if name in jit_fns or name.rsplit(".", 1)[-1] in jit_fns:
            return True
    return False


def is_observability_callback(call: ast.Call) -> Optional[str]:
    """A pull-style observability callback — ``<profile|hbm|slo|
    ledger>.sample/evaluate/...`` — returns the dotted spelling for the
    diagnostic, or None.  These walk registries and fire the
    profile.sample / hbm.ledger / slo.evaluate chaos sites (delay/
    hang): legal on scrape/bench threads, a lock-discipline finding
    under any serve-path lock."""
    func = call.func
    if not isinstance(func, ast.Attribute):
        return None
    if not _OBS_CALLBACK_METHOD_RE.match(func.attr):
        return None
    receiver = dotted_name(func.value)
    if receiver is None:
        return None
    if _OBS_RECEIVER_RE.search(receiver.rsplit(".", 1)[-1]):
        return f"{receiver}.{func.attr}"
    return None


def is_stream_io(call: ast.Call) -> Optional[str]:
    """``<something spelled like a stream/link/peer>.send/recv/
    send_request(...)`` — blocking network I/O by the fabric/exchange
    convention.  Returns the dotted spelling for the diagnostic, or
    None."""
    func = call.func
    if not isinstance(func, ast.Attribute):
        return None
    if not _STREAM_IO_METHOD_RE.match(func.attr):
        return None
    receiver = dotted_name(func.value)
    if receiver is None:
        return None
    if _STREAM_RECEIVER_RE.search(receiver.rsplit(".", 1)[-1]):
        return f"{receiver}.{func.attr}"
    return None


def is_dispatch_booking(call: ast.Call) -> Optional[str]:
    """A runtime dispatch-budget booking: a bare or attribute call whose
    leaf is ``record_dispatch`` / ``record_fetch`` (ops/dispatch_counter).
    Returns the leaf name, or None."""
    name = dotted_name(call.func)
    if name is None:
        return None
    leaf = name.rsplit(".", 1)[-1]
    return leaf if leaf in _BOOKING_LEAVES else None


def booking_declares_fanout(call: ast.Call) -> bool:
    """Whether a dispatch booking carries the ``shards=`` keyword — the
    scatter-gather fan-out convention (1 logical + N physical)."""
    return any(kw.arg == "shards" for kw in call.keywords)


def is_cache_wrapper(scope_name: str) -> bool:
    """A scope following the cache-wrapper naming convention (see
    ``_CACHE_WRAPPER_RE``): its miss-path dispatch is accounted by the
    calling serve path's dispatch group."""
    return bool(_CACHE_WRAPPER_RE.match(scope_name or ""))


def is_cache_access(call: ast.Call) -> Optional[str]:
    """``<something spelled like a cache>.get/put/lookup/store/...`` —
    returns the dotted spelling for the diagnostic, or None."""
    func = call.func
    if not isinstance(func, ast.Attribute):
        return None
    if not _CACHE_METHOD_RE.match(func.attr):
        return None
    receiver = dotted_name(func.value)
    if receiver is None:
        return None
    if _CACHE_RECEIVER_RE.search(receiver.rsplit(".", 1)[-1]):
        return f"{receiver}.{func.attr}"
    return None


def is_jit_call(call: ast.Call, jit_fns: Set[str]) -> bool:
    callee = dotted_name(call.func)
    if callee is None:
        return False
    if callee in jit_fns or callee.rsplit(".", 1)[-1] in jit_fns:
        return True
    return _is_retry_wrapped_dispatch(call, jit_fns)


def is_device_value_arg(
    call: ast.Call, jit_fns: Set[str], device_vars: Set[str]
) -> bool:
    """First positional argument of ``call`` is a device value: either a
    direct jitted call, or a (possibly subscripted) name holding one —
    shared by the lock-discipline and hidden-sync rules so the resolution
    cannot drift between them."""
    if not call.args:
        return False
    arg = call.args[0]
    if isinstance(arg, ast.Call):
        return is_jit_call(arg, jit_fns)
    name = dotted_name(arg)  # Subscript-transparent: out[:n] -> "out"
    return name is not None and name in device_vars


def is_device_value_base(call: ast.Call, device_vars: Set[str]) -> bool:
    """``call`` is a method on a device value (``out.item()``,
    ``out[0].item()``)."""
    if not isinstance(call.func, ast.Attribute):
        return False
    base = dotted_name(call.func.value)
    return base is not None and base in device_vars


# a serve completion handle comes back from the submit/complete contract:
# ``handle = <obj>.submit(...)`` (FusedEncodeSearch, RetrieveRerankPipeline,
# CrossEncoderModel, ServeScheduler all follow it).  Dotted only — a bare
# ``submit(...)`` is some local helper, not the serving contract.
_SUBMIT_LEAF_RE = re.compile(r"^submit$")
# ...but ``executor.submit``/``pool.submit`` is the concurrent.futures
# convention, not a serve handle: waiting on a thread-pool future under a
# lock can be legitimate off the serve path, and flagging it with a
# "serve handle" diagnostic would be a false positive with a misleading
# message.  Receivers named like executors are excluded by convention.
_EXECUTOR_RECEIVER_RE = re.compile(r"(pool|executor)s?$", re.IGNORECASE)
# completing methods: ``handle()`` is the fetch itself; ``.result()`` is
# the ticket/future spelling; ``.advance()`` completes stage 1 (a host
# fetch) and dispatches stage 2
_HANDLE_COMPLETE_ATTRS = ("result", "advance")


def scope_handle_vars(
    scope: ast.AST, inherited: Optional[Set[str]] = None
) -> Set[str]:
    """Local names holding serve completion handles — assigned from a
    dotted ``<obj>.submit(...)`` call.  ``inherited`` seeds closures with
    the enclosing scope's handles (a completion closure capturing one is
    how the fetch legally escapes the dispatching scope)."""
    handles: Set[str] = set(inherited or ())
    for node in walk_scope(scope):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        if not isinstance(value, ast.Call):
            continue
        callee = dotted_name(value.func)
        if (
            callee is None
            or "." not in callee
            or not _SUBMIT_LEAF_RE.match(callee.rsplit(".", 1)[-1])
        ):
            continue
        receiver = callee.rsplit(".", 2)[-2]
        if _EXECUTOR_RECEIVER_RE.search(receiver):
            continue  # concurrent.futures convention, not a serve handle
        for tgt in node.targets:
            handles.update(_target_names(tgt))
    return handles


def is_handle_fetch(call: ast.Call, handle_vars: Set[str]) -> Optional[str]:
    """The spelled-out completion of a tracked handle: ``handle()``,
    ``handle.result(...)``, or ``handle.advance()``.  Returns the dotted
    spelling for the diagnostic, or None."""
    func = call.func
    if isinstance(func, ast.Name) and func.id in handle_vars:
        return func.id
    if isinstance(func, ast.Attribute) and func.attr in _HANDLE_COMPLETE_ATTRS:
        base = dotted_name(func.value)
        if base is not None and base in handle_vars:
            return f"{base}.{func.attr}"
    return None
