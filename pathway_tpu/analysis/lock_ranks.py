"""The declared lock hierarchy for the serve stack — ONE ordered table.

The thread fabric behind the serve tier holds 60+ distinct locks across
the scheduler, decode slot pool, cache tiers, indexes, shard group,
exchange plane and the observe stack.  Per-module lock discipline
(``lock_discipline.py``) keeps device work out of lock bodies, but says
nothing about cross-module ACQUISITION ORDER — the deadlock dimension.
This module declares the order; ``lock_order.py`` (static) and
``sanitizer.py`` (runtime) enforce it.

The hierarchy, lowest to highest::

    observe < cache < model < index < shard < scheduler < pool

reads "a lock on the LEFT may be acquired while holding a lock on the
RIGHT".  Equivalently: **threads acquire in descending rank order** —
while holding a lock of rank ``r`` you may only acquire locks of rank
``< r`` (equal ranks are ordered by the cycle check instead, so two
same-domain locks may nest as long as every thread agrees on the
direction).  The top of the table is the outermost coordination layer
(the decode slot pool and admission scheduler own threads and drive the
layers below); the bottom is leaf bookkeeping (metrics counters, trace
stores) that every layer may touch last.

Domain assignment is by DEFINING module: a lock created in
``cache/store.py`` is a ``cache``-rank lock wherever it is acquired.
Modules outside the serve stack (engine operators, IO connectors,
stdlib, xpacks) are **unranked**: their locks still participate in
deadlock-cycle detection, but the rank table makes no claim about them.

A deliberate exception to the declared order is waived in place with a
reviewed pragma naming the rank exception::

    with self._lock:  # pathway: allow(lock-order): <which ranks and why safe>
"""

from __future__ import annotations

import re
from typing import Optional, Tuple

__all__ = [
    "DECLARED_EXCEPTIONS",
    "RANK_ORDER",
    "domain_of_path",
    "domain_of_receiver",
    "pair_waived",
    "rank_name",
    "rank_of_path",
    "rank_of_receiver",
    "table",
]

# lowest (innermost leaf) → highest (outermost coordinator)
RANK_ORDER: Tuple[str, ...] = (
    "observe",    # 0: metrics/trace/profiler/SLO bookkeeping, tripwires
    "cache",      # 1: result/embedding/prefix-KV tiers, object cache
    "model",      # 2: encoder/cross-encoder/generator compiled-fn caches
    "index",      # 3: IVF, forward index, kNN structures
    "shard",      # 4: shard group, exchange plane, process clusters
    "scheduler",  # 5: admission queue, serve pipelines, batch handoff
    "pool",       # 6: continuous-decode slot pool (owns the step loop)
)

_RANK_BY_NAME = {name: i for i, name in enumerate(RANK_ORDER)}

# ordered (pattern, domain) table over repo-relative display paths; the
# FIRST match wins.  Paths normalised to "/" before matching.
_DOMAIN_PATTERNS: Tuple[Tuple[re.Pattern, str], ...] = tuple(
    (re.compile(pat), dom)
    for pat, dom in (
        # observe: the flight recorder + derived samplers, plus the
        # runtime tripwires (dispatch counter, recompile guard) and the
        # robust layer's breaker/retry/inject bookkeeping — all leaf
        # locks held only around counter/dict updates
        (r"(^|/)observe/", "observe"),
        (r"(^|/)robust/", "observe"),
        (r"(^|/)ops/dispatch_counter\.py$", "observe"),
        (r"(^|/)ops/recompile_guard\.py$", "observe"),
        (r"(^|/)analysis/", "observe"),
        # cache: the serve cache tiers and the persistence object cache
        (r"(^|/)cache/", "cache"),
        (r"(^|/)persistence/", "cache"),
        # model: per-model compiled-fn caches and parameter state
        (r"(^|/)models/", "model"),
        # index: IVF + forward + kNN/LSH structures
        (r"(^|/)ops/ivf\.py$", "index"),
        (r"(^|/)ops/knn\.py$", "index"),
        (r"(^|/)index/", "index"),
        (r"(^|/)stdlib/ml/", "index"),
        # shard: device shard group + host exchange/cluster planes
        (r"(^|/)parallel/", "shard"),
        # scheduler: admission + serve pipelines (the coalescing
        # scheduler, fused search, retrieve→rerank handoff locks)
        (r"(^|/)serve/scheduler\.py$", "scheduler"),
        (r"(^|/)ops/serving\.py$", "scheduler"),
        (r"(^|/)ops/retrieve_rerank\.py$", "scheduler"),
        # pool: the continuous-decode slot pool
        (r"(^|/)serve/decode\.py$", "pool"),
    )
)


# receiver-name convention for OPAQUE lock sites (`with child._lock:`
# where `child`'s class is statically unknown): the serve stack names
# its cross-object receivers consistently, so the spelling carries the
# domain even when the defining class does not resolve.  Only receivers
# listed here get a rank; everything else stays unranked.
_RECEIVER_DOMAINS = {
    "index": "index",
    "ivf": "index",
    "forward": "index",
    "child": "index",      # shard-resident per-child index handles
    "shard": "shard",
    "plane": "shard",
    "group": "shard",
    "gen": "model",
    "generator": "model",
    "encoder": "model",
    "model": "model",
    "cache": "cache",
    "tier": "cache",
    "sched": "scheduler",
    "scheduler": "scheduler",
    "pipe": "scheduler",
    "pipeline": "scheduler",
    "pool": "pool",
    "engine": "pool",
}


# reviewed DOMAIN-PAIR exceptions to the descending rule — the runtime
# sanitizer's mirror of the `# pathway: allow(lock-order)` pragmas in
# code (the static side waives at the acquisition site; the runtime side
# sees the lock's REAL defining module, so the same exception must be
# declared here).  (outer, inner) means "an `outer`-domain lock may be
# held while acquiring an `inner`-domain lock despite inner > outer".
# Adding a pair here is a review event, exactly like adding a pragma.
DECLARED_EXCEPTIONS = frozenset(
    {
        # index-before-pipeline: the fused-serve pair order at every
        # site (IVF absorb DONATES slab buffers, forcing the stage-1
        # launch before the index lock drops; the pipeline's compiled-fn
        # guard nests inside) — see ops/serving.py's lock-order pragmas
        ("index", "scheduler"),
    }
)


def pair_waived(outer_rank: Optional[int], inner_rank: Optional[int]) -> bool:
    """True when (outer, inner) is a declared rank-pair exception."""
    if outer_rank is None or inner_rank is None:
        return False
    return (
        RANK_ORDER[outer_rank], RANK_ORDER[inner_rank]
    ) in DECLARED_EXCEPTIONS


def domain_of_receiver(receiver: str) -> Optional[str]:
    """Rank domain for an opaque lock's receiver spelling (``child`` in
    ``with child._lock:``), or None when the name carries no convention."""
    return _RECEIVER_DOMAINS.get(receiver.lstrip("_"))


def rank_of_receiver(receiver: str) -> Optional[int]:
    domain = domain_of_receiver(receiver)
    return None if domain is None else _RANK_BY_NAME[domain]


def domain_of_path(display_path: str) -> Optional[str]:
    """Rank domain for a lock DEFINED in ``display_path`` (repo-relative
    or absolute; separators normalised), or None when the module is off
    the declared serve stack."""
    path = display_path.replace("\\", "/")
    for pattern, domain in _DOMAIN_PATTERNS:
        if pattern.search(path):
            return domain
    return None


def rank_of_path(display_path: str) -> Optional[int]:
    """Numeric rank (0 = innermost leaf) for a defining module, or None
    when unranked."""
    domain = domain_of_path(display_path)
    return None if domain is None else _RANK_BY_NAME[domain]


def rank_name(rank: Optional[int]) -> str:
    if rank is None:
        return "unranked"
    return f"{RANK_ORDER[rank]}({rank})"


def table() -> str:
    """The one-line rendering used by docs and diagnostics."""
    return " < ".join(RANK_ORDER)
