"""value-flow: where the bytes go — use-after-donate, hidden host
transfers, redundant uploads.

The fifth analyzer family.  The serve stack leans hard on donated
device buffers (IVF absorb slabs, forward-index scatter commits) and on
the "every host↔device crossing is booked" discipline (the 2+2 budget,
``record_fetch``), but the four existing families only police WHERE
code runs (under a lock, in a serve scope) — not where the VALUES flow.
This family runs an interprocedural dataflow over the residency lattice
(``residency.py``: ``host < device < donated-consumed``) through
assignments, helper calls, ``retry_call``/``profile.wrap`` wrappers and
the compiled-fn cache-getter conventions (``registry.py``), and checks:

1. **use-after-donate** — a value passed at a ``donate_argnums``
   position of a donating jitted callable (module-local
   ``@partial(jax.jit, donate_argnums=...)`` defs + the seeded
   ``residency.DONATION_SITES`` registry + helper functions that
   forward a parameter into a donating position, resolved to a
   fixpoint in ``finalize``) is read, fetched, or re-dispatched
   afterwards.  XLA reused the buffer for the outputs; jax marks the
   reference deleted — on TPU the read is garbage-or-crash, on CPU it
   raises, and either way the bug only surfaces at runtime without
   this check.  Rebinding the name (the sanctioned
   ``self._slabs, self._bias = _absorb_scatter(self._slabs, ...)``
   shape) clears the poison.
2. **hidden host transfer** — an IMPLICIT device→host sync the
   hidden-sync family cannot see: ``bool(dv)`` / branching on a device
   value (``if dv > 0:``), iterating one (``for x in dv:`` fetches per
   element), ``dv.tolist()``, plus — in modules hidden-sync does not
   cover — explicit coercions (``np.asarray``/``float``/``int``/
   ``.item()``) of a provably-device value, and coercion of an
   unknown-residency PARAMETER inside a lock body (callers hand the
   encoder's device rows straight to ``add(keys, vectors)``; the sync
   then happens under the lock).  A scope that books the crossing with
   ``record_fetch`` is clean.
3. **redundant upload** — a host→device transfer (``jnp.asarray`` /
   ``jnp.array`` / ``jax.device_put``) of a loop-invariant value inside
   a serve-path loop: the same bytes ride the PCIe/ICI link once per
   iteration (the exact-tail re-upload PR 1 fixed by hand — this makes
   the class unreintroducible).  Hoist the upload or cache the device
   buffer; a deliberate per-target scatter is waived with a reviewed
   pragma mirrored in ``residency.DECLARED_TRANSFERS``.

Runtime twin: ``ops/donation_guard.py`` (``PATHWAY_DONATION_GUARD=1``)
poisons donated references dynamically — touching one raises under
pytest and logs + counts ``pathway_donation_violations_total{site}`` in
production.

A reviewed exception is waived at the site::

    return np.asarray(rows)  # pathway: allow(value-flow): <why the crossing is sound>
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, ModuleContext, Rule
from .registry import (
    collect_donating_jits,
    dotted_name,
    is_device_producer_call,
    is_jit_call,
    scope_jit_and_device_vars,
    walk_scope,
)
from . import residency

__all__ = ["ValueFlowRule"]

_EXPLICIT_COERCIONS = {
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "float", "int", "jax.device_get",
}
_PARAM_COERCIONS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
_UPLOAD_CALLS = {
    "jnp.asarray", "jnp.array", "jax.numpy.asarray", "jax.numpy.array",
    "jax.device_put", "device_put",
}
# wrapper spellings whose call dispatches their FUNCTION argument with
# the remaining args (the robust-retry convention: retry_call("site",
# fn, *args)) — donated positions shift past the two leading args
_RETRY_LEAVES = {"retry_call"}
# wrapper BINDINGS that alias a donating callable: w = profile.wrap(
# "site", fn) / w = donation_guard.wrap("site", fn) — calling w donates
# exactly like fn
_ALIAS_WRAP_LEAVES = {"wrap"}


def _pure_dotted(node: ast.AST) -> Optional[str]:
    """Dotted spelling of a Name/Attribute/Subscript chain containing no
    embedded calls (``self._slabs``, ``out[0]``), else None."""
    probe = node
    while True:
        if isinstance(probe, ast.Attribute):
            probe = probe.value
        elif isinstance(probe, ast.Subscript):
            probe = probe.value
        elif isinstance(probe, ast.Name):
            return dotted_name(node)
        else:
            return None


def _component_prefixed(name: str, prefix: str) -> bool:
    """``self._slabs`` poisons ``self._slabs`` and ``self._slabs.shape``
    but NOT ``self._slabs_host`` — prefixing is per dotted component."""
    return name == prefix or name.startswith(prefix + ".")


class _FunctionFacts:
    """Ordered event stream for one function scope: calls (with dotted
    arg spellings), loads and rebinds of candidate names — everything
    the finalize-side donation replay needs, JSON-able for the cache."""

    def __init__(self, params: List[str]):
        self.params = params
        self.events: List[list] = []  # [line, col, kind, ...]
        self._arg_names: Set[str] = set()

    def call(
        self,
        line: int,
        col: int,
        leaves: List[str],
        args: List[Optional[str]],
        method: bool,
    ) -> None:
        self.events.append([line, col, "call", leaves, args, method])
        self._arg_names.update(a for a in args if a)

    def load(self, line: int, col: int, name: str) -> None:
        self.events.append([line, col, "load", name])

    def bind(self, line: int, col: int, name: str) -> None:
        self.events.append([line, col, "bind", name])

    def compact(self) -> dict:
        """Drop load/bind events that can never interact with a donated
        name: only names related (component-prefix either way) to some
        call argument can be poisoned."""
        cands = self._arg_names

        def relevant(name: str) -> bool:
            return any(
                _component_prefixed(name, c) or _component_prefixed(c, name)
                for c in cands
            )

        events = [
            ev
            for ev in self.events
            if ev[2] == "call" or relevant(ev[3])
        ]
        return {"params": self.params, "events": events}


class _Extractor:
    """One pass over a module: reports the per-module findings (hidden
    host transfers, redundant uploads) and extracts the donation facts
    (donating defs, wrap aliases, per-function event streams) for the
    whole-program use-after-donate pass."""

    def __init__(self, ctx: ModuleContext, rule_name: str):
        self.ctx = ctx
        self.rule_name = rule_name
        self.donating = {
            name: list(pos)
            for name, pos in collect_donating_jits(ctx.tree).items()
        }
        self.aliases: Dict[str, str] = {}
        self.functions: Dict[str, dict] = {}
        self._collect_aliases(ctx.tree)
        self._visit_scope(ctx.tree, None, None, None)

    # -- alias bindings: w = profile.wrap("site", fn) ----------------------
    def _collect_aliases(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            if not isinstance(value, ast.Call):
                continue
            callee = dotted_name(value.func)
            if callee is None:
                continue
            if callee.rsplit(".", 1)[-1] not in _ALIAS_WRAP_LEAVES:
                continue
            for arg in value.args:
                target = dotted_name(arg)
                if target is None:
                    continue
                leaf = target.rsplit(".", 1)[-1]
                if leaf in self.donating or leaf in residency.DONATION_SITES:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            self.aliases[tgt.id] = leaf
                    break

    # -- scope walk (hidden-transfer + redundant-upload + events) ----------
    def _visit_scope(self, scope, cls, inherited_fns, inherited_vars) -> None:
        jit_fns, device_vars = scope_jit_and_device_vars(
            scope, self.ctx.jit_names, inherited_fns, inherited_vars
        )
        device_vars = set(device_vars) | self._producer_vars(scope)
        if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
            local = f"{cls}.{scope.name}" if cls else scope.name
            if (
                scope.name not in self.ctx.jit_names
                and scope.name not in self.donating
            ):
                self._check_transfers(scope, jit_fns, device_vars)
                self._check_uploads(scope)
                if local not in self.functions:
                    self.functions[local] = self._extract_events(scope)
        for child in ast.iter_child_nodes(scope):
            self._recurse(child, cls, jit_fns, device_vars)

    def _recurse(self, node, cls, fns, dvars) -> None:
        if isinstance(node, ast.ClassDef):
            for child in ast.iter_child_nodes(node):
                self._recurse(child, node.name, fns, dvars)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._visit_scope(node, cls, fns, dvars)
            return
        if isinstance(node, ast.Lambda):
            return
        for child in ast.iter_child_nodes(node):
            self._recurse(child, cls, fns, dvars)

    def _producer_vars(self, scope) -> Set[str]:
        """Names assigned from a device-producer call (the encoder
        ``.encode`` convention) — device values even in modules with no
        jit registry of their own."""
        out: Set[str] = set()
        for node in walk_scope(scope):
            if not isinstance(node, ast.Assign):
                continue
            if isinstance(node.value, ast.Call) and is_device_producer_call(
                node.value
            ):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
        return out

    # -- rule 2: hidden host transfer --------------------------------------
    def _residency_of(self, node, jit_fns, device_vars) -> int:
        """HOST/DEVICE classification for one expression over the
        residency lattice (the DONATED state is per-NAME, tracked by the
        finalize replay's poison map)."""
        if isinstance(node, ast.Call):
            if is_jit_call(node, jit_fns) or is_device_producer_call(node):
                return residency.DEVICE
            return residency.HOST
        name = _pure_dotted(node)
        if name is not None and name in device_vars:
            return residency.DEVICE
        return residency.HOST

    def _is_device_expr(self, node, jit_fns, device_vars) -> bool:
        return self._residency_of(node, jit_fns, device_vars) >= residency.DEVICE

    def _test_device_name(self, test, device_vars) -> Optional[str]:
        """A device value used as a DIRECT operand of a branch/loop/
        assert test (``if dv:``, ``if dv > 0:``, ``while not dv:``) —
        the bool() of the comparison result syncs.  Metadata reads
        (``len(dv)``, ``dv.shape[0]``) are free and stay quiet: only an
        exact device-var spelling (possibly subscripted) matches."""

        def direct(node) -> Optional[str]:
            if isinstance(node, (ast.Name, ast.Attribute, ast.Subscript)):
                name = _pure_dotted(node)
                if name is not None and name in device_vars:
                    return name
                return None
            if isinstance(node, ast.Compare):
                # `is` / `is not` are pure reference checks — `if dv is
                # None:` never fetches; only value comparisons sync
                if all(
                    isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
                ):
                    return None
                for operand in [node.left] + list(node.comparators):
                    got = direct(operand)
                    if got is not None:
                        return got
                return None
            if isinstance(node, ast.BoolOp):
                for operand in node.values:
                    got = direct(operand)
                    if got is not None:
                        return got
                return None
            if isinstance(node, ast.UnaryOp):
                return direct(node.operand)
            return None

        return direct(test)

    def _check_transfers(self, scope, jit_fns, device_vars) -> None:
        ctx = self.ctx
        has_record_fetch = False
        found: List[Tuple[ast.AST, str]] = []
        params = {
            a.arg
            for a in list(scope.args.args) + list(scope.args.kwonlyargs)
            if a.arg not in ("self", "cls")
        }
        lock_depth_nodes = self._lock_bodies(scope)
        for node in walk_scope(scope):
            if isinstance(node, ast.Call):
                callee = dotted_name(node.func)
                leaf = callee.rsplit(".", 1)[-1] if callee else ""
                if leaf == "record_fetch":
                    has_record_fetch = True
                elif leaf == "tolist" and isinstance(node.func, ast.Attribute):
                    base = _pure_dotted(node.func.value)
                    if base is not None and base in device_vars:
                        found.append(
                            (
                                node,
                                f"`{base}.tolist()` forces an element-wise "
                                "device→host transfer of the whole array",
                            )
                        )
                elif leaf == "bool" and node.args and self._is_device_expr(
                    node.args[0], jit_fns, device_vars
                ):
                    found.append(
                        (
                            node,
                            "`bool()` of a device value blocks on the "
                            "transfer just to branch",
                        )
                    )
                elif (
                    not ctx.serve_path
                    and callee in _EXPLICIT_COERCIONS
                    and node.args
                    and self._is_device_expr(
                        node.args[0], jit_fns, device_vars
                    )
                ):
                    found.append(
                        (
                            node,
                            f"`{callee}` of a device value — an unbooked "
                            "device→host sync",
                        )
                    )
                elif (
                    not ctx.serve_path
                    and leaf == "item"
                    and isinstance(node.func, ast.Attribute)
                ):
                    base = _pure_dotted(node.func.value)
                    if base is not None and base in device_vars:
                        found.append(
                            (
                                node,
                                f"`{base}.item()` — an unbooked device→host "
                                "sync",
                            )
                        )
                elif (
                    callee in _PARAM_COERCIONS
                    and node.args
                    and isinstance(node.args[0], ast.Name)
                    and node.args[0].id in params
                    and node in lock_depth_nodes
                ):
                    found.append(
                        (
                            node,
                            f"`{callee}({node.args[0].id})` coerces a "
                            "caller-provided value inside a lock body — "
                            "callers hand device arrays here (the encoder "
                            "convention), making this a device→host sync "
                            "under the lock; coerce BEFORE acquiring it",
                        )
                    )
            elif isinstance(node, ast.For):
                name = _pure_dotted(node.iter)
                if name is not None and name in device_vars:
                    found.append(
                        (
                            node,
                            f"iterating device value `{name}` fetches one "
                            "element per step — a transfer per iteration",
                        )
                    )
            elif isinstance(node, (ast.If, ast.While, ast.Assert)):
                name = self._test_device_name(node.test, device_vars)
                if name is not None:
                    found.append(
                        (
                            node,
                            f"branching on device value `{name}` forces an "
                            "implicit bool() sync",
                        )
                    )
        if has_record_fetch:
            return  # the scope books its crossing: not hidden
        for node, what in found:
            self.ctx.report(
                self.rule_name, node,
                f"hidden host transfer: {what} outside a record_fetch "
                "scope — book the crossing (record_fetch) or move it off "
                "the hot path",
            )

    def _lock_bodies(self, scope) -> Set[ast.AST]:
        """Every node lexically inside a ``with <lock>:`` body of this
        scope (nested defs excluded, same as every other rule)."""
        from .registry import is_lock_context

        out: Set[ast.AST] = set()
        for node in walk_scope(scope):
            if isinstance(node, ast.With) and is_lock_context(node):
                for inner in walk_scope(node):
                    out.add(inner)
        return out

    # -- rule 3: redundant upload ------------------------------------------
    def _check_uploads(self, scope) -> None:
        if not self.ctx.serve_path:
            return
        reported: Set[int] = set()  # one finding per call site: nested
        # loops each walk the inner call, but it is ONE upload
        for node in walk_scope(scope):
            if not isinstance(node, (ast.For, ast.While)):
                continue
            assigned = self._loop_assigned(node)
            for inner in walk_scope(node):
                if not isinstance(inner, ast.Call):
                    continue
                if id(inner) in reported:
                    continue
                callee = dotted_name(inner.func)
                if callee not in _UPLOAD_CALLS or not inner.args:
                    continue
                name = _pure_dotted(inner.args[0])
                if name is None:
                    continue
                root = name.split(".", 1)[0]
                if name in assigned or root in assigned:
                    continue  # varies per iteration: a real per-item upload
                reported.add(id(inner))
                self.ctx.report(
                    self.rule_name, inner,
                    f"redundant upload: `{callee}({name})` inside a "
                    "serve-path loop re-transfers a loop-invariant value "
                    "every iteration — hoist the upload (or cache the "
                    "device buffer, the PR-1 exact-tail lesson); a "
                    "deliberate per-target scatter needs a reviewed "
                    "pragma mirrored in residency.DECLARED_TRANSFERS",
                )

    def _loop_assigned(self, loop) -> Set[str]:
        """Names that may vary per iteration: anything (re)bound inside
        the loop, the loop target(s), and the RECEIVER of any method
        call (``rows.append(item)`` mutates ``rows`` in place — a value
        grown per iteration is not loop-invariant even though it is
        never re-assigned)."""
        out: Set[str] = set()

        def add_target(tgt) -> None:
            if isinstance(tgt, (ast.Tuple, ast.List)):
                for elt in tgt.elts:
                    add_target(elt)
                return
            name = _pure_dotted(tgt)
            if name is not None:
                out.add(name)
                out.add(name.split(".", 1)[0])

        if isinstance(loop, ast.For):
            add_target(loop.target)
        for node in walk_scope(loop):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    add_target(tgt)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                add_target(node.target)
            elif isinstance(node, ast.For):
                add_target(node.target)
            elif isinstance(node, ast.withitem) and node.optional_vars:
                add_target(node.optional_vars)
            elif isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                # conservative: a method receiver may have been mutated
                # in place — erring this way only SILENCES the rule
                add_target(node.func.value)
        return out

    # -- rule 1 facts: ordered event extraction ----------------------------
    def _extract_events(self, scope) -> dict:
        params = [a.arg for a in scope.args.args]
        facts = _FunctionFacts(params)

        def emit_expr(node) -> None:
            if node is None:
                return
            if isinstance(node, ast.Call):
                emit_call(node)
                return
            if isinstance(node, (ast.Name, ast.Attribute, ast.Subscript)):
                name = _pure_dotted(node)
                if name is not None:
                    facts.load(node.lineno, node.col_offset, name)
                    if isinstance(node, ast.Subscript):
                        emit_expr(node.slice)
                    return
            if isinstance(node, (ast.Lambda,)):
                return  # separate execution scope
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    emit_expr(child)

        def emit_call(node: ast.Call) -> None:
            callee = dotted_name(node.func)
            leaf = callee.rsplit(".", 1)[-1] if callee else ""
            args = list(node.args)
            method = isinstance(node.func, ast.Attribute)
            if leaf in _RETRY_LEAVES and len(args) >= 2:
                # retry_call("site", fn, *args): the wrapper dispatches
                # fn — donated positions index into args[2:]
                fn_name = dotted_name(args[1])
                leaf = fn_name.rsplit(".", 1)[-1] if fn_name else ""
                method = False
                args = args[2:]
            arg_names: List[Optional[str]] = []
            for arg in args:
                name = _pure_dotted(arg)
                arg_names.append(name)
                if name is None:
                    emit_expr(arg)
                elif isinstance(arg, ast.Subscript):
                    emit_expr(arg.slice)
            for kw in node.keywords:
                emit_expr(kw.value)
            # a method call READS its receiver: self._slabs.sum() after
            # a donation is a use (the bare `self` of helper calls never
            # poisons, so this stays quiet for plain self.helper())
            if isinstance(node.func, ast.Attribute):
                base = _pure_dotted(node.func.value)
                if base is not None:
                    facts.load(
                        node.func.value.lineno,
                        node.func.value.col_offset,
                        base,
                    )
            leaves = [self.aliases.get(leaf, leaf)] if leaf else []
            facts.call(
                node.lineno, node.col_offset, leaves, arg_names, method
            )

        def emit_binds(tgt) -> None:
            if isinstance(tgt, (ast.Tuple, ast.List)):
                for elt in tgt.elts:
                    emit_binds(elt)
                return
            if isinstance(tgt, ast.Subscript):
                emit_expr(tgt.slice)
                return  # x[i] = v mutates in place: x stays whatever it was
            name = _pure_dotted(tgt)
            if name is not None:
                facts.bind(tgt.lineno, tgt.col_offset, name)

        def emit_stmt(stmt) -> None:
            if isinstance(
                stmt,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                return
            if isinstance(stmt, ast.Assign):
                emit_expr(stmt.value)
                for tgt in stmt.targets:
                    emit_binds(tgt)
                return
            if isinstance(stmt, (ast.AugAssign,)):
                emit_expr(stmt.value)
                name = _pure_dotted(stmt.target)
                if name is not None:
                    facts.load(
                        stmt.target.lineno, stmt.target.col_offset, name
                    )
                return
            if isinstance(stmt, ast.AnnAssign):
                emit_expr(stmt.value)
                emit_binds(stmt.target)
                return
            if isinstance(stmt, ast.For):
                emit_expr(stmt.iter)
                emit_binds(stmt.target)
                for s in stmt.body + stmt.orelse:
                    emit_stmt(s)
                return
            if isinstance(stmt, ast.Delete):
                # `del snapshot` discards the reference — that is the
                # sanctioned way to DROP a donated ref, not a read
                for tgt in stmt.targets:
                    emit_binds(tgt)
                return
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    emit_expr(child)
                elif isinstance(child, ast.stmt):
                    emit_stmt(child)
                elif isinstance(child, ast.withitem):
                    emit_expr(child.context_expr)
                    if child.optional_vars is not None:
                        emit_binds(child.optional_vars)
                elif isinstance(child, ast.ExceptHandler):
                    for s in child.body:
                        emit_stmt(s)

        for stmt in scope.body:
            emit_stmt(stmt)
        return facts.compact()

    def summary(self) -> dict:
        return {
            "donating": self.donating,
            "functions": self.functions,
        }


class _DonationProgram:
    """The whole-program use-after-donate pass: merge every module's
    donating registry (seed table + AST-discovered defs), propagate
    donation through helper functions to a fixpoint (a helper that
    forwards a parameter into a donated position donates that
    parameter), then replay each function's event stream."""

    def __init__(self, summaries: Dict[str, dict]):
        self.summaries = summaries
        # leaf -> (positions, has_self)
        self.donating: Dict[str, Tuple[Tuple[int, ...], bool]] = {
            leaf: (tuple(pos), False)
            for leaf, pos in residency.DONATION_SITES.items()
        }
        for path in sorted(summaries):
            for name, pos in summaries[path].get("donating", {}).items():
                self.donating.setdefault(
                    name.rsplit(".", 1)[-1], (tuple(pos), False)
                )
        self._fixpoint()

    def _donated_args(
        self, leaves: Sequence[str], args: Sequence[Optional[str]],
        method: bool,
    ) -> Tuple[Optional[str], List[Optional[str]]]:
        """(callee leaf, donated arg names) when the call donates."""
        for leaf in leaves:
            entry = self.donating.get(leaf)
            if entry is None:
                continue
            positions, has_self = entry
            offset = 1 if (has_self and method) else 0
            out: List[Optional[str]] = []
            for p in positions:
                i = p - offset
                out.append(args[i] if 0 <= i < len(args) else None)
            return leaf, out
        return None, []

    def _fixpoint(self) -> None:
        for _ in range(20):
            changed = False
            for path in sorted(self.summaries):
                funcs = self.summaries[path].get("functions", {})
                for local in sorted(funcs):
                    rec = funcs[local]
                    params = rec["params"]
                    leaf = local.rsplit(".", 1)[-1]
                    if leaf in self.donating:
                        continue
                    donated_params: Set[int] = set()
                    for ev in rec["events"]:
                        if ev[2] != "call":
                            continue
                        _callee, names = self._donated_args(
                            ev[3], ev[4], ev[5]
                        )
                        for name in names:
                            if name in params:
                                donated_params.add(params.index(name))
                    if donated_params:
                        has_self = bool(params) and params[0] in (
                            "self", "cls"
                        )
                        self.donating[leaf] = (
                            tuple(sorted(donated_params)), has_self
                        )
                        changed = True
            if not changed:
                return

    def findings(self) -> List[Finding]:
        out: List[Finding] = []
        for path in sorted(self.summaries):
            funcs = self.summaries[path].get("functions", {})
            for local in sorted(funcs):
                out.extend(self._replay(path, local, funcs[local]))
        return out

    def _replay(self, path: str, local: str, rec: dict) -> List[Finding]:
        poison: Dict[str, Tuple[str, int]] = {}
        out: List[Finding] = []

        def poisoned(name: str) -> Optional[Tuple[str, Tuple[str, int]]]:
            # a USE must reach the buffer: the loaded name is the
            # poisoned name or a path UNDER it.  A bare prefix load
            # (`self` as a helper-call receiver after `self._slabs` was
            # donated) is not a use — matching the other direction
            # would flag every `self.helper()` between a donating call
            # and its rebind.
            for p, origin in poison.items():
                if _component_prefixed(name, p):
                    return p, origin
            return None

        for ev in rec["events"]:
            line, col, kind = ev[0], ev[1], ev[2]
            if kind == "call":
                leaves, args, method = ev[3], ev[4], ev[5]
                for name in args:
                    if name is None:
                        continue
                    hit = poisoned(name)
                    if hit is not None:
                        p, (origin, oline) = hit
                        out.append(
                            Finding(
                                path, line, col, "value-flow",
                                f"use-after-donate: `{name}` passed to "
                                f"`{'/'.join(leaves) or '<call>'}(...)` "
                                f"after `{p}` was donated to `{origin}` "
                                f"at line {oline} — the buffer was "
                                "consumed in place; snapshot before the "
                                "donating call or rebind from its "
                                "results",
                            )
                        )
                        del poison[p]  # report each donation once
                callee, donated = self._donated_args(leaves, args, method)
                if callee is not None:
                    for name in donated:
                        if name is not None:
                            poison[name] = (callee, line)
            elif kind == "load":
                name = ev[3]
                hit = poisoned(name)
                if hit is not None:
                    p, (origin, oline) = hit
                    out.append(
                        Finding(
                            path, line, col, "value-flow",
                            f"use-after-donate: `{name}` read after "
                            f"`{p}` was donated to `{origin}` at line "
                            f"{oline} — the buffer was consumed in "
                            "place (jax marks it deleted); snapshot "
                            "before the donating call or rebind from "
                            "its results",
                        )
                    )
                    del poison[p]
            elif kind == "bind":
                name = ev[3]
                for p in [
                    p for p in poison if _component_prefixed(p, name)
                ]:
                    del poison[p]
        return out


class ValueFlowRule(Rule):
    name = "value-flow"
    salt_sources = ("value_flow.py", "residency.py")
    description = (
        "device value-flow over the residency lattice: use-after-donate "
        "(static twin of ops/donation_guard.py), hidden host transfers "
        "(implicit device→host syncs), redundant loop-invariant uploads"
    )

    def __init__(self) -> None:
        self._summaries: Dict[str, dict] = {}

    def run(self, ctx: ModuleContext) -> None:
        extractor = _Extractor(ctx, self.name)
        self._summaries[ctx.display_path] = extractor.summary()

    def dump_summary(self, display_path: str) -> Optional[dict]:
        return self._summaries.get(display_path)

    def load_summary(self, display_path: str, summary: dict) -> None:
        self._summaries[display_path] = summary

    def finalize(self) -> List[Finding]:
        return _DonationProgram(self._summaries).findings()
